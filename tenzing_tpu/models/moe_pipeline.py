"""Single-chip MoE dispatch/combine pipeline: a benchmark workload.

The multi-chip MoE layer (models/moe.py) moves routed tokens between expert
shards with all-to-alls.  The environment benches on ONE chip, so — exactly
like the halo pipeline (models/halo_pipeline.py) — the network hop is realized
as the chip's asynchronous host round-trip DMA (``HostSpillStart`` ->
``HostFetchStart``): routed tokens travel device -> pinned-host -> device to
the resident experts and their outputs travel back the same way, the
single-chip analog of an expert-parallel deployment's dispatch and combine
transfers.  Numerically this is the 1-shard degenerate case: all experts are
resident, so Y must equal the dense routed evaluation regardless of schedule.

Per microbatch chunk ``c`` the DAG is::

    pack_c (DeviceOp, lane-searched)   # gather routed tokens into slot table
      -> spilld_c -> fetchd_c -> awaitd_c   # dispatch round trip (post/wait)
      -> ffn_c (DeviceOp / ChoiceOp)        # per-expert gelu MLP (MXU)
      -> spillc_c -> fetchc_c -> awaitc_c   # combine round trip (post/wait)
      -> combine_c (DeviceOp, lane-searched)  # weighted scatter-add
    all combine_c -> concat -> finish

Round 3 adds the transfer-ENGINE dimension: each chunk chain's dispatch
and combine hops can run as the host-staged round trip (spill+fetch, the
non-GPU-aware-MPI staging analog) or as a device-resident remote-DMA copy
(ops/rdma.py, the CUDA-aware analog) — ``engine="rdma"`` wires the latter,
``staging="choice"`` searches the full precision x engine menu.

The ``n_chunks`` chains are independent: the searched freedom is how chunk
A's DMAs hide behind chunk B's expert compute and how the two DMA directions
pipeline — the schedule MoE systems hand-tune.  The routing is host-side
setup (top-1 gating into capacity-padded slot tables, the negotiation analog
of models/moe.py), and staged transfers use the (rows, 128) flat layout the
host-offload path is reliable for (see models/halo_pipeline.PackFlat).

With ``impl_choice=True`` the expert MLP becomes a ChoiceOp over XLA einsums
vs the Pallas per-expert kernel (ops/ffn_pallas.py ffn_pallas_batched).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    ChoiceOp,
    CompoundOp,
    DeviceOp,
    Finish,
    OpBase,
    Start,
)
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.models.halo_pipeline import flatten_face, unflatten_face
from tenzing_tpu.ops.comm_ops import AwaitTransfer, HostFetchStart, HostSpillStart
from tenzing_tpu.utils.numeric import gelu_tanh


@dataclass(frozen=True)
class MoEPipeArgs:
    n_experts: int = 8
    tokens: int = 8192  # total tokens on the chip
    d_model: int = 512
    d_ff: int = 2048
    n_chunks: int = 4  # independent dispatch->expert->combine chains
    dtype: str = "float32"

    @property
    def chunk_tokens(self) -> int:
        assert self.tokens % self.n_chunks == 0
        return self.tokens // self.n_chunks


def _slot_shape(args: MoEPipeArgs, cap: int) -> Tuple[int, int, int]:
    return (args.n_experts, cap, args.d_model)


class DispatchPackPipe(DeviceOp):
    """Gather chunk ``c``'s routed tokens into the capacity-padded slot table
    and emit it in the (rows, 128) staging layout the host round trip needs.
    With ``prec="bf16"`` the staging buffer is bfloat16 — half the DMA bytes,
    and numerically free on this platform: the expert matmuls truncate their
    operands to bf16 on the MXU regardless (xla_allow_excess_precision,
    experiments/device_numerics.py)."""

    def __init__(self, name: str, c: int, args: MoEPipeArgs, cap: int,
                 prec: str = "f32"):
        super().__init__(name)
        self._c, self._args, self._cap = c, args, cap
        self._sfx = "16" if prec == "bf16" else ""

    def reads(self):
        return ["X", f"idx_{self._c}"]

    def writes(self):
        return [f"send{self._sfx}_{self._c}"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        a, tc_ = self._args, self._args.chunk_tokens
        xc = bufs["X"][self._c * tc_ : (self._c + 1) * tc_]  # (Tc, d)
        slots = xc[bufs[f"idx_{self._c}"]]  # (E, C, d)
        if self._sfx:
            slots = slots.astype(jnp.bfloat16)
        return {f"send{self._sfx}_{self._c}": flatten_face(slots, _slot_shape(a, self._cap))}


class ExpertFFNPipe(DeviceOp):
    """Run every resident expert's gelu MLP over its received slots (the MXU
    compute the DMAs hide behind)."""

    def __init__(self, name: str, c: int, args: MoEPipeArgs, cap: int,
                 prec: str = "f32"):
        super().__init__(name)
        self._c, self._args, self._cap = c, args, cap
        self._sfx = "16" if prec == "bf16" else ""

    def reads(self):
        return [f"recv{self._sfx}_{self._c}", "W1", "W2"]

    def writes(self):
        return [f"out{self._sfx}_{self._c}"]

    def _mlp(self, x3, w1, w2):
        import jax
        import jax.numpy as jnp

        h = jax.nn.gelu(
            jnp.einsum("ecd,edf->ecf", x3, w1, preferred_element_type=jnp.float32)
        )
        return jnp.einsum(
            "ecf,efd->ecd", h.astype(x3.dtype), w2,
            preferred_element_type=jnp.float32,
        )

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        shape = _slot_shape(self._args, self._cap)
        raw = unflatten_face(bufs[f"recv{self._sfx}_{self._c}"], shape)
        x3 = raw.astype(jnp.float32) if self._sfx else raw
        y = self._mlp(x3, bufs["W1"], bufs["W2"])
        y = y.astype(jnp.bfloat16 if self._sfx else x3.dtype)
        return {f"out{self._sfx}_{self._c}": flatten_face(y, shape)}


    # -- op-chunking protocol (core/chunking.py, T3): the expert MLP splits
    # over the expert axis into n partial FFNs, each updating its expert
    # slice of the output slot table — so the combine-side DMA (or another
    # chunk's transfer) can interleave with the tail partials instead of
    # waiting for every expert.  XLA variant only: the Pallas kernel owns
    # its internal blocking.
    def chunkable(self) -> bool:
        return True

    def chunk_counts(self) -> List[int]:
        from tenzing_tpu.core.chunking import pow2_counts

        return pow2_counts(self._args.n_experts)

    def split(self, n: int) -> List["ExpertFFNPipePartial"]:
        e = self._args.n_experts
        if n < 1 or e % n:
            raise ValueError(f"{e} experts do not split {n} ways")
        return [
            ExpertFFNPipePartial(f"{self.name()}.c{n}p{j}", self._c,
                                 self._args, self._cap, j, n,
                                 "bf16" if self._sfx else "f32")
            for j in range(n)
        ]


class ExpertFFNPipePartial(ExpertFFNPipe):
    """Partial ``j`` of an ``n``-way expert split: run the MLP over its
    expert-row slice of the received slot table and fold the result into
    the output buffer (read-modify-write — the combine is the accumulating
    slice update, so the partials chain serially through the buffer
    version and the schedule interleaves OTHER ops between them)."""

    def __init__(self, name: str, c: int, args: MoEPipeArgs, cap: int,
                 part: int, n_parts: int, prec: str = "f32"):
        super().__init__(name, c, args, cap, prec)
        self._part, self._n_parts = part, n_parts

    def chunkable(self) -> bool:
        return False  # a partial never re-splits

    def reads(self):
        return super().reads() + [f"out{self._sfx}_{self._c}"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp
        from jax import lax

        shape = _slot_shape(self._args, self._cap)
        lo = self._part * (shape[0] // self._n_parts)
        hi = lo + shape[0] // self._n_parts
        raw = unflatten_face(bufs[f"recv{self._sfx}_{self._c}"], shape)
        x3 = raw.astype(jnp.float32) if self._sfx else raw
        y = self._mlp(x3[lo:hi], bufs["W1"][lo:hi], bufs["W2"][lo:hi])
        y = y.astype(jnp.bfloat16 if self._sfx else x3.dtype)
        cur = unflatten_face(bufs[f"out{self._sfx}_{self._c}"], shape)
        upd = lax.dynamic_update_slice_in_dim(cur, y.astype(cur.dtype), lo, 0)
        return {f"out{self._sfx}_{self._c}": flatten_face(upd, shape)}


class ExpertFFNPipePallas(ExpertFFNPipe):
    """Same per-expert MLP through the Pallas kernel (one expert's weight pair
    + one row tile per program in VMEM)."""

    def _mlp(self, x3, w1, w2):
        from tenzing_tpu.ops.ffn_pallas import ffn_pallas_batched

        return ffn_pallas_batched(x3, w1, w2)

    def uses_pallas(self) -> bool:
        return True

    def chunkable(self) -> bool:
        return False  # the kernel owns its internal blocking


def ffn_chunk_menu(args: MoEPipeArgs, cap: int, relax: bool = False):
    """(pruned counts, {count: est hidden µs}) for one chunk's expert FFN —
    the roofline sketch constraint (bench/roofline.py::prune_chunkings).
    The neighboring transfer is the combine-side staging DMA of the output
    slot table; ``relax=True`` (CPU smoke / library tests) keeps every
    structurally-valid count so toy shapes stay searchable."""
    from tenzing_tpu.bench import roofline

    bpe = np.dtype(args.dtype).itemsize
    e, d, dff = args.n_experts, args.d_model, args.d_ff
    slots = float(e * cap)
    table = slots * d * bpe  # one slot-table pass
    cost = roofline.Cost(
        flops=4.0 * slots * d * dff,
        hbm_bytes=2.0 * table + float(e * 2 * d * dff * bpe))
    # combine cost: every extra partial re-presents the output table
    # (read + write of the RMW slice update)
    return roofline.chunk_menu(
        ExpertFFNPipe("probe", 0, args, cap).chunk_counts(), cost,
        comm_us=table / (roofline.V5E_XFER_GBS * 1e9) * 1e6,
        combine_bytes=2.0 * table, relax=relax)


class ExpertFFNPipeChoice(ChoiceOp):
    def __init__(self, name: str, c: int, args: MoEPipeArgs, cap: int,
                 prec: str = "f32", chunk_counts=(), chunk_est=None):
        super().__init__(name)
        self._c, self._args, self._cap, self._prec = c, args, cap, prec
        self._chunks = tuple(int(n) for n in chunk_counts if int(n) > 1)
        self._chunk_est = dict(chunk_est or {})
        if chunk_counts:
            from tenzing_tpu.core.chunking import menu_info

            self.chunk_menu = menu_info(name + ".xla", chunk_counts,
                                        self._chunk_est)

    def choices(self) -> List[OpBase]:
        from tenzing_tpu.core.chunking import ChunkedOp

        out: List[OpBase] = [
            ExpertFFNPipe(self.name() + ".xla", self._c, self._args, self._cap,
                          self._prec),
            ExpertFFNPipePallas(
                self.name() + ".pallas", self._c, self._args, self._cap,
                self._prec
            ),
        ]
        # chunked alternatives of the XLA expert MLP: ordinary menu entries
        # the solvers pick like any kernel (core/chunking.py)
        out += [
            ChunkedOp(ExpertFFNPipe(self.name() + ".xla", self._c,
                                    self._args, self._cap, self._prec),
                      n, est_hidden_us=self._chunk_est.get(n))
            for n in self._chunks
        ]
        return out


class CombinePipe(DeviceOp):
    """Scatter-add the returned expert outputs into token order scaled by the
    gate weights (padding slots carry weight 0)."""

    def __init__(self, name: str, c: int, args: MoEPipeArgs, cap: int,
                 prec: str = "f32"):
        super().__init__(name)
        self._c, self._args, self._cap = c, args, cap
        self._sfx = "16" if prec == "bf16" else ""

    def reads(self):
        return [f"ret{self._sfx}_{self._c}", f"idx_{self._c}", f"w_{self._c}"]

    def writes(self):
        return [f"Y_{self._c}"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        a = self._args
        vals = unflatten_face(bufs[f"ret{self._sfx}_{self._c}"],
                              _slot_shape(a, self._cap))
        vals = vals.astype(jnp.float32)
        idx = bufs[f"idx_{self._c}"].reshape(-1)
        w = bufs[f"w_{self._c}"].reshape(-1, 1)
        y = jnp.zeros((a.chunk_tokens, a.d_model), vals.dtype)
        return {f"Y_{self._c}": y.at[idx].add(w * vals.reshape(-1, a.d_model))}


class ConcatPipe(DeviceOp):
    def __init__(self, name: str, args: MoEPipeArgs):
        super().__init__(name)
        self._args = args

    def reads(self):
        return [f"Y_{c}" for c in range(self._args.n_chunks)]

    def writes(self):
        return ["Y"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        return {
            "Y": jnp.concatenate(
                [bufs[f"Y_{c}"] for c in range(self._args.n_chunks)], axis=0
            )
        }


def chunk_ops(args: MoEPipeArgs, c: int, cap: int, impl_choice: bool = False,
              prec: str = "f32", engine: str = "host",
              op_chunk_counts=(), op_chunk_est=None):
    """The op chain for one microbatch chunk.  ``prec="bf16"`` routes the
    staged transfers through the half-width bfloat16 buffer set (op and
    buffer names carry a ``16`` suffix so both variants can coexist in one
    choice graph); ``engine="rdma"`` replaces each host round trip with a
    device-resident remote-DMA copy (ops/rdma.py — the CUDA-aware-MPI
    analog; the host buffers stay declared but untouched).
    ``op_chunk_counts``/``op_chunk_est`` add T3-style chunked expert-FFN
    alternatives to the menus (core/chunking.py; :func:`ffn_chunk_menu`)."""
    if engine not in ("host", "rdma"):
        raise ValueError(f"unknown transfer engine {engine!r}")
    s = "16" if prec == "bf16" else ""
    counts = tuple(n for n in (op_chunk_counts or ()) if int(n) > 1)
    if impl_choice:
        mk = lambda name, c_, a_, cap_, p_: ExpertFFNPipeChoice(
            name, c_, a_, cap_, p_, chunk_counts=op_chunk_counts,
            chunk_est=op_chunk_est)
    elif counts:
        from tenzing_tpu.core.chunking import ChunkChoice, chunk_variants

        def mk(name, c_, a_, cap_, p_):
            op = ExpertFFNPipe(name, c_, a_, cap_, p_)
            return ChunkChoice(op, chunk_variants(op, counts, op_chunk_est))
    else:
        mk = ExpertFFNPipe
    pack = DispatchPackPipe(f"pack{s}_{c}", c, args, cap, prec)
    if engine == "rdma":
        from tenzing_tpu.ops.rdma import RdmaCopyStart

        xfer_d = (RdmaCopyStart(f"xferd{s}_{c}.rdma", f"send{s}_{c}",
                                f"recv{s}_{c}"),)
        xfer_c = (RdmaCopyStart(f"xferc{s}_{c}.rdma", f"out{s}_{c}",
                                f"ret{s}_{c}"),)
    else:
        xfer_d = (
            HostSpillStart(f"spilld{s}_{c}", f"send{s}_{c}", f"hdisp{s}_{c}"),
            HostFetchStart(f"fetchd{s}_{c}", f"hdisp{s}_{c}", f"recv{s}_{c}"),
        )
        xfer_c = (
            HostSpillStart(f"spillc{s}_{c}", f"out{s}_{c}", f"hcomb{s}_{c}"),
            HostFetchStart(f"fetchc{s}_{c}", f"hcomb{s}_{c}", f"ret{s}_{c}"),
        )
    awaitd = AwaitTransfer(f"awaitd{s}_{c}", f"recv{s}_{c}")
    ffn = mk(f"ffn{s}_{c}", c, args, cap, prec)
    awaitc = AwaitTransfer(f"awaitc{s}_{c}", f"ret{s}_{c}")
    comb = CombinePipe(f"combine{s}_{c}", c, args, cap, prec)
    return (pack,) + xfer_d + (awaitd, ffn) + xfer_c + (awaitc, comb)


class ChunkChain(CompoundOp):
    """One chunk's whole dispatch->expert->combine chain as a compound, at a
    fixed staging precision — the unit the staging ChoiceOp selects."""

    def __init__(self, c: int, args: MoEPipeArgs, cap: int,
                 impl_choice: bool, prec: str, engine: str = "host",
                 op_chunk_counts=(), op_chunk_est=None):
        super().__init__(f"chain_{c}.{prec}-{engine}")
        self._c, self._args, self._cap = c, args, cap
        self._impl_choice, self._prec = impl_choice, prec
        self._engine = engine
        self._op_chunk_counts = tuple(op_chunk_counts)
        self._op_chunk_est = dict(op_chunk_est or {})

    def graph(self) -> Graph:
        g = Graph()
        ops = chunk_ops(self._args, self._c, self._cap, self._impl_choice,
                        self._prec, self._engine,
                        self._op_chunk_counts, self._op_chunk_est)
        g.start_then(ops[0])
        for a, b in zip(ops, ops[1:]):
            g.then(a, b)
        g.then_finish(ops[-1])
        return g


class StagingChoice(ChoiceOp):
    """The staging-precision menu for one chunk: f32 transfers vs half-width
    bf16 transfers.  On this platform bf16 staging is numerically free on the
    dispatch side (the expert matmuls truncate operands to bf16 regardless —
    xla_allow_excess_precision, experiments/device_numerics.py) and rounds
    the combine-side outputs to bf16; whether the halved DMA bytes win is the
    solver's question."""

    def __init__(self, c: int, args: MoEPipeArgs, cap: int, impl_choice: bool,
                 op_chunk_counts=(), op_chunk_est=None):
        super().__init__(f"chain_{c}")
        self._c, self._args, self._cap = c, args, cap
        self._impl_choice = impl_choice
        self._op_chunk_counts = tuple(op_chunk_counts)
        self._op_chunk_est = dict(op_chunk_est or {})

    def choices(self) -> List[OpBase]:
        return [
            ChunkChain(self._c, self._args, self._cap, self._impl_choice,
                       prec, engine, self._op_chunk_counts,
                       self._op_chunk_est)
            for prec in ("f32", "bf16")
            for engine in ("host", "rdma")
        ]


PHASES = ("start", "pack", "spilld", "fetchd", "xferd", "awaitd", "ffn",
          "spillc", "fetchc", "xferc", "awaitc", "combine", "concat", "finish")


def build_graph(args: MoEPipeArgs, cap: int, impl_choice: bool = False,
                staging: str = "f32", engine: str = "host",
                chunk: bool = False, chunk_relax: bool = False) -> Graph:
    """``n_chunks`` independent chains joined by the final concat (the
    multi-chip MoELayer's shape with the all-to-alls replaced by host round
    trips).  ``staging``: "f32" or "bf16" wires that variant directly;
    "choice" wraps each chunk's chain in a :class:`StagingChoice` so the
    solver also searches the transfer precision (buffers must come from
    ``make_pipe_buffers(..., staging="choice")``).

    ``chunk=True`` adds T3-style chunked expert-FFN alternatives to each
    chunk chain's menus (core/chunking.py; :func:`ffn_chunk_menu` prunes
    the counts through the roofline — ``chunk_relax`` skips the pruning,
    the CPU-smoke/tests mode)."""
    counts, est = ((), None)
    if chunk:
        counts, est = ffn_chunk_menu(args, cap, relax=chunk_relax)
    g = Graph()
    cat = ConcatPipe("concat", args)
    for c in range(args.n_chunks):
        if staging == "choice":
            chain = StagingChoice(c, args, cap, impl_choice, counts, est)
            g.start_then(chain)
            g.then(chain, cat)
            continue
        ops = chunk_ops(args, c, cap, impl_choice, prec=staging,
                        engine=engine, op_chunk_counts=counts,
                        op_chunk_est=est)
        g.start_then(ops[0])
        for a, b in zip(ops, ops[1:]):
            g.then(a, b)
        g.then(ops[-1], cat)
    g.then_finish(cat)
    return g


def naive_order(args: MoEPipeArgs, cap: int, platform) -> Sequence:
    """The naive sequential baseline: one lane, each chunk's chain completed
    (posts immediately awaited) before the next starts."""
    lane = platform.lanes[0]
    ops: List = [Start()]
    for c in range(args.n_chunks):
        for op in chunk_ops(args, c, cap):
            ops.append(op.bind(lane) if isinstance(op, DeviceOp) else op)
    cat = ConcatPipe("concat", args)
    ops += [cat.bind(lane), Finish()]
    return Sequence(ops)


def greedy_overlap_order(args: MoEPipeArgs, cap: int, platform,
                         staging: str = "f32", engine: str = "host") -> Sequence:
    """Phase-ordered incumbent: all packs, all dispatch posts, ... — the
    software-pipelined discipline, via the shared greedy (solve/greedy.py).
    ``staging="bf16"`` yields the half-width-transfer incumbent;
    ``engine="rdma"`` the device-resident-transfer incumbent."""
    from tenzing_tpu.solve.greedy import greedy_phase_order

    return greedy_phase_order(
        build_graph(args, cap, staging=staging, engine=engine),
        platform, PHASES)


def route_tokens(
    x: np.ndarray, wg: np.ndarray, args: MoEPipeArgs
) -> Tuple[int, Dict[str, np.ndarray]]:
    """Host-side top-1 routing into per-chunk capacity-padded slot tables
    (idx_{c} (E, C) int32, w_{c} (E, C) float32) — the setup-negotiation
    analog (models/moe.py, reference row_part_spmv.cuh:259-423).  Returns
    (capacity, tables); the (expert, gate) assignment comes from the shared
    :func:`~tenzing_tpu.models.moe.top1_route` rule."""
    from tenzing_tpu.models.moe import top1_route

    e_, tc_ = args.n_experts, args.chunk_tokens
    expert, gate = top1_route(x, wg)

    cap = 1
    for c in range(args.n_chunks):
        e_blk = expert[c * tc_ : (c + 1) * tc_]
        cap = max(cap, int(np.bincount(e_blk, minlength=e_).max()))
    tables: Dict[str, np.ndarray] = {}
    for c in range(args.n_chunks):
        idx = np.zeros((e_, cap), dtype=np.int32)
        w = np.zeros((e_, cap), dtype=np.dtype(args.dtype))
        fill = [0] * e_
        for j in range(tc_):
            e = int(expert[c * tc_ + j])
            idx[e, fill[e]] = j
            w[e, fill[e]] = gate[c * tc_ + j]
            fill[e] += 1
        tables[f"idx_{c}"] = idx
        tables[f"w_{c}"] = w
    return cap, tables


def make_pipe_buffers(
    args: MoEPipeArgs, seed: int = 0, with_expected: bool = True,
    staging: str = "f32"
) -> Tuple[Dict[str, np.ndarray], Optional[np.ndarray], int]:
    """(buffers, expected Y or None, capacity).  Routing runs here on the
    host; the expected Y is the dense routed evaluation in float64.
    ``staging`` declares the transfer buffer set(s) to match ``build_graph``:
    "f32", "bf16", or "choice" (both sets — either chain variant may
    execute)."""
    rng = np.random.default_rng(seed)
    e_, t, d, dff = args.n_experts, args.tokens, args.d_model, args.d_ff
    dt = np.dtype(args.dtype)
    x = rng.standard_normal((t, d)).astype(dt)
    wg = rng.standard_normal((d, e_)).astype(dt)
    w1 = (rng.standard_normal((e_, d, dff)) / np.sqrt(d)).astype(dt)
    w2 = (rng.standard_normal((e_, dff, d)) / np.sqrt(dff)).astype(dt)
    cap, tables = route_tokens(x, wg, args)

    bufs: Dict[str, np.ndarray] = {"X": x, "W1": w1, "W2": w2,
                                   "Y": np.zeros((t, d), dt)}
    bufs.update(tables)
    rows = -(-int(np.prod(_slot_shape(args, cap))) // 128)
    flat = np.zeros((rows, 128), dt)
    import ml_dtypes  # ships with jax

    flat16 = np.zeros((rows, 128), ml_dtypes.bfloat16)
    suffixes = {"f32": ("",), "bf16": ("16",), "choice": ("", "16")}[staging]
    for c in range(args.n_chunks):
        for s in suffixes:
            proto = flat16 if s else flat
            for nm in (f"send{s}_{c}", f"hdisp{s}_{c}", f"recv{s}_{c}",
                       f"out{s}_{c}", f"hcomb{s}_{c}", f"ret{s}_{c}"):
                bufs[nm] = proto.copy()
        bufs[f"Y_{c}"] = np.zeros((args.chunk_tokens, d), dt)

    want = None
    if with_expected:
        from tenzing_tpu.models.moe import top1_route

        expert, gate = top1_route(x, wg)
        want64 = np.zeros((t, d), np.float64)
        for e in range(e_):
            sel = expert == e
            h = gelu_tanh(x[sel].astype(np.float64) @ w1[e].astype(np.float64))
            want64[sel] = gate[sel, None] * (h @ w2[e].astype(np.float64))
        want = want64.astype(dt)  # workload dtype (ADVICE r2)
    return bufs, want, cap


def host_buffer_names(args: MoEPipeArgs, staging: str = "f32") -> List[str]:
    """Buffers the caller must device_put into pinned_host."""
    suffixes = {"f32": ("",), "bf16": ("16",), "choice": ("", "16")}[staging]
    return [f"hdisp{s}_{c}" for c in range(args.n_chunks) for s in suffixes] + [
        f"hcomb{s}_{c}" for c in range(args.n_chunks) for s in suffixes
    ]
