"""Single-chip 3D halo-exchange pipeline: the north-star benchmark workload.

Parity target: the reference's halo-exchange benchmark graph
(``HaloExchange::add_to_graph``, src/halo_exchange/ops_halo_exchange.cu:33-257)
— per face direction ``Pack(GpuOp) -> OwningIsend -> MultiWait`` and
``OwningIrecv -> Wait -> Unpack(GpuOp)``, searched over order x stream
assignment with config nQ=3, 512^3 cells, radius 3
(halo_run_strategy.hpp:42-49; BASELINE.md).

TPU-native single-chip realization.  The environment benches on ONE chip, so
the network hop is realized as the chip's asynchronous host round-trip DMA
(``HostSpillStart`` -> ``HostFetchStart``, the measured overlap substrate of
experiments/lane_overlap.py) — each direction's face travels
device -> pinned-host -> device, the single-chip analog of the reference's
staging through MPI.  Numerically this is the periodic 1x1x1-shard case: every
ghost shell receives the shard's own opposite interior face (the same result
``models/halo.py`` computes on an ``mx=my=mz=1`` mesh).

Per direction ``d`` the DAG is::

    pack_d (DeviceOp, lane-searched)      # slice interior face -> buf_d
      -> spill_d (HostSpillStart)         # post async device->host DMA
      -> fetch_d (HostFetchStart)         # post async host->device DMA
      -> await_d (AwaitTransfer)          # the reference's Wait
      -> unpack_d (DeviceOp, lane-searched)  # write ghost shell

The six chains are independent: the searched freedom is exactly the
reference's — how the six posts, waits, packs and unpacks interleave across
lanes, with the naive baseline (``naive_order``) the fully-synchronous
serialization that finishes each direction before starting the next (post
immediately awaited: MPI_Send-like blocking semantics).

Send-side completion note: the reference wires every ``OwningIsend`` into one
``MultiWait("he_wait_sends")`` because MPI requests must be waited.  Here the
spill's completion handle is the host buffer itself, which the fetch consumes
as a data dependency, so a separate send-side wait op would be a no-op by
construction (comm_ops.AwaitTransfer skips host-space buffers); the
post/await split on the receive side carries the whole overlap freedom.

With ``impl_choice=True`` pack/unpack become ChoiceOps over an XLA-slice vs
Pallas-kernel menu (ops/halo_pallas.py) — the analog of the reference's two
storage-order CUDA kernel families (ops_halo_exchange.cu:519-699).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import ChoiceOp, CompoundOp, Finish, Start
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.models.halo import (
    DIRECTIONS,
    HaloArgs,
    Pack,
    Unpack,
    _face_slices,
    dir_name,
    sublane_tile,
)
from tenzing_tpu.ops.comm_ops import AwaitTransfer, HostFetchStart, HostSpillStart


def _flat_rows(sizes) -> int:
    """Rows of the (rows, 128) staging layout for a face of ``sizes``."""
    n = int(np.prod(sizes))
    return -(-n // 128)


def flatten_face(face, sizes):
    """Face tensor -> (rows, 128) staging layout (shared by the XLA and Pallas
    pack variants; the inverse of :func:`unflatten_face`)."""
    import jax.numpy as jnp

    n = int(np.prod(sizes))
    flat = jnp.pad(face.reshape(-1), (0, _flat_rows(sizes) * 128 - n))
    return flat.reshape(-1, 128)


def unflatten_face(flat, sizes):
    """(rows, 128) staging layout -> face tensor of ``sizes``."""
    n = int(np.prod(sizes))
    return flat.reshape(-1)[:n].reshape(tuple(sizes))


class PackFlat(Pack):
    """Pack that emits the face as a 128-lane-flattened (rows, 128) staging
    buffer.  Probed on both the CPU backend and TPU v5e: spilling a 4D face
    with a tiny trailing dim (z-faces are (nq, lx, ly, r)) through
    pinned-host memory corrupts the round-trip (XLA copies only a partial
    stripe — a layout bug in mixed-memory copies of oddly-shaped tensors), so
    every staged transfer uses the 2D tiled layout the host-offload path is
    reliable for — which is also what the reference does with its staging
    buffers (contiguous pack buffers, ops_halo_exchange.hpp:97-186).

    INDEX_TIE: the op's token dependence rides the slice START index (an
    int32 zero derived from the token, ``ctx.tok_index_zero``) rather than a
    value-add on the 2 GB grid — six packs value-tying the same grid version
    forked it into full-grid add fusions (measured: 21 ms/iter on v5e).  The
    zero is added on the DIRECTION axis, where ``start < dim - size`` keeps
    the dynamic-slice clamp range non-degenerate: on a full-extent axis the
    clamp is provably 0 and XLA folds the tie away (probed — the compiled
    program had static slices and no token edge)."""

    INDEX_TIE = True

    def apply(self, bufs, ctx):
        import jax.lax as lax

        starts, sizes = _face_slices(self._args, self._d, "pack")
        # MUST come from the executor contract — a missing/None value means
        # the op would trace with no ordering edge at all, so fail loudly
        z = ctx.tok_index_zero
        if z is None:
            raise RuntimeError(
                f"{self.desc()}: INDEX_TIE op traced without tok_index_zero "
                "(executor contract violated — the pack would have no "
                "happens-before edge)"
            )
        axis = 1 + [i for i, v in enumerate(self._d) if v != 0][0]
        starts = tuple(
            s + z if i == axis else s for i, s in enumerate(starts)
        )
        sl = lax.dynamic_slice(bufs["U"], starts, sizes)
        return {f"buf_{dir_name(self._d)}": flatten_face(sl, sizes)}


class UnpackRecv(Unpack):
    """Unpack reading the fetched (round-tripped) flat staging buffer: reshape
    back to the face extents, then the same ghost-shell write as
    models/halo.Unpack."""

    def apply(self, bufs, ctx):
        import jax.lax as lax

        starts, _ = _face_slices(self._args, self._d, "unpack")
        _, sizes = _face_slices(self._args, self._d, "pack")
        face = unflatten_face(bufs[f"recv_{dir_name(self._d)}"], sizes)
        return {"U": lax.dynamic_update_slice(bufs["U"], face, starts)}


class HostRoundTrip(CompoundOp):
    """The host-staged transfer as one expandable vertex: post the
    device->host spill, then the host->device fetch — the non-GPU-aware-MPI
    staging analog, packaged so it can sit in a ChoiceOp next to the
    device-resident RDMA alternative."""

    def __init__(self, name: str, dname: str, buf: str, host: str, recv: str):
        super().__init__(name)
        self._dname = dname
        self._buf, self._host, self._recv = buf, host, recv

    def graph(self) -> Graph:
        g = Graph()
        spill = HostSpillStart(f"spill_{self._dname}", self._buf, self._host)
        fetch = HostFetchStart(f"fetch_{self._dname}", self._host, self._recv)
        g.start_then(spill)
        g.then(spill, fetch)
        g.then_finish(fetch)
        return g


class TransferChoice(ChoiceOp):
    """The transfer-engine menu for one direction's network hop: the
    host-staged round trip (PCIe + host memory, the non-CUDA-aware staging
    analog) vs a device-resident RDMA copy (the chip's DMA engine, the
    CUDA-aware analog — SURVEY §7.0's 'device buffers addressed by ICI DMA').
    Which engine, like which kernel, is the solver's question."""

    def __init__(self, d: Tuple[int, int, int]):
        name = dir_name(d)
        super().__init__(f"xfer_{name}")
        self._d = tuple(d)

    def choices(self) -> List:
        from tenzing_tpu.ops.rdma import RdmaCopyStart

        name = dir_name(self._d)
        return [
            HostRoundTrip(
                f"xfer_{name}.host", name, f"buf_{name}", f"host_{name}",
                f"recv_{name}"
            ),
            RdmaCopyStart(f"xfer_{name}.rdma", f"buf_{name}", f"recv_{name}"),
        ]


def direction_ops(args: HaloArgs, d: Tuple[int, int, int], impl_choice: bool = False,
                  xfer_choice: bool = False, engine: str = "host"):
    """The op chain for one face direction: (pack, transfer ops, await,
    unpack).  ``impl_choice`` turns pack/unpack into the kernel menu;
    ``xfer_choice`` turns the transfer into the engine menu; ``engine``
    ("host" | "rdma" | "mixed") wires one engine directly when no menu is
    wanted (the heuristic incumbents pick an engine up front —
    greedy_phase_order makes no ChooseOp decisions); "mixed" alternates
    engines across directions so both physical transfer paths run
    concurrently (the flagship 1.337x incumbent)."""
    if engine not in ("host", "rdma", "mixed"):
        raise ValueError(f"unknown transfer engine {engine!r}")
    name = dir_name(d)
    if impl_choice:
        from tenzing_tpu.ops.halo_pallas import PackChoice, UnpackChoice

        pack = PackChoice(args, d)
        unpack = UnpackChoice(args, d)
    else:
        pack = PackFlat(args, d)
        unpack = UnpackRecv(args, d)
    if engine == "mixed":
        # alternate engines across directions: the host path (PCIe + host
        # memory) and the on-device DMA engine are DIFFERENT physical
        # transfer resources, so a mixed assignment moves faces over both
        # concurrently — a point the per-direction ChoiceOp space contains
        # and this incumbent seeds directly
        engine = "rdma" if DIRECTIONS.index(tuple(d)) % 2 == 0 else "host"
    if xfer_choice:
        xfer: Tuple = (TransferChoice(d),)
    elif engine == "rdma":
        from tenzing_tpu.ops.rdma import RdmaCopyStart

        xfer = (RdmaCopyStart(f"xfer_{name}.rdma", f"buf_{name}", f"recv_{name}"),)
    else:
        xfer = (
            HostSpillStart(f"spill_{name}", f"buf_{name}", f"host_{name}"),
            HostFetchStart(f"fetch_{name}", f"host_{name}", f"recv_{name}"),
        )
    await_ = AwaitTransfer(f"await_{name}", f"recv_{name}")
    return (pack,) + xfer + (await_, unpack)


def add_to_graph(
    g: Graph,
    args: HaloArgs,
    preds: Optional[List] = None,
    succs: Optional[List] = None,
    impl_choice: bool = False,
    xfer_choice: bool = False,
    engine: str = "host",
) -> Graph:
    """Six independent pack -> transfer -> await -> unpack chains
    (reference HaloExchange::add_to_graph shape, ops_halo_exchange.cu:33-257)."""
    preds = preds if preds is not None else [g.start()]
    succs = succs if succs is not None else [g.finish()]
    for d in DIRECTIONS:
        ops = direction_ops(args, d, impl_choice, xfer_choice, engine)
        pack, unpack = ops[0], ops[-1]
        for p in preds:
            g.then(p, pack)
        for a, b in zip(ops, ops[1:]):
            g.then(a, b)
        for s in succs:
            g.then(unpack, s)
    return g


def build_graph(args: HaloArgs, impl_choice: bool = False,
                xfer_choice: bool = False, engine: str = "host") -> Graph:
    return add_to_graph(Graph(), args, impl_choice=impl_choice,
                        xfer_choice=xfer_choice, engine=engine)


# phase order of the pipeline's op-name prefixes (greedy incumbents and the
# hill-climb policy share it; covers both transfer engines)
HALO_PHASES = ("start", "pack", "spill", "fetch", "xfer", "await", "unpack",
               "finish")


def naive_order(args: HaloArgs, platform) -> Sequence:
    """The naive sequential baseline: one lane, each direction's chain completed
    (post immediately awaited) before the next starts — the fully-synchronous
    program the search must beat (BASELINE.md north star)."""
    lane = platform.lanes[0]
    ops: List = [Start()]
    for d in DIRECTIONS:
        pack, spill, fetch, await_, unpack = direction_ops(args, d)
        ops += [pack.bind(lane), spill, fetch, await_, unpack.bind(lane)]
    ops.append(Finish())
    return Sequence(ops)


def greedy_overlap_order(args: HaloArgs, platform, engine: str = "host") -> Sequence:
    """The post-all-before-await-any heuristic schedule, derived through the
    SDP machinery so the required sync ops are inserted exactly as the solver
    would.  This is the discipline the *reference's* halo graph hard-codes
    with its every-post-before-any-wait edges (ops_halo_exchange.cu:249-256);
    here the graph leaves the order free and this incumbent seeds the anytime
    search with it: packs round-robin across lanes, every transfer posted
    before any await, unpacks last (solve/greedy.py)."""
    from tenzing_tpu.solve.greedy import greedy_phase_order

    return greedy_phase_order(build_graph(args, engine=engine), platform,
                              HALO_PHASES)


def paired_priority(engine: str = "mixed"):
    """Per-op priority for the PAIRED overlap discipline: all packs, all
    posts, then per-direction ``await_d -> unpack_d`` pairs — each face is
    unpacked as soon as ITS transfer lands instead of after ALL transfers
    land (the phase discipline's all-awaits barrier).  Directions are visited
    fastest-engine-first: with ``engine='mixed'`` the on-chip DMA dirs
    (even DIRECTIONS indices) complete in microseconds and their unpacks run
    while the host round trips are still in flight — exactly the overlap the
    post/wait split exists to expose (reference Wait placement freedom,
    ops_mpi.hpp:121-131).  For phase_policy(priority=...) and the climb."""
    order = sorted(range(len(DIRECTIONS)),
                   key=lambda i: (i % 2 if engine == "mixed" else 0, i))
    rank = {dir_name(DIRECTIONS[i]): r for r, i in enumerate(order)}

    def priority(name: str) -> int:
        if name.startswith(("start",)):
            return 0
        if name.startswith("pack"):
            return 1
        if name.startswith(("spill", "fetch", "xfer")):
            return 2
        if name.startswith(("await", "unpack")):
            d = name.split("_", 1)[1].split(".", 1)[0]
            return 10 + 2 * rank[d] + (0 if name.startswith("await") else 1)
        return 99  # finish

    return priority


def paired_overlap_order(args: HaloArgs, platform, engine: str = "mixed") -> Sequence:
    """The paired await/unpack incumbent schedule (see :func:`paired_priority`),
    derived through the SDP machinery like the greedy incumbents."""
    from tenzing_tpu.solve.local import drive, phase_policy

    seq, _ = drive(
        build_graph(args, engine=engine), platform,
        phase_policy(platform, HALO_PHASES, priority=paired_priority(engine)),
    )
    return seq


def _padded_shape(shape: Tuple[int, int, int, int],
                  itemsize: int = 4) -> Tuple[int, int, int, int]:
    """U allocated with trailing dims padded to TPU tiling (sublane tile x
    128 lanes; the sublane tile scales with dtype width — 8 for 4-byte, 16
    for 2-byte, 32 for 1-byte): Mosaic requires HBM plane DMAs tile-aligned
    (ops/halo_pallas.py), and the padding is invisible to the XLA slice path
    (all face slices are interior)."""
    nq, x, y, z = shape
    st = sublane_tile(itemsize)
    return (nq, x, -(-y // st) * st, -(-z // 128) * 128)


def make_pipeline_buffers(
    args: HaloArgs, seed: int = 0, with_expected: bool = True
) -> Tuple[Dict[str, np.ndarray], Optional[np.ndarray]]:
    """(buffers, expected U): ghost shells filled with the shard's own opposite
    interior faces (periodic 1-shard domain).  ``with_expected=False`` skips
    the expected-U copy (a ~2 GB allocation at the reference bench config).
    The grid dtype is ``args.dtype`` — one source of truth shared with the
    Pallas menu gate (ops/halo_pallas.py ``_face_bx``)."""
    r = args.radius
    dtype = np.dtype(args.dtype)
    rng = np.random.default_rng(seed)
    U = np.zeros(_padded_shape(args.local_shape(), dtype.itemsize),
                 dtype=dtype)
    U[:, r : r + args.lx, r : r + args.ly, r : r + args.lz] = rng.random(
        (args.nq, args.lx, args.ly, args.lz), dtype=np.float32
    ).astype(dtype, copy=False)
    want = None
    if with_expected:
        want = U.copy()
        for d in DIRECTIONS:
            ps, sz = _face_slices(args, d, "pack")
            us, _ = _face_slices(args, d, "unpack")
            face = U[
                :, ps[1] : ps[1] + sz[1], ps[2] : ps[2] + sz[2], ps[3] : ps[3] + sz[3]
            ]
            want[
                :, us[1] : us[1] + sz[1], us[2] : us[2] + sz[2], us[3] : us[3] + sz[3]
            ] = face
    bufs: Dict[str, np.ndarray] = {"U": U}
    for d in DIRECTIONS:
        name = dir_name(d)
        _, sz = _face_slices(args, d, "pack")
        flat = np.zeros((_flat_rows(sz), 128), dtype=dtype)
        bufs[f"buf_{name}"] = flat
        bufs[f"host_{name}"] = flat.copy()  # placed in pinned_host by the caller
        bufs[f"recv_{name}"] = flat.copy()
    return bufs, want


def host_buffer_names() -> List[str]:
    """Buffers that must be device_put into pinned_host before execution (the
    executor detects host residency from the array's sharding memory_kind)."""
    return [f"host_{dir_name(d)}" for d in DIRECTIONS]
