"""Pipeline parallelism: microbatch pipelining as a searchable op DAG.

The reference has no model layers (SURVEY.md §2.5: TP/PP/EP absent; the op-DAG
must nonetheless *express* such programs).  This model is the
pipeline-parallel (PP) member of that family: stage ``s`` of an ``S``-stage
network lives on mesh-axis-``pp`` shard ``s``, and activations flow stage to
stage over ICI.  In SPMD form every device runs the same per-tick program —
compute the resident stage on the resident microbatch, then shift activations
one hop forward (`lax.ppermute`) — and a microbatch emerges from the last
stage ``S-1`` ticks after it was injected at stage 0.

What makes it a *search* problem (the whole point of this framework): the
microbatches are split across ``n_chains`` independent virtual pipelines,
each with its own double-buffer-free serial tick chain

    inject_t -> compute_t -> rotate_t(post) -> await_t -> inject_{t+1} -> ...
                         \\-> collect_t   (once the pipe is full)

and the chains share nothing until the final interleave.  The solver's
order/lane freedom across chains is exactly the 1F1B-style interleaving
question: a good schedule hides chain A's ICI rotate behind chain B's stage
compute (the post/wait split of ``rotate`` is the reference's Isend/Wait
split, ops_mpi.hpp:17-146).  Hand-tuned PP runtimes bake one such schedule
in; here it is searched and benchmarked.

Numerics are checked against the host evaluation of the full stage stack per
microbatch (tests/test_pipeline.py; ``dryrun_multichip`` covers the sharded
path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import CompoundOp, DeviceOp
from tenzing_tpu.ops.comm_ops import AwaitTransfer, PermuteStart

AXIS = "pp"


@dataclass(frozen=True)
class PipelineArgs:
    n_pp: int  # pipeline stages == mesh shards
    n_microbatches: int = 4
    n_chains: int = 2  # interleaved virtual pipelines (the searched freedom)
    mb_size: int = 4  # rows per microbatch
    d_model: int = 8
    dtype: str = "float32"

    @property
    def chain_microbatches(self) -> int:
        assert self.n_microbatches % self.n_chains == 0
        return self.n_microbatches // self.n_chains

    @property
    def chain_ticks(self) -> int:
        return self.chain_microbatches + self.n_pp - 1


def _act(v: int, t: int) -> str:
    """Activation buffer chain ``v`` reads at tick ``t`` (ping-pong pair)."""
    return f"act_{v}_{t % 2}"


class Inject(DeviceOp):
    """Tick ``t`` < M_v: stage 0 swaps microbatch ``t``'s input into its
    activation slot (other stages keep what the rotate delivered)."""

    def __init__(self, name: str, v: int, t: int):
        super().__init__(name)
        self._v, self._t = v, t

    def reads(self):
        return [_act(self._v, self._t), f"X_{self._v}"]

    def writes(self):
        return [_act(self._v, self._t)]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp
        from jax import lax

        p = lax.axis_index(AXIS)
        x = bufs[f"X_{self._v}"][self._t]  # (B, d) replicated
        act = bufs[_act(self._v, self._t)]
        return {_act(self._v, self._t): jnp.where(p == 0, x, act)}


class StageCompute(DeviceOp):
    """Apply the resident stage's layer to the resident activation (every
    stage computes every tick — SPMD; ticks whose slot holds no live
    microbatch produce garbage that is never collected)."""

    def __init__(self, name: str, v: int, t: int, mb_rows: int = None):
        super().__init__(name)
        self._v, self._t = v, t
        self._mb = mb_rows  # per-shard activation rows, for chunk_counts

    def reads(self):
        return [_act(self._v, self._t), "W"]

    def writes(self):
        return [f"out_{self._v}"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp

        w = bufs["W"][0]  # this shard's stage weights (d, d)
        act = bufs[_act(self._v, self._t)]
        return {
            f"out_{self._v}": jax.nn.gelu(
                jnp.dot(act, w, preferred_element_type=jnp.float32)
            ).astype(act.dtype)
        }

    # -- op-chunking protocol (core/chunking.py, T3): the stage GEMM splits
    # over the activation rows into n partial GEMMs, each folding its row
    # slice into the outgoing buffer — so the stage send (the rotate post)
    # can launch against the tail partials instead of waiting for the
    # whole stage.
    def chunkable(self) -> bool:
        return True

    def chunk_counts(self) -> List[int]:
        # validity only: powers of two dividing the per-shard row count
        # (the mb_size rows every stage computes per tick); an op built
        # without mb_rows is not chunkable — never guess the extent
        from tenzing_tpu.core.chunking import pow2_counts

        return pow2_counts(self._mb)

    def split(self, n: int) -> List["StageComputePartial"]:
        rows = self._mb
        if rows is None:
            raise ValueError(
                f"{self.name()}: split() needs the mb_rows extent")
        if n < 1 or rows % n:
            raise ValueError(f"{rows} activation rows do not split {n} ways")
        return [StageComputePartial(f"{self.name()}.c{n}p{j}", self._v,
                                    self._t, j, n, mb_rows=rows)
                for j in range(n)]


class StageComputePartial(StageCompute):
    """Partial ``j`` of an ``n``-way row split of :class:`StageCompute`:
    the stage GEMM over its row slice of the resident activation, folded
    into ``out_v`` by an accumulating slice update (read-modify-write —
    the combine is the update chain, so the rotate post or another
    chain's compute interleaves between the partials)."""

    def __init__(self, name: str, v: int, t: int, part: int, n_parts: int,
                 mb_rows: int = None):
        super().__init__(name, v, t, mb_rows=mb_rows)
        self._part, self._n_parts = part, n_parts

    def chunkable(self) -> bool:
        return False  # a partial never re-splits

    def reads(self):
        return super().reads() + [f"out_{self._v}"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp
        from jax import lax

        w = bufs["W"][0]
        act = bufs[_act(self._v, self._t)]
        rows = act.shape[0]
        if rows % self._n_parts:
            # chunk validity was checked against the build-time mb_rows;
            # a sharded layout can hand this op fewer runtime rows — fail
            # at trace time rather than slice 0/partial rows silently
            raise ValueError(
                f"{self.name()}: {rows} runtime rows do not split "
                f"{self._n_parts} ways")
        lo = self._part * (rows // self._n_parts)
        xs = lax.dynamic_slice_in_dim(act, lo, rows // self._n_parts, 0)
        y = jax.nn.gelu(
            jnp.dot(xs, w, preferred_element_type=jnp.float32)
        ).astype(act.dtype)
        return {f"out_{self._v}": lax.dynamic_update_slice_in_dim(
            bufs[f"out_{self._v}"], y, lo, 0)}


class Collect(DeviceOp):
    """Tick ``t`` >= S-1: the last stage banks microbatch ``t-(S-1)``'s
    finished output into its slot of the chain's result buffer."""

    def __init__(self, name: str, v: int, t: int, args: PipelineArgs):
        super().__init__(name)
        self._v, self._t = v, t
        self._args = args

    def reads(self):
        return [f"out_{self._v}", f"Y_{self._v}"]

    def writes(self):
        return [f"Y_{self._v}"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp
        from jax import lax

        p = lax.axis_index(AXIS)
        m = self._t - (self._args.n_pp - 1)
        yv = bufs[f"Y_{self._v}"]  # (M_v, B, d) per shard
        upd = yv.at[m].set(bufs[f"out_{self._v}"])
        return {f"Y_{self._v}": jnp.where(p == self._args.n_pp - 1, upd, yv)}


class InterleaveY(DeviceOp):
    """Merge the chains' results back into microbatch order
    (chain ``v`` slot ``j`` holds microbatch ``v + j*n_chains``)."""

    def __init__(self, name: str, args: PipelineArgs):
        super().__init__(name)
        self._args = args

    def reads(self):
        return [f"Y_{v}" for v in range(self._args.n_chains)]

    def writes(self):
        return ["Y"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        chains = jnp.stack(
            [bufs[f"Y_{v}"] for v in range(self._args.n_chains)], axis=1
        )  # (M_v, V, B, d)
        mv, v, b, d = chains.shape
        return {"Y": chains.reshape(mv * v, b, d)}


def _forward_chain(
    g: Graph,
    v: int,
    a: PipelineArgs,
    make_compute,
    inject_prefix: str = "inject",
    rotate_prefix: str = "rotate",
    await_prefix: str = "await",
    with_collect: bool = True,
):
    """Wire one chain's forward tick chain — inject (while microbatches
    remain) -> compute -> rotate-post -> await -> next tick — shared by the
    forward-only Pipeline and the PipelineTrain compounds.  Returns
    (last compute op, last collect op or None)."""
    mv, ticks = a.chain_microbatches, a.chain_ticks
    prev_entry = None  # the op that delivers tick t's activation
    prev_collect = None
    comp = None
    for t in range(ticks):
        comp = make_compute(v, t)
        if t < mv:
            inj = Inject(f"{inject_prefix}_{v}_{t}", v, t)
            if prev_entry is None:
                g.start_then(inj)
            else:
                g.then(prev_entry, inj)
            g.then(inj, comp)
        else:
            g.then(prev_entry, comp)
        if prev_collect is not None:
            # WAR: compute_t overwrites out_v that collect_{t-1} read
            g.then(prev_collect, comp)
        if t < ticks - 1:
            post = PermuteStart(
                f"{rotate_prefix}_{v}_{t}", f"out_{v}", _act(v, t + 1), AXIS
            )
            await_ = AwaitTransfer(f"{await_prefix}_{v}_{t}", _act(v, t + 1))
            g.then(comp, post)
            g.then(post, await_)
            prev_entry = await_
        if with_collect and t >= a.n_pp - 1:
            col = Collect(f"collect_{v}_{t}", v, t, a)
            g.then(comp, col)
            if prev_collect is not None:
                g.then(prev_collect, col)  # RAW: Y_v chain
            prev_collect = col
    return comp, prev_collect


def stage_chunk_menu(args: PipelineArgs, relax: bool = False):
    """(pruned counts, {count: est hidden µs}) for one stage-tick GEMM —
    the roofline sketch constraint (bench/roofline.py::prune_chunkings).
    The neighboring transfer is the stage send (the ICI rotate of the
    tick's output rows); ``relax=True`` (tests / toy shapes) keeps every
    structurally valid count."""
    from tenzing_tpu.bench import roofline

    bpe = np.dtype(args.dtype).itemsize
    b, d = args.mb_size, args.d_model
    act = float(b * d * bpe)  # one shard's activation rows
    cost = roofline.Cost(flops=2.0 * b * d * d,
                         hbm_bytes=2.0 * act + float(d * d * bpe))
    return roofline.chunk_menu(
        StageCompute("probe", 0, 0, mb_rows=args.mb_size).chunk_counts(),
        cost, comm_us=act / (roofline.V5E_XFER_GBS * 1e9) * 1e6,
        combine_bytes=2.0 * act, relax=relax)


class Pipeline(CompoundOp):
    """The whole pipelined forward as one compound op: ``n_chains``
    independent tick chains, each with the post/wait-split rotate, joined by
    the final interleave.

    ``chunk=True`` wraps each tick's stage GEMM in a
    :class:`~tenzing_tpu.core.chunking.ChunkChoice` so the solvers search
    T3-style row splits whose tail partials the rotate post overlaps
    (core/chunking.py; :func:`stage_chunk_menu` prunes the counts through
    the roofline — ``chunk_relax`` skips the pruning, the tests mode)."""

    def __init__(self, args: PipelineArgs, name: str = "pipeline",
                 chunk: bool = False, chunk_relax: bool = False):
        super().__init__(name)
        self._args = args
        self._chunk = chunk
        self._chunk_relax = chunk_relax

    def args(self) -> PipelineArgs:
        return self._args

    def graph(self) -> Graph:
        a = self._args
        g = Graph()
        counts, est = ((), None)
        if self._chunk:
            counts, est = stage_chunk_menu(a, relax=self._chunk_relax)

        def mk(vv, tt):
            step = StageCompute(f"compute_{vv}_{tt}", vv, tt,
                                mb_rows=a.mb_size)
            if any(int(n) > 1 for n in counts):
                from tenzing_tpu.core.chunking import (
                    ChunkChoice,
                    chunk_variants,
                )

                return ChunkChoice(step, chunk_variants(step, counts, est))
            return step

        inter = InterleaveY("pp_interleave", a)
        for v in range(a.n_chains):
            _comp, last_collect = _forward_chain(g, v, a, mk)
            g.then(last_collect, inter)
        g.then_finish(inter)
        return g


class TrainForward(DeviceOp):
    """Forward stage compute that also stashes this microbatch's input
    activation and pre-activation for the backward pass (the per-device
    activation memory a pipeline training step carries)."""

    def __init__(self, name: str, v: int, t: int, args: PipelineArgs):
        super().__init__(name)
        self._v, self._t, self._args = v, t, args

    def reads(self):
        return [_act(self._v, self._t), "W",
                f"stash_a_{self._v}", f"stash_z_{self._v}"]

    def writes(self):
        return [f"out_{self._v}", f"stash_a_{self._v}", f"stash_z_{self._v}"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp
        from jax import lax

        a = self._args
        p = lax.axis_index(AXIS)
        m = self._t - p  # this shard's live microbatch (may be out of range)
        valid = (m >= 0) & (m < a.chain_microbatches)
        idx = jnp.clip(m, 0, a.chain_microbatches - 1)
        act = bufs[_act(self._v, self._t)]  # (B, d)
        z = jnp.dot(act, bufs["W"][0], preferred_element_type=jnp.float32)
        z = z.astype(act.dtype)
        out = jax.nn.gelu(z)

        def stash(old, val):
            upd = lax.dynamic_update_slice_in_dim(old, val[None], idx, 0)
            return jnp.where(valid, upd, old)

        return {
            f"out_{self._v}": out,
            f"stash_a_{self._v}": stash(bufs[f"stash_a_{self._v}"], act),
            f"stash_z_{self._v}": stash(bufs[f"stash_z_{self._v}"], z),
        }


class BwdInject(DeviceOp):
    """Backward tick ``u`` < M_v: the last stage seeds microbatch ``u``'s
    gradient g = y - target (L2 loss; y recomputed from the stashed
    pre-activation).  Other stages keep what the reverse rotate delivered."""

    def __init__(self, name: str, v: int, u: int, args: PipelineArgs):
        super().__init__(name)
        self._v, self._u, self._args = v, u, args

    def reads(self):
        return [_act(self._v, self._u) + "g", f"stash_z_{self._v}",
                f"target_{self._v}"]

    def writes(self):
        return [_act(self._v, self._u) + "g"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp
        from jax import lax

        a = self._args
        p = lax.axis_index(AXIS)
        y = jax.nn.gelu(bufs[f"stash_z_{self._v}"][self._u])
        seed = y - bufs[f"target_{self._v}"][self._u]
        g = bufs[_act(self._v, self._u) + "g"]
        return {_act(self._v, self._u) + "g": jnp.where(p == a.n_pp - 1, seed, g)}


class BwdCompute(DeviceOp):
    """One backward stage step: dz = g * gelu'(z), dW += a^T dz (masked to
    ticks where this shard holds a live microbatch), and the outgoing
    gradient dz W^T for the reverse rotate."""

    def __init__(self, name: str, v: int, u: int, args: PipelineArgs):
        super().__init__(name)
        self._v, self._u, self._args = v, u, args

    def reads(self):
        return [_act(self._v, self._u) + "g", "W", f"stash_a_{self._v}",
                f"stash_z_{self._v}", f"dW_{self._v}"]

    def writes(self):
        return [f"gout_{self._v}", f"dW_{self._v}"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp
        from jax import lax

        a = self._args
        p = lax.axis_index(AXIS)
        m = self._u - (a.n_pp - 1 - p)
        valid = (m >= 0) & (m < a.chain_microbatches)
        idx = jnp.clip(m, 0, a.chain_microbatches - 1)
        g = bufs[_act(self._v, self._u) + "g"]  # (B, d) incoming dL/dout
        z = bufs[f"stash_z_{self._v}"][idx]
        a_in = bufs[f"stash_a_{self._v}"][idx]
        _, vjp = jax.vjp(jax.nn.gelu, z)
        dz = vjp(g)[0]
        w = bufs["W"][0]
        dw = jnp.dot(a_in.T, dz, preferred_element_type=jnp.float32)
        dw = jnp.where(valid, dw.astype(g.dtype), jnp.zeros_like(dw, g.dtype))
        gout = jnp.dot(dz, w.T, preferred_element_type=jnp.float32)
        return {
            f"gout_{self._v}": gout.astype(g.dtype),
            f"dW_{self._v}": bufs[f"dW_{self._v}"] + dw[None],
        }


class AddGrads(DeviceOp):
    """Sum the per-chain weight-gradient accumulators (per-chain buffers keep
    the chains' backward passes DAG-independent — a shared accumulator would
    serialize them through SSA)."""

    def __init__(self, name: str, args: PipelineArgs):
        super().__init__(name)
        self._args = args

    def reads(self):
        return [f"dW_{v}" for v in range(self._args.n_chains)]

    def writes(self):
        return ["dW"]

    def apply(self, bufs, ctx):
        out = bufs["dW_0"]
        for v in range(1, self._args.n_chains):
            out = out + bufs[f"dW_{v}"]
        return {"dW": out}


class PipelineTrain(CompoundOp):
    """A FULL pipeline-parallel training step as one compound op: per chain,
    the forward tick chain (with activation stashes), then the reverse-ring
    backward chain seeding gradients at the last stage and accumulating dW
    per stage; chains share nothing until the final gradient sum, so the
    solver's order/lane freedom is the interleaved-1F1B question — chain A's
    backward overlapping chain B's forward, with every rotate a post/wait
    split the search places."""

    def __init__(self, args: PipelineArgs, name: str = "pipeline_train"):
        super().__init__(name)
        self._args = args

    def args(self) -> PipelineArgs:
        return self._args

    def graph(self) -> Graph:
        a = self._args
        g = Graph()
        add = AddGrads("pt_addgrads", a)
        for v in range(a.n_chains):
            mv, ticks = a.chain_microbatches, a.chain_ticks
            last_fwd, _ = _forward_chain(
                g, v, a,
                lambda vv, tt: TrainForward(f"fcompute_{vv}_{tt}", vv, tt, a),
                inject_prefix="finject", rotate_prefix="frotate",
                await_prefix="fawait", with_collect=False,
            )
            # backward: strictly after the chain's forward (the stashes are
            # complete); other chains' forwards are free to overlap
            prev_entry = last_fwd
            for u in range(ticks):
                bcomp = BwdCompute(f"bcompute_{v}_{u}", v, u, a)
                if u < mv:
                    binj = BwdInject(f"binject_{v}_{u}", v, u, a)
                    g.then(prev_entry, binj)
                    g.then(binj, bcomp)
                else:
                    g.then(prev_entry, bcomp)
                if u < ticks - 1:
                    post = PermuteStart(
                        f"brotate_{v}_{u}", f"gout_{v}",
                        _act(v, u + 1) + "g", AXIS, shift=-1,
                    )
                    await_ = AwaitTransfer(
                        f"bawait_{v}_{u}", _act(v, u + 1) + "g"
                    )
                    g.then(bcomp, post)
                    g.then(post, await_)
                    prev_entry = await_
            g.then(bcomp, add)
        g.then_finish(add)
        return g


def make_train_buffers(
    args: PipelineArgs, seed: int = 0
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """(buffers, partition specs, expected dW) for the training step on a
    1-D ``("pp",)`` mesh.  Expected dW is the host float64 backward of the
    L2 loss 0.5*sum((stack(x_m) - target_m)^2) over every microbatch,
    stacked per stage (shard p's block is stage p's gradient)."""
    from jax.sharding import PartitionSpec as P

    from tenzing_tpu.utils.numeric import gelu_tanh, gelu_tanh_grad

    rng = np.random.default_rng(seed)
    s, m, v = args.n_pp, args.n_microbatches, args.n_chains
    b, d = args.mb_size, args.d_model
    mv = args.chain_microbatches
    dt = np.dtype(args.dtype)
    x = rng.standard_normal((m, b, d)).astype(dt)
    w = rng.standard_normal((s, d, d)).astype(dt) / np.sqrt(d)
    target = rng.standard_normal((m, b, d)).astype(dt)

    # host float64 forward + backward
    w64 = w.astype(np.float64)
    dw = np.zeros((s, d, d), np.float64)
    for mb in range(m):
        acts, zs = [x[mb].astype(np.float64)], []
        for st in range(s):
            zs.append(acts[-1] @ w64[st])
            acts.append(gelu_tanh(zs[-1]))
        g = acts[-1] - target[mb].astype(np.float64)
        for st in reversed(range(s)):
            dz = g * gelu_tanh_grad(zs[st])
            dw[st] += acts[st].T @ dz
            g = dz @ w64[st].T

    bufs: Dict[str, np.ndarray] = {
        "W": w,
        "dW": np.zeros((s, d, d), dt),
    }
    specs: Dict[str, object] = {
        "W": P(AXIS, None, None),
        "dW": P(AXIS, None, None),
    }
    for c in range(v):
        bufs[f"X_{c}"] = x[c::v]
        specs[f"X_{c}"] = P(None, None, None)
        bufs[f"target_{c}"] = target[c::v]
        specs[f"target_{c}"] = P(None, None, None)
        for par in (0, 1):
            bufs[f"act_{c}_{par}"] = np.zeros((s * b, d), dt)
            specs[f"act_{c}_{par}"] = P(AXIS, None)
            bufs[f"act_{c}_{par}g"] = np.zeros((s * b, d), dt)
            specs[f"act_{c}_{par}g"] = P(AXIS, None)
        bufs[f"out_{c}"] = np.zeros((s * b, d), dt)
        specs[f"out_{c}"] = P(AXIS, None)
        bufs[f"gout_{c}"] = np.zeros((s * b, d), dt)
        specs[f"gout_{c}"] = P(AXIS, None)
        bufs[f"stash_a_{c}"] = np.zeros((s * mv, b, d), dt)
        specs[f"stash_a_{c}"] = P(AXIS, None, None)
        bufs[f"stash_z_{c}"] = np.zeros((s * mv, b, d), dt)
        specs[f"stash_z_{c}"] = P(AXIS, None, None)
        bufs[f"dW_{c}"] = np.zeros((s, d, d), dt)
        specs[f"dW_{c}"] = P(AXIS, None, None)
    return bufs, specs, dw.astype(dt)  # expected in workload dtype (ADVICE r2)


def make_pipeline_buffers(
    args: PipelineArgs, seed: int = 0
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """(buffers, partition specs, expected Y) for the PP forward on a 1-D
    ``("pp",)`` mesh.  Expected Y is zero except the last stage's shard block,
    where microbatch ``m``'s slot holds the full stage stack applied to its
    input (computed densely on the host in float64)."""
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(seed)
    s, m, v = args.n_pp, args.n_microbatches, args.n_chains
    b, d = args.mb_size, args.d_model
    mv = args.chain_microbatches
    dt = np.dtype(args.dtype)
    x = rng.standard_normal((m, b, d)).astype(dt)
    w = rng.standard_normal((s, d, d)).astype(dt) / np.sqrt(d)

    from tenzing_tpu.utils.numeric import gelu_tanh

    y = x.astype(np.float64)
    for st in range(s):
        y = gelu_tanh(y @ w[st].astype(np.float64))

    bufs: Dict[str, np.ndarray] = {"W": w, "Y": np.zeros((s * m, b, d), dt)}
    specs: Dict[str, object] = {"W": P(AXIS, None, None), "Y": P(AXIS, None, None)}
    for c in range(v):
        bufs[f"X_{c}"] = x[c::v]  # (M_v, B, d), chain c's microbatches
        specs[f"X_{c}"] = P(None, None, None)  # replicated: stage 0 reads it
        for par in (0, 1):
            bufs[f"act_{c}_{par}"] = np.zeros((s * b, d), dt)
            specs[f"act_{c}_{par}"] = P(AXIS, None)
        bufs[f"out_{c}"] = np.zeros((s * b, d), dt)
        specs[f"out_{c}"] = P(AXIS, None)
        bufs[f"Y_{c}"] = np.zeros((s * mv, b, d), dt)
        specs[f"Y_{c}"] = P(AXIS, None, None)

    want = np.zeros((s * m, b, d), dt)
    want[(s - 1) * m : s * m] = y.astype(dt)  # last stage's block
    return bufs, specs, want
