"""Pipeline parallelism: microbatch pipelining as a searchable op DAG.

The reference has no model layers (SURVEY.md §2.5: TP/PP/EP absent; the op-DAG
must nonetheless *express* such programs).  This model is the
pipeline-parallel (PP) member of that family: stage ``s`` of an ``S``-stage
network lives on mesh-axis-``pp`` shard ``s``, and activations flow stage to
stage over ICI.  In SPMD form every device runs the same per-tick program —
compute the resident stage on the resident microbatch, then shift activations
one hop forward (`lax.ppermute`) — and a microbatch emerges from the last
stage ``S-1`` ticks after it was injected at stage 0.

What makes it a *search* problem (the whole point of this framework): the
microbatches are split across ``n_chains`` independent virtual pipelines,
each with its own double-buffer-free serial tick chain

    inject_t -> compute_t -> rotate_t(post) -> await_t -> inject_{t+1} -> ...
                         \\-> collect_t   (once the pipe is full)

and the chains share nothing until the final interleave.  The solver's
order/lane freedom across chains is exactly the 1F1B-style interleaving
question: a good schedule hides chain A's ICI rotate behind chain B's stage
compute (the post/wait split of ``rotate`` is the reference's Isend/Wait
split, ops_mpi.hpp:17-146).  Hand-tuned PP runtimes bake one such schedule
in; here it is searched and benchmarked.

Numerics are checked against the host evaluation of the full stage stack per
microbatch (tests/test_pipeline.py; ``dryrun_multichip`` covers the sharded
path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import CompoundOp, DeviceOp
from tenzing_tpu.ops.comm_ops import AwaitTransfer, PermuteStart

AXIS = "pp"


@dataclass(frozen=True)
class PipelineArgs:
    n_pp: int  # pipeline stages == mesh shards
    n_microbatches: int = 4
    n_chains: int = 2  # interleaved virtual pipelines (the searched freedom)
    mb_size: int = 4  # rows per microbatch
    d_model: int = 8
    dtype: str = "float32"

    @property
    def chain_microbatches(self) -> int:
        assert self.n_microbatches % self.n_chains == 0
        return self.n_microbatches // self.n_chains

    @property
    def chain_ticks(self) -> int:
        return self.chain_microbatches + self.n_pp - 1


def _act(v: int, t: int) -> str:
    """Activation buffer chain ``v`` reads at tick ``t`` (ping-pong pair)."""
    return f"act_{v}_{t % 2}"


class Inject(DeviceOp):
    """Tick ``t`` < M_v: stage 0 swaps microbatch ``t``'s input into its
    activation slot (other stages keep what the rotate delivered)."""

    def __init__(self, name: str, v: int, t: int):
        super().__init__(name)
        self._v, self._t = v, t

    def reads(self):
        return [_act(self._v, self._t), f"X_{self._v}"]

    def writes(self):
        return [_act(self._v, self._t)]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp
        from jax import lax

        p = lax.axis_index(AXIS)
        x = bufs[f"X_{self._v}"][self._t]  # (B, d) replicated
        act = bufs[_act(self._v, self._t)]
        return {_act(self._v, self._t): jnp.where(p == 0, x, act)}


class StageCompute(DeviceOp):
    """Apply the resident stage's layer to the resident activation (every
    stage computes every tick — SPMD; ticks whose slot holds no live
    microbatch produce garbage that is never collected)."""

    def __init__(self, name: str, v: int, t: int):
        super().__init__(name)
        self._v, self._t = v, t

    def reads(self):
        return [_act(self._v, self._t), "W"]

    def writes(self):
        return [f"out_{self._v}"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp

        w = bufs["W"][0]  # this shard's stage weights (d, d)
        act = bufs[_act(self._v, self._t)]
        return {
            f"out_{self._v}": jax.nn.gelu(
                jnp.dot(act, w, preferred_element_type=jnp.float32)
            ).astype(act.dtype)
        }


class Collect(DeviceOp):
    """Tick ``t`` >= S-1: the last stage banks microbatch ``t-(S-1)``'s
    finished output into its slot of the chain's result buffer."""

    def __init__(self, name: str, v: int, t: int, args: PipelineArgs):
        super().__init__(name)
        self._v, self._t = v, t
        self._args = args

    def reads(self):
        return [f"out_{self._v}", f"Y_{self._v}"]

    def writes(self):
        return [f"Y_{self._v}"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp
        from jax import lax

        p = lax.axis_index(AXIS)
        m = self._t - (self._args.n_pp - 1)
        yv = bufs[f"Y_{self._v}"]  # (M_v, B, d) per shard
        upd = yv.at[m].set(bufs[f"out_{self._v}"])
        return {f"Y_{self._v}": jnp.where(p == self._args.n_pp - 1, upd, yv)}


class InterleaveY(DeviceOp):
    """Merge the chains' results back into microbatch order
    (chain ``v`` slot ``j`` holds microbatch ``v + j*n_chains``)."""

    def __init__(self, name: str, args: PipelineArgs):
        super().__init__(name)
        self._args = args

    def reads(self):
        return [f"Y_{v}" for v in range(self._args.n_chains)]

    def writes(self):
        return ["Y"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        chains = jnp.stack(
            [bufs[f"Y_{v}"] for v in range(self._args.n_chains)], axis=1
        )  # (M_v, V, B, d)
        mv, v, b, d = chains.shape
        return {"Y": chains.reshape(mv * v, b, d)}


class Pipeline(CompoundOp):
    """The whole pipelined forward as one compound op: ``n_chains``
    independent tick chains, each with the post/wait-split rotate, joined by
    the final interleave."""

    def __init__(self, args: PipelineArgs, name: str = "pipeline"):
        super().__init__(name)
        self._args = args

    def args(self) -> PipelineArgs:
        return self._args

    def graph(self) -> Graph:
        a = self._args
        g = Graph()
        inter = InterleaveY("pp_interleave", a)
        for v in range(a.n_chains):
            mv, ticks = a.chain_microbatches, a.chain_ticks
            prev_entry = None  # the op that delivers tick t's activation
            prev_collect = None
            for t in range(ticks):
                comp = StageCompute(f"compute_{v}_{t}", v, t)
                if t < mv:
                    inj = Inject(f"inject_{v}_{t}", v, t)
                    if prev_entry is None:
                        g.start_then(inj)
                    else:
                        g.then(prev_entry, inj)
                    g.then(inj, comp)
                else:
                    g.then(prev_entry, comp)
                if prev_collect is not None:
                    # WAR: compute_t overwrites out_v that collect_{t-1} read
                    g.then(prev_collect, comp)
                if t < ticks - 1:
                    post = PermuteStart(
                        f"rotate_{v}_{t}", f"out_{v}", _act(v, t + 1), AXIS
                    )
                    await_ = AwaitTransfer(f"await_{v}_{t}", _act(v, t + 1))
                    g.then(comp, post)
                    g.then(post, await_)
                    prev_entry = await_
                if t >= a.n_pp - 1:
                    col = Collect(f"collect_{v}_{t}", v, t, a)
                    g.then(comp, col)
                    if prev_collect is not None:
                        g.then(prev_collect, col)  # RAW: Y_v chain
                    prev_collect = col
            g.then(prev_collect, inter)
        g.then_finish(inter)
        return g


def make_pipeline_buffers(
    args: PipelineArgs, seed: int = 0
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """(buffers, partition specs, expected Y) for the PP forward on a 1-D
    ``("pp",)`` mesh.  Expected Y is zero except the last stage's shard block,
    where microbatch ``m``'s slot holds the full stage stack applied to its
    input (computed densely on the host in float64)."""
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(seed)
    s, m, v = args.n_pp, args.n_microbatches, args.n_chains
    b, d = args.mb_size, args.d_model
    mv = args.chain_microbatches
    dt = np.dtype(args.dtype)
    x = rng.standard_normal((m, b, d)).astype(dt)
    w = rng.standard_normal((s, d, d)).astype(dt) / np.sqrt(d)

    from tenzing_tpu.utils.numeric import gelu_tanh

    y = x.astype(np.float64)
    for st in range(s):
        y = gelu_tanh(y @ w[st].astype(np.float64))

    bufs: Dict[str, np.ndarray] = {"W": w, "Y": np.zeros((s * m, b, d), dt)}
    specs: Dict[str, object] = {"W": P(AXIS, None, None), "Y": P(AXIS, None, None)}
    for c in range(v):
        bufs[f"X_{c}"] = x[c::v]  # (M_v, B, d), chain c's microbatches
        specs[f"X_{c}"] = P(None, None, None)  # replicated: stage 0 reads it
        for par in (0, 1):
            bufs[f"act_{c}_{par}"] = np.zeros((s * b, d), dt)
            specs[f"act_{c}_{par}"] = P(AXIS, None)
        bufs[f"out_{c}"] = np.zeros((s * b, d), dt)
        specs[f"out_{c}"] = P(AXIS, None)
        bufs[f"Y_{c}"] = np.zeros((s * mv, b, d), dt)
        specs[f"Y_{c}"] = P(AXIS, None, None)

    want = np.zeros((s * m, b, d), np.float32)
    want[(s - 1) * m : s * m] = y.astype(np.float32)  # last stage's block
    return bufs, specs, want
