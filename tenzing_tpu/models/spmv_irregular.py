"""Distributed SpMV with a general irregular remote-column exchange.

Parity target: reference ``RowPartSpmv`` setup for *arbitrary* sparsity —
the root splits local vs remote columns and negotiates per-rank send/recv
column lists with an Isend/Probe/Recv handshake
(row_part_spmv.cuh:259-423), then the schedule overlaps per-neighbor
PostSend/PostRecv/WaitRecv comm ops (ops_spmv.cuh:217-304) with the local
SpMV.  ``models/spmv_dist.py`` covers only band matrices whose remote columns
live in adjacent shards; this module handles any sparsity pattern
(VERDICT r1 item 3).

TPU-native redesign.  There is no ragged all-to-all on ICI, so the negotiated
exchange is realized as **per-distance permute steps** over the ``sp`` ring:

* **Setup (host-side numpy — the negotiation analog).**  For every requester
  shard ``p`` and cyclic distance ``d``, the send list ``S_d[p]`` is the
  sorted set of global columns that ``p``'s rows reference and shard
  ``(p-d) % n_sp`` owns.  Because setup is host-global (the driver holds the
  whole matrix, like the reference root), the Isend/Probe/Recv handshake
  collapses to array arithmetic; what is preserved is its *product*: exact
  per-pair column lists, gather index slabs, and a remote-column renumbering
  into a contiguous halo buffer (split_mat.hpp:22-136).
* **Data plane (schedulable ops).**  Distances with empty lists everywhere are
  dropped; for each retained ``d``:
  ``gather_d`` (DeviceOp, lane-searched — the reference Scatter,
  ops_spmv.cuh:194-215) packs the requested x entries into a width-padded
  send buffer; ``permute_d`` (PermuteStart — the post half of
  Isend/Irecv) shifts it ``d`` hops over ICI; ``await_d`` (AwaitTransfer —
  the reference WaitRecv) joins completion into the host chain.  The solver
  schedules compute between every post and its await.
* A band matrix fed through this path naturally degenerates to the two
  adjacent-distance steps of ``spmv_dist.py`` — the static-neighbor case is
  just the irregular machinery with ``steps = [1, n_sp-1]``.

Graph shape (reference SpMV compound, ops_spmv.cuh:306-436):

    start -> spmv_local ----------------------------> y_add -> finish
    start -> gather_d -> permute_d -> await_d -+
                          (one chain per d)    +-> spmv_halo -> y_add
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import ChoiceOp, CompoundOp, DeviceOp, OpBase
from tenzing_tpu.models.spmv import CooMat, CsrMat
from tenzing_tpu.models.spmv_dist import AddShards, SpMVLocalShard
from tenzing_tpu.ops.comm_ops import AllToAllStart, AwaitTransfer, PermuteStart


@dataclass
class ExchangePlan:
    """The negotiated exchange: everything the reference's setup handshake
    produces (row_part_spmv.cuh:259-423), computed host-side.

    ``send_lists[d][p]`` — sorted global columns shard ``p`` receives from
    shard ``(p-d) % n_sp`` at distance ``d`` (the reference's recv list; the
    sender's send list is the same array read from the other side).
    """

    n_sp: int
    block: int
    steps: List[int] = field(default_factory=list)
    widths: Dict[int, int] = field(default_factory=dict)
    send_lists: Dict[int, List[np.ndarray]] = field(default_factory=dict)
    offsets: Dict[int, int] = field(default_factory=dict)
    halo_width: int = 0

    def owner(self, col: int) -> int:
        return min(int(col) // self.block, self.n_sp - 1)

    def halo_slot(self, p: int, col: int) -> int:
        """Position of global column ``col`` in requester ``p``'s halo buffer."""
        q = self.owner(col)
        d = (p - q) % self.n_sp
        lst = self.send_lists[d][p]
        j = int(np.searchsorted(lst, col))
        assert j < len(lst) and lst[j] == col, (p, col, d)
        return self.offsets[d] + j


def negotiate_exchange(a: CsrMat, n_sp: int) -> ExchangePlan:
    """Compute per-(requester, distance) column lists for arbitrary sparsity —
    the host-side product of the reference's send/recv negotiation
    (row_part_spmv.cuh:259-423 Isend/Probe/Recv handshake)."""
    assert a.m % n_sp == 0, "rows must divide evenly across sp shards"
    block = a.m // n_sp
    plan = ExchangePlan(n_sp=n_sp, block=block)
    needed: List[List[np.ndarray]] = [[np.array([], dtype=np.int64)] * n_sp
                                      for _ in range(n_sp)]  # [d][p]
    for p in range(n_sp):
        lo, hi = p * block, (p + 1) * block
        rows = a.retain_rows(lo, hi)
        cols = np.unique(rows.cols.astype(np.int64))
        remote = cols[(cols < lo) | (cols >= hi)]
        owners = np.minimum(remote // block, n_sp - 1)
        for q in np.unique(owners):
            d = (p - int(q)) % n_sp
            needed[d][p] = remote[owners == q]  # sorted (np.unique order)
    off = 0
    for d in range(1, n_sp):
        w = max((len(needed[d][p]) for p in range(n_sp)), default=0)
        if w == 0:
            continue
        plan.steps.append(d)
        plan.widths[d] = w
        plan.send_lists[d] = needed[d]
        plan.offsets[d] = off
        off += w
    plan.halo_width = max(1, off)
    return plan


class GatherSend(DeviceOp):
    """Pack the x entries a distance-``d`` receiver asked for into the padded
    send buffer (reference Scatter, ops_spmv.cuh:194-215: gather owned x into
    the send buf the Isend ships)."""

    def __init__(self, name: str, d: int):
        super().__init__(name)
        self._d = d

    def reads(self):
        return ["X", f"send_idx_{self._d}"]

    def writes(self):
        return [f"send_{self._d}"]

    def apply(self, bufs, ctx):
        idx = bufs[f"send_idx_{self._d}"][0]  # (w_d,) this shard's gather list
        return {f"send_{self._d}": bufs["X"][:, idx]}


class SpMVHaloIrregular(DeviceOp):
    """Y_rem against the concatenated received halo segments (reference yr
    SpMVKernel over the renumbered remote matrix, ops_spmv.cuh:398-401)."""

    def __init__(self, name: str, steps: List[int]):
        super().__init__(name)
        self._steps = list(steps)

    def reads(self):
        return [f"recv_{d}" for d in self._steps] + ["A_rem_vals", "A_rem_cols"]

    def writes(self):
        return ["Y_rem"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        halo = jnp.concatenate([bufs[f"recv_{d}"] for d in self._steps], axis=1)
        rv, rc = bufs["A_rem_vals"], bufs["A_rem_cols"]
        return {"Y_rem": jnp.einsum("rw,brw->br", rv, halo[:, rc])}


class GatherAll(DeviceOp):
    """Pack every receiver's requested entries into the (n_sp, w_max) send
    matrix the all-to-all ships (the Scatter of the Ialltoallv path)."""

    def reads(self):
        return ["X", "send_idx_all"]

    def writes(self):
        return ["send_all"]

    def apply(self, bufs, ctx):
        idx = bufs["send_idx_all"][0]  # (n_sp, w_max) this shard's lists
        return {"send_all": bufs["X"][:, idx]}


class UnpackA2A(DeviceOp):
    """Split the all-to-all result back into the per-distance recv buffers, so
    downstream ops are identical to the permute path (same halo layout)."""

    def __init__(self, name: str, steps: List[int], widths: Dict[int, int]):
        super().__init__(name)
        self._steps = list(steps)
        self._widths = dict(widths)

    def reads(self):
        return ["recv_a2a"]

    def writes(self):
        return [f"recv_{d}" for d in self._steps]

    def apply(self, bufs, ctx):
        import jax
        from jax import lax

        out = bufs["recv_a2a"]  # (b, n_sp, w_max): row q = sent by shard q
        p = lax.axis_index("sp")
        n = lax.axis_size("sp")
        res = {}
        for d in self._steps:
            row = lax.dynamic_index_in_dim(out, (p - d) % n, axis=1, keepdims=False)
            res[f"recv_{d}"] = row[:, : self._widths[d]]
        return res


def _add_distance_chain(g: Graph, d: int, preds: List, succs: List) -> None:
    """Wire one gather -> permute-start -> await chain for distance ``d``
    between ``preds`` and ``succs`` (shared by the plain and choice paths)."""
    gather = GatherSend(f"gather_{d}", d)
    post = PermuteStart(f"permute_{d}", f"send_{d}", f"recv_{d}", axis="sp", shift=d)
    await_ = AwaitTransfer(f"await_{d}", f"recv_{d}")
    for p in preds:
        g.then(p, gather)
    g.then(gather, post)
    g.then(post, await_)
    for s in succs:
        g.then(await_, s)


class PermuteExchange(CompoundOp):
    """Exchange via one gather -> permute-start -> await chain per retained
    cyclic distance (per-neighbor Isend/Irecv shape)."""

    def __init__(self, steps: List[int], name: str = "exchange.permute"):
        super().__init__(name)
        self._steps = list(steps)

    def graph(self) -> Graph:
        g = Graph()
        for d in self._steps:
            _add_distance_chain(g, d, [g.start()], [g.finish()])
        return g


class A2AExchange(CompoundOp):
    """Exchange via one padded all-to-all (the reference Ialltoallv,
    ops_mpi.hpp:82-119): gather-all -> a2a-start -> await -> unpack."""

    def __init__(self, steps: List[int], widths: Dict[int, int],
                 name: str = "exchange.a2a"):
        super().__init__(name)
        self._steps = list(steps)
        self._widths = dict(widths)

    def graph(self) -> Graph:
        g = Graph()
        gather = GatherAll("gather_all")
        post = AllToAllStart("a2a_post", "send_all", "recv_a2a", axis="sp")
        await_ = AwaitTransfer("a2a_await", "recv_a2a")
        unpack = UnpackA2A("a2a_unpack", self._steps, self._widths)
        g.start_then(gather)
        g.then(gather, post)
        g.then(post, await_)
        g.then(await_, unpack)
        g.then_finish(unpack)
        return g


class RdmaExchange(CompoundOp):
    """Exchange via one gather -> remote-DMA-start -> await chain per retained
    cyclic distance: each shard DMA-writes its negotiated column block into
    its ``+d`` neighbor's receive buffer (ops/rdma.py ``RdmaShiftStart``) —
    the per-neighbor computed-offset DMA that is the TPU analog of the
    reference's negotiated Isend/Irecv exchange (row_part_spmv.cuh:259-423),
    vs the compiler-scheduled collective of :class:`PermuteExchange`."""

    def __init__(self, steps: List[int], name: str = "exchange.rdma"):
        super().__init__(name)
        self._steps = list(steps)

    def graph(self) -> Graph:
        from tenzing_tpu.ops.rdma import RdmaShiftStart

        g = Graph()
        for d in self._steps:
            gather = GatherSend(f"gather_{d}", d)
            post = RdmaShiftStart(
                f"rdma_{d}", f"send_{d}", f"recv_{d}", axis="sp", shift=d,
                collective_id=d,
            )
            await_ = AwaitTransfer(f"await_{d}", f"recv_{d}")
            g.start_then(gather)
            g.then(gather, post)
            g.then(post, await_)
            g.then_finish(await_)
        return g


class ExchangeChoice(ChoiceOp):
    """The exchange-implementation menu: per-distance permutes vs one padded
    all-to-all vs per-distance remote DMA — which wins depends on how many
    distances are live and how ragged the lists are, so it is the solver's
    question."""

    def __init__(self, steps: List[int], widths: Dict[int, int],
                 name: str = "exchange"):
        super().__init__(name)
        self._steps = list(steps)
        self._widths = dict(widths)

    def choices(self) -> List[OpBase]:
        return [
            PermuteExchange(self._steps),
            A2AExchange(self._steps, self._widths),
            RdmaExchange(self._steps),
        ]


class IrregularSpMV(CompoundOp):
    """The whole irregular-exchange SpMV iteration as one compound op.
    ``steps``/``widths`` must match the plan the buffers were built with.
    With ``impl_choice=True`` the exchange realization becomes a ChoiceOp
    (requires buffers built with ``impl_choice=True`` too)."""

    def __init__(self, steps: List[int], name: str = "irr_spmv",
                 widths: Optional[Dict[int, int]] = None,
                 impl_choice: bool = False):
        super().__init__(name)
        self._steps = list(steps)
        self._widths = dict(widths) if widths else {}
        self._impl_choice = impl_choice
        if impl_choice and steps and not self._widths:
            raise ValueError(
                "impl_choice=True needs widths=plan.widths (the a2a unpack "
                "slices each distance's segment by its negotiated width)"
            )

    def graph(self) -> Graph:
        g = Graph()
        loc = SpMVLocalShard("spmv_local")
        add = AddShards("y_add")
        if not self._steps:  # block-diagonal matrix: nothing to exchange
            g.start_then(loc)
            g.then(loc, add)  # Y_rem stays the declared zero buffer
            g.then_finish(add)
            return g
        halo = SpMVHaloIrregular("spmv_halo", self._steps)
        g.start_then(loc)
        if self._impl_choice:
            exch = ExchangeChoice(self._steps, self._widths)
            g.start_then(exch)
            g.then(exch, halo)
        else:
            for d in self._steps:
                _add_distance_chain(g, d, [g.start()], [halo])
        g.then(loc, add)
        g.then(halo, add)
        g.then_finish(add)
        return g


def make_irregular_spmv_buffers(
    a: CsrMat,
    n_sp: int,
    batch: int = 8,
    seed: int = 0,
    impl_choice: bool = False,
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray, ExchangePlan]:
    """(buffers, partition specs, expected Y, plan) for an arbitrary-sparsity
    square matrix row-partitioned over ``n_sp`` shards on a ("dp", "sp") mesh.

    The local slab gathers from the owned x block; the remote slab's columns
    are renumbered into the contiguous halo layout the retained permute steps
    deliver (reference split_local_remote renumbering, split_mat.hpp:22-136)."""
    from jax.sharding import PartitionSpec as P

    assert a.m == a.n, "square matrix (y and x share the row partition)"
    plan = negotiate_exchange(a, n_sp)
    block = plan.block

    loc_slabs, rem_slabs = [], []
    for p in range(n_sp):
        lo, hi = p * block, (p + 1) * block
        rows = a.retain_rows(lo, hi)
        l_r, l_c, l_v = [], [], []
        r_r, r_c, r_v = [], [], []
        for i in range(rows.m):
            for j in range(rows.indptr[i], rows.indptr[i + 1]):
                c = int(rows.cols[j])
                if lo <= c < hi:
                    l_r.append(i); l_c.append(c - lo); l_v.append(rows.vals[j])
                else:
                    r_r.append(i); r_c.append(plan.halo_slot(p, c))
                    r_v.append(rows.vals[j])
        loc_slabs.append(CooMat(rows.m, block, np.array(l_r, dtype=np.int64),
                                np.array(l_c, dtype=np.int64),
                                np.array(l_v, dtype=np.float32)).to_csr())
        rem_slabs.append(CooMat(rows.m, plan.halo_width,
                                np.array(r_r, dtype=np.int64),
                                np.array(r_c, dtype=np.int64),
                                np.array(r_v, dtype=np.float32)).to_csr())
    wl = max(1, max(int(s.row_widths().max(initial=0)) for s in loc_slabs))
    wr = max(1, max(int(s.row_widths().max(initial=0)) for s in rem_slabs))
    lslabs = [s.to_slab(wl) for s in loc_slabs]
    rslabs = [s.to_slab(wr) for s in rem_slabs]
    lv = np.concatenate([v for v, _ in lslabs])
    lc = np.concatenate([c for _, c in lslabs])
    rv = np.concatenate([v for v, _ in rslabs])
    rc = np.concatenate([c for _, c in rslabs])

    rng = np.random.default_rng(seed + 1)
    X = rng.random((batch, a.m), dtype=np.float32)
    want = np.stack([a.matvec(X[b]) for b in range(batch)])

    bufs: Dict[str, np.ndarray] = {
        "X": X,
        "A_loc_vals": lv,
        "A_loc_cols": lc.astype(np.int32),
        "A_rem_vals": rv,
        "A_rem_cols": rc.astype(np.int32),
        "Y_loc": np.zeros_like(X),
        "Y_rem": np.zeros_like(X),
        "Y": np.zeros_like(X),
    }
    specs: Dict[str, object] = {
        "X": P("dp", "sp"),
        "A_loc_vals": P("sp", None),
        "A_loc_cols": P("sp", None),
        "A_rem_vals": P("sp", None),
        "A_rem_cols": P("sp", None),
        "Y_loc": P("dp", "sp"),
        "Y_rem": P("dp", "sp"),
        "Y": P("dp", "sp"),
    }
    for d in plan.steps:
        w = plan.widths[d]
        idx = np.zeros((n_sp, w), dtype=np.int32)
        for q in range(n_sp):
            # sender q serves receiver (q+d) % n_sp: gather that receiver's
            # list (all owned by q) out of q's local x block
            lst = plan.send_lists[d][(q + d) % n_sp]
            idx[q, : len(lst)] = lst - q * block
        bufs[f"send_idx_{d}"] = idx
        bufs[f"send_{d}"] = np.zeros((batch, n_sp * w), dtype=np.float32)
        bufs[f"recv_{d}"] = np.zeros((batch, n_sp * w), dtype=np.float32)
        specs[f"send_idx_{d}"] = P("sp", None)
        specs[f"send_{d}"] = P("dp", "sp")
        specs[f"recv_{d}"] = P("dp", "sp")
    if impl_choice and plan.steps:
        # the padded all-to-all alternative (ExchangeChoice): per-pair lists in
        # one (n_sp, w_max) send matrix per shard
        wmax = max(plan.widths[d] for d in plan.steps)
        idx_all = np.zeros((n_sp, n_sp, wmax), dtype=np.int32)
        for q in range(n_sp):
            for r in range(n_sp):
                d = (r - q) % n_sp
                if d not in plan.widths:
                    continue
                lst = plan.send_lists[d][r]  # what q ships to r (owned by q)
                idx_all[q, r, : len(lst)] = lst - q * block
        bufs["send_idx_all"] = idx_all
        bufs["send_all"] = np.zeros((batch, n_sp * n_sp, wmax), dtype=np.float32)
        bufs["recv_a2a"] = np.zeros((batch, n_sp * n_sp, wmax), dtype=np.float32)
        specs["send_idx_all"] = P("sp", None, None)
        specs["send_all"] = P("dp", "sp", None)
        specs["recv_a2a"] = P("dp", "sp", None)
    return bufs, specs, want, plan
