"""Tensor parallelism: a Megatron-style sharded MLP as a searchable op DAG.

The reference has no model layers (SURVEY.md §2.5: TP/PP/EP absent; the op-DAG
must nonetheless *express* such programs).  This model is the tensor-parallel
(TP) member of that family: each layer's first matmul is column-sharded over
mesh axis ``"tp"`` and the second row-sharded, so every shard computes a
*partial* layer output that an all-reduce (``lax.psum``) completes —

    h_p    = gelu(x @ W1[:, p-th column block])      (local, MXU)
    part_p = h_p @ W2[p-th row block]                (local, MXU)
    y      = sum_p part_p                            (all-reduce over ICI)

The all-reduce is the schedulable transfer: :class:`~tenzing_tpu.ops.comm_ops.
PsumStart` posts it and ``AwaitTransfer`` joins its completion, the same
post/wait split as every other comm op (reference Isend/Wait,
ops_mpi.hpp:17-146).  Within one chain the layers are serial (layer ``l+1``
consumes the reduced output of layer ``l``), so the schedule freedom comes
from splitting the batch into ``n_chunks`` independent microbatch chains:
a good schedule hides chunk A's all-reduce behind chunk B's matmuls — the
overlap TP training stacks hand-implement; here it is searched.

Numerics are checked against the host evaluation of the unsharded layer stack
(tests/test_tp_mlp.py; ``dryrun_multichip`` covers the sharded path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import CompoundOp, DeviceOp
from tenzing_tpu.ops.comm_ops import AwaitTransfer, PsumStart

AXIS = "tp"


@dataclass(frozen=True)
class TpMlpArgs:
    n_tp: int  # tensor-parallel shards
    n_layers: int = 2
    n_chunks: int = 2  # independent microbatch chains (the searched freedom)
    mb_size: int = 4  # rows per chunk
    d_model: int = 8
    d_ff: int = 16  # global hidden width (sharded n_tp ways)
    dtype: str = "float32"


class TpLayerPartial(DeviceOp):
    """One layer's local half: gelu(x @ W1-column-block) @ W2-row-block —
    both matmuls on the MXU, producing this shard's partial output."""

    def __init__(self, name: str, c: int, layer: int, mb_rows: int = None):
        super().__init__(name)
        self._c, self._l = c, layer
        self._mb = mb_rows  # per-chunk batch rows, for chunk_counts

    def _in(self) -> str:
        return f"X_{self._c}" if self._l == 0 else f"sum_{self._c}_{self._l - 1}"

    def reads(self):
        return [self._in(), "W1", "W2"]

    def writes(self):
        return [f"part_{self._c}_{self._l}"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp

        x = bufs[self._in()]  # (B, d) replicated across tp
        w1 = bufs["W1"][self._l, :, :]  # (d, dff_local) this shard's columns
        w2 = bufs["W2"][self._l, :, :]  # (dff_local, d) this shard's rows
        h = jax.nn.gelu(jnp.dot(x, w1, preferred_element_type=jnp.float32))
        part = jnp.dot(h.astype(x.dtype), w2, preferred_element_type=jnp.float32)
        return {f"part_{self._c}_{self._l}": part.astype(x.dtype)}

    # -- op-chunking protocol (core/chunking.py, T3): the layer's two
    # matmuls split over the batch rows into n partial GEMM pairs, each
    # folding its row slice into the partial-output buffer — so the
    # all-reduce post (the psum of this layer's output) can launch against
    # the tail partials instead of waiting for the whole layer.
    def chunkable(self) -> bool:
        return True

    def chunk_counts(self) -> List[int]:
        # an op built without mb_rows is not chunkable — never guess
        from tenzing_tpu.core.chunking import pow2_counts

        return pow2_counts(self._mb)

    def split(self, n: int) -> List["TpLayerRowsPartial"]:
        rows = self._mb
        if rows is None:
            raise ValueError(
                f"{self.name()}: split() needs the mb_rows extent")
        if n < 1 or rows % n:
            raise ValueError(f"{rows} batch rows do not split {n} ways")
        return [TpLayerRowsPartial(f"{self.name()}.c{n}p{j}", self._c,
                                   self._l, j, n, mb_rows=rows)
                for j in range(n)]


class TpLayerRowsPartial(TpLayerPartial):
    """Partial ``j`` of an ``n``-way batch-row split of
    :class:`TpLayerPartial` (the name avoids overloading "partial", which
    in TP already means the per-shard pre-psum output): both matmuls over
    its row slice, folded into the partial-output buffer by an
    accumulating slice update (read-modify-write — the combine is the
    update chain, so the psum post or another chunk's compute interleaves
    between the partials)."""

    def __init__(self, name: str, c: int, layer: int, part: int,
                 n_parts: int, mb_rows: int = None):
        super().__init__(name, c, layer, mb_rows=mb_rows)
        self._part, self._n_parts = part, n_parts

    def chunkable(self) -> bool:
        return False  # a partial never re-splits

    def reads(self):
        return super().reads() + [f"part_{self._c}_{self._l}"]

    def apply(self, bufs, ctx):
        import jax
        import jax.numpy as jnp
        from jax import lax

        x = bufs[self._in()]
        w1 = bufs["W1"][self._l, :, :]
        w2 = bufs["W2"][self._l, :, :]
        rows = x.shape[0]
        if rows % self._n_parts:
            # chunk validity was checked against the build-time mb_rows;
            # a sharded layout (dp) can hand this op fewer runtime rows —
            # fail at trace time rather than slice 0/partial rows silently
            raise ValueError(
                f"{self.name()}: {rows} runtime rows do not split "
                f"{self._n_parts} ways")
        lo = self._part * (rows // self._n_parts)
        xs = lax.dynamic_slice_in_dim(x, lo, rows // self._n_parts, 0)
        h = jax.nn.gelu(jnp.dot(xs, w1, preferred_element_type=jnp.float32))
        y = jnp.dot(h.astype(x.dtype), w2,
                    preferred_element_type=jnp.float32).astype(x.dtype)
        return {f"part_{self._c}_{self._l}": lax.dynamic_update_slice_in_dim(
            bufs[f"part_{self._c}_{self._l}"], y, lo, 0)}


def mlp_chunk_menu(args: TpMlpArgs, relax: bool = False):
    """(pruned counts, {count: est hidden µs}) for one chunk-layer's local
    MLP half — the roofline sketch constraint
    (bench/roofline.py::prune_chunkings).  The neighboring transfer is the
    layer's all-reduce (a ring psum moves ~2x the partial-output bytes);
    ``relax=True`` (tests / toy shapes) keeps every structurally valid
    count."""
    from tenzing_tpu.bench import roofline

    bpe = np.dtype(args.dtype).itemsize
    b, d = args.mb_size, args.d_model
    dffl = args.d_ff // args.n_tp  # this shard's hidden columns
    part = float(b * d * bpe)  # the partial-output rows (the psum payload)
    cost = roofline.Cost(
        flops=4.0 * b * d * dffl,
        hbm_bytes=2.0 * part + float(2 * d * dffl * bpe))
    return roofline.chunk_menu(
        TpLayerPartial("probe", 0, 0, mb_rows=args.mb_size).chunk_counts(),
        cost, comm_us=2.0 * part / (roofline.V5E_XFER_GBS * 1e9) * 1e6,
        combine_bytes=2.0 * part, relax=relax)


# -- synthesized all-reduce (collectives/synth.py) --------------------------
#
# Each layer's psum site can decompose into chunk-routed p2p steps over the
# tp ring: ring reduce (k chunks), reverse-rotation ring, and recursive
# halving/doubling — each an ordinary choice alternative next to the fixed
# PsumStart chain, searched by the solvers with zero solver changes.


def tp_mlp_synth_counts(args: TpMlpArgs, n_dp: int = 1) -> List[int]:
    """Ring chunk counts that split one chunk's per-device batch rows:
    {1, 2} filtered by divisibility — c1 is the classic ring, c2 the
    chunk-routed variant whose two chains interleave."""
    rows = args.mb_size // max(1, n_dp)
    return [k for k in (1, 2) if 1 <= k <= rows and rows % k == 0]


def tp_mlp_synth_plans(args: TpMlpArgs, c: int, layer: int, n_dp: int = 1):
    """All sketch instantiations of layer ``layer``'s all-reduce for chunk
    ``c``: ring.c{k} forward rotations, ringr.c1 reverse, and rhd.c1 when
    the tp extent is a power of two.  Shapes are per-device (the runtime
    view inside shard_map): ``mb_size // n_dp`` rows of ``d_model``."""
    from tenzing_tpu.collectives.synth import (
        plan_rhd_all_reduce,
        plan_ring_all_reduce,
    )

    if args.n_tp < 2:
        return []
    rows = args.mb_size // max(1, n_dp)
    shape = (rows, args.d_model)
    bpe = int(np.dtype(args.dtype).itemsize)
    base = f"psum_{c}_{layer}"
    src, dst = f"part_{c}_{layer}", f"sum_{c}_{layer}"
    plans = [
        plan_ring_all_reduce(base, src, dst, AXIS, args.n_tp, shape, k,
                             itemsize=bpe)
        for k in tp_mlp_synth_counts(args, n_dp)
    ]
    plans.append(plan_ring_all_reduce(base, src, dst, AXIS, args.n_tp, shape,
                                      1, itemsize=bpe, reverse=True))
    if args.n_tp & (args.n_tp - 1) == 0:
        plans.append(plan_rhd_all_reduce(base, src, dst, AXIS, args.n_tp,
                                         shape, itemsize=bpe))
    return plans


class ConcatOut(DeviceOp):
    """Stack the chunks' final reduced outputs back into batch order."""

    def __init__(self, name: str, args: TpMlpArgs):
        super().__init__(name)
        self._args = args

    def reads(self):
        last = self._args.n_layers - 1
        return [f"sum_{c}_{last}" for c in range(self._args.n_chunks)]

    def writes(self):
        return ["Y"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        last = self._args.n_layers - 1
        return {
            "Y": jnp.concatenate(
                [bufs[f"sum_{c}_{last}"] for c in range(self._args.n_chunks)],
                axis=0,
            )
        }


class TpMlp(CompoundOp):
    """The whole TP forward as one compound: ``n_chunks`` independent
    layer chains (partial -> psum-post -> await per layer), joined by the
    final concat.

    ``chunk=True`` wraps each layer's local MLP half in a
    :class:`~tenzing_tpu.core.chunking.ChunkChoice` so the solvers search
    T3-style batch-row splits whose tail partials the psum post overlaps
    (core/chunking.py; :func:`mlp_chunk_menu` prunes the counts through
    the roofline — ``chunk_relax`` skips the pruning, the tests mode).

    ``synth=True`` additionally wraps each layer's all-reduce in a
    :class:`~tenzing_tpu.collectives.synth.SynthCollectiveChoice`: the
    fixed ``PsumStart -> AwaitTransfer`` chain competes against ring /
    reverse-ring / recursive-halving-doubling decompositions synthesized
    over the tp ring topology (:func:`tp_mlp_synth_plans`), priced per
    link and pruned against the psum's one-post floor.  ``synth_relax``
    keeps analytically-losing instantiations searchable (tests / toy
    shapes); ``synth_dp`` is the dp extent the runtime shards batch rows
    over, so chunk counts validate against the true per-device rows."""

    def __init__(self, args: TpMlpArgs, name: str = "tp_mlp",
                 chunk: bool = False, chunk_relax: bool = False,
                 synth: bool = False, synth_relax: bool = False,
                 synth_dp: int = 1):
        super().__init__(name)
        self._args = args
        self._chunk = chunk
        self._chunk_relax = chunk_relax
        self._synth = synth
        self._synth_relax = synth_relax
        self._synth_dp = max(1, synth_dp)

    def args(self) -> TpMlpArgs:
        return self._args

    def graph(self) -> Graph:
        a = self._args
        g = Graph()
        counts, est = ((), None)
        if self._chunk:
            counts, est = mlp_chunk_menu(a, relax=self._chunk_relax)

        def mk(cc, ll):
            step = TpLayerPartial(f"mlp_{cc}_{ll}", cc, ll,
                                  mb_rows=a.mb_size)
            if any(int(n) > 1 for n in counts):
                from tenzing_tpu.core.chunking import (
                    ChunkChoice,
                    chunk_variants,
                )

                return ChunkChoice(step, chunk_variants(step, counts, est))
            return step

        cat = ConcatOut("tp_concat", a)
        for c in range(a.n_chunks):
            prev = None
            for l in range(a.n_layers):
                mlp = mk(c, l)
                post = PsumStart(
                    f"psum_{c}_{l}", f"part_{c}_{l}", f"sum_{c}_{l}", AXIS
                )
                await_ = AwaitTransfer(f"await_{c}_{l}", f"sum_{c}_{l}")
                if prev is None:
                    g.start_then(mlp)
                else:
                    g.then(prev, mlp)
                variants = []
                if self._synth:
                    from tenzing_tpu.collectives.synth import (
                        FixedCollective,
                        SynthCollectiveChoice,
                        sketch_menu,
                    )
                    from tenzing_tpu.collectives.topology import mesh_topology

                    bpe = np.dtype(a.dtype).itemsize
                    part_bytes = (a.mb_size // self._synth_dp) * a.d_model * bpe
                    variants, menu = sketch_menu(
                        tp_mlp_synth_plans(a, c, l, n_dp=self._synth_dp),
                        mesh_topology({AXIS: a.n_tp}, host=False),
                        # the psum floor: a ring all-reduce moves ~2x the
                        # partial bytes in one fused post
                        fixed_bytes=2.0 * part_bytes,
                        relax=self._synth_relax, collective="all_reduce")
                if variants:
                    choice = SynthCollectiveChoice(
                        f"psum_{c}_{l}",
                        FixedCollective(f"psum_{c}_{l}", [post, await_]),
                        variants, menu)
                    g.then(mlp, choice)
                    prev = choice
                else:
                    g.then(mlp, post)
                    g.then(post, await_)
                    prev = await_
            g.then(prev, cat)
        g.then_finish(cat)
        return g


def make_tp_mlp_buffers(
    args: TpMlpArgs, seed: int = 0, n_dp: int = 1, synth: bool = False
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """(buffers, partition specs, expected Y) for the TP forward.  W1 is
    column-sharded, W2 row-sharded (Megatron layout); chunk inputs are
    replicated across tp; written activations are shard-stacked (see the
    layout note below).

    With ``n_dp > 1`` the specs target a 2-D ``("dp", "tp")`` mesh — data
    parallelism composed with tensor parallelism, the standard 2-D training
    layout: each chunk's batch rows are additionally sharded over ``dp``
    (``mb_size`` must divide by ``n_dp``), weights are replicated across
    ``dp``, and the all-reduce still runs over ``tp`` only, so ICI traffic
    stays within each dp replica's tp group."""
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(seed)
    L, v = args.n_layers, args.n_chunks
    b, d, dff = args.mb_size, args.d_model, args.d_ff
    assert dff % args.n_tp == 0, "d_ff must divide across tp shards"
    assert b % n_dp == 0, "mb_size must divide across dp shards"
    dt = np.dtype(args.dtype)
    x = rng.standard_normal((v * b, d)).astype(dt)
    w1 = rng.standard_normal((L, d, dff)).astype(dt) / np.sqrt(d)
    w2 = rng.standard_normal((L, dff, d)).astype(dt) / np.sqrt(dff)

    from tenzing_tpu.utils.numeric import gelu_tanh

    y = x.astype(np.float64)
    for l in range(L):
        y = gelu_tanh(y @ w1[l].astype(np.float64)) @ w2[l].astype(np.float64)

    dp = ("dp",) if n_dp > 1 else ()
    # written buffers are laid out shard-stacked over tp (and their batch
    # rows sharded over dp when present), even where the math makes every tp
    # shard's block identical (post-psum sums, Y): the executor's ordering
    # tokens are shard-varying, and a tied value cannot satisfy a
    # statically-replicated out_spec under shard_map's vma check
    bufs: Dict[str, np.ndarray] = {
        "W1": w1,
        "W2": w2,
        "Y": np.zeros((args.n_tp * v * b, d), dt),
    }
    specs: Dict[str, object] = {
        "W1": P(None, None, AXIS),  # column-sharded, dp-replicated
        "W2": P(None, AXIS, None),  # row-sharded, dp-replicated
        "Y": P((AXIS,) + dp, None),
    }
    for c in range(v):
        bufs[f"X_{c}"] = x[c * b : (c + 1) * b]
        # batch rows dp-sharded, tp-replicated; never written
        specs[f"X_{c}"] = P(dp if dp else None, None)
        for l in range(L):
            bufs[f"part_{c}_{l}"] = np.zeros((args.n_tp * b, d), dt)
            specs[f"part_{c}_{l}"] = P((AXIS,) + dp, None)
            bufs[f"sum_{c}_{l}"] = np.zeros((args.n_tp * b, d), dt)
            specs[f"sum_{c}_{l}"] = P((AXIS,) + dp, None)
            if synth:
                # staging decls of the synthesized all-reduce sketches: the
                # plans carry per-device shapes; globals shard-stack them
                # over (tp, dp) like every other written activation
                for plan in tp_mlp_synth_plans(args, c, l, n_dp=n_dp):
                    for decl in plan.buffers:
                        if decl.name in bufs:
                            continue
                        gshape = ((args.n_tp * n_dp * decl.shape[0],)
                                  + tuple(decl.shape[1:]))
                        bufs[decl.name] = np.zeros(gshape, dt)
                        specs[decl.name] = P((AXIS,) + dp, None)
    # expected Y in the device layout: under P(("tp","dp")) each (tp, dp)
    # shard holds one contiguous global block containing ITS dp-slice of
    # every chunk in chunk order — so per tp copy, rows group dp-major
    bs = b // n_dp
    per_tp = np.concatenate([
        np.concatenate([y[c * b + j * bs : c * b + (j + 1) * bs]
                        for c in range(v)])
        for j in range(n_dp)
    ])
    want = np.tile(per_tp.astype(dt), (args.n_tp, 1))  # workload dtype (ADVICE r2)
    return bufs, specs, want
