"""Distributed SpMV workload: y = A @ x, row-partitioned, local/remote split.

Parity target: reference ``include/tenzing/spmv/`` + ``src/spmv/`` (C12 in
SURVEY.md §2): CSR/COO host structures (csr_mat.hpp, coo_mat.hpp), random band
matrix generators (csr_mat.hpp:299-369), 1-D block partition helpers
(partition.hpp:11-75), local/remote column split + renumbering
(split_mat.hpp:22-136), the ``RowPartSpmv`` setup engine (row_part_spmv.cuh), the
device ops SpMVKernel/Scatter/VectorAdd (ops_spmv.cuh:61-215 — VectorAdd is
actually implemented here, fixing the reference's no-op defect,
src/spmv/ops_spmv.cu:44-46 / SURVEY.md §7.3), and the ``SpMV`` CompoundOp wiring
the whole dataflow (ops_spmv.cuh:306-436).

TPU-native design: the sparse kernel avoids cuSPARSE-style scalar gathers.  A CSR
matrix is lowered once, host-side, to a dense **band/ELL slab**: values padded to
a fixed row width with a companion column-index slab.  The SpMV is then
``sum(vals * x[cols], axis=1)`` — a gather + VPU multiply-reduce over a static
shape, which XLA vectorizes and tiles; for the band matrices of the reference's
benchmark the slab is dense and this is bandwidth-optimal.  The remote half runs
against the renumbered remote columns exactly like the reference's split SpMV.

The comm ops here are the single-device slice (device-local gather standing for
the ICI exchange); the multi-chip exchange ops live in models/spmv_dist.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import ChoiceOp, CompoundOp, DeviceOp, OpBase


# -- host-side matrix structures (reference coo_mat.hpp / csr_mat.hpp) -----------


@dataclass
class CooMat:
    """Coordinate-format host matrix (reference CooMat, coo_mat.hpp:12-76)."""

    m: int
    n: int
    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray

    def nnz(self) -> int:
        return len(self.vals)

    def to_csr(self) -> "CsrMat":
        order = np.lexsort((self.cols, self.rows))
        rows, cols, vals = self.rows[order], self.cols[order], self.vals[order]
        indptr = np.zeros(self.m + 1, dtype=np.int32)
        np.add.at(indptr, rows + 1, 1)
        indptr = np.cumsum(indptr).astype(np.int32)
        return CsrMat(self.m, self.n, indptr, cols.astype(np.int32), vals)


@dataclass
class CsrMat:
    """CSR host matrix (reference CsrMat<host>, csr_mat.hpp:34-155)."""

    m: int
    n: int
    indptr: np.ndarray
    cols: np.ndarray
    vals: np.ndarray

    def nnz(self) -> int:
        return len(self.vals)

    def retain_rows(self, lo: int, hi: int) -> "CsrMat":
        """Row slice [lo, hi) (reference retain_rows, csr_mat.hpp:101-155)."""
        a, b = self.indptr[lo], self.indptr[hi]
        return CsrMat(
            hi - lo,
            self.n,
            (self.indptr[lo : hi + 1] - a).astype(np.int32),
            self.cols[a:b],
            self.vals[a:b],
        )

    def row_widths(self) -> np.ndarray:
        return np.diff(self.indptr)

    def to_slab(self, width: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Lower to a dense (m, width) ELL slab: (vals, cols), zero-padded.
        Padded entries point at column 0 with value 0 so the gather stays in
        bounds and contributes nothing."""
        wmax = int(self.row_widths().max(initial=0))
        w = int(width) if width is not None else max(1, wmax)
        if w < wmax:
            raise ValueError(
                f"slab width {w} would truncate rows (widest row has {wmax} nonzeros)"
            )
        vals = np.zeros((self.m, w), dtype=self.vals.dtype)
        cols = np.zeros((self.m, w), dtype=np.int32)
        if self.nnz():
            rows = np.repeat(np.arange(self.m), self.row_widths())
            pos = np.arange(self.nnz()) - self.indptr[rows]
            vals[rows, pos] = self.vals
            cols[rows, pos] = self.cols
        return vals, cols

    def matvec(self, x: np.ndarray) -> np.ndarray:
        """Host-side reference y = A @ x (vectorized; no dense materialization)."""
        if not self.nnz():
            return np.zeros(self.m, dtype=self.vals.dtype)
        rows = np.repeat(np.arange(self.m), self.row_widths())
        prods = (self.vals.astype(np.float64)) * x.astype(np.float64)[self.cols]
        return np.bincount(rows, weights=prods, minlength=self.m).astype(self.vals.dtype)

    def toarray(self) -> np.ndarray:
        """Dense form — small matrices / tests only."""
        out = np.zeros((self.m, self.n), dtype=self.vals.dtype)
        for i in range(self.m):
            for j in range(self.indptr[i], self.indptr[i + 1]):
                out[i, self.cols[j]] += self.vals[j]
        return out


def random_band_matrix(
    m: int, bw: int, nnz: int, seed: int = 0, dtype=np.float32
) -> CsrMat:
    """Random square band matrix: nnz entries within ``bw`` of the diagonal
    (reference random_band_matrix, csr_mat.hpp:335-369)."""
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, m, size=nnz)
    offs = rng.integers(-bw, bw + 1, size=nnz)
    cols = np.clip(rows + offs, 0, m - 1)
    vals = rng.random(nnz, dtype=np.float64).astype(dtype)
    return CooMat(m, m, rows, cols, vals).to_csr()


def random_matrix(m: int, n: int, nnz: int, seed: int = 0, dtype=np.float32) -> CsrMat:
    """Uniform random sparse matrix (reference random_matrix, csr_mat.hpp:299-333)."""
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, m, size=nnz)
    cols = rng.integers(0, n, size=nnz)
    vals = rng.random(nnz, dtype=np.float64).astype(dtype)
    return CooMat(m, n, rows, cols, vals).to_csr()


def read_matrix_market(path: str, dtype=np.float32) -> CsrMat:
    """Load a MatrixMarket coordinate file (the reference reads .mtx inputs via
    the vendored ``mm`` reader, tenzing-dfs/examples/spmv.cu:23,35-37).

    Supports ``coordinate`` matrices with field real/integer/pattern and
    symmetry general/symmetric/skew-symmetric (off-diagonal entries mirrored,
    skew negated).  Indices in the file are 1-based per the format."""
    with open(path) as f:
        header = f.readline().split()
        if (
            len(header) < 5
            or header[0] != "%%MatrixMarket"
            or header[1].lower() != "matrix"
            or header[2].lower() != "coordinate"
        ):
            raise ValueError(f"{path}: not a MatrixMarket coordinate file: {header}")
        field, symmetry = header[3].lower(), header[4].lower()
        if field not in ("real", "integer", "pattern"):
            raise ValueError(f"{path}: unsupported field {field!r}")
        if symmetry not in ("general", "symmetric", "skew-symmetric"):
            raise ValueError(f"{path}: unsupported symmetry {symmetry!r}")
        line = f.readline()
        while line and (line.lstrip().startswith("%") or not line.strip()):
            line = f.readline()
        if not line:
            raise ValueError(f"{path}: truncated file (no size line)")
        m, n, nnz = (int(t) for t in line.split())
        rows = np.empty(nnz, dtype=np.int64)
        cols = np.empty(nnz, dtype=np.int64)
        vals = np.ones(nnz, dtype=dtype)
        k = 0
        for line in f:
            t = line.split()
            if not t or t[0].startswith("%"):
                continue
            rows[k], cols[k] = int(t[0]) - 1, int(t[1]) - 1
            if field != "pattern":
                vals[k] = float(t[2])
            k += 1
        if k != nnz:
            raise ValueError(f"{path}: header promised {nnz} entries, found {k}")
    if symmetry != "general":
        off = rows != cols
        sign = -1.0 if symmetry == "skew-symmetric" else 1.0
        rows, cols, vals = (
            np.concatenate([rows, cols[off]]),
            np.concatenate([cols, rows[off]]),
            np.concatenate([vals, (sign * vals[off]).astype(dtype)]),
        )
    return CooMat(m, n, rows, cols, vals).to_csr()


# -- partition helpers (reference partition.hpp:11-75) ---------------------------


def part_by_rows(m: int, parts: int) -> List[Tuple[int, int]]:
    """Contiguous 1-D row partition: ``parts`` (lo, hi) ranges."""
    base, rem = divmod(m, parts)
    out = []
    lo = 0
    for p in range(parts):
        hi = lo + base + (1 if p < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


def get_owner(m: int, parts: int, row: int) -> int:
    """Owning partition of a row (reference get_owner, partition.hpp:43-75)."""
    for p, (lo, hi) in enumerate(part_by_rows(m, parts)):
        if lo <= row < hi:
            return p
    raise IndexError(row)


# -- local/remote split (reference split_mat.hpp:22-136) -------------------------


@dataclass
class SplitMat:
    """A row-partition's matrix split by column ownership: ``local`` covers
    owned columns (renumbered to local x indices), ``remote`` covers off-part
    columns renumbered densely; ``remote_cols`` maps the dense remote index back
    to the global column."""

    local: CsrMat
    remote: CsrMat
    remote_cols: np.ndarray  # global column of each renumbered remote column


def split_local_remote(a: CsrMat, col_lo: int, col_hi: int) -> SplitMat:
    """Split by column range ownership, renumbering both halves
    (reference split_local_remote, split_mat.hpp:22-136)."""
    loc_rows, loc_cols, loc_vals = [], [], []
    rem_rows, rem_cols, rem_vals = [], [], []
    for i in range(a.m):
        for j in range(a.indptr[i], a.indptr[i + 1]):
            c = a.cols[j]
            if col_lo <= c < col_hi:
                loc_rows.append(i)
                loc_cols.append(c - col_lo)
                loc_vals.append(a.vals[j])
            else:
                rem_rows.append(i)
                rem_cols.append(c)
                rem_vals.append(a.vals[j])
    uniq = np.unique(np.asarray(rem_cols, dtype=np.int64)) if rem_cols else np.array([], dtype=np.int64)
    renum = {c: k for k, c in enumerate(uniq)}
    local = CooMat(
        a.m,
        col_hi - col_lo,
        np.asarray(loc_rows, dtype=np.int64),
        np.asarray(loc_cols, dtype=np.int64),
        np.asarray(loc_vals, dtype=a.vals.dtype),
    ).to_csr()
    remote = CooMat(
        a.m,
        max(1, len(uniq)),
        np.asarray(rem_rows, dtype=np.int64),
        np.asarray([renum[c] for c in rem_cols], dtype=np.int64),
        np.asarray(rem_vals, dtype=a.vals.dtype),
    ).to_csr()
    return SplitMat(local=local, remote=remote, remote_cols=uniq)


# -- device ops ------------------------------------------------------------------


class SpMVOp(DeviceOp):
    """ELL-slab SpMV: y = sum(vals * x[cols], axis=1) (reference SpMVKernel,
    ops_spmv.cuh:61-163 — cuSPARSE there, gather+VPU-reduce here)."""

    def __init__(self, name: str, x: str, y: str, vals: str, cols: str):
        super().__init__(name)
        self._x, self._y, self._vals, self._cols = x, y, vals, cols

    def reads(self):
        return [self._x, self._vals, self._cols]

    def writes(self):
        return [self._y]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        vals, cols, x = bufs[self._vals], bufs[self._cols], bufs[self._x]
        return {self._y: jnp.sum(vals * x[cols], axis=1)}

    # megakernel fusion (runtime/fused.py): rows are independent — the slab
    # and output decompose along axis 0; the gathered x must stay whole
    def fusible(self) -> bool:
        return True

    def fuse_tiling(self):
        return {self._vals: 0, self._cols: 0, self._y: 0, self._x: None}


class SpMVPallasOp(SpMVOp):
    """ELL-slab SpMV via the Pallas masked vreg-gather kernel
    (ops/spmv_pallas.py).  Falls back to the XLA gather (the parent op) when x
    is too large for the in-kernel gather decomposition (see ops/spmv_pallas.py
    hardware note) so the op is always valid; where both kernels apply, which
    is faster is the solver's ChoiceOp question."""

    def apply(self, bufs, ctx):
        from tenzing_tpu.ops.spmv_pallas import ell_spmv_pallas, supports

        vals, cols, x = bufs[self._vals], bufs[self._cols], bufs[self._x]
        if not supports(x.shape[0]):
            return super().apply(bufs, ctx)
        return {self._y: ell_spmv_pallas(vals, cols, x)}

    def uses_pallas(self) -> bool:
        return True


class SpMVImplChoice(ChoiceOp):
    """Implementation menu for one SpMV: XLA-gather vs Pallas vreg-gather
    (reference ChoiceOp, operation.hpp:90-93; the scheduler replaces it via a
    ChooseOp decision, state.cpp:61-65).

    When the x-vector length is known at graph construction (``x_size``), the
    Pallas choice is offered only if the kernel actually supports it — otherwise
    SpMVPallasOp would silently fall back to the XLA path and the menu would
    double the structural-variant space with duplicate candidates (ADVICE r1)."""

    def __init__(self, name: str, x: str, y: str, vals: str, cols: str,
                 x_size: Optional[int] = None):
        super().__init__(name)
        self._args = (x, y, vals, cols)
        self._x_size = x_size

    def choices(self) -> List[OpBase]:
        from tenzing_tpu.ops.spmv_pallas import supports

        x, y, vals, cols = self._args
        out: List[OpBase] = [SpMVOp(self.name() + ".xla", x, y, vals, cols)]
        if self._x_size is None or supports(self._x_size):
            out.append(SpMVPallasOp(self.name() + ".pallas", x, y, vals, cols))
        return out


class Scatter(DeviceOp):
    """Gather owned x entries into a contiguous send buffer (reference Scatter,
    ops_spmv.cuh:194-215)."""

    def __init__(self, name: str, x: str, idx: str, out: str):
        super().__init__(name)
        self._x, self._idx, self._out = x, idx, out

    def reads(self):
        return [self._x, self._idx]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._x][bufs[self._idx]]}

    # fusion: each gathered entry depends only on its own index row
    def fusible(self) -> bool:
        return True

    def fuse_tiling(self):
        return {self._x: None, self._idx: 0, self._out: 0}


class VectorAdd(DeviceOp):
    """y = yl + yr (reference VectorAdd — a no-op there,
    src/spmv/ops_spmv.cu:44-46; implemented here per SURVEY.md §7.3)."""

    def __init__(self, name: str, a: str, b: str, out: str):
        super().__init__(name)
        self._a, self._b, self._out = a, b, out

    def reads(self):
        return [self._a, self._b]

    def writes(self):
        return [self._out]

    def apply(self, bufs, ctx):
        return {self._out: bufs[self._a] + bufs[self._b]}

    # fusion: elementwise
    def fusible(self) -> bool:
        return True

    def fuse_tiling(self):
        return {self._a: 0, self._b: 0, self._out: 0}


class LocalExchange(DeviceOp):
    """Single-device stand-in for the ICI exchange: moves the scattered send
    buffer into the remote-x buffer (the multi-chip version is a ppermute-based
    neighbor exchange, models/spmv_dist.py)."""

    def __init__(self, name: str, src: str, dst: str):
        super().__init__(name)
        self._src, self._dst = src, dst

    def reads(self):
        return [self._src]

    def writes(self):
        return [self._dst]

    def apply(self, bufs, ctx):
        return {self._dst: bufs[self._src]}

    # fusion: a device-local copy, trivially row-independent
    def fusible(self) -> bool:
        return True

    def fuse_tiling(self):
        return {self._src: 0, self._dst: 0}


# -- synthesized exchange (collectives/synth.py) ---------------------------------

#: The synth site name of the host x-exchange: the directive rides the
#: executed schedule as ``x_exchange.synth.pipe.c<K>``.
SPMV_SYNTH_BASE = "x_exchange"


def spmv_synth_counts(n_remote: Optional[int]) -> List[int]:
    """Structurally valid pipe chunk counts for an ``n_remote``-entry
    exchange payload: 2 and 4 where they fit (k=1 staged routing IS the
    fixed round trip — offering it would duplicate the fixed alternative).
    Unknown payload -> no counts, never guessed."""
    return [k for k in (2, 4) if 2 <= k <= int(n_remote or 0)]


def spmv_synth_plans(n_remote: Optional[int]):
    """The pipe-sketch instantiations of the host x-exchange — the single
    source of truth for BOTH the graph's step chains and the buffer
    builder's staging decls (same plan, same names, same shapes)."""
    from tenzing_tpu.collectives.synth import plan_host_pipe

    return [plan_host_pipe(SPMV_SYNTH_BASE, "send_buf", "x_remote",
                           int(n_remote), k)
            for k in spmv_synth_counts(n_remote)]


class SpMVCompound(CompoundOp):
    """The whole SpMV iteration as one compound op (reference SpMV CompoundOp,
    ops_spmv.cuh:306-436): start -> {local spmv, scatter -> exchange}; exchange
    -> remote spmv; {local, remote} -> add -> finish.

    With ``impl_choice=True`` the two SpMV kernels become implementation
    ChoiceOps (XLA gather vs Pallas vreg-gather) and the solver searches the
    kernel menu alongside order and lane assignment.

    ``exchange`` picks the single-chip stand-in for the reference's MPI x
    exchange (PostSend/WaitRecv ops, ops_spmv.cuh:217-304):

    * ``"local"`` (default) — a device-to-device copy.  All-compute DAG: on a
      TPU core, compute ops cannot overlap across lanes, so schedule order
      barely matters (measured: paired speedup CI straddles 1.0).
    * ``"host"`` — an async host round-trip DMA with the post/wait split
      (spill -> fetch -> await), the same substrate as the halo pipeline.
      This is the faithful analog of the reference's network hop: the search
      can hide the transfer behind the local SpMV, and the naive
      serialization pays it in full.

    ``synth=True`` (requires ``exchange="host"``) additionally decomposes
    the exchange through the synthesized-collectives subsystem
    (collectives/synth.py): the fixed round trip becomes one alternative of
    a :class:`~tenzing_tpu.collectives.synth.SynthCollectiveChoice` whose
    other alternatives pipeline the payload device->host->device in k
    chunks (the ``pipe`` sketch — pure movement, bit-identical), so the
    solvers search the chunk routing of the exchange itself.  The remote-x
    length must be known (``x_sizes["x_remote"]``) — an unknown payload is
    never synthesized, the ``pow2_counts`` never-guess discipline.
    ``synth_relax`` keeps analytically-losing instantiations searchable
    (tests / toy smoke shapes), the ``chunk_relax`` twin."""

    def __init__(self, name: str = "spmv", impl_choice: bool = False,
                 x_sizes: Optional[Dict[str, int]] = None,
                 exchange: str = "local", synth: bool = False,
                 synth_relax: bool = False):
        super().__init__(name)
        self._impl_choice = impl_choice
        # buffer-name -> x length, when known (prunes unsupported Pallas choices)
        self._x_sizes = dict(x_sizes) if x_sizes else {}
        if exchange not in ("local", "host"):
            raise ValueError(f"exchange must be 'local' or 'host', got {exchange!r}")
        if synth and exchange != "host":
            raise ValueError("synth=True needs the exchange='host' round trip "
                             "(the PCIE link is what the pipe sketch routes)")
        self._exchange = exchange
        self._synth = synth
        self._synth_relax = synth_relax

    def graph(self) -> Graph:
        g = Graph()
        if self._impl_choice:
            def mk(name, x, y, vals, cols):
                return SpMVImplChoice(name, x, y, vals, cols,
                                      x_size=self._x_sizes.get(x))
        else:
            mk = SpMVOp
        yl = mk("spmv_local", "x_local", "y_local", "A_loc_vals", "A_loc_cols")
        scatter = Scatter("scatter", "x_local", "send_idx", "send_buf")
        yr = mk("spmv_remote", "x_remote", "y_remote", "A_rem_vals", "A_rem_cols")
        add = VectorAdd("y_add", "y_local", "y_remote", "y")
        g.start_then(yl)
        g.start_then(scatter)
        if self._exchange == "host":
            from tenzing_tpu.ops.comm_ops import (
                AwaitTransfer,
                HostFetchStart,
                HostSpillStart,
            )

            spill = HostSpillStart("spill_x", "send_buf", "host_x")
            fetch = HostFetchStart("fetch_x", "host_x", "x_remote")
            await_ = AwaitTransfer("await_x", "x_remote")
            variants = []
            if self._synth:
                from tenzing_tpu.collectives.synth import (
                    FixedCollective,
                    SynthCollectiveChoice,
                    sketch_menu,
                )
                from tenzing_tpu.collectives.topology import host_topology

                n_rem = self._x_sizes.get("x_remote")
                variants, menu = sketch_menu(
                    spmv_synth_plans(n_rem), host_topology(),
                    # the fixed floor: the round trip's bytes in one
                    # optimistic post (spill+fetch move them twice)
                    fixed_bytes=2.0 * 4 * int(n_rem or 0),
                    relax=self._synth_relax, collective="exchange")
            if variants:
                choice = SynthCollectiveChoice(
                    SPMV_SYNTH_BASE,
                    FixedCollective(SPMV_SYNTH_BASE, [spill, fetch, await_]),
                    variants, menu)
                g.then(scatter, choice)
                g.then(choice, yr)
            else:
                g.then(scatter, spill)
                g.then(spill, fetch)
                g.then(fetch, await_)
                g.then(await_, yr)
        else:
            exch = LocalExchange("exchange", "send_buf", "x_remote")
            g.then(scatter, exch)
            g.then(exch, yr)
        g.then(yl, add)
        g.then(yr, add)
        g.then_finish(add)
        return g


def make_spmv_buffers(
    m: int = 4096,
    nnz_per_row: int = 10,
    bw: Optional[int] = None,
    seed: int = 0,
    slab_width: Optional[int] = None,
    matrix: Optional[CsrMat] = None,
    synth: bool = False,
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """Build the buffer dict for the single-device SpMV slice and the dense
    reference answer.  The matrix is split at the column midpoint to mimic the
    distributed local/remote structure (reference spmv_run_strategy.cuh:44-47
    config: m rows, nnz=10*m, band bw).  Pass ``matrix`` (e.g. from
    ``read_matrix_market``) to benchmark a concrete input instead of the random
    band matrix, matching the reference's .mtx path (spmv.cu:35-37)."""
    if matrix is not None:
        if matrix.m != matrix.n:
            raise ValueError(f"SpMV slice needs a square matrix, got {matrix.m}x{matrix.n}")
        a, m = matrix, matrix.m
    else:
        bw = bw if bw is not None else max(1, m // 8)
        a = random_band_matrix(m, bw, nnz_per_row * m, seed=seed)
    half = m // 2
    sp = split_local_remote(a, 0, half)
    lv, lc = sp.local.to_slab(slab_width)
    rv, rc = sp.remote.to_slab(slab_width)
    rng = np.random.default_rng(seed + 1)
    x = rng.random(m, dtype=np.float32)
    # remote x entries come from the "other rank"'s region via scatter+exchange
    send_idx = sp.remote_cols.astype(np.int32)
    if len(send_idx) == 0:  # degenerate split: keep buffer shapes static
        send_idx = np.zeros(1, dtype=np.int32)
    bufs = {
        "x_local": x,  # this slice owns columns [0, half) but keeps full x for the gather
        "A_loc_vals": lv,
        "A_loc_cols": lc,
        "A_rem_vals": rv,
        "A_rem_cols": rc,
        "send_idx": send_idx,
        "send_buf": np.zeros(len(send_idx), dtype=np.float32),
        # staging buffer for the exchange="host" round trip (place in
        # pinned_host, see spmv_host_buffer_names); unused by exchange="local"
        "host_x": np.zeros(len(send_idx), dtype=np.float32),
        "x_remote": np.zeros(len(send_idx), dtype=np.float32),
        "y_local": np.zeros(m, dtype=np.float32),
        "y_remote": np.zeros(m, dtype=np.float32),
        "y": np.zeros(m, dtype=np.float32),
    }
    if synth:
        # staging decls of the synthesized exchange (pipe sketch): the same
        # plans the graph builds from, so names/shapes cannot drift
        for plan in spmv_synth_plans(len(send_idx)):
            for d in plan.buffers:
                bufs[d.name] = np.zeros(d.shape, dtype=np.float32)
    want = a.matvec(x)
    return bufs, want


def spmv_host_buffer_names(n_remote: Optional[int] = None,
                           synth: bool = False) -> List[str]:
    """Buffers to device_put into pinned_host for ``exchange="host"`` (the
    executor detects host residency from the array's sharding memory kind).
    With ``synth=True`` the pipe sketch's per-chunk host staging pieces are
    included (``n_remote`` = the exchange payload length, i.e. the
    ``send_idx`` extent the buffers were built with)."""
    out = ["host_x"]
    if synth:
        for plan in spmv_synth_plans(n_remote):
            out += [d.name for d in plan.buffers if d.space == "host"]
    return out
