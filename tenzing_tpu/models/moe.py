"""Mixture-of-Experts layer: expert parallelism as a searchable op DAG.

The reference has no ML layers (SURVEY.md §2.5: TP/PP/EP absent; the op-DAG
must nonetheless *express* such programs).  This model is the expert-parallel
(EP) member of that family, the structural sibling of the irregular SpMV
exchange (models/spmv_irregular.py): tokens are routed to experts that live on
other shards, so the layer is dispatch (all-to-all) -> expert FFN -> combine
(all-to-all back) — the reference's ``Ialltoallv`` pattern
(ops_mpi.hpp:82-119) with MXU compute between the two exchanges.

Design:

* **Routing is host-side setup** (the analog of ``RowPartSpmv``'s send/recv
  negotiation, row_part_spmv.cuh:259-423): top-1 gating over a fixed gate
  matrix is evaluated on the host when buffers are built, producing static
  per-(shard, expert) slot tables — ``disp_idx`` (which local token fills
  each capacity slot) and ``disp_w`` (its gate weight; 0 marks padding).
  Raggedness is handled by padding every (src, dst) pair to the common
  capacity, exactly like the irregular SpMV's width-padded lists — there is
  no ragged all-to-all on ICI.
* **The data plane is schedulable.**  Tokens are split into ``n_chunks``
  microbatch chunks; each chunk is an independent chain

      pack_c -> a2a_disp_c(post) -> await -> ffn_c -> a2a_comb_c(post)
             -> await -> combine_c

  so the solver can pipeline chunks: expert compute of chunk 0 overlaps the
  dispatch of chunk 1 (the schedule MoE systems hand-tune; here it is
  *searched*).  The reference hard-codes its overlap discipline with
  post-all-before-wait-any edges (ops_halo_exchange.cu:249-256); this graph
  deliberately leaves that freedom to the search.
* The expert FFN (gelu MLP, the MXU hot spot) has an implementation ChoiceOp:
  XLA einsums vs the Pallas tiled-matmul kernel (ops/ffn_pallas.py).

Numerics are checked against a dense host evaluation of the routed layer
(tests/test_moe.py; ``dryrun_multichip`` covers the full sharded path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import ChoiceOp, CompoundOp, DeviceOp, OpBase
from tenzing_tpu.ops.comm_ops import AllToAllStart, AwaitTransfer

AXIS = "ep"


@dataclass(frozen=True)
class MoEArgs:
    n_ep: int  # expert-parallel shards == experts (one expert per shard)
    tokens_per_shard: int = 16
    d_model: int = 8
    d_ff: int = 16
    n_chunks: int = 2  # microbatch chunks (the pipelining freedom)
    dtype: str = "float32"

    @property
    def chunk_tokens(self) -> int:
        assert self.tokens_per_shard % self.n_chunks == 0
        return self.tokens_per_shard // self.n_chunks


from tenzing_tpu.utils.numeric import gelu_tanh as _gelu


def top1_route(x: np.ndarray, wg: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Top-1 gating in float64: (expert index, softmax gate weight) per token
    — the single source of the routing rule for every MoE buffer builder
    (multi-chip here, single-chip models/moe_pipeline.py) and its expected-Y
    host references."""
    logits = x.astype(np.float64) @ wg.astype(np.float64)  # (T, E)
    expert = np.argmax(logits, axis=1)
    pz = np.exp(logits - logits.max(axis=1, keepdims=True))
    pz /= pz.sum(axis=1, keepdims=True)
    gate = pz[np.arange(len(x)), expert]
    return expert, gate


class DispatchPack(DeviceOp):
    """Fill chunk ``c``'s capacity-padded send buffer from the local tokens the
    router assigned to each expert (the gather the reference's Scatter op does
    for the Ialltoallv send buffer, ops_spmv.cuh:194-215)."""

    def __init__(self, name: str, c: int, args: MoEArgs):
        super().__init__(name)
        self._c = c
        self._args = args

    def reads(self):
        return ["X", f"disp_idx_{self._c}"]

    def writes(self):
        return [f"send_disp_{self._c}"]

    def apply(self, bufs, ctx):
        tc_ = self._args.chunk_tokens
        xc = bufs["X"][self._c * tc_ : (self._c + 1) * tc_]  # (Tc, d)
        idx = bufs[f"disp_idx_{self._c}"][0]  # (n_ep, C)
        return {f"send_disp_{self._c}": xc[idx]}  # (n_ep, C, d)


class ExpertFFN(DeviceOp):
    """Run the resident expert's gelu MLP over every received token (the MXU
    compute between the two exchanges).  Padding slots carry real numbers but
    combine multiplies them by weight 0."""

    def __init__(self, name: str, c: int, args: MoEArgs):
        super().__init__(name)
        self._c = c
        self._args = args

    def reads(self):
        return [f"recv_disp_{self._c}", "W1", "W2"]

    def writes(self):
        return [f"ffn_out_{self._c}"]

    def _mlp(self, x2d, w1, w2):
        import jax
        import jax.numpy as jnp

        h = jax.nn.gelu(jnp.dot(x2d, w1, preferred_element_type=jnp.float32))
        return jnp.dot(h.astype(x2d.dtype), w2, preferred_element_type=jnp.float32)

    def apply(self, bufs, ctx):
        x = bufs[f"recv_disp_{self._c}"]  # (n_ep, C, d) rows by source shard
        w1, w2 = bufs["W1"][0], bufs["W2"][0]  # this shard's expert
        n, cap, d = x.shape
        y = self._mlp(x.reshape(n * cap, d), w1, w2).astype(x.dtype)
        return {f"ffn_out_{self._c}": y.reshape(n, cap, d)}

    # -- op-chunking protocol (core/chunking.py, T3): the expert MLP splits
    # over the source-shard rows of the received slot table (the token
    # axis), each partial folding its row slice into the output — so the
    # combine all-to-all (or another chunk's dispatch) can post against the
    # tail partials instead of waiting for the whole FFN.  XLA only: the
    # Pallas subclass owns its internal blocking.
    def chunkable(self) -> bool:
        return True

    def chunk_counts(self) -> List[int]:
        from tenzing_tpu.core.chunking import pow2_counts

        return pow2_counts(self._args.n_ep)

    def split(self, n: int) -> List["ExpertFFNPartial"]:
        e = self._args.n_ep
        if n < 1 or e % n:
            raise ValueError(f"{e} slot-table rows do not split {n} ways")
        return [ExpertFFNPartial(f"{self.name()}.c{n}p{j}", self._c,
                                 self._args, j, n)
                for j in range(n)]


class ExpertFFNPartial(ExpertFFN):
    """Partial ``j`` of an ``n``-way token split of :class:`ExpertFFN`:
    the MLP over its source-shard row slice, folded into the output buffer
    by an accumulating slice update (read-modify-write — the combine is
    the update chain, so other ops interleave between the partials)."""

    def __init__(self, name: str, c: int, args: MoEArgs, part: int,
                 n_parts: int):
        super().__init__(name, c, args)
        self._part, self._n_parts = part, n_parts

    def chunkable(self) -> bool:
        return False  # a partial never re-splits

    def reads(self):
        return super().reads() + [f"ffn_out_{self._c}"]

    def apply(self, bufs, ctx):
        from jax import lax

        x = bufs[f"recv_disp_{self._c}"]  # (n_ep, C, d)
        w1, w2 = bufs["W1"][0], bufs["W2"][0]
        n, cap, d = x.shape
        if n % self._n_parts:
            # chunk validity was checked against the build-time n_ep —
            # fail at trace time rather than slice partial rows silently
            raise ValueError(
                f"{self.name()}: {n} slot-table rows do not split "
                f"{self._n_parts} ways")
        lo = self._part * (n // self._n_parts)
        xs = x[lo : lo + n // self._n_parts]
        y = self._mlp(xs.reshape(-1, d), w1, w2).astype(x.dtype)
        y = y.reshape(n // self._n_parts, cap, d)
        return {f"ffn_out_{self._c}": lax.dynamic_update_slice_in_dim(
            bufs[f"ffn_out_{self._c}"], y, lo, 0)}


class ExpertFFNPallas(ExpertFFN):
    """Same MLP through the Pallas tiled-matmul kernel (ops/ffn_pallas.py)."""

    def _mlp(self, x2d, w1, w2):
        from tenzing_tpu.ops.ffn_pallas import ffn_pallas

        return ffn_pallas(x2d, w1, w2)

    def uses_pallas(self) -> bool:
        return True

    def chunkable(self) -> bool:
        return False  # the kernel owns its internal blocking


def ffn_chunk_menu(args: MoEArgs, relax: bool = False):
    """(pruned counts, {count: est hidden µs}) for one chunk's expert FFN —
    the roofline sketch constraint (bench/roofline.py::prune_chunkings).
    The neighboring transfer is the combine all-to-all returning the expert
    outputs; ``relax=True`` (tests / toy shapes) keeps every structurally
    valid count."""
    from tenzing_tpu.bench import roofline

    bpe = np.dtype(args.dtype).itemsize
    cap = args.chunk_tokens  # capacity upper bound per (src, dst) pair
    slots = float(args.n_ep * cap)
    d, dff = args.d_model, args.d_ff
    table = slots * d * bpe  # one slot-table pass (the a2a payload)
    cost = roofline.Cost(
        flops=4.0 * slots * d * dff,
        hbm_bytes=2.0 * table + float(2 * d * dff * bpe))
    return roofline.chunk_menu(
        ExpertFFN("probe", 0, args).chunk_counts(), cost,
        comm_us=table / (roofline.V5E_XFER_GBS * 1e9) * 1e6,
        combine_bytes=2.0 * table, relax=relax)


class ExpertFFNChoice(ChoiceOp):
    """Kernel menu for chunk ``c``'s expert MLP: XLA einsums vs Pallas tiles
    (plus T3-style chunked expansions of the XLA kernel when
    ``chunk_counts`` is given — core/chunking.py)."""

    def __init__(self, name: str, c: int, args: MoEArgs,
                 chunk_counts=(), chunk_est=None):
        super().__init__(name)
        self._c = c
        self._args = args
        self._chunks = tuple(int(n) for n in chunk_counts if int(n) > 1)
        self._chunk_est = dict(chunk_est or {})
        if chunk_counts:
            from tenzing_tpu.core.chunking import menu_info

            self.chunk_menu = menu_info(name + ".xla", chunk_counts,
                                        self._chunk_est)

    def choices(self) -> List[OpBase]:
        from tenzing_tpu.core.chunking import ChunkedOp

        out: List[OpBase] = [
            ExpertFFN(self.name() + ".xla", self._c, self._args),
            ExpertFFNPallas(self.name() + ".pallas", self._c, self._args),
        ]
        out += [
            ChunkedOp(ExpertFFN(self.name() + ".xla", self._c, self._args),
                      n, est_hidden_us=self._chunk_est.get(n))
            for n in self._chunks
        ]
        return out


# -- synthesized all-to-all (collectives/synth.py) --------------------------


def moe_synth_plans(args: MoEArgs, c: int, site: str, cap: int = None):
    """Ring all-to-all instantiations for chunk ``c``'s dispatch or combine
    exchange (``site`` in ``{"disp", "comb"}``): n-1 single-hop rotations
    replace the fused ``AllToAllStart``, each await free to interleave.
    ``cap`` is the capacity (slot-table width); the graph-time default
    ``chunk_tokens`` is its upper bound (pricing only — the buffer builder
    passes the routed capacity)."""
    from tenzing_tpu.collectives.synth import plan_ring_all_to_all

    if args.n_ep < 2:
        return []
    cap = int(args.chunk_tokens if cap is None else cap)
    src = f"send_disp_{c}" if site == "disp" else f"ffn_out_{c}"
    dst = f"recv_disp_{c}" if site == "disp" else f"recv_comb_{c}"
    return [plan_ring_all_to_all(
        f"a2a_{site}_{c}", src, dst, AXIS, args.n_ep,
        (cap, args.d_model), itemsize=np.dtype(args.dtype).itemsize)]


class CombineScatter(DeviceOp):
    """Scatter-add the returned expert outputs back into token order, scaled
    by the gate weights (padding slots have weight 0)."""

    def __init__(self, name: str, c: int, args: MoEArgs):
        super().__init__(name)
        self._c = c
        self._args = args

    def reads(self):
        return [f"recv_comb_{self._c}", f"disp_idx_{self._c}", f"disp_w_{self._c}"]

    def writes(self):
        return [f"Y_{self._c}"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        vals = bufs[f"recv_comb_{self._c}"]  # (n_ep, C, d) rows by expert
        idx = bufs[f"disp_idx_{self._c}"][0].reshape(-1)  # (n_ep*C,)
        w = bufs[f"disp_w_{self._c}"][0].reshape(-1, 1)  # (n_ep*C, 1)
        d = vals.shape[-1]
        y = jnp.zeros((self._args.chunk_tokens, d), vals.dtype)
        return {f"Y_{self._c}": y.at[idx].add(w * vals.reshape(-1, d))}


class ConcatChunks(DeviceOp):
    """Stitch the per-chunk outputs back into the token-order output."""

    def __init__(self, name: str, args: MoEArgs):
        super().__init__(name)
        self._args = args

    def reads(self):
        return [f"Y_{c}" for c in range(self._args.n_chunks)]

    def writes(self):
        return ["Y"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        return {
            "Y": jnp.concatenate(
                [bufs[f"Y_{c}"] for c in range(self._args.n_chunks)], axis=0
            )
        }


class MoELayer(CompoundOp):
    """The whole EP layer as one compound: ``n_chunks`` independent
    dispatch -> expert -> combine chains joined by the final concat.  With
    ``impl_choice`` each chunk's FFN kernel is searched; ``chunk=True``
    adds T3-style chunked expert-FFN alternatives to the menus
    (core/chunking.py; :func:`ffn_chunk_menu` prunes the counts through
    the roofline — ``chunk_relax`` skips the pruning, the tests mode).
    ``synth=True`` puts synthesized ring all-to-all decompositions
    (collectives/synth.py) next to each chunk's fused dispatch/combine
    exchange in one ChooseOp; ``synth_relax`` keeps analytically-dominated
    instantiations searchable."""

    def __init__(self, args: MoEArgs, name: str = "moe",
                 impl_choice: bool = False, chunk: bool = False,
                 chunk_relax: bool = False, synth: bool = False,
                 synth_relax: bool = False):
        super().__init__(name)
        self._args = args
        self._impl_choice = impl_choice
        self._chunk = chunk
        self._chunk_relax = chunk_relax
        self._synth = synth
        self._synth_relax = synth_relax

    def args(self) -> MoEArgs:
        return self._args

    def graph(self) -> Graph:
        g = Graph()
        cat = ConcatChunks("moe_concat", self._args)
        counts, est = ((), None)
        if self._chunk:
            counts, est = ffn_chunk_menu(self._args,
                                         relax=self._chunk_relax)
        if self._impl_choice:
            mk = lambda name, c_, a_: ExpertFFNChoice(
                name, c_, a_, chunk_counts=counts, chunk_est=est)
        elif any(int(n) > 1 for n in counts):
            from tenzing_tpu.core.chunking import ChunkChoice, chunk_variants

            def mk(name, c_, a_):
                op = ExpertFFN(name, c_, a_)
                return ChunkChoice(op, chunk_variants(op, counts, est))
        else:
            mk = ExpertFFN

        def a2a(base, src, dst, prev, nxt):
            start = AllToAllStart(base, src, dst, AXIS, split_axis=0)
            await_ = AwaitTransfer(f"await_{base[4:]}", dst)
            if self._synth and self._args.n_ep >= 2:
                from tenzing_tpu.collectives.synth import (
                    FixedCollective, SynthCollectiveChoice, sketch_menu)
                from tenzing_tpu.collectives.topology import mesh_topology

                a = self._args
                cap = a.chunk_tokens  # capacity upper bound for pricing
                bpe = np.dtype(a.dtype).itemsize
                site = "disp" if "disp" in base else "comb"
                variants, menu = sketch_menu(
                    moe_synth_plans(a, c, site),
                    mesh_topology({AXIS: a.n_ep}, host=False),
                    fixed_bytes=float(a.n_ep * cap * a.d_model * bpe),
                    relax=self._synth_relax, collective="all_to_all")
                if variants:
                    node = SynthCollectiveChoice(
                        base, FixedCollective(base, [start, await_]),
                        variants, menu)
                    g.then(prev, node)
                    g.then(node, nxt)
                    return
            g.then(prev, start)
            g.then(start, await_)
            g.then(await_, nxt)

        for c in range(self._args.n_chunks):
            pack = DispatchPack(f"pack_{c}", c, self._args)
            ffn = mk(f"ffn_{c}", c, self._args)
            scat = CombineScatter(f"combine_{c}", c, self._args)
            g.start_then(pack)
            a2a(f"a2a_disp_{c}", f"send_disp_{c}", f"recv_disp_{c}",
                pack, ffn)
            a2a(f"a2a_comb_{c}", f"ffn_out_{c}", f"recv_comb_{c}",
                ffn, scat)
            g.then(scat, cat)
        g.then_finish(cat)
        return g


def make_moe_buffers(
    args: MoEArgs, seed: int = 0, synth: bool = False
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """(buffers, partition specs, expected Y) for the EP layer on a 1-D
    ``("ep",)`` mesh.  Routing (top-1 gating) runs here, on the host, against
    a fixed random gate matrix — the setup-negotiation analog; its product is
    the static slot tables the device ops consume."""
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(seed)
    n, t, d, dff = args.n_ep, args.tokens_per_shard, args.d_model, args.d_ff
    tc_ = args.chunk_tokens
    dt = np.dtype(args.dtype)
    x = rng.standard_normal((n * t, d)).astype(dt)
    wg = rng.standard_normal((d, n)).astype(dt)
    w1 = rng.standard_normal((n, d, dff)).astype(dt) / np.sqrt(d)
    w2 = rng.standard_normal((n, dff, d)).astype(dt) / np.sqrt(dff)

    # host routing: top-1 expert + softmax gate weight per token
    expert, gate = top1_route(x, wg)

    # capacity: max tokens any (shard, chunk) sends to any expert
    cap = 1
    for s in range(n):
        for c in range(args.n_chunks):
            lo = s * t + c * tc_
            e_blk = expert[lo : lo + tc_]
            if len(e_blk):
                cap = max(cap, int(np.bincount(e_blk, minlength=n).max()))

    bufs: Dict[str, np.ndarray] = {
        "X": x,
        "W1": w1,
        "W2": w2,
        "Y": np.zeros((n * t, d), dt),
    }
    specs: Dict[str, object] = {
        "X": P(AXIS, None),
        "W1": P(AXIS, None, None),
        "W2": P(AXIS, None, None),
        "Y": P(AXIS, None),
    }
    for c in range(args.n_chunks):
        idx = np.zeros((n, n, cap), dtype=np.int32)
        w = np.zeros((n, n, cap), dtype=dt)
        for s in range(n):
            lo = s * t + c * tc_
            fill = [0] * n
            for j in range(tc_):
                e = int(expert[lo + j])
                idx[s, e, fill[e]] = j
                w[s, e, fill[e]] = gate[lo + j]
                fill[e] += 1
        bufs[f"disp_idx_{c}"] = idx
        bufs[f"disp_w_{c}"] = w
        specs[f"disp_idx_{c}"] = P(AXIS, None, None)
        specs[f"disp_w_{c}"] = P(AXIS, None, None)
        for nm in (f"send_disp_{c}", f"recv_disp_{c}", f"ffn_out_{c}",
                   f"recv_comb_{c}"):
            bufs[nm] = np.zeros((n * n, cap, d), dt)
            specs[nm] = P(AXIS, None, None)
        bufs[f"Y_{c}"] = np.zeros((n * tc_, d), dt)
        specs[f"Y_{c}"] = P(AXIS, None)
        if synth:
            # staging buffers for the synthesized ring all-to-all: plans
            # price against the chunk_tokens upper bound, but allocation
            # uses the routed capacity so runtime shapes line up
            for site in ("disp", "comb"):
                for plan in moe_synth_plans(args, c, site, cap=cap):
                    for decl in plan.buffers:
                        if decl.name in bufs:
                            continue
                        gshape = ((n * decl.shape[0],)
                                  + tuple(decl.shape[1:]))
                        bufs[decl.name] = np.zeros(gshape, dt)
                        specs[decl.name] = P(
                            AXIS, *([None] * (len(gshape) - 1)))

    # dense host reference: y[t] = gate * expert_e(x[t]) in float64
    x64 = x.astype(np.float64)
    want = np.zeros((n * t, d), np.float64)
    for e in range(n):
        sel = expert == e
        h = _gelu(x64[sel] @ w1[e].astype(np.float64))
        want[sel] = gate[sel, None] * (h @ w2[e].astype(np.float64))
    # expected cast to the workload dtype (ADVICE r2) so a bf16 config
    # compares bf16-vs-bf16; callers comparing a non-f32 config must
    # choose tolerances to match (~0.4% relative at bf16)
    return bufs, specs, want.astype(dt)
