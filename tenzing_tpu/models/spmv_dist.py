"""Distributed SpMV over a device mesh: row-partitioned band matrix with
neighbor halo exchange over ICI.

Parity target: reference ``RowPartSpmv`` (row_part_spmv.cuh:105-445) — the root
partitions the matrix by rows, splits local vs remote columns, and negotiates
per-rank send/recv lists; the schedule then overlaps the remote-x exchange with
the local SpMV (ops_spmv.cuh:306-436 dataflow).

TPU-native redesign: the mesh has axes ``("dp", "sp")`` — ``sp`` shards matrix
rows and the x block (the reference's row partition), ``dp`` shards a batch of
right-hand sides (data parallelism the reference gets by running ranks
independently).  For a band matrix with half-bandwidth < block size, every remote
column lives in an adjacent ``sp`` shard, so the irregular send/recv negotiation
(row_part_spmv.cuh:259-423) collapses to two static neighbor ``ppermute`` steps —
the idiomatic ICI realization; each shard's gather indices are precomputed
host-side into sharded index slabs (the analog of the reference's device
scatter-index buffer).  The post/wait split survives as schedulable ops: the
exchanges are DeviceOps on searchable lanes, so the solver decides how they
overlap with the local SpMV.

Graph shape (matches the reference compound, ops_spmv.cuh:394-417):
  start -> {spmv_local, exchange_left, exchange_right}
  {exchange_left, exchange_right} -> spmv_halo
  {spmv_local, spmv_halo} -> y_add -> finish
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import CompoundOp, DeviceOp
from tenzing_tpu.models.spmv import CooMat, CsrMat, random_band_matrix


class ExchangeLeft(DeviceOp):
    """Receive the left neighbor's x block (shard p gets shard p-1's block);
    edge shard receives zeros.  A static neighbor permute over ICI."""

    def reads(self):
        return ["X"]

    def writes(self):
        return ["x_left"]

    def apply(self, bufs, ctx):
        import jax

        n = jax.lax.axis_size("sp")
        perm = [(i, i + 1) for i in range(n - 1)]
        return {"x_left": jax.lax.ppermute(bufs["X"], "sp", perm)}


class ExchangeRight(DeviceOp):
    """Receive the right neighbor's x block (shard p gets shard p+1's block)."""

    def reads(self):
        return ["X"]

    def writes(self):
        return ["x_right"]

    def apply(self, bufs, ctx):
        import jax

        n = jax.lax.axis_size("sp")
        perm = [(i + 1, i) for i in range(n - 1)]
        return {"x_right": jax.lax.ppermute(bufs["X"], "sp", perm)}


class SpMVLocalShard(DeviceOp):
    """Y_loc = local-slab SpMV against the owned x block."""

    def reads(self):
        return ["X", "A_loc_vals", "A_loc_cols"]

    def writes(self):
        return ["Y_loc"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        lv, lc, x = bufs["A_loc_vals"], bufs["A_loc_cols"], bufs["X"]
        return {"Y_loc": jnp.einsum("rw,brw->br", lv, x[:, lc])}


class SpMVHaloShard(DeviceOp):
    """Y_rem = halo-slab SpMV against [x_left ++ x_right] (remote columns)."""

    def reads(self):
        return ["x_left", "x_right", "A_rem_vals", "A_rem_cols"]

    def writes(self):
        return ["Y_rem"]

    def apply(self, bufs, ctx):
        import jax.numpy as jnp

        halo = jnp.concatenate([bufs["x_left"], bufs["x_right"]], axis=1)
        rv, rc = bufs["A_rem_vals"], bufs["A_rem_cols"]
        return {"Y_rem": jnp.einsum("rw,brw->br", rv, halo[:, rc])}


class AddShards(DeviceOp):
    """Y = Y_loc + Y_rem (the reference's VectorAdd, implemented)."""

    def reads(self):
        return ["Y_loc", "Y_rem"]

    def writes(self):
        return ["Y"]

    def apply(self, bufs, ctx):
        return {"Y": bufs["Y_loc"] + bufs["Y_rem"]}


class DistSpMV(CompoundOp):
    def __init__(self, name: str = "dist_spmv"):
        super().__init__(name)

    def graph(self) -> Graph:
        g = Graph()
        loc = SpMVLocalShard("spmv_local")
        exl = ExchangeLeft("exchange_left")
        exr = ExchangeRight("exchange_right")
        halo = SpMVHaloShard("spmv_halo")
        add = AddShards("y_add")
        g.start_then(loc)
        g.start_then(exl)
        g.start_then(exr)
        g.then(exl, halo)
        g.then(exr, halo)
        g.then(loc, add)
        g.then(halo, add)
        g.then_finish(add)
        return g


def make_dist_spmv_buffers(
    n_sp: int,
    batch: int = 8,
    rows_per_shard: int = 256,
    nnz_per_row: int = 8,
    seed: int = 0,
) -> Tuple[Dict[str, np.ndarray], Dict[str, object], np.ndarray]:
    """Build (buffers, partition specs, expected Y) for a mesh with ``n_sp`` row
    shards.  The global band matrix has half-bandwidth < rows_per_shard so all
    remote columns are in adjacent shards (reference RowPartSpmv setup,
    row_part_spmv.cuh:159-444, done here with host-side sharding math)."""
    from jax.sharding import PartitionSpec as P

    m = n_sp * rows_per_shard
    bw = max(1, rows_per_shard // 2)
    a = random_band_matrix(m, bw, nnz_per_row * m, seed=seed)

    # per-shard local/halo slabs, padded to a common width
    loc_slabs, rem_slabs = [], []
    for p in range(n_sp):
        lo, hi = p * rows_per_shard, (p + 1) * rows_per_shard
        rows = a.retain_rows(lo, hi)
        lv_r, lv_c, lv_v = [], [], []
        rv_r, rv_c, rv_v = [], [], []
        for i in range(rows.m):
            for j in range(rows.indptr[i], rows.indptr[i + 1]):
                c = int(rows.cols[j])
                if lo <= c < hi:
                    lv_r.append(i); lv_c.append(c - lo); lv_v.append(rows.vals[j])
                elif c < lo:  # left neighbor block -> halo slot [0, B)
                    slot = c - (lo - rows_per_shard)
                    rv_r.append(i); rv_c.append(slot); rv_v.append(rows.vals[j])
                else:  # right neighbor block -> halo slot [B, 2B)
                    slot = rows_per_shard + (c - hi)
                    rv_r.append(i); rv_c.append(slot); rv_v.append(rows.vals[j])
        loc_slabs.append(
            CooMat(rows.m, rows_per_shard, np.array(lv_r, dtype=np.int64),
                   np.array(lv_c, dtype=np.int64),
                   np.array(lv_v, dtype=np.float32)).to_csr()
        )
        rem_slabs.append(
            CooMat(rows.m, 2 * rows_per_shard, np.array(rv_r, dtype=np.int64),
                   np.array(rv_c, dtype=np.int64),
                   np.array(rv_v, dtype=np.float32)).to_csr()
        )
    wl = max(1, max(s.row_widths().max(initial=0) for s in loc_slabs))
    wr = max(1, max(s.row_widths().max(initial=0) for s in rem_slabs))
    lv = np.concatenate([s.to_slab(wl)[0] for s in loc_slabs])
    lc = np.concatenate([s.to_slab(wl)[1] for s in loc_slabs])
    rv = np.concatenate([s.to_slab(wr)[0] for s in rem_slabs])
    rc = np.concatenate([s.to_slab(wr)[1] for s in rem_slabs])

    rng = np.random.default_rng(seed + 1)
    X = rng.random((batch, m), dtype=np.float32)
    want = np.stack([a.matvec(X[b]) for b in range(batch)])

    bufs = {
        "X": X,
        "A_loc_vals": lv,
        "A_loc_cols": lc.astype(np.int32),
        "A_rem_vals": rv,
        "A_rem_cols": rc.astype(np.int32),
        "x_left": np.zeros_like(X),
        "x_right": np.zeros_like(X),
        "Y_loc": np.zeros_like(X),
        "Y_rem": np.zeros_like(X),
        "Y": np.zeros_like(X),
    }
    specs = {
        "X": P("dp", "sp"),
        "A_loc_vals": P("sp", None),
        "A_loc_cols": P("sp", None),
        "A_rem_vals": P("sp", None),
        "A_rem_cols": P("sp", None),
        "x_left": P("dp", "sp"),
        "x_right": P("dp", "sp"),
        "Y_loc": P("dp", "sp"),
        "Y_rem": P("dp", "sp"),
        "Y": P("dp", "sp"),
    }
    return bufs, specs, want
