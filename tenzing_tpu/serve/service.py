"""The in-process schedule service: warm / query / merge / stats.

Composes the serving pieces into the one object a host embeds (and the
``python -m tenzing_tpu.serve`` CLI wraps, serve/__main__.py):

* ``warm`` — mine recorded search databases (``bench.py --dump-csv``
  corpora) into the store under the corpus workload's fingerprint:
  per-file in-file paired ratios against the row-0 naive anchor (the
  same regime-honest ranking bench/recorded.py warm-starts from), top-k
  distinct winners by ``canonical_key`` equivalence, sha256 source
  digests in provenance.  Optionally trains the PR-2 surrogate on the
  same corpus (the near tier's pricing model) and stamps driver-JSON
  verdict provenance onto the warmed entries.
* ``query`` — tiered resolution (serve/resolver.py).
* ``merge`` — combine independently-warmed stores (commutative,
  idempotent — serve/store.py).
* ``stats`` — store + queue occupancy for dashboards and the corpus
  report CLI (``python -m tenzing_tpu.obs.report --store``).

The service never opens a device: warm deserializes and featurizes
against the driver's device-free graphs
(:func:`~tenzing_tpu.bench.driver.graph_for`), and resolution is
store/model arithmetic.  Measurement happens only when a driver drains
the cold-request work queue.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

from tenzing_tpu.bench.driver import DriverRequest, graph_for, metric_for
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.serve.fingerprint import fingerprint_of, schedule_key
from tenzing_tpu.serve.resolver import Resolution, Resolver
from tenzing_tpu.serve.store import ScheduleStore, WorkQueue, open_store


def default_model_path(store_path: str) -> str:
    """Where ``warm --train`` saves the surrogate next to its store —
    one convention shared by the CLI and the service so a warmed store
    directory is self-contained.  Works for both backends: a trailing
    separator on a segmented store *directory* is stripped so the model
    lands beside the store, never hidden inside it."""
    return store_path.rstrip(os.sep).rstrip("/") + ".model.json"


class ScheduleService:
    """See module docstring.  ``model_path`` defaults next to the store;
    an existing model loads eagerly (the near tier needs it), a missing
    one leaves near-miss resolution disabled until ``warm(train=True)``
    creates it.

    Point ``model_path`` only at a surrogate trained with the SAME
    device-free ``nbytes`` map resolution featurizes with — i.e. one
    ``warm(train=True)`` produced.  A model from ``bench.py
    --learn-train`` on a TPU host was trained against real device-buffer
    sizes; for workloads where :func:`~tenzing_tpu.bench.driver.
    graph_for` returns an empty map (full-size halo), its comm-bytes and
    makespan features would be systematically shifted at predict time,
    miscalibrating the near tier's uncertainty gate (the train/predict
    feature contract, learn/train.py)."""

    def __init__(self, store_path: str, queue_dir: Optional[str] = None,
                 model_path: Optional[str] = None, tenant: str = "local",
                 verify: bool = True, near_max_sigma: float = 0.75,
                 log: Optional[Callable[[str], None]] = None):
        self._log = log
        # .json paths open the legacy monolithic store; anything else
        # opens the segmented store (serve/store.py open_store — one
        # dispatch rule for every entry point)
        self.store = open_store(store_path, tenant=tenant, log=log)
        self.queue = WorkQueue(queue_dir) if queue_dir else None
        self.verify = verify
        self.model_path = model_path or default_model_path(store_path)
        self.model = self._load_model()
        self.resolver = Resolver(self.store, queue=self.queue,
                                 model=self.model, verify=verify,
                                 near_max_sigma=near_max_sigma, log=log)

    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def _load_model(self):
        if not os.path.exists(self.model_path):
            return None
        from tenzing_tpu.learn import FEATURE_NAMES, RidgeEnsemble

        return RidgeEnsemble.load(self.model_path,
                                  expect_features=list(FEATURE_NAMES))

    # -- warm ----------------------------------------------------------------
    def warm(self, req: DriverRequest, csv_globs: List[str],
             bench_globs: Optional[List[str]] = None, topk: int = 3,
             train: bool = True) -> Dict[str, Any]:
        """Mine recorded corpora for ``req``'s workload into the store
        (see module docstring); returns a summary dict."""
        from tenzing_tpu.bench.recorded import scored_rows

        tr = get_tracer()
        paths = sorted(p for pat in csv_globs for p in _glob.glob(pat))
        fp = fingerprint_of(req)
        graph, nbytes = graph_for(req)
        with tr.span("serve.warm", workload=req.workload,
                     n_files=len(paths)):
            # THE shared admission/ranking rule (bench/recorded.py):
            # the serving corpus and the search's warm-start loader can
            # never drift on which recorded rows count
            scored, stats = scored_rows(paths, graph, log=self._note)
            seen: set = set()
            added = rejected = 0
            verifier = None
            for ratio, pct50, seq, path in scored:
                if added >= topk:
                    break
                key = schedule_key(seq)
                if key in seen:
                    continue
                seen.add(key)
                # ADMISSION-TIME verification (docs/serving.md): verify
                # once, here, under this fingerprint's graph — the exact
                # tier then serves the stamped record with zero per-query
                # verifier invocations.  An unsound row is stored flagged
                # (visible in stats/report, never served, never counted
                # against topk) — the PR-7 never-serve-unsound guarantee
                # moves to the door instead of being re-proved per query.
                verified = None
                if self.verify:
                    if verifier is None:
                        from tenzing_tpu.verify import ScheduleVerifier

                        verifier = ScheduleVerifier(graph)
                    verified = bool(verifier(seq).ok)
                    if not verified:
                        get_metrics().counter(
                            "serve.admission.unsound").inc()
                        self._note(f"serve: admission rejected unsound "
                                   f"{key[:8]} from "
                                   f"{os.path.basename(path)} — stored "
                                   "flagged, never served")
                        self.store.add(fp, seq, pct50_us=pct50 * 1e6,
                                       vs_naive=ratio, source=path,
                                       verified=False)
                        rejected += 1
                        continue
                    get_metrics().counter("serve.admission.verified").inc()
                self.store.add(fp, seq, pct50_us=pct50 * 1e6,
                               vs_naive=ratio, source=path,
                               verified=verified)
                added += 1
            summary: Dict[str, Any] = {
                "workload": req.workload, "exact": fp.exact_digest,
                "bucket": fp.bucket_digest, "files": stats["files"],
                "rows": stats["rows"], "candidates": len(scored),
                "added": added,
                "admission": {"verified": added if self.verify else None,
                              "rejected_unsound": rejected},
            }
            if bench_globs:
                summary["driver_provenance"] = self._stamp_driver_jsons(
                    req, fp, bench_globs)
            if train:
                summary["model"] = self._train(req, paths, graph, nbytes)
            self.store.flush()
        get_metrics().counter("serve.warmed").inc(added)
        return summary

    def _stamp_driver_jsons(self, req: DriverRequest, fp,
                            bench_globs: List[str]) -> Dict[str, Any]:
        """Attach driver-JSON verdict provenance (vs_baseline, the
        result-integrity gate's ``verified`` stamp) to the warmed
        fingerprint — the store records not just what the corpus says
        but what the last full driver runs concluded."""
        from tenzing_tpu.obs.report import load_driver_json

        metric = metric_for(req.workload, req)
        matched = 0
        best_vs = None
        verified = None
        for pat in bench_globs:
            for path in sorted(_glob.glob(pat)):
                try:
                    d = load_driver_json(path)
                except (OSError, ValueError):
                    continue
                if d.get("metric") != metric:
                    continue
                matched += 1
                vs = d.get("vs_baseline")
                if vs is not None and (best_vs is None or vs > best_vs):
                    best_vs = vs
                    verified = (d.get("fault") or {}).get("verified")
        out = {"matched": matched, "best_vs_baseline": best_vs,
               "verified": verified}
        rec = self.store.best(fp.exact_digest)
        if rec is not None and matched:
            rec.setdefault("provenance", {})["driver"] = out
        return out

    def _train(self, req: DriverRequest, paths: List[str], graph,
               nbytes) -> Dict[str, Any]:
        """Train the near tier's surrogate on the warmed corpus through
        THE shared recipe (learn/train.py — the same call behind
        ``bench.py --learn-train``), with this workload's device-free
        ``nbytes`` map so train-time and resolve-time features agree by
        construction."""
        from tenzing_tpu.learn import train_from_corpus

        model, info = train_from_corpus(paths, graph, nbytes=nbytes,
                                        log=self._note)
        if model is None:
            return info
        # warm trains before the store's first flush creates the
        # directory — the model save must not trip over it either
        os.makedirs(os.path.dirname(os.path.abspath(self.model_path)),
                    exist_ok=True)
        model.save(self.model_path)
        self.model = model
        self.resolver.model = model
        return {"path": self.model_path, "rows": info["rows"],
                "train_spearman": info["train_spearman"]}

    # -- query / merge / stats ----------------------------------------------
    def query(self, req: DriverRequest,
              fp_key: Optional[tuple] = None) -> Resolution:
        """Tiered resolution.  ``fp_key`` (the verbatim request-kwargs
        tuple, :func:`~tenzing_tpu.serve.resolver.fp_cache_key`) seeds
        the fingerprint cache and the lock-free fast path for callers
        that have the raw kwargs (the listen loop)."""
        return self.resolver.resolve(req, fp_key=fp_key)

    def merge(self, other_path: str) -> Dict[str, Any]:
        other = ScheduleStore(other_path, log=self._note)
        n = self.store.merge_from(other)
        self.store.flush()
        return {"merged_records": n, "from": other_path,
                "records": len(self.store)}

    def stats(self) -> Dict[str, Any]:
        out = {"store": self.store.stats(),
               "model": (self.model_path
                         if os.path.exists(self.model_path) else None)}
        if self.queue is not None:
            # full queue stats (serve/store.py WorkQueue.stats): depth by
            # reason plus the drain-daemon protocol state — the torn set
            # (visible rot, never silently dropped), live leases with
            # heartbeat ages, and the poison quarantine
            out["queue"] = self.queue.stats()
        return out
