"""The hardened drain daemon: leased cold-queue claims, crash-resume,
poison quarantine — the serve→search→serve loop's missing half.

The resolver's cold tier enqueues checkpointed
:class:`~tenzing_tpu.bench.driver.DriverRequest` work items
(serve/store.py ``WorkQueue``); this daemon drains them through
``bench/driver.py:run`` and re-warms the
:class:`~tenzing_tpu.serve.store.ScheduleStore` from the resulting
recorded database, so the next query of the same fingerprint answers
exact-tier with zero compiles (docs/serving.md "Drain daemon").  It is
built to survive the failure modes a long-lived multi-worker service
actually meets — crashes, hangs, rival workers, and malformed requests —
with the PR-3 fault machinery applied at the queue granularity:

* **Leased claims** (serve/lease.py — THE shared lease protocol, also
  guarding the segment compactor) — a worker claims ``work-<exact>.json``
  by atomically publishing ``lease-<exact>.json`` (payload written to a
  private temp file, then hard-linked into place: exactly one of any
  number of rivals succeeds, the rest see ``FileExistsError`` and move
  on).  A heartbeat thread renews the lease's **mtime**; a lease whose
  mtime is older than the TTL is *expired* and reclaimed by atomic
  rename (again: exactly one contender wins the rename), so a SIGKILLed
  worker's item is never lost and two daemons on one queue never
  double-run an item.  The renewal checks the lease inode — a worker
  that lost its lease to a reclaim (e.g. after a long stall) kills its
  own drain instead of double-running.
* **Crash-resume** — each item is drained under its suggested
  ``SearchCheckpoint`` directory (``ckpt-<exact>/``): the measurement
  journal is appended as each measurement lands, so a killed daemon's
  successor resumes mid-search with zero re-measurement, exactly like
  ``bench.py --resume``.
* **Classified failure handling** — a failed drain is classified by
  :func:`~tenzing_tpu.fault.errors.classify_error`: transients retry
  through the shared :func:`~tenzing_tpu.fault.backoff.retry_call`
  (bounded, backed off, each retry a ``fault.retry`` event); a per-item
  watchdog timeout kills a hung drain (the subprocess runner enforces it
  with SIGKILL); ``device_lost`` stops the daemon (no queue can drain on
  a dead device).  **Deterministic** failures accumulate in a persistent
  ``fail-<exact>.json`` sidecar, and after ``max_failures`` of them the
  item is moved to the **poison quarantine** (``poison-<exact>.json``,
  the failure history inside) — one malformed request can never wedge
  the queue forever.  Unknown child deaths lean deterministic, the same
  asymmetry fault/errors.py documents: mis-poisoning costs one
  quarantined item (still visible, still replayable by hand),
  mis-retrying costs a failing drain per pass, forever.
* **Exactly-once effect** — the item and its lease are deleted only
  *after* the store merge lands (``ScheduleStore.flush`` is commutative
  and flock-serialized, so concurrent re-warms are safe).  A crash
  between merge and delete re-drains the item, but the resume journal
  answers its measurements and the merge is idempotent — the effect on
  the store is exactly-once even when the drain is at-least-once.

It is a real daemon: graceful SIGTERM/SIGINT (the in-flight child is
interrupted so it checkpoints, the lease is released, the status file is
stamped ``interrupted``), ``--once`` / ``--max-items`` / ``--idle-exit``
modes for CI, a heartbeat/status JSON (``status-<owner>.json``) for
liveness probes, and full ``daemon.*`` telemetry
(claimed/completed/retried/poisoned/reclaimed counters, ``daemon.drain``
spans, queue-depth and lease-age gauges — docs/observability.md).

Run it::

    python -m tenzing_tpu.serve.daemon --queue QDIR --store STORE.json

The default runner drains each item in a **subprocess** (the same
interpreter, ``--exec-item``): the watchdog can actually kill a hang,
a ``smoke`` item's process-global CPU pinning cannot leak into the next
item, and a SIGKILL of the daemon's process group takes the drain down
with it (no orphan measuring behind a reclaimed lease).  ``--in-process``
trades all that for zero process overhead (tests, embedded drains).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tenzing_tpu.fault.backoff import BackoffPolicy, retry_call
from tenzing_tpu.fault.checkpoint import (
    FENCE_ENV,
    atomic_write_json,
    read_checked_json,
)
from tenzing_tpu.fault.errors import (
    DeterministicScheduleError,
    DeviceLostError,
    FaultClass,
    FencedWriteError,
    MeasurementTimeout,
    StoreReadonlyError,
    TransientError,
    classify_error,
    is_transient_io,
    is_unwritable_io,
)
from tenzing_tpu.obs import context as obs_context
from tenzing_tpu.obs.metrics import MetricsSnapshotWriter, get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.serve.lease import LeaseFile
from tenzing_tpu.serve.store import (
    WorkQueue,
    mark_store_unwritable,
    probe_store_writable,
    store_readonly,
)
from tenzing_tpu.utils.atomic import atomic_dump_json

STATUS_VERSION = 1
FAIL_VERSION = 1
# set (to the daemon's choice) when the daemon itself traces: the drain
# child reads it and archives its own bundle under the item's checkpoint
# directory, the third leg of the stitched fleet trace
TRACE_CHILD_ENV = "TENZING_TRACE_CHILD"
# a long-lived daemon visits items forever; every in-memory / on-disk
# accumulation is bounded (consumers only ever read the tail anyway)
HISTORY_CAP = 200
FAIL_ATTEMPT_CAP = 50


class _Interrupted(BaseException):
    """Control flow only: the daemon was asked to stop mid-drain (the
    child has checkpointed and died); never a failure verdict."""


class _LeaseLost(BaseException):
    """Control flow only: the heartbeat found our lease reclaimed (or
    gone) — the item belongs to someone else now; abandon it without
    merging and without releasing what is no longer ours."""


def drain_checkpoint_of(payload: Dict[str, Any], item_path: str) -> str:
    """The item's checkpoint directory: the enqueue-time suggestion, or
    (for hand-written items that lack one) the queue's own convention
    next to the item file."""
    ckpt = payload.get("checkpoint")
    if ckpt:
        return ckpt
    return os.path.join(os.path.dirname(os.path.abspath(item_path)),
                        f"ckpt-{WorkQueue.exact_of(item_path)}")


def drain_csv_path(ckpt_dir: str) -> str:
    """Where the drain's recorded database lands (the re-warm source)."""
    return os.path.join(ckpt_dir, "drain.csv")


def drain_verdict_path(ckpt_dir: str) -> str:
    """Where the drain's driver-JSON verdict lands (merge provenance,
    and the child→parent error report on failure)."""
    return os.path.join(ckpt_dir, "verdict.json")


def parse_override(spec: str) -> tuple:
    """``key=value`` → (key, typed value): values parse as JSON when they
    can (``8`` → int, ``true`` → bool, ``null`` → None) and stay strings
    otherwise — the same forgiving rule for the CLI and work-item tests."""
    if "=" not in spec:
        raise ValueError(f"override {spec!r} is not key=value")
    key, _, raw = spec.partition("=")
    try:
        return key, json.loads(raw)
    except ValueError:
        return key, raw


def apply_overrides(request: Dict[str, Any],
                    overrides: Optional[Dict[str, Any]]):
    """The item's request with budget overrides applied, **identity
    guarded**: an override may change search budgets (``mcts_iters``,
    ``climb_budget``, …) but must not change what the request *is* — the
    merged record is keyed by the original request's fingerprint, so an
    override that moves the fingerprint would warm the wrong slot.
    Returns the effective :class:`DriverRequest`."""
    from tenzing_tpu.bench.driver import DriverConfigError, DriverRequest

    known = {f.name for f in dataclasses.fields(DriverRequest)}
    req_d = dict(request)
    for k, v in (overrides or {}).items():
        if k not in known:
            raise DriverConfigError(f"unknown override field {k!r}")
        req_d[k] = v
    req = DriverRequest(**req_d)
    if overrides:
        from tenzing_tpu.serve.fingerprint import fingerprint_of

        try:
            base_digest = fingerprint_of(DriverRequest(**request)).exact_digest
            new_digest = fingerprint_of(req).exact_digest
        except DriverConfigError:
            raise
        except Exception:
            # identity not computable here (e.g. a malformed workload):
            # let run() raise its own config error, classified normally
            return req
        if base_digest != new_digest:
            raise DriverConfigError(
                "override changes the request fingerprint "
                f"({base_digest} -> {new_digest}); budget fields only")
    return req


def exec_item(payload: Dict[str, Any], item_path: str,
              overrides: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """THE drain step: ``run(DriverRequest(**item["request"]))`` under
    the item's checkpoint directory, resuming from any journal a
    previous (killed) drain left, dumping the recorded database the
    re-warm mines.  Returns the driver verdict dict; raises a classified
    error on failure (a backend-init verdict — the tunnel is down — is a
    :class:`TransientError`, not an answer)."""
    # adopt the originating query's trace context — the envelope copy
    # first (SIGKILL-survivable: a successor daemon re-reads it from
    # disk), the env var as the live-parent fallback — as the process
    # default, so every span the drive emits (any thread) links back to
    # the query.  Restored on the way out: the in-process runner drains
    # many items in one process, and item N's context must not bleed
    # into item N+1.
    ctx = (obs_context.from_json(payload.get("trace"))
           or obs_context.from_env())
    prev_ctx = obs_context.set_process_default(ctx) if ctx is not None \
        else None
    try:
        return _exec_item(payload, item_path, overrides)
    finally:
        if ctx is not None:
            obs_context.set_process_default(prev_ctx)


def _exec_item(payload: Dict[str, Any], item_path: str,
               overrides: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    from tenzing_tpu.bench.driver import run

    req = apply_overrides(payload["request"], overrides)
    ckpt = drain_checkpoint_of(payload, item_path)
    os.makedirs(ckpt, exist_ok=True)
    req.checkpoint = ckpt
    if os.environ.get(TRACE_CHILD_ENV) and not req.trace_out:
        # the tracing daemon asked its children to archive their own
        # bundles: one directory per item, next to the drain journal
        req.trace_out = os.path.join(ckpt, "trace")
    # resume iff a previous drain already journaled state there: the
    # successor of a SIGKILLed worker replays every landed measurement
    # instead of re-paying the device (fault/checkpoint.py)
    req.resume = (os.path.exists(os.path.join(ckpt, "measurements.jsonl"))
                  or os.path.exists(os.path.join(ckpt, "state.json")))
    if not req.dump_csv:
        req.dump_csv = drain_csv_path(ckpt)
    verdict = run(req).verdict
    if "error" in verdict:
        raise TransientError(verdict["error"])
    return verdict


def _exec_item_main(item_path: str, out_path: str,
                    overrides: Optional[Dict[str, Any]]) -> int:
    """The subprocess entry (``--exec-item``): drain one item, write the
    verdict (or a classified error report) to ``out_path``.  Exit 0 on
    success; 3 on failure — the parent reads the report and re-raises the
    class, so the daemon's retry/poison policy never depends on parsing
    stderr."""
    # the report write retries transients in-process (same shared-backoff
    # rule as store and checkpoint writes): this is the child's ONLY way
    # to tell the parent what happened, a fresh child replays the same
    # injected-fault schedule, and "exited with no error report" is
    # classified deterministic — a drained item would poison on a
    # bounded write burst after the work already succeeded
    def report(doc: Dict[str, Any]) -> None:
        retry_call(
            lambda: atomic_dump_json(out_path, doc, prefix=".verdict."),
            policy=BackoffPolicy(retries=4, base_secs=0.05, factor=2.0,
                                 max_secs=0.5),
            retry_on=is_transient_io, where="serve.drain.report")

    try:
        payload = read_checked_json(item_path)
        verdict = exec_item(payload, item_path, overrides)
    except BaseException as e:
        report({
            "error": str(e)[:2000],
            "error_class": classify_error(e),
            "error_type": type(e).__name__,
        })
        return 3
    report(verdict)
    return 0


@dataclass
class DaemonOpts:
    """Knobs of one :class:`DrainDaemon` (CLI flags map 1:1)."""

    queue_dir: str
    store_path: str
    owner: str = ""                  # default: <host>-<pid>
    tenant: str = "daemon"
    lease_ttl_secs: float = 60.0     # mtime older than this = expired
    heartbeat_secs: float = 5.0      # lease renewal + status rewrite
    poll_secs: float = 2.0           # queue re-scan interval when idle
    item_timeout_secs: Optional[float] = 3600.0  # per-attempt watchdog
    retries: int = 2                 # transient retries per item visit
    backoff_base_secs: float = 1.0
    max_failures: int = 3            # deterministic failures before poison
    stop_grace_secs: float = 20.0    # SIGINT→SIGKILL window on shutdown
    once: bool = False               # one scan pass, then exit
    max_items: Optional[int] = None  # stop after draining this many
    idle_exit_secs: Optional[float] = None  # exit after idling this long
    topk: int = 3                    # winners admitted per re-warm
    train: bool = False              # retrain the near-tier surrogate
    in_process: bool = False         # no subprocess, no hard watchdog
    status_path: Optional[str] = None  # default: <queue>/status-<owner>.json
    model_path: Optional[str] = None
    handle_signals: bool = True      # SIGTERM/SIGINT graceful stop
    overrides: Dict[str, Any] = field(default_factory=dict)
    # enable tracing and write this daemon's JSONL bundle here on exit;
    # drain children then archive their own bundles under each item's
    # ckpt-<exact>/trace/ (the stitched fleet trace's second/third legs)
    trace_out: Optional[str] = None
    metrics_ring: int = 8            # metric-snapshot ring per owner


class DrainDaemon:
    """See module docstring.  ``runner(item_path, payload, timeout)`` is
    injectable for tests; the default is the subprocess runner (or the
    in-process one under ``opts.in_process``)."""

    def __init__(self, opts: DaemonOpts,
                 runner: Optional[Callable] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.opts = opts
        self.owner = opts.owner or f"{socket.gethostname()}-{os.getpid()}"
        self.queue = WorkQueue(opts.queue_dir)
        self._log_fn = log
        self._runner = runner or (self._run_in_process if opts.in_process
                                  else self._run_subprocess)
        self.status_path = opts.status_path or os.path.join(
            opts.queue_dir, f"status-{self.owner}.json")
        # streaming metric snapshots next to the status doc (bounded
        # ring, obs/metrics.py) — written on every status rewrite, read
        # by the report CLI's --follow fleet view
        self._snapshots = MetricsSnapshotWriter(
            os.path.dirname(os.path.abspath(self.status_path)), self.owner,
            ring=opts.metrics_ring)
        self.counters: Dict[str, int] = {
            k: 0 for k in ("claimed", "completed", "retried", "poisoned",
                           "reclaimed", "released", "failed_transient",
                           "failed_deterministic", "lease_lost", "fenced",
                           "store_unwritable", "signals")}
        self.history: List[Dict[str, Any]] = []
        self.device_lost = False
        self.started_at = time.time()
        self._stop = threading.Event()
        self._lease_lost = threading.Event()
        self._lease: Optional[LeaseFile] = None
        self._child: Optional[subprocess.Popen] = None
        self._depth = 0
        self._prev_handlers: Dict[int, Any] = {}

    def _log(self, msg: str) -> None:
        if self._log_fn is not None:
            self._log_fn(msg)
        else:
            sys.stderr.write(f"daemon[{self.owner}]: {msg}\n")

    # -- lease protocol (serve/lease.py — THE shared implementation) ---------
    def _claim(self, exact: str) -> Optional[str]:
        """Claim ``exact``'s item; None when a rival holds a fresh lease
        or wins either race (serve/lease.py for the protocol)."""
        lease = LeaseFile(self.queue.lease_path_for(exact), self.owner,
                          ttl_secs=self.opts.lease_ttl_secs)
        info = lease.claim(extra={"exact": exact})
        if info is None:
            return None
        if info.reclaimed:
            self.counters["reclaimed"] += 1
            get_metrics().counter("daemon.reclaimed").inc()
            tr = get_tracer()
            if tr.enabled:
                tr.event("daemon.reclaim", exact=exact,
                         prev_owner=info.prev_owner, age_s=info.age_s)
            self._log(f"reclaimed expired lease for {exact[:12]} "
                      f"(owner {info.prev_owner}, {info.age_s:.1f}s stale)")
        self._lease = lease
        self._lease_lost.clear()
        self.counters["claimed"] += 1
        get_metrics().counter("daemon.claimed").inc()
        return lease.path

    def _renew(self, lease: str) -> bool:
        """Heartbeat: renew the claim's mtime (serve/lease.py — nonce
        re-read, never an inode check).  A failed renew means a rival
        reclaimed it during a stall; flag it so the drain aborts instead
        of double-running."""
        lf = self._lease
        if lf is None or lf.path != lease or not lf.renew():
            self._lease_lost.set()
            return False
        return True

    def _release(self, lease: str) -> None:
        """Release the claim iff still ours — the grab-inspect-release
        discipline lives in :meth:`LeaseFile.release`; a rival's live
        lease is restored, never deleted."""
        lf = self._lease
        if lf is None or lf.path != lease:
            return
        if lf.release():
            self.counters["released"] += 1
        self._lease = None

    # -- status / liveness ---------------------------------------------------
    def _write_status(self, state: str,
                      item: Optional[Dict[str, Any]] = None) -> None:
        doc = {
            "version": STATUS_VERSION,
            "owner": self.owner,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "started_at": self.started_at,
            "heartbeat_at": time.time(),
            "uptime_s": round(time.time() - self.started_at, 1),
            "state": state,
            "item": item,
            "queue_depth": self._depth,
            "counters": dict(self.counters),
            # the read-only degradation latch (serve/store.py): non-None
            # while claims are paused because store writes cannot land
            "store_readonly": store_readonly(self.opts.store_path),
            # bounded per-item drain economics, mined by the report CLI
            "history": self.history[-20:],
        }
        try:
            atomic_dump_json(self.status_path, doc, prefix=".status.")
        except OSError as e:
            self._log(f"status write failed ({e})")
        try:
            self._snapshots.write(state=state, extra={
                "counters": dict(self.counters),
                "queue_depth": self._depth,
                "uptime_s": round(time.time() - self.started_at, 1)})
        except OSError as e:
            self._log(f"metrics snapshot failed ({e})")

    # -- failure history / poison -------------------------------------------
    def _load_fail_doc(self, exact: str) -> Dict[str, Any]:
        try:
            with open(self.queue.fail_path_for(exact)) as f:
                doc = json.load(f)
            if doc.get("version") != FAIL_VERSION:
                return {}
            return doc
        except (OSError, ValueError):
            return {}

    def _load_failures(self, exact: str) -> List[Dict[str, Any]]:
        return list(self._load_fail_doc(exact).get("attempts", []))

    def _record_failure(self, exact: str, exc: BaseException,
                        error_class: str) -> int:
        """Append one failed drain to the persistent sidecar; returns the
        deterministic-failure count so far (the poison trigger).  The
        attempt list keeps only the newest ``FAIL_ATTEMPT_CAP`` entries —
        a transient-failing item is revisited every poll, forever — but
        the deterministic count persists separately so trimming can never
        reset poison progress."""
        doc = self._load_fail_doc(exact)
        attempts = list(doc.get("attempts", []))
        det = doc.get("det_count")
        if det is None:  # pre-det_count sidecar: recover from the list
            det = sum(1 for a in attempts
                      if a.get("error_class") == FaultClass.DETERMINISTIC)
        if error_class == FaultClass.DETERMINISTIC:
            det += 1
        attempts.append({
            "at": time.time(),
            "owner": self.owner,
            "error": type(exc).__name__,
            "error_class": error_class,
            "message": str(exc)[:500],
        })
        try:
            atomic_dump_json(self.queue.fail_path_for(exact), {
                "version": FAIL_VERSION, "exact": exact, "det_count": det,
                "attempts": attempts[-FAIL_ATTEMPT_CAP:],
            }, prefix=".fail.")
        except OSError as e:
            # a full/hostile filesystem must not turn a failure *record*
            # into a daemon crash — the item stays queued either way; the
            # only cost is poison progress not advancing this visit
            if is_unwritable_io(e):
                mark_store_unwritable(self.opts.store_path, e)
            self._log(f"failure sidecar write failed for {exact[:12]} ({e})")
        return det

    def _poison(self, item_path: str, payload: Dict[str, Any],
                exact: str) -> None:
        """Move the item to the poison quarantine: the original payload
        plus its whole failure history, in the same digest-checked
        envelope, then remove item + sidecar so the queue never offers
        it again (the scan also skips items with a poison marker)."""
        attempts = self._load_failures(exact)
        atomic_write_json(self.queue.poison_path_for(exact), {
            "kind": "poisoned_request",
            "exact": exact,
            "reason": payload.get("reason"),
            "fingerprint": payload.get("fingerprint"),
            "request": payload.get("request"),
            "checkpoint": payload.get("checkpoint"),
            "attempts": attempts,
            "poisoned_by": self.owner,
            "poisoned_at": time.time(),
        })
        for p in (item_path, self.queue.fail_path_for(exact)):
            try:
                os.unlink(p)
            except OSError:
                pass
        self.counters["poisoned"] += 1
        get_metrics().counter("daemon.poisoned").inc()
        tr = get_tracer()
        if tr.enabled:
            tr.event("daemon.poison", exact=exact, attempts=len(attempts))
        self._log(f"poisoned {exact[:12]} after {len(attempts)} failed "
                  f"attempt(s)")

    # -- runners -------------------------------------------------------------
    def _run_in_process(self, item_path: str, payload: Dict[str, Any],
                        timeout: Optional[float]) -> Dict[str, Any]:
        """No subprocess, no hard watchdog (a hung in-process drain
        cannot be killed — the resilient layer's per-measurement
        watchdog, ``measure_timeout`` on the request, is the only hang
        bound here).  The production path is the subprocess runner."""
        fence = self._fence_token()
        prev = os.environ.get(FENCE_ENV)
        if fence is not None:
            os.environ[FENCE_ENV] = fence
        try:
            return exec_item(payload, item_path, self.opts.overrides)
        finally:
            if fence is not None:
                if prev is None:
                    os.environ.pop(FENCE_ENV, None)
                else:
                    os.environ[FENCE_ENV] = prev

    def _fence_token(self) -> Optional[str]:
        """``<lease-path>:<epoch>`` for the current claim, or None when
        the claim stands unfenced (registry write failed — serve/lease.py
        degrades to nonce checks).  Exported to the drain runner so the
        checkpoint journal refuses a zombie's late appends
        (fault/checkpoint.py ``FENCE_ENV``)."""
        lf = self._lease
        if lf is None or lf.epoch is None:
            return None
        return f"{lf.path}:{lf.epoch}"

    def _run_subprocess(self, item_path: str, payload: Dict[str, Any],
                        timeout: Optional[float]) -> Dict[str, Any]:
        """Drain in a child interpreter (``--exec-item``): the watchdog
        SIGKILLs a hang, a graceful daemon stop SIGINTs the child (its
        driver trap checkpoints + stamps ``interrupted``), and the child
        shares our process group so a SIGKILL of the daemon's group
        cannot orphan a drain behind a reclaimable lease."""
        ckpt = drain_checkpoint_of(payload, item_path)
        os.makedirs(ckpt, exist_ok=True)
        out = drain_verdict_path(ckpt)
        try:
            os.unlink(out)
        except OSError:
            pass
        cmd = [sys.executable, "-m", "tenzing_tpu.serve.daemon",
               "--exec-item", item_path, "--verdict-out", out]
        for k, v in self.opts.overrides.items():
            cmd += ["--override", f"{k}={json.dumps(v)}"]
        log_path = os.path.join(ckpt, "drain.log")
        deadline = (time.time() + timeout) if timeout else None
        # the child inherits the item's trace context via the
        # environment (obs/context.py TRACE_ENV; the envelope's `trace`
        # key is the redundant, SIGKILL-survivable copy) and — when this
        # daemon traces — the ask to archive its own bundle
        env = obs_context.to_env(
            dict(os.environ),
            obs_context.from_json(payload.get("trace")))
        if self.opts.trace_out:
            env[TRACE_CHILD_ENV] = "1"
        fence = self._fence_token()
        if fence is not None:
            # the child's checkpoint journal checks our lease epoch on
            # every append: if a rival fences us mid-drain, the zombie
            # child's late writes die there instead of landing stale
            env[FENCE_ENV] = fence
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(cmd, stdout=log_f, stderr=log_f,
                                    env=env)
            self._child = proc
            try:
                rc = self._wait_child(proc, deadline)
            finally:
                self._child = None
        if rc == 0:
            with open(out) as f:
                return json.load(f)
        if rc < 0:
            if self._stop.is_set():
                raise _Interrupted()
            raise TransientError(
                f"drain child died with signal {-rc} (see {log_path})")
        if self._stop.is_set():
            # our SIGINT may have landed before the child's driver trap
            # was armed (it dies through the generic KeyboardInterrupt
            # path, rc != 0) — a stop is never a failure verdict
            raise _Interrupted()
        try:
            with open(out) as f:
                report = json.load(f)
        except (OSError, ValueError):
            # the child crashed before it could report: unknown leans
            # deterministic (fault/errors.py) — poison is bounded and
            # visible, an unbounded retry loop is neither
            raise DeterministicScheduleError(
                f"drain child exited rc={rc} with no error report "
                f"(see {log_path})")
        msg = f"{report.get('error_type', 'Error')}: {report.get('error')}"
        cls = report.get("error_class")
        if cls == FaultClass.TRANSIENT:
            raise TransientError(msg)
        if cls == FaultClass.DEVICE_LOST:
            raise DeviceLostError(msg)
        raise DeterministicScheduleError(msg)

    def _wait_child(self, proc: subprocess.Popen,
                    deadline: Optional[float]) -> int:
        interrupted_at = None
        while True:
            try:
                return proc.wait(timeout=0.25)
            except subprocess.TimeoutExpired:
                pass
            if self._lease_lost.is_set():
                proc.kill()
                proc.wait()
                raise _LeaseLost()
            if self._stop.is_set():
                if interrupted_at is None:
                    interrupted_at = time.time()
                    # graceful: the child's driver trap checkpoints +
                    # stamps interrupted, then the process dies (SIG_DFL)
                    proc.send_signal(signal.SIGINT)
                elif time.time() - interrupted_at > self.opts.stop_grace_secs:
                    proc.kill()
            elif deadline is not None and time.time() > deadline:
                # the per-item watchdog: a hung drain (stuck collective,
                # dead tunnel that never errors) is killed and classified
                # transient — the retry gets a fresh dispatch and the
                # journal keeps everything already measured
                proc.kill()
                proc.wait()
                raise MeasurementTimeout(
                    f"drain exceeded {self.opts.item_timeout_secs}s watchdog")

    # -- merge ---------------------------------------------------------------
    def _merge(self, item_path: str, payload: Dict[str, Any],
               verdict: Dict[str, Any]) -> int:
        """Re-warm the store from the drain's recorded database + verdict
        provenance — the same admission rule as ``serve warm``
        (bench/recorded.py ``scored_rows``), so a drained answer and a
        hand-warmed one can never disagree about what counts.  Returns
        the number of records admitted."""
        from tenzing_tpu.serve.service import ScheduleService

        req = apply_overrides(payload["request"], self.opts.overrides)
        ckpt = drain_checkpoint_of(payload, item_path)
        # the override-applied request decides where the drain dumped its
        # database (exec_item honors the same overrides) — the raw item
        # request may name a different, never-written path
        csv = req.dump_csv or drain_csv_path(ckpt)
        svc = ScheduleService(self.opts.store_path, queue_dir=None,
                              model_path=self.opts.model_path,
                              tenant=self.opts.tenant, log=self._log_fn)
        summary = svc.warm(req, [csv],
                           bench_globs=[drain_verdict_path(ckpt)],
                           topk=self.opts.topk, train=self.opts.train)
        return int(summary.get("added", 0))

    # -- one item ------------------------------------------------------------
    def _journal_lines(self, ckpt_dir: str) -> int:
        try:
            with open(os.path.join(ckpt_dir, "measurements.jsonl")) as f:
                return sum(1 for line in f if line.strip())
        except OSError:
            return 0

    def _drain_one(self, item_path: str, payload: Dict[str, Any],
                   lease: str) -> str:
        """Drain one claimed item end to end; returns the outcome tag.
        Raises :class:`_Interrupted` through (the run loop stops)."""
        exact = self.queue.exact_of(item_path)
        ckpt = drain_checkpoint_of(payload, item_path)
        prior = self._journal_lines(ckpt)
        t0 = time.time()
        attempts = {"n": 1}
        hb_stop = threading.Event()

        def heartbeat():
            while not hb_stop.wait(self.opts.heartbeat_secs):
                self._renew(lease)
                self._write_status("draining", item={
                    "exact": exact, "path": item_path,
                    "since": t0, "attempts": attempts["n"]})

        hb = threading.Thread(target=heartbeat, name="daemon-heartbeat",
                              daemon=True)
        hb.start()
        self._write_status("draining", item={"exact": exact,
                                             "path": item_path, "since": t0})
        outcome, merged, err = "completed", 0, None
        try:
            def on_retry(e, attempt, delay):
                # `attempt` is the 0-based index of the attempt that just
                # failed; the invocation about to run is number attempt+2
                attempts["n"] = attempt + 2
                self.counters["retried"] += 1
                get_metrics().counter("daemon.retried").inc()
                self._log(f"retrying {exact[:12]} after transient "
                          f"({type(e).__name__}: {str(e)[:120]})")

            verdict = retry_call(
                lambda: self._runner(item_path, payload,
                                     self.opts.item_timeout_secs),
                policy=BackoffPolicy(retries=self.opts.retries,
                                     base_secs=self.opts.backoff_base_secs),
                where="daemon.drain", on_retry=on_retry)
            # the epoch fence: if a rival reclaimed us during a stall
            # (coarse/skewed mtimes can make our lease look expired while
            # our own clock says it is fresh), the registry holds a newer
            # epoch and this raises — the stale merge never starts
            if self._lease is not None and self._lease.path == lease:
                self._lease.check_fence()
            merged = self._merge(item_path, payload, verdict)
            # the merge has landed (flushed under the store flock):
            # ONLY NOW may item + sidecar + lease disappear — a crash
            # before this line re-drains, a crash after loses nothing
            for p in (item_path, self.queue.fail_path_for(exact)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            if self._lease is not None and self._lease.path == lease:
                # effects landed: retire the fencing epochs so the
                # registry stays bounded (serve/lease.py EPOCH_KEEP)
                self._lease.purge_epochs()
            self.counters["completed"] += 1
            get_metrics().counter("daemon.completed").inc()
            self._log(f"completed {exact[:12]} ({merged} record(s) merged, "
                      f"{time.time() - t0:.1f}s)")
        except _Interrupted:
            outcome = "interrupted"
            raise
        except KeyboardInterrupt:
            # an in-process drain's Ctrl-C is a stop request, never a
            # failure verdict (the classifier would call it deterministic)
            outcome = "interrupted"
            self._stop.set()
            raise _Interrupted() from None
        except _LeaseLost:
            outcome = "lease_lost"
            self.counters["lease_lost"] += 1
            self._log(f"lease for {exact[:12]} reclaimed by a rival — "
                      "abandoning (no merge)")
        except FencedWriteError as e:
            # a rival holds a newer epoch: we are the zombie the fence
            # exists for.  Abandon without merging, without a failure
            # record (the item is in better hands, never evidence
            # against the request), and without releasing a lease that
            # is no longer ours
            outcome = "fenced"
            self.counters["fenced"] += 1
            get_metrics().counter("daemon.fenced").inc()
            self._log(f"fenced on {exact[:12]}: {e} — abandoning (no merge)")
        except BaseException as e:
            err = e
            if isinstance(e, StoreReadonlyError) or is_unwritable_io(e):
                # the store cannot take the merge (ENOSPC/EROFS/quota):
                # latch read-only and leave the item queued — NOT a
                # failure of the request, so no fail sidecar, no poison
                # progress; the run loop pauses claims until a probe
                # write succeeds
                outcome = "store_unwritable"
                self.counters["store_unwritable"] += 1
                mark_store_unwritable(self.opts.store_path, e)
                get_metrics().counter("daemon.store_unwritable").inc()
                self._log(f"store unwritable on {exact[:12]} ({e}) — "
                          "pausing claims until writable")
                return outcome
            if not os.path.exists(item_path):
                # a rival completed + deleted the item between our queue
                # scan and this drain (the lease was already gone, so the
                # claim looked fresh) — the failure is an artifact of
                # draining a ghost, never evidence against the request
                outcome = "vanished"
                self._log(f"item {exact[:12]} vanished mid-drain "
                          "(completed by a rival) — abandoning")
                return outcome
            cls = classify_error(e)
            if cls == FaultClass.DEVICE_LOST:
                outcome = "device_lost"
                self.device_lost = True
                self._record_failure(exact, e, cls)
                self._log(f"device lost draining {exact[:12]}: {e}")
                self._stop.set()
            elif cls == FaultClass.TRANSIENT:
                # retries exhausted: leave the item for a later pass /
                # another worker; the journal keeps what already landed
                outcome = "transient"
                self.counters["failed_transient"] += 1
                self._record_failure(exact, e, cls)
                self._log(f"transient drain failure on {exact[:12]} "
                          f"(retries exhausted): {e}")
            else:
                outcome = "failed"
                self.counters["failed_deterministic"] += 1
                n_det = self._record_failure(exact, e, cls)
                get_metrics().counter("daemon.failed").inc()
                self._log(f"deterministic drain failure {n_det}/"
                          f"{self.opts.max_failures} on {exact[:12]}: {e}")
                if n_det >= self.opts.max_failures:
                    self._poison(item_path, payload, exact)
                    outcome = "poisoned"
        finally:
            hb_stop.set()
            hb.join(timeout=5.0)
            if outcome not in ("lease_lost", "fenced"):
                # fenced = a rival holds a newer claim under our old
                # name: what's on disk is theirs, not ours to delete
                self._release(lease)
            else:
                self._lease = None
            after = self._journal_lines(ckpt)
            self.history.append({
                "exact": exact,
                "outcome": outcome,
                "wall_s": round(time.time() - t0, 3),
                "attempts": attempts["n"],
                "journal_lines_prior": prior,
                "journal_lines_after": after,
                "resumed": prior > 0,
                "merged": merged,
                **({"error": f"{type(err).__name__}: {str(err)[:200]}"}
                   if err is not None else {}),
                "ended_at": time.time(),
            })
            del self.history[:-HISTORY_CAP]
        return outcome

    # -- main loop -----------------------------------------------------------
    def _observe_queue(self) -> List:
        items = self.queue.items()
        self._depth = len(items)
        reg = get_metrics()
        reg.gauge("daemon.queue_depth").set(float(len(items)))
        # queue age: how long the oldest still-queued item has waited —
        # the fleet-sizing signal (depth alone hides a stuck old item
        # behind a churning queue)
        now = time.time()
        ages = []
        for path, _ in items:
            try:
                ages.append(now - os.path.getmtime(path))
            except OSError:
                pass  # claimed + deleted mid-scan
        reg.gauge("daemon.item_age_s").set(
            round(max(ages), 3) if ages else 0.0)
        leases = self.queue.leases()
        if leases:
            reg.gauge("daemon.lease_age_s").set(
                max(l["age_s"] for l in leases))
        return items

    def stop(self) -> None:
        """Ask the daemon to stop after the in-flight item checkpoints
        (the programmatic twin of SIGTERM)."""
        self._stop.set()

    def _on_signal(self, signum, frame) -> None:
        self.counters["signals"] += 1
        self._stop.set()
        if self.counters["signals"] >= 2 and self._child is not None:
            # second signal: the operator means NOW
            try:
                self._child.kill()
            except OSError:
                pass

    def _install_signals(self) -> None:
        if not self.opts.handle_signals:
            return
        if threading.current_thread() is not threading.main_thread():
            return  # embedded in a worker thread: caller drives stop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev_handlers[sig] = signal.signal(sig, self._on_signal)

    def _restore_signals(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (OSError, ValueError):
                pass
        self._prev_handlers.clear()

    def run(self) -> Dict[str, Any]:
        """Drain until stopped (or ``--once`` / ``--max-items`` /
        ``--idle-exit`` says done); returns the summary dict the CLI
        prints as its one JSON line."""
        self._install_signals()
        if self.opts.trace_out:
            from tenzing_tpu.obs.tracer import configure

            configure(enabled=True)
        tr = get_tracer()
        drained = 0
        idle_since: Optional[float] = None
        interrupted = False
        self._write_status("idle")
        try:
            while not self._stop.is_set():
                if store_readonly(self.opts.store_path) is not None:
                    # degraded read-only: merges cannot land, so claiming
                    # would only churn leases and burn drain work.  Pause
                    # (visible in the status doc) and probe each poll —
                    # the latch clears itself the moment a write lands.
                    if not probe_store_writable(self.opts.store_path):
                        self._observe_queue()
                        self._write_status("paused")
                        self._stop.wait(self.opts.poll_secs)
                        if self.opts.once:
                            break
                        continue
                    self._log("store writable again — resuming claims")
                    # rewrite the status doc NOW: the paused doc (with
                    # its latch block) is what keeps store_unwritable
                    # firing, and an idle daemon may not write another
                    # status until it exits
                    self._write_status("idle")
                items = self._observe_queue()
                processed = progressed = 0
                for path, payload in items:
                    if self._stop.is_set():
                        break
                    if (self.opts.max_items is not None
                            and drained >= self.opts.max_items):
                        self._stop.set()
                        break
                    exact = self.queue.exact_of(path)
                    if os.path.exists(self.queue.poison_path_for(exact)):
                        continue  # quarantined: never re-claimed
                    lease = self._claim(exact)
                    if lease is None:
                        continue
                    if not os.path.exists(path):
                        # completed + deleted by a rival after our scan:
                        # the fresh-looking claim was for a ghost
                        self._release(lease)
                        continue
                    processed += 1
                    try:
                        # the item's trace context (stamped at enqueue
                        # by the query that went cold) is ambient for
                        # the whole drain: the daemon.drain span, the
                        # store merge, and — via env + envelope — the
                        # subprocess's own spans all carry its trace_id
                        with obs_context.use(
                                obs_context.from_json(
                                    payload.get("trace"))), \
                                tr.span("daemon.drain", exact=exact,
                                        owner=self.owner) as sp:
                            outcome = self._drain_one(path, payload, lease)
                            sp.set("outcome", outcome)
                    except _Interrupted:
                        interrupted = True
                        break
                    if outcome in ("completed", "poisoned"):
                        drained += 1
                        progressed += 1
                if self.opts.once:
                    break
                if processed:
                    idle_since = None
                    if progressed:
                        continue  # more work may have arrived while draining
                    # every visit failed (transient exhaustion, lost
                    # leases): wait a poll before re-claiming the same
                    # items, or a down device turns into a spawn spin
                    self._stop.wait(self.opts.poll_secs)
                    continue
                if idle_since is None:
                    idle_since = time.time()
                if (self.opts.idle_exit_secs is not None
                        and time.time() - idle_since
                        >= self.opts.idle_exit_secs):
                    self._log(f"idle for {self.opts.idle_exit_secs}s — "
                              "exiting")
                    break
                self._stop.wait(self.opts.poll_secs)
        finally:
            interrupted = interrupted or (self._stop.is_set()
                                          and self.counters["signals"] > 0)
            state = "interrupted" if interrupted else "stopped"
            self._observe_queue()
            self._write_status(state)
            self._restore_signals()
            if self.opts.trace_out:
                from tenzing_tpu.obs.export import write_jsonl

                try:
                    write_jsonl(tr, self.opts.trace_out)
                    self._log(f"trace bundle: {self.opts.trace_out}")
                except OSError as e:
                    self._log(f"trace bundle failed ({e})")
        return {
            "owner": self.owner,
            "state": state,
            "drained": drained,
            "queue_depth": self._depth,
            "counters": dict(self.counters),
            "status": self.status_path,
        }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.serve.daemon",
        description="Drain the cold-request work queue through "
                    "bench/driver.py:run and re-warm the schedule store "
                    "(docs/serving.md 'Drain daemon').")
    ap.add_argument("--queue", metavar="DIR",
                    help="work-queue directory (serve/store.py WorkQueue)")
    ap.add_argument("--store", metavar="PATH",
                    help="schedule store JSON to re-warm")
    ap.add_argument("--owner", default=None,
                    help="worker id for leases/status (default host-pid)")
    ap.add_argument("--tenant", default="daemon",
                    help="provenance tenant for re-warmed records")
    ap.add_argument("--once", action="store_true",
                    help="one queue pass, then exit")
    ap.add_argument("--max-items", type=int, default=None,
                    help="stop after draining (completing/poisoning) N items")
    ap.add_argument("--idle-exit", type=float, default=None, metavar="SECS",
                    help="exit after the queue stays empty this long")
    ap.add_argument("--poll", type=float, default=2.0, metavar="SECS",
                    help="queue re-scan interval when idle")
    ap.add_argument("--lease-ttl", type=float, default=60.0, metavar="SECS",
                    help="lease heartbeat age after which a rival may "
                         "reclaim the claim")
    ap.add_argument("--heartbeat", type=float, default=5.0, metavar="SECS",
                    help="lease-renewal / status-write interval")
    ap.add_argument("--item-timeout", type=float, default=3600.0,
                    metavar="SECS",
                    help="per-attempt drain watchdog (0 disables)")
    ap.add_argument("--retries", type=int, default=2,
                    help="bounded transient retries per item visit")
    ap.add_argument("--max-failures", type=int, default=3,
                    help="deterministic failures before poison quarantine")
    ap.add_argument("--topk", type=int, default=3,
                    help="winners admitted into the store per drain")
    ap.add_argument("--train", action="store_true",
                    help="retrain the near-tier surrogate on each re-warm")
    ap.add_argument("--in-process", action="store_true",
                    help="drain in this process (no hard watchdog; "
                         "see docs/serving.md)")
    ap.add_argument("--status", default=None, metavar="PATH",
                    help="status JSON path (default "
                         "<queue>/status-<owner>.json)")
    ap.add_argument("--model", default=None, metavar="PATH",
                    help="surrogate model path for --train "
                         "(default <store>.model.json)")
    ap.add_argument("--override", action="append", default=[],
                    metavar="K=V",
                    help="request-budget override applied to every drained "
                         "item (e.g. mcts_iters=8); identity fields refuse")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="enable tracing; write this daemon's telemetry "
                         "JSONL bundle here on exit (drain children "
                         "archive theirs under each item's ckpt dir) — "
                         "stitch with python -m tenzing_tpu.obs.export")
    # the subprocess entry — not for operators (the daemon spawns it)
    ap.add_argument("--exec-item", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--verdict-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    try:
        overrides = dict(parse_override(s) for s in args.override)
    except ValueError as e:
        ap.error(str(e))
    if args.exec_item:
        if not args.verdict_out:
            ap.error("--exec-item requires --verdict-out")
        return _exec_item_main(args.exec_item, args.verdict_out, overrides)
    if not args.queue or not args.store:
        ap.error("--queue and --store are required")
    opts = DaemonOpts(
        queue_dir=args.queue, store_path=args.store,
        owner=args.owner or "", tenant=args.tenant,
        lease_ttl_secs=args.lease_ttl, heartbeat_secs=args.heartbeat,
        poll_secs=args.poll,
        item_timeout_secs=args.item_timeout or None,
        retries=args.retries, max_failures=args.max_failures,
        once=args.once, max_items=args.max_items,
        idle_exit_secs=args.idle_exit, topk=args.topk, train=args.train,
        in_process=args.in_process, status_path=args.status,
        model_path=args.model, overrides=overrides,
        trace_out=args.trace_out)
    daemon = DrainDaemon(opts)
    summary = daemon.run()
    sys.stdout.write(json.dumps(summary) + "\n")
    # device loss is the one terminal verdict: the queue cannot drain on
    # a dead device, so the exit code tells the supervisor not to just
    # restart into the same wall
    return 1 if daemon.device_lost else 0


if __name__ == "__main__":
    sys.exit(main())
