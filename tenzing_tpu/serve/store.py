"""The persistent schedule store + the cold-request work queue.

**Store file** (one JSON document, written atomically via
utils/atomic.py — the same tmp+fsync+rename discipline as the checkpoint
state and the quarantine, docs/serving.md):

``{"version": 1, "entries": {<exact-digest>: {<schedule-key>: record}}}``

A **record** is schema-versioned (``"schema"``) and carries everything a
resolution needs without re-deriving: the fingerprint document, the
winning sequence's serialized ops, its measured ``pct50_us`` and
``vs_naive`` (the in-file paired ratio against the corpus's own naive
anchor — regime-honest, bench/recorded.py), a provenance block (tenant,
source file, fidelity), the sha256 digests of the source corpus files,
and mutable ``flags`` (e.g. ``needs_refinement``, stamped by the
resolver's near-miss tier).

**Merge** is commutative and idempotent by construction: records union
by ``(exact-digest, schedule-key)``; a conflict resolves by a *total
order* on records (higher ``vs_naive``, then lower ``pct50_us``, then
the lexicographically larger canonical serialization — no tie can
survive), while ``sources`` union and ``flags`` OR sticky.  Stores
warmed on independent hosts/CI runs therefore combine without loss in
either merge order (tests/test_serve_store.py asserts commutativity and
idempotence literally).

**Durability**: loads tolerate damage the way the quarantine does — a
corrupt store file is *quarantined* (renamed to ``<path>.corrupt-<id>``)
and reported, never fatal, and never silently clobbered by the next
flush (read-only callers pass ``quarantine_corrupt=False`` to report
without renaming); an individual record that fails validation is
skipped with a note.  ``flush()`` re-reads the file and merges before
writing, the whole read-merge-rename serialized under an advisory
``flock`` on ``<path>.lock`` — concurrent writers, interleaved or
simultaneous, land a merged superset.

**Schema evolution**: ``RECORD_SCHEMA`` stamps every record;
:func:`migrate_record` upgrades older schemas in place on load (schema 1
predates ``sources``/``flags``), and a record from a *newer* schema than
this code is skipped loudly rather than mis-read.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tenzing_tpu.fault.backoff import BackoffPolicy, retry_call
from tenzing_tpu.fault.checkpoint import atomic_write_json, read_checked_json
from tenzing_tpu.fault.errors import is_transient_io, is_unwritable_io
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer, short_digest
from tenzing_tpu.utils.atomic import atomic_dump_json

STORE_VERSION = 1
RECORD_SCHEMA = 2

Record = Dict[str, Any]

# -- read-only degradation latch --------------------------------------------
# One process-wide latch per store path (abspath-keyed): when a durable
# store write dies on the unwritable errno family (ENOSPC/EDQUOT/EROFS —
# fault/errors.py), the serve plane degrades instead of thrashing: the
# listen loop keeps answering exact from the sealed cache and sheds
# cold/near with reason "store_readonly", the drain daemon pauses claims
# instead of accumulating bogus poison verdicts, and reqlog counts-and-
# drops.  The latch clears on any successful write — a real flush or an
# explicit probe (docs/robustness.md "Disaster recovery").
_READONLY: Dict[str, Dict[str, Any]] = {}
_READONLY_LOCK = threading.Lock()

# transient-EIO policy for durable store writes: a flaky-disk write
# retries through THE shared backoff (fault/backoff.py) on a millisecond
# timescale; the unwritable family never retries (space does not come
# back between attempts), it latches
_IO_RETRY = BackoffPolicy(retries=2, base_secs=0.05, factor=4.0,
                          max_secs=0.5, jitter=0.25)


def _store_key(path: str) -> str:
    return os.path.abspath(path)


def mark_store_unwritable(path: str, exc: BaseException) -> Dict[str, Any]:
    """Latch ``path``'s store read-only (idempotent; first trip counts
    ``serve.store.readonly_trips`` and stamps the latch doc)."""
    key = _store_key(path)
    with _READONLY_LOCK:
        doc = _READONLY.get(key)
        if doc is None:
            doc = {
                "reason": "store_readonly",
                "errno": getattr(exc, "errno", None),
                "error": f"{type(exc).__name__}: {str(exc)[:200]}",
                "since": time.time(),
            }
            _READONLY[key] = doc
            get_metrics().counter("serve.store.readonly_trips").inc()
            get_metrics().gauge("serve.store.readonly").set(1.0)
            tr = get_tracer()
            if tr.enabled:
                tr.event("serve.store.readonly", store=key,
                         error=doc["error"])
    return doc


def store_readonly(path: Optional[str]) -> Optional[Dict[str, Any]]:
    """The latch doc when ``path``'s store is degraded read-only, else
    None.  Read by the resolver's cold/near gates, the daemon's pause
    loop, and every status/report surface."""
    if path is None:
        return None
    with _READONLY_LOCK:
        return _READONLY.get(_store_key(path))


def clear_store_unwritable(path: str) -> bool:
    """Drop the latch (a write landed / a probe succeeded); True iff it
    was set."""
    with _READONLY_LOCK:
        doc = _READONLY.pop(_store_key(path), None)
    if doc is not None:
        get_metrics().gauge("serve.store.readonly").set(0.0)
        tr = get_tracer()
        if tr.enabled:
            tr.event("serve.store.writable", store=_store_key(path))
    return doc is not None


def probe_store_writable(path: str) -> bool:
    """Attempt one tiny durable write next to the store (through the
    same atomic seam real writes use, so chaos governs it too); clears
    the latch and returns True on success.  The listen heartbeat and the
    paused daemon poll this — the ``store_unwritable`` alert resolves
    when it starts succeeding."""
    if path.endswith(".json") and not os.path.isdir(path):
        probe = path + ".probe"
    else:
        probe = os.path.join(path, ".probe.json")
    try:
        atomic_dump_json(probe, {"probe_at": time.time()}, prefix=".probe.")
    except OSError:
        return False
    try:
        os.unlink(probe)
    except OSError:
        pass
    clear_store_unwritable(path)
    return True


def guarded_store_write(store_path: Optional[str], fn,
                        where: str = "serve.store.write"):
    """Run one durable store write: transient I/O errors (EIO family)
    retry through THE shared fault/backoff.py; the unwritable family
    latches the store read-only and re-raises; success clears any
    latch.  Every segment/manifest/monolithic flush funnels through
    here (serve/segments.py too)."""
    try:
        out = retry_call(fn, policy=_IO_RETRY, retry_on=is_transient_io,
                         where=where)
    except OSError as e:
        if store_path is not None and is_unwritable_io(e):
            mark_store_unwritable(store_path, e)
        raise
    if store_path is not None and store_readonly(store_path) is not None:
        clear_store_unwritable(store_path)
    return out


def file_digest(path: str) -> str:
    """sha256 hex of a source corpus file — the provenance link from a
    store record back to the bytes it was mined from."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def migrate_record(rec: Record) -> Optional[Record]:
    """Upgrade ``rec`` to :data:`RECORD_SCHEMA`; None when it cannot be
    trusted (newer schema, or missing the identity fields no default can
    supply)."""
    if not isinstance(rec, dict):
        return None
    schema = rec.get("schema", 1)
    if schema > RECORD_SCHEMA:
        return None
    for key in ("exact", "bucket", "key", "ops", "workload"):
        if key not in rec:
            return None
    out = dict(rec)
    if schema < 2:
        # schema 1 predates multi-tenant merge provenance
        out.setdefault("sources", [])
        out.setdefault("flags", {})
        out.setdefault("provenance", {})
    out["schema"] = RECORD_SCHEMA
    return out


def _order_key(rec: Record) -> Tuple:
    """The total order merge resolves conflicts by: best record wins,
    deterministically in either merge order."""
    return (
        float(rec.get("vs_naive") or 0.0),
        -float(rec.get("pct50_us") or float("inf")),
        # canonical serialization as the final tiebreak: NO pair of
        # distinct records compares equal, so max() is order-independent
        json.dumps(rec, sort_keys=True),
    )


def merge_records(a: Record, b: Record) -> Record:
    """One (exact, key) slot's merge: the better record by
    :func:`_order_key`, with ``sources`` unioned, ``flags`` ORed sticky
    (a refinement flag set by either tenant survives), and provenance
    keys the winner lacks filled from the loser — a driver-verdict stamp
    (service.py ``warm --bench``) must survive merging with an unstamped
    twin of the same schedule.  Winner precedence keeps this commutative:
    which record is "winner" depends only on the pair, not the order."""
    win, lose = (a, b) if _order_key(a) >= _order_key(b) else (b, a)
    winner = dict(win)
    winner["provenance"] = {**lose.get("provenance", {}),
                            **win.get("provenance", {})}
    winner["sources"] = sorted(
        set(a.get("sources", [])) | set(b.get("sources", [])))
    # the admission-time verification stamp ORs sticky, exactly like a
    # flag: two spellings of one (exact, key) slot name the SAME schedule
    # under the SAME deterministic graph, so a verdict from either host
    # covers both (docs/serving.md "Admission-time verification")
    if a.get("verified_at_admission") or b.get("verified_at_admission"):
        winner["verified_at_admission"] = True
    flags: Dict[str, bool] = {}
    for src in (a.get("flags", {}), b.get("flags", {})):
        for k, v in src.items():
            # boolean OR — commutative by construction, so merge order
            # cannot change the outcome (flags are sticky booleans)
            flags[k] = bool(flags.get(k, False) or v)
    winner["flags"] = dict(sorted(flags.items()))
    return winner


class ScheduleStore:
    """In-memory store view, optionally file-backed (see module
    docstring).  ``tenant`` stamps the provenance of records added
    through this instance; merged records keep their original tenants."""

    def __init__(self, path: Optional[str] = None, tenant: str = "local",
                 log: Optional[Callable[[str], None]] = None,
                 quarantine_corrupt: bool = True,
                 _count_metrics: bool = True):
        self.path = path
        self.tenant = tenant
        self._log = log
        # False = read-only callers (the report CLI): an unreadable file
        # is reported but LEFT IN PLACE — renaming evidence aside is the
        # serving process's prerogative, not a diagnostics command's
        self.quarantine_corrupt = quarantine_corrupt
        # False = flush()'s throwaway re-read: bookkeeping, not a real
        # load — counting it would inflate serve.store.loaded by the
        # full record count on every flush
        self._count_metrics = _count_metrics
        self.entries: Dict[str, Dict[str, Record]] = {}
        self.skipped = 0  # records dropped by validation/migration on load
        # bumped on every record landing (_put: load, add, merge) — the
        # resolver's exact-tier cache keys its validity on this, so a
        # merge can never serve a stale cached answer
        self.generation = 0
        if path is not None and os.path.exists(path):
            self._load(path)

    # -- loading ------------------------------------------------------------
    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def _load(self, path: str) -> None:
        try:
            with open(path) as f:
                doc = json.load(f)
            if doc.get("version") != STORE_VERSION:
                raise ValueError(
                    f"store version {doc.get('version')!r} != "
                    f"{STORE_VERSION}")
            entries = doc["entries"]
            if not isinstance(entries, dict):
                raise ValueError("entries is not an object")
        except Exception as e:
            if not self.quarantine_corrupt:
                self._note(f"store: unreadable {path} "
                           f"({type(e).__name__}: {e}); left in place")
                return
            # quarantine, don't clobber: the damaged bytes move aside for
            # post-mortem and the next flush starts a fresh file — losing
            # a store to corruption is recoverable (re-warm), silently
            # overwriting evidence is not
            quarantined = f"{path}.corrupt-{short_digest(str(e))[:8]}"
            try:
                os.replace(path, quarantined)
                self._note(f"store: quarantined unreadable {path} -> "
                           f"{quarantined} ({type(e).__name__}: {e})")
            except OSError:
                self._note(f"store: unreadable {path} "
                           f"({type(e).__name__}: {e})")
            get_metrics().counter("serve.store.quarantined").inc()
            return
        n = 0
        for exact, by_key in entries.items():
            if not isinstance(by_key, dict):
                # structurally malformed slot (valid JSON, wrong shape):
                # skip it like a bad record — construction must stay
                # never-fatal so flush()'s re-read (under the flock),
                # the CLI, and the report all survive a damaged file
                self.skipped += 1
                self._note(f"store: skipped malformed slot {exact[:8]}")
                continue
            for key, rec in by_key.items():
                mig = migrate_record(rec)
                if mig is None:
                    self.skipped += 1
                    schema = (rec.get("schema")
                              if isinstance(rec, dict) else type(rec).__name__)
                    self._note(f"store: skipped record {exact[:8]}/{key[:8]} "
                               f"(schema {schema!r})")
                    continue
                self._put(mig)
                n += 1
        if self._count_metrics:
            get_metrics().counter("serve.store.loaded").inc(n)

    # -- writing ------------------------------------------------------------
    def _put(self, rec: Record) -> Record:
        slot = self.entries.setdefault(rec["exact"], {})
        prev = slot.get(rec["key"])
        slot[rec["key"]] = rec if prev is None else merge_records(prev, rec)
        self.generation += 1
        return slot[rec["key"]]

    def add(self, fingerprint, seq, pct50_us: float, vs_naive: float,
            source: Optional[str] = None, fidelity: str = "full",
            extra_provenance: Optional[Dict[str, Any]] = None,
            verified: Optional[bool] = None) -> Record:
        """Record ``seq`` (a Sequence) as a winner for ``fingerprint``.
        ``source`` is the corpus file it was mined from (digested into
        ``sources``).  ``verified`` is the **admission-time** soundness
        verdict (docs/serving.md): ``True`` stamps
        ``verified_at_admission`` (the exact tier serves it with zero
        per-query verifier invocations), ``False`` flags the record
        ``unsound`` (stored for visibility, never served, never cached),
        ``None`` leaves it unstamped (the resolver verifies lazily,
        once)."""
        from tenzing_tpu.bench.benchmarker import schedule_id
        from tenzing_tpu.core.serdes import sequence_to_json
        from tenzing_tpu.serve.fingerprint import schedule_key

        prov: Dict[str, Any] = {"tenant": self.tenant, "fid": fidelity}
        if source is not None:
            prov["source"] = os.path.basename(source)
        if extra_provenance:
            prov.update(extra_provenance)
        rec: Record = {
            "schema": RECORD_SCHEMA,
            "workload": fingerprint.workload,
            "exact": fingerprint.exact_digest,
            "bucket": fingerprint.bucket_digest,
            "fingerprint": fingerprint.to_json(),
            "key": schedule_key(seq),
            "sid": schedule_id(seq),
            "ops": sequence_to_json(seq),
            "pct50_us": float(pct50_us),
            "vs_naive": float(vs_naive),
            "provenance": prov,
            "sources": ([file_digest(source)]
                        if source is not None and os.path.exists(source)
                        else []),
            "flags": {},
        }
        if verified is True:
            rec["verified_at_admission"] = True
        elif verified is False:
            rec["flags"]["unsound"] = True
        get_metrics().counter("serve.store.added").inc()
        return self._put(rec)

    def flag(self, exact: str, key: str, **flags: Any) -> None:
        """Set sticky flags on a record (e.g. ``needs_refinement=True``
        from the resolver's near-miss tier) and persist — but only when
        something actually changed: a hot near-tier fingerprint re-flags
        on every query, and an already-set flag must not pay the full
        read-merge-fsync-rename cycle per request."""
        rec = self.entries.get(exact, {}).get(key)
        if rec is None:
            return
        cur = rec.setdefault("flags", {})
        if all(cur.get(k) == v for k, v in flags.items()):
            return
        cur.update(flags)
        # a flag mutation changes what may be served (unsound above
        # all): the resolver's exact cache must see it as a new
        # generation, same as a record landing
        self.generation += 1
        self.flush()

    # -- queries ------------------------------------------------------------
    def __len__(self) -> int:
        return sum(len(v) for v in self.entries.values())

    def records(self) -> List[Record]:
        return [r for by_key in self.entries.values()
                for r in by_key.values()]

    def best(self, exact: str) -> Optional[Record]:
        """The best record for an exact fingerprint digest, by the same
        total order merge resolves with — resolution and merge can never
        disagree about which schedule a fingerprint serves."""
        slot = self.entries.get(exact)
        if not slot:
            return None
        return max(slot.values(), key=_order_key)

    def exact_records(self, exact: str) -> List[Record]:
        """ALL records under an exact digest, best-first — the exact
        tier walks this so one unsound/unresolvable best record cannot
        permanently block a sound runner-up (resolver.py)."""
        slot = self.entries.get(exact)
        if not slot:
            return []
        return sorted(slot.values(), key=_order_key, reverse=True)

    def bucket_records(self, bucket: str,
                       exclude_exact: Optional[str] = None) -> List[Record]:
        """All records in a fingerprint bucket (the near-miss
        neighborhood), best-first, optionally excluding one exact
        digest (the requester's own)."""
        out = [r for r in self.records()
               if r.get("bucket") == bucket
               and (exclude_exact is None or r["exact"] != exclude_exact)]
        out.sort(key=_order_key, reverse=True)
        return out

    # -- merge / persistence ------------------------------------------------
    def merge_from(self, other: "ScheduleStore") -> int:
        """Merge another store's records into this one (see module
        docstring for the algebra); returns how many records were
        examined."""
        n = 0
        for rec in other.records():
            self._put(dict(rec))
            n += 1
        get_metrics().counter("serve.store.merged").inc(n)
        return n

    def to_json(self) -> Dict[str, Any]:
        return {"version": STORE_VERSION, "entries": self.entries}

    def flush(self) -> None:
        """Persist: re-read the file, merge (another writer may have
        flushed since our load), write atomically — the whole
        read-merge-rename held under an advisory ``flock`` on a sidecar
        ``<path>.lock`` so two *simultaneous* writers serialize instead
        of racing (without the lock, both could re-read the same disk
        state and the second rename would drop the first's records).
        The lock file is never renamed — flocking the store file itself
        would be defeated by the atomic-replace.  On platforms without
        ``fcntl`` the merge-on-flush still protects interleaved (
        non-simultaneous) writers."""
        if self.path is None:
            return
        # the flush span is the "store merge" leg of a request's
        # cross-process trace: under a drain's ambient context it stamps
        # the trace_id that started the cold query (obs/context.py)
        with get_tracer().span("serve.store.flush", backend="monolithic",
                               records=len(self)):
            # the CLI promises "created on first flush": the directory
            # must exist before the .lock sidecar opens (atomic_dump_json
            # would create it, but the lock comes first)
            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)
            try:
                import fcntl
            except ImportError:  # pragma: no cover — non-POSIX fallback
                fcntl = None
            lock_f = None
            try:
                if fcntl is not None:
                    lock_f = open(self.path + ".lock", "w")
                    fcntl.flock(lock_f, fcntl.LOCK_EX)
                if os.path.exists(self.path):
                    # uncounted throwaway read + plain re-puts: this is
                    # flush bookkeeping, not a real load or merge — the
                    # documented store-economics counters must not grow
                    # with flush count
                    disk = ScheduleStore(self.path, tenant=self.tenant,
                                         log=self._log,
                                         _count_metrics=False)
                    for rec in disk.records():
                        self._put(dict(rec))
                # transient-EIO retries + the read-only latch on the
                # unwritable errno family (guarded_store_write above)
                guarded_store_write(
                    self.path,
                    lambda: atomic_dump_json(self.path, self.to_json(),
                                             prefix=".store."),
                    where="serve.store.flush")
            finally:
                if lock_f is not None:
                    lock_f.close()  # releases the flock
        get_metrics().counter("serve.store.flushed").inc()

    def stats(self) -> Dict[str, Any]:
        by_wl: Dict[str, int] = {}
        flagged = 0
        tenants = set()
        for rec in self.records():
            by_wl[rec.get("workload", "?")] = \
                by_wl.get(rec.get("workload", "?"), 0) + 1
            if any(rec.get("flags", {}).values()):
                flagged += 1
            t = rec.get("provenance", {}).get("tenant")
            if t:
                tenants.add(t)
        out = {
            "path": self.path,
            "fingerprints": len(self.entries),
            "records": len(self),
            "by_workload": dict(sorted(by_wl.items())),
            "flagged": flagged,
            "tenants": sorted(tenants),
            "skipped_on_load": self.skipped,
        }
        ro = store_readonly(self.path) if self.path else None
        if ro is not None:
            out["readonly"] = ro
        return out


def open_store(path: Optional[str], **kwargs) -> "ScheduleStore":
    """THE store-backend dispatcher: a ``*.json`` path opens the legacy
    monolithic :class:`ScheduleStore` (every committed store, the daemon
    smokes, old CLIs keep working unchanged); anything else — a
    directory, existing or to-be-created — opens the segmented store
    (serve/segments.py, docs/serving.md "Segmented store").  One rule,
    used by the service, the CLI, the report CLI, and the replay
    benchmark, so no two entry points can disagree about what a store
    path means."""
    if path is None:
        return ScheduleStore(None, **kwargs)
    if path.endswith(".json") and not os.path.isdir(path):
        return ScheduleStore(path, **kwargs)
    from tenzing_tpu.serve.segments import SegmentedStore

    return SegmentedStore(path, **kwargs)


class WorkQueue:
    """The cold-request queue: one checkpointed work item per missing
    fingerprint, written in the fault/checkpoint.py envelope format
    (``atomic_write_json`` — versioned, sha256-digest-checked) so a
    drainer validates an item with the same ``read_checked_json`` the
    resume path trusts.  The payload is a serialized
    :class:`~tenzing_tpu.bench.driver.DriverRequest`:
    ``run(DriverRequest(**item["request"]))`` IS the drain step, and the
    suggested ``checkpoint`` directory makes the search itself
    kill-resumable.  Item filenames key on the exact fingerprint digest,
    so re-querying a cold fingerprint re-asserts one item instead of
    piling duplicates.

    The queue directory is also where the drain daemon
    (serve/daemon.py, docs/serving.md "Drain daemon") keeps its
    per-item protocol state, all keyed by the same exact digest:

    * ``lease-<exact>.json``  — a live claim (owner payload; the file's
      mtime is the heartbeat — a stale mtime is an expired lease);
    * ``fail-<exact>.json``   — the persistent failure history a poison
      verdict accumulates across daemon restarts;
    * ``poison-<exact>.json`` — a poisoned item (checkpoint envelope,
      ``kind: "poisoned_request"``) quarantined out of the drain loop;
    * ``ckpt-<exact>/``       — the item's ``SearchCheckpoint``
      directory (suggested at enqueue time, used by the drain);
    * ``status-<owner>.json`` — each daemon's liveness/status document.

    Only ``work-*.json`` files are queue *items*; every listing method
    here ignores the rest, and vice versa.
    """

    def __init__(self, directory: str):
        # the directory is created on first enqueue, NOT here: read-only
        # callers (serve stats/query before anything is queued, the
        # report CLI) must not silently materialize a typo'd --queue
        # path and then report an empty queue where the real one lives
        # elsewhere
        self.dir = directory
        # torn/corrupt item files seen by the LAST items() scan — the
        # visible-rot satellite: a drainer must skip a torn item, but
        # skipping silently hides queue damage from every dashboard
        self.torn_paths: List[str] = []
        # (name, mtime) pairs already counted, so a polling daemon does
        # not inflate serve.queue.torn once per scan of the same damage
        # (a rewrite of the file — new mtime — counts again)
        self._torn_seen: set = set()

    def path_for(self, exact: str) -> str:
        return os.path.join(self.dir, f"work-{exact}.json")

    def lease_path_for(self, exact: str) -> str:
        return os.path.join(self.dir, f"lease-{exact}.json")

    def fail_path_for(self, exact: str) -> str:
        return os.path.join(self.dir, f"fail-{exact}.json")

    def poison_path_for(self, exact: str) -> str:
        return os.path.join(self.dir, f"poison-{exact}.json")

    def checkpoint_dir_for(self, exact: str) -> str:
        return os.path.join(self.dir, f"ckpt-{exact}")

    @staticmethod
    def exact_of(path: str) -> str:
        """The exact fingerprint digest a queue file is keyed by."""
        name = os.path.basename(path)
        stem = name[:-len(".json")] if name.endswith(".json") else name
        return stem.split("-", 1)[1] if "-" in stem else stem

    def ensure(self, fingerprint, request: Dict[str, Any],
               reason: str, trace=None) -> str:
        """:meth:`enqueue` only when no valid item already exists for
        this fingerprint — the hot-path variant (the near tier
        re-resolves a popular fingerprint at fleet rates, and an
        identical re-write would pay json+sha256+fsync+rename per
        request); an existing-but-unreadable item IS rewritten.  The
        first enqueuer's trace context sticks: re-asserting queries do
        not rewrite the item, so the drain links back to the query that
        actually created the work."""
        path = self.path_for(fingerprint.exact_digest)
        if os.path.exists(path):
            try:
                read_checked_json(path)
                return path
            except Exception:
                pass  # torn/corrupt item: re-assert it below
        return self.enqueue(fingerprint, request, reason, trace=trace)

    def enqueue(self, fingerprint, request: Dict[str, Any],
                reason: str, trace=None) -> str:
        """``trace`` is an :class:`~tenzing_tpu.obs.context.TraceContext`
        (or None): stamped into the checkpoint envelope so the drain —
        possibly days later, on another host, after the enqueuing
        process died — still runs under the originating query's
        trace_id (docs/observability.md "Fleet telemetry plane")."""
        os.makedirs(self.dir, exist_ok=True)
        path = self.path_for(fingerprint.exact_digest)
        doc = {
            "kind": "search_request",
            "reason": reason,
            "fingerprint": fingerprint.to_json(),
            "request": request,
            "checkpoint": self.checkpoint_dir_for(fingerprint.exact_digest),
        }
        if trace is not None:
            doc["trace"] = trace.to_json()
        atomic_write_json(path, doc)
        get_metrics().counter("serve.queue.enqueued").inc()
        tr = get_tracer()
        if tr.enabled:
            tr.event("serve.enqueue", exact=fingerprint.exact_digest,
                     reason=reason, workload=fingerprint.workload)
        return path

    def items(self) -> List[Tuple[str, Dict[str, Any]]]:
        """(path, payload) per valid queued item; invalid files are
        skipped (a drainer must never crash on one torn item), and a
        not-yet-created queue directory is simply empty.  Torn/corrupt
        item files are *counted* (``serve.queue.torn`` + a
        ``serve.queue.torn_item`` tracer event, deduped per damaged
        version) and kept in :attr:`torn_paths` so queue rot is visible
        in ``serve stats`` and the report CLI instead of silently
        shrinking the depth."""
        out = []
        torn: List[str] = []
        torn_keys: set = set()
        if not os.path.isdir(self.dir):
            self._torn_seen = torn_keys
            self.torn_paths = torn
            return out
        for name in sorted(os.listdir(self.dir)):
            if not (name.startswith("work-") and name.endswith(".json")):
                continue
            path = os.path.join(self.dir, name)
            try:
                out.append((path, read_checked_json(path)))
            except Exception as e:
                torn.append(path)
                try:
                    key = (name, os.path.getmtime(path))
                except OSError:
                    key = (name, None)
                torn_keys.add(key)
                if key not in self._torn_seen:
                    self._torn_seen.add(key)
                    get_metrics().counter("serve.queue.torn").inc()
                    tr = get_tracer()
                    if tr.enabled:
                        tr.event("serve.queue.torn_item", file=name,
                                 error=type(e).__name__,
                                 message=str(e)[:200])
                continue
        # the dedup set tracks only the *currently* torn versions — a
        # long-lived poller facing an ever-rewriting broken producer must
        # not accumulate one key per damaged version forever
        self._torn_seen &= torn_keys
        self.torn_paths = torn
        return out

    def leases(self) -> List[Dict[str, Any]]:
        """Live-claim documents, one per ``lease-*.json``: the owner
        payload (tolerating a torn lease — only the mtime is
        load-bearing for expiry) plus ``age_s`` since the last heartbeat
        renewal."""
        out: List[Dict[str, Any]] = []
        if not os.path.isdir(self.dir):
            return out
        now = time.time()
        for name in sorted(os.listdir(self.dir)):
            if not (name.startswith("lease-") and name.endswith(".json")):
                continue
            path = os.path.join(self.dir, name)
            doc: Dict[str, Any] = {"path": path,
                                   "exact": self.exact_of(path)}
            try:
                doc["age_s"] = round(now - os.path.getmtime(path), 3)
            except OSError:
                continue  # released between listdir and stat
            try:
                with open(path) as f:
                    doc.update(json.load(f))
            except (OSError, ValueError):
                pass  # claim raced mid-publish; mtime alone still counts
            out.append(doc)
        return out

    def poisoned(self) -> List[Tuple[str, Dict[str, Any]]]:
        """(path, payload) per poison-quarantined item (the drain
        daemon's deterministic-failure verdicts, docs/serving.md);
        unreadable poison files are returned with an ``unreadable``
        payload rather than hidden — poison is exactly the rot a
        dashboard must see."""
        out: List[Tuple[str, Dict[str, Any]]] = []
        if not os.path.isdir(self.dir):
            return out
        for name in sorted(os.listdir(self.dir)):
            if not (name.startswith("poison-") and name.endswith(".json")):
                continue
            path = os.path.join(self.dir, name)
            try:
                out.append((path, read_checked_json(path)))
            except Exception as e:
                out.append((path, {"unreadable": f"{type(e).__name__}: "
                                                 f"{str(e)[:200]}"}))
        return out

    def stats(self) -> Dict[str, Any]:
        """Queue occupancy for ``serve stats`` and the report CLI:
        depth + reasons, the torn set (visible rot), live leases, and
        poison quarantine size."""
        items = self.items()
        by_reason: Dict[str, int] = {}
        for _, payload in items:
            r = payload.get("reason", "?")
            by_reason[r] = by_reason.get(r, 0) + 1
        return {
            "dir": self.dir,
            "depth": len(items),
            "reasons": sorted(by_reason),
            "by_reason": dict(sorted(by_reason.items())),
            "torn": [os.path.basename(p) for p in self.torn_paths],
            "leases": self.leases(),
            "poisoned": [os.path.basename(p) for p, _ in self.poisoned()],
        }

    def __len__(self) -> int:
        return len(self.items())
