"""Schedule serving: the offline search corpus as a queryable service.

The north-star is a fleet serving schedule requests for millions of users
(ROADMAP, "Schedule-serving at fleet scale"); re-running a multi-hour
search per request is not a serving path.  "Machine Learning for CUDA+MPI
Design Rules" (PAPERS.md) reads a corpus of searched schedules as an
asset — mineable to answer and prune future requests — and six rounds of
searching left exactly that corpus on disk.  This package composes the
existing offline pieces behind a request/response API:

* :mod:`~tenzing_tpu.serve.fingerprint` — a stable workload fingerprint
  (workload kind, shape bucket, mesh signature, engine kind-sets) with
  power-of-two shape bucketing so nearby shapes share entries; schedule
  keying via the existing ``canonical_key``.
* :mod:`~tenzing_tpu.serve.store` — a persistent, schema-versioned,
  multi-tenant schedule store (atomic writes via utils/atomic.py) with a
  commutative, idempotent ``merge`` so stores from independent hosts/CI
  runs combine without loss; plus the checkpointed cold-request
  :class:`~tenzing_tpu.serve.store.WorkQueue`.
* :mod:`~tenzing_tpu.serve.resolver` — tiered resolution: **exact** hits
  answer instantly from the store (re-verified through
  :class:`~tenzing_tpu.verify.ScheduleVerifier`, zero compiles, zero
  measurements), **near** misses answer from the PR-2 surrogate under an
  uncertainty gate with ``was_predicted`` provenance, **cold** requests
  enqueue a :class:`~tenzing_tpu.bench.driver.DriverRequest` work item a
  driver drains.
* :mod:`~tenzing_tpu.serve.service` — the in-process API and the
  ``python -m tenzing_tpu.serve`` CLI (``warm`` / ``query`` / ``merge`` /
  ``stats``).
* :mod:`~tenzing_tpu.serve.daemon` — the hardened drain daemon
  (``python -m tenzing_tpu.serve.daemon``): leased claims over the work
  queue, crash-resume through each item's checkpoint, bounded classified
  retries, poison quarantine, status/heartbeat JSON — the
  serve→search→serve loop closed end-to-end (docs/serving.md
  "Drain daemon").
* :mod:`~tenzing_tpu.serve.fleet` — N daemons work-stealing one queue
  (``python -m tenzing_tpu.serve.fleet``): the launcher, the
  exactly-once double-run audit, and the drain-rate scaling harness
  (docs/serving.md "Drain fleet").

Workflow and formats: docs/serving.md.  Telemetry: ``serve.*`` counters
(hit/near/cold), the ``serve.resolve_us`` latency histogram, and
``serve.query`` spans (docs/observability.md).
"""

from tenzing_tpu.serve.daemon import DaemonOpts, DrainDaemon
from tenzing_tpu.serve.fingerprint import (
    WorkloadFingerprint,
    fingerprint_of,
    schedule_key,
    shape_bucket,
)
from tenzing_tpu.serve.fleet import FleetOpts, measure_scaling, run_fleet
from tenzing_tpu.serve.resolver import Resolution, Resolver, fp_cache_key
from tenzing_tpu.serve.service import ScheduleService
from tenzing_tpu.serve.store import ScheduleStore, WorkQueue, merge_records

__all__ = [
    "DaemonOpts",
    "DrainDaemon",
    "FleetOpts",
    "Resolution",
    "Resolver",
    "fp_cache_key",
    "measure_scaling",
    "run_fleet",
    "ScheduleService",
    "ScheduleStore",
    "WorkQueue",
    "WorkloadFingerprint",
    "fingerprint_of",
    "merge_records",
    "schedule_key",
    "shape_bucket",
]
