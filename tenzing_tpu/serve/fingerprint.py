"""Stable workload fingerprints: the serving store's key space.

A serving key must be (a) **stable** — the same request yields the same
bytes across process restarts, hosts, and argument orderings, so
independently-warmed stores merge instead of fragmenting; and (b)
**bucketed** — nearby shapes share an entry, because a schedule searched
at ``n=512`` is the right warm answer for ``n=480`` (the schedule is a
*structure*; only its measured numbers are shape-specific).

The fingerprint is the tuple the ISSUE names:

* **workload kind + variant** — ``halo``/``spmv``/``attn``/``moe``,
  smoke vs full (the two build different choice graphs, so their
  schedules are not interchangeable);
* **shape** — the exact builder-resolved shape parameters
  (:func:`~tenzing_tpu.bench.driver.workload_shape` — THE single source,
  kept next to the builders), plus their power-of-two **bucket**;
* **mesh signature** — the search platform's lane count
  (:func:`~tenzing_tpu.bench.driver.search_lanes`, the same default rule
  the driver applies);
* **engine kind-sets** — ``bench/model.py``'s ``ICI_KINDS``/``PCIE_KINDS``:
  the transfer-engine vocabulary the analytic model and the surrogate
  featurizer agree on.  A change to the engine model changes every
  fingerprint, which is correct: stored schedules were searched (and the
  surrogate trained) under the old vocabulary.

Two digests derive from it: ``exact_digest`` keys exact hits (precise
shape), ``bucket_digest`` keys the near-miss neighborhood (bucketed
shape).  Both are ``sha1`` short digests of sorted-key canonical JSON —
no Python ``hash()``, no dict-order dependence, no ``PYTHONHASHSEED``
sensitivity (tests/test_serve_fingerprint.py pins this across
subprocesses with different hash seeds).

Schedules themselves key by the existing
:func:`~tenzing_tpu.core.sequence.canonical_key` modulo redundant syncs
(:func:`schedule_key`) — the same equivalence every benchmark cache,
verifier and recorded database already matches on.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from functools import cached_property
from typing import Any, Dict, Tuple

from tenzing_tpu.bench.model import ICI_KINDS, PCIE_KINDS
from tenzing_tpu.obs.tracer import short_digest

FINGERPRINT_VERSION = 1


def shape_bucket(n: int) -> int:
    """THE bucketing rule: the next power of two at or above ``n`` (0 for
    non-positive).  Geometric buckets match how schedule structure scales
    — a halo at 300^3 and 512^3 cells wants the same overlap discipline,
    while 512 vs 513 crossing a boundary is the price of a rule simple
    enough to pin with golden tests (boundaries: 2^k maps to 2^k, 2^k+1
    to 2^(k+1))."""
    if n <= 0:
        return 0
    return 1 << (int(n) - 1).bit_length()


def _canonical(doc: Any) -> str:
    """Deterministic serialization: sorted keys, no whitespace variance,
    ASCII-safe — the byte stream both digests hash."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


@dataclass(frozen=True)
class WorkloadFingerprint:
    """The serving key of one workload configuration (see module
    docstring).  ``shape``/``bucket``/``mesh`` are sorted name/value
    tuples so construction order can never leak into the digest."""

    workload: str
    variant: str  # "smoke" | "full"
    shape: Tuple[Tuple[str, int], ...]
    bucket: Tuple[Tuple[str, int], ...]
    mesh: Tuple[Tuple[str, int], ...]
    engines: Tuple[Tuple[str, Tuple[str, ...]], ...]

    def _digest(self, shape_field: Tuple) -> str:
        return short_digest(_canonical({
            "v": FINGERPRINT_VERSION,
            "workload": self.workload,
            "variant": self.variant,
            "shape": [list(kv) for kv in shape_field],
            "mesh": [list(kv) for kv in self.mesh],
            "engines": [[k, list(v)] for k, v in self.engines],
        }))

    # cached: one resolution touches each digest several times (cache
    # probe, span attributes, response serialization), and each compute
    # is a canonical-JSON dump + sha1 — real microseconds on the
    # serving hot path.  ``cached_property`` stores into ``__dict__``
    # directly, which a frozen dataclass permits; the fingerprint is
    # immutable, so the cache can never go stale.
    @cached_property
    def exact_digest(self) -> str:
        """Keys exact hits: precise shape."""
        return self._digest(self.shape)

    @cached_property
    def bucket_digest(self) -> str:
        """Keys the near-miss neighborhood: bucketed shape."""
        return self._digest(self.bucket)

    def to_json(self) -> Dict[str, Any]:
        return {
            "version": FINGERPRINT_VERSION,
            "workload": self.workload,
            "variant": self.variant,
            "shape": {k: v for k, v in self.shape},
            "bucket": {k: v for k, v in self.bucket},
            "mesh": {k: v for k, v in self.mesh},
            "engines": {k: list(v) for k, v in self.engines},
            "exact": self.exact_digest,
            "bucket_digest": self.bucket_digest,
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "WorkloadFingerprint":
        return cls(
            workload=j["workload"],
            variant=j["variant"],
            shape=_sorted_items(j["shape"]),
            bucket=_sorted_items(j["bucket"]),
            mesh=_sorted_items(j["mesh"]),
            engines=tuple(sorted(
                (k, tuple(v)) for k, v in j["engines"].items())),
        )


def _sorted_items(d: Dict[str, int]) -> Tuple[Tuple[str, int], ...]:
    return tuple(sorted((str(k), int(v)) for k, v in d.items()))


def fingerprint_of(req) -> WorkloadFingerprint:
    """The fingerprint of a :class:`~tenzing_tpu.bench.driver.
    DriverRequest` — pure request arithmetic (no jax, no buffers, no
    backend): the serving front door must fingerprint a request on a host
    with no accelerator."""
    from tenzing_tpu.bench.driver import search_lanes, workload_shape

    shape = workload_shape(req)
    return WorkloadFingerprint(
        workload=req.workload,
        variant="smoke" if req.smoke else "full",
        shape=_sorted_items(shape),
        bucket=_sorted_items({k: shape_bucket(v) for k, v in shape.items()}),
        mesh=_sorted_items({"lanes": search_lanes(req)}),
        engines=tuple(sorted((("ici", tuple(ICI_KINDS)),
                              ("pcie", tuple(PCIE_KINDS))))),
    )


def schedule_key(seq) -> str:
    """The store's schedule key: a short digest of the canonical form
    modulo redundant syncs — the SAME equivalence the benchmark cache,
    the verifier cache, and ``CsvBenchmarker(normalize=True)`` match on,
    so a DFS-dumped and an MCTS-cleaned spelling of one program occupy
    one store slot."""
    from tenzing_tpu.core.schedule import remove_redundant_syncs
    from tenzing_tpu.core.sequence import canonical_key

    return short_digest(repr(canonical_key(remove_redundant_syncs(seq))))
