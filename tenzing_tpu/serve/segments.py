"""The segmented schedule store: sealed per-bucket segments, a tiny
atomic manifest, and a crash-consistent offline compactor.

The monolithic store (serve/store.py) re-reads and re-merges one JSON
document on every flush — correct, but flush cost scales with corpus
size, and every failure mode shares one blast radius: a torn byte
anywhere quarantines the whole corpus.  This module is the fleet-grade
replacement (docs/serving.md "Segmented store"):

**Layout** (one store = one directory)::

    <store>/manifest.json        # the index (atomic, flock-serialized)
    <store>/manifest.lock        # flock sidecar (never renamed)
    <store>/segments/seg-<bucket>-<stamp>-<owner>-<n>.jsonl
    <store>/compact.lease        # the compactor's lease (serve/lease.py)

**Segments** are sealed, append-only-in-spirit JSONL files, one *bucket
digest* each: line 0 is a header (``kind/version/bucket/n_records``),
every following line is ``{"sha256": <hex>, "record": {...}}`` — the
checksum is of the record's canonical serialization, so **every record
is self-certifying**: a bit-flip is detected per record, a truncation is
detected against the header count, and salvage never has to trust
framing.  Segments are published complete (private temp, fsync,
hard-link, directory fsync) — a reader can never observe a torn segment
that the writer acknowledged.

**The manifest is an index, not the ground truth.**  Loading *scans* the
segments directory; the manifest contributes live/listed status, byte
counts, and the compaction ledger.  A torn manifest therefore costs
nothing but metadata: the loader falls back to the scan and recovers
every record (the torn file is quarantined aside for post-mortem).
Likewise a crash anywhere in flush or compaction leaves at worst an
*orphan* segment (published but not yet indexed) — still loaded, later
adopted or merged by the compactor.  ``SIGKILL`` at any instant recovers
to a **superset** of the acknowledged records.

**Damage handling**, per kind, never fatal:

* bit-flipped record → checksum mismatch: that record is skipped and
  counted (``serve.store.checksum_failed``); the segment's surviving
  records are salvaged.
* truncated / torn segment → every checksum-valid record is salvaged,
  marked dirty (re-persisted by the next flush), and the damaged file is
  quarantined to ``*.corrupt-<id>`` (writers only — read-only loaders
  report and leave it in place).
* torn manifest → quarantined (writers only); the scan recovers the
  corpus; the next flush/compaction rebuilds the index.
* a segment or manifest from a **newer** version is skipped loudly,
  never quarantined — future data is not damage.

**Flush** groups the records dirtied since the last flush by bucket,
publishes one new segment per dirty bucket, and appends the segment
names to the manifest under a non-blocking ``flock`` taken through the
shared bounded backoff (fault/backoff.py; exhaustion raises
:class:`~tenzing_tpu.fault.errors.StoreLockTimeout`, a transient).
Flush cost is proportional to the *dirty* record count — it no longer
scales with corpus size.

**Compaction** (:class:`Compactor`, ``python -m tenzing_tpu.serve
compact``) merges each multi-segment bucket through the same commutative
:func:`~tenzing_tpu.serve.store.merge_records` the monolithic store
uses, publishes the merged segment, republishes the manifest (drop
inputs, add output, ledger entry), and only then unlinks the inputs —
the reclaim order that makes ``kill -9`` recover to a superset at every
instant.  Two compactors race safely on the lease-file protocol
extracted from the drain daemon (serve/lease.py); orphan segments are
adopted into the manifest; stale temp files older than a grace period
are collected.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import signal
import socket
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from tenzing_tpu.fault.backoff import BackoffPolicy, retry_call
from tenzing_tpu.fault.errors import StoreLockTimeout
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer, short_digest
from tenzing_tpu.serve.lease import LeaseFile
from tenzing_tpu.serve.store import (
    RECORD_SCHEMA,
    Record,
    ScheduleStore,
    guarded_store_write,
    migrate_record,
)
from tenzing_tpu.utils.atomic import atomic_dump_json, publish_sealed

SEGMENT_VERSION = 1
MANIFEST_VERSION = 1
# a long-lived store compacts forever; the ledger is bounded like the
# daemon's history (consumers only ever read the tail)
COMPACTION_HISTORY_CAP = 50

MANIFEST_NAME = "manifest.json"
MANIFEST_LOCK = "manifest.lock"
SEGMENTS_DIR = "segments"
COMPACT_LEASE = "compact.lease"


def record_digest(rec: Record) -> str:
    """sha256 hex of the record's canonical serialization — the
    per-record checksum that makes every stored record self-certifying
    (module docstring)."""
    return hashlib.sha256(
        json.dumps(rec, sort_keys=True, separators=(",", ":"))
        .encode()).hexdigest()


def _owner_token(owner: str) -> str:
    """Owner id as a filename token (dashes survive; the bucket field is
    parsed positionally so an owner dash cannot confuse it)."""
    return re.sub(r"[^A-Za-z0-9_.-]+", "-", owner) or "anon"


def segment_bucket_of(name: str) -> str:
    """The bucket digest a segment file is keyed by (positional: the
    digest is hex, never dashed — owner tokens after it may be)."""
    parts = name.split("-")
    return parts[1] if len(parts) > 1 else "?"


def is_segment_name(name: str) -> bool:
    return name.startswith("seg-") and name.endswith(".jsonl")


class SegmentedStore(ScheduleStore):
    """Drop-in :class:`~tenzing_tpu.serve.store.ScheduleStore` with
    segmented persistence (module docstring).  The in-memory view,
    merge algebra, record schema and query methods are untouched — only
    ``_load``/``flush``/``flag``/``stats`` change, so the resolver and
    the report CLI cannot tell the backends apart except by speed."""

    def __init__(self, directory: Optional[str], tenant: str = "local",
                 log: Optional[Callable[[str], None]] = None,
                 quarantine_corrupt: bool = True,
                 _count_metrics: bool = True):
        self.dir = directory
        self.owner = _owner_token(f"{socket.gethostname()}-{os.getpid()}")
        self._seg_counter = 0
        self._loading = False
        # ordered set of (exact, key) mutated since the last flush — the
        # flush unit; segment append cost is proportional to THIS, never
        # to the corpus
        self._dirty: Dict[Tuple[str, str], None] = {}
        # per live segment file: bucket/records/bytes/listed/salvaged —
        # built on load, consumed by stats(), the compactor and the
        # report CLI
        self.segment_info: Dict[str, Dict[str, Any]] = {}
        self.manifest_doc: Optional[Dict[str, Any]] = None
        self.quarantined_segments: List[str] = []
        self.orphan_segments: List[str] = []
        self.missing_segments: List[str] = []
        self.vanished_segments: List[str] = []
        self.newer_segments: List[str] = []
        self.checksum_failed = 0
        self.salvaged = 0
        super().__init__(path=None, tenant=tenant, log=log,
                         quarantine_corrupt=quarantine_corrupt,
                         _count_metrics=_count_metrics)
        self.path = directory
        if directory is not None and os.path.isdir(directory):
            self._loading = True
            try:
                self._load_segments()
            finally:
                self._loading = False

    # -- paths ---------------------------------------------------------------
    @property
    def manifest_path(self) -> str:
        return os.path.join(self.dir, MANIFEST_NAME)

    @property
    def segments_path(self) -> str:
        return os.path.join(self.dir, SEGMENTS_DIR)

    # -- dirty tracking -------------------------------------------------------
    def _put(self, rec: Record) -> Record:
        if self._loading:
            return super()._put(rec)
        slot = self.entries.get(rec.get("exact"), {})
        prev = slot.get(rec.get("key"))
        out = super()._put(rec)
        if prev is None or prev != out:
            self._dirty[(out["exact"], out["key"])] = None
        return out

    def flag(self, exact: str, key: str, **flags: Any) -> None:
        rec = self.entries.get(exact, {}).get(key)
        if rec is None:
            return
        cur = rec.setdefault("flags", {})
        if all(cur.get(k) == v for k, v in flags.items()):
            return  # hot-path short-circuit, same as the monolithic store
        cur.update(flags)
        self.generation += 1  # the exact cache must see the mutation
        self._dirty[(exact, key)] = None
        self.flush()

    # -- manifest ------------------------------------------------------------
    @contextmanager
    def _manifest_lock(self):
        """Non-blocking ``flock`` on the sidecar, acquired through the
        shared bounded backoff (fault/backoff.py) — a serving request
        must never wait forever behind a stuck writer; exhaustion raises
        :class:`StoreLockTimeout` (transient: the rival will finish)."""
        try:
            import fcntl
        except ImportError:  # pragma: no cover — non-POSIX fallback
            yield
            return
        lock_f = open(os.path.join(self.dir, MANIFEST_LOCK), "w")

        def acquire():
            try:
                fcntl.flock(lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as e:
                raise StoreLockTimeout(
                    f"manifest lock contended ({e})") from None

        try:
            retry_call(acquire,
                       policy=BackoffPolicy(retries=40, base_secs=0.005,
                                            factor=1.5, max_secs=0.25,
                                            jitter=0.5),
                       where="serve.manifest_lock")
            yield
        finally:
            lock_f.close()  # releases the flock

    def _read_manifest(self) -> Optional[Dict[str, Any]]:
        path = self.manifest_path
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError("manifest is not an object")
            if doc.get("version", 0) > MANIFEST_VERSION:
                # future data is not damage: ignore the index (the scan
                # is ground truth), never quarantine it
                self._note(f"store: manifest version {doc.get('version')!r}"
                           f" > {MANIFEST_VERSION}; scanning instead")
                return None
            if not isinstance(doc.get("segments"), dict):
                raise ValueError("manifest without a segments object")
            return doc
        except Exception as e:
            # the manifest is an index: losing it costs metadata, never
            # records — quarantine (writers) or report (read-only) and
            # fall back to the scan
            if self.quarantine_corrupt:
                quarantined = f"{path}.corrupt-{short_digest(str(e))[:8]}"
                try:
                    os.replace(path, quarantined)
                    self._note(f"store: quarantined torn manifest -> "
                               f"{quarantined} ({type(e).__name__}: {e}); "
                               "recovering from segment scan")
                except OSError:
                    self._note(f"store: torn manifest {path} "
                               f"({type(e).__name__}: {e})")
                if self._count_metrics:
                    get_metrics().counter(
                        "serve.store.manifest_quarantined").inc()
            else:
                self._note(f"store: torn manifest {path} "
                           f"({type(e).__name__}: {e}); left in place")
            return None

    def _mutate_manifest(self, fn: Callable[[Dict[str, Any]],
                                            Dict[str, Any]]) -> None:
        """Read-modify-write under the flock: ``fn`` mutates (and
        returns) the manifest doc; a missing/torn manifest starts empty
        — the scan-recovered records become orphans the compactor
        re-indexes, never losses."""
        with self._manifest_lock():
            doc = self._read_manifest() or {
                "version": MANIFEST_VERSION, "segments": {},
                "compactions": []}
            doc = fn(doc)
            # hardened: transient EIO retries through the shared backoff;
            # ENOSPC/EROFS latches the store read-only (serve/store.py)
            guarded_store_write(
                self.dir,
                lambda: atomic_dump_json(self.manifest_path, doc,
                                         prefix=".manifest."),
                where="serve.store.manifest")
        self.manifest_doc = doc

    # -- loading -------------------------------------------------------------
    def _scan_names(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.segments_path)
                          if is_segment_name(n))
        except OSError:
            return []

    def _load_segments(self) -> None:
        man = self._read_manifest()
        self.manifest_doc = man
        listed = dict((man or {}).get("segments", {}))
        n_loaded = 0
        seen: set = set()
        names = self._scan_names()
        for _pass in (0, 1):
            for name in names:
                if name in seen:
                    continue
                seen.add(name)
                n_loaded += self._load_one_segment(name, name in listed)
            vanished = [n for n in self.vanished_segments if n in seen]
            if _pass == 0 and vanished:
                # a compactor published + reclaimed between our listdir
                # and our reads: one re-list picks up its output segment
                # (publish strictly precedes reclaim, so it exists now)
                names = self._scan_names()
            else:
                break
        self.orphan_segments = sorted(
            n for n in self.segment_info if n not in listed)
        self.missing_segments = sorted(
            n for n in listed
            if n not in self.segment_info
            and n not in self.vanished_segments
            and n not in self.quarantined_segments)
        for name in self.missing_segments:
            self._note(f"store: segment {name} listed in the manifest "
                       "but missing on disk")
        if self._count_metrics:
            get_metrics().counter("serve.store.loaded").inc(n_loaded)

    def _load_one_segment(self, name: str, listed: bool) -> int:
        path = os.path.join(self.segments_path, name)
        try:
            # bytes first: a bit flip can make the file invalid UTF-8,
            # and that must damage ONE line's checksum, not crash the
            # whole load
            with open(path, "rb") as f:
                lines = f.read().decode(
                    "utf-8", errors="replace").splitlines()
        except OSError:
            # unlinked between listdir and open: a compactor reclaimed
            # it — its records live in the published compact segment
            self.vanished_segments.append(name)
            return 0
        header: Dict[str, Any] = {}
        damage: List[str] = []
        if lines:
            try:
                header = json.loads(lines[0])
                if not isinstance(header, dict) or \
                        header.get("kind") != "segment":
                    raise ValueError("not a segment header")
            except ValueError:
                header = {}
                damage.append("bad-header")
        else:
            damage.append("empty")
        if header.get("version", 0) > SEGMENT_VERSION:
            # future data is not damage — skip loudly, never quarantine
            self.newer_segments.append(name)
            self._note(f"store: segment {name} has newer version "
                       f"{header.get('version')!r}; skipped")
            return 0
        valid: List[Record] = []
        bad_checksum = torn_lines = 0
        for line in lines[1:]:
            if not line.strip():
                continue
            try:
                j = json.loads(line)
            except ValueError:
                torn_lines += 1
                continue
            rec = j.get("record") if isinstance(j, dict) else None
            if not isinstance(rec, dict) or \
                    record_digest(rec) != j.get("sha256"):
                bad_checksum += 1
                continue
            valid.append(rec)
        n_expected = header.get("n_records")
        if torn_lines:
            damage.append(f"torn-lines:{torn_lines}")
        if bad_checksum:
            damage.append(f"checksum:{bad_checksum}")
            self.checksum_failed += bad_checksum
            if self._count_metrics:
                get_metrics().counter(
                    "serve.store.checksum_failed").inc(bad_checksum)
        if isinstance(n_expected, int) and \
                len(valid) + bad_checksum < n_expected:
            damage.append(
                f"truncated:{len(valid) + bad_checksum}/{n_expected}")
        n = 0
        for rec in valid:
            mig = migrate_record(rec)
            if mig is None:
                self.skipped += 1
                continue
            out = self._put(mig)
            if damage:
                # salvage: every checksum-valid record survives, and is
                # re-persisted by the next flush (the damaged file moves
                # aside below — without the dirty mark the salvage would
                # evaporate on the next load)
                self._dirty[(out["exact"], out["key"])] = None
                self.salvaged += 1
            n += 1
        if damage:
            tag = ",".join(damage)
            if self.quarantine_corrupt:
                quarantined = f"{path}.corrupt-{short_digest(tag)[:8]}"
                try:
                    os.replace(path, quarantined)
                    self._note(f"store: quarantined damaged segment "
                               f"{name} ({tag}; salvaged {n} record(s))")
                except OSError:
                    self._note(f"store: damaged segment {name} ({tag})")
                self.quarantined_segments.append(name)
                if self._count_metrics:
                    get_metrics().counter(
                        "serve.store.segment_quarantined").inc()
                tr = get_tracer()
                if tr.enabled:
                    tr.event("serve.store.segment_quarantined",
                             segment=name, damage=tag, salvaged=n)
                return n
            self._note(f"store: damaged segment {name} ({tag}; "
                       f"{n} valid record(s)); left in place")
        self.segment_info[name] = {
            "bucket": header.get("bucket", segment_bucket_of(name)),
            "records": n,
            "bytes": sum(len(line) + 1 for line in lines),
            "listed": listed,
            "damaged": bool(damage),
        }
        return n

    # -- flushing ------------------------------------------------------------
    def _publish_segment(self, bucket: str, recs: List[Record],
                         source: str) -> Tuple[str, Dict[str, Any]]:
        """Write one sealed segment (complete, fsynced, hard-linked into
        place, directory fsynced — utils/atomic.py ``publish_sealed``)
        and return ``(name, manifest meta)``.  The caller indexes it;
        until then it is a loadable orphan."""
        header = {"kind": "segment", "version": SEGMENT_VERSION,
                  "bucket": bucket, "n_records": len(recs),
                  "schema": RECORD_SCHEMA, "created_at": time.time(),
                  "owner": self.owner, "source": source}
        body = [json.dumps(header, sort_keys=True)]
        body += [json.dumps({"sha256": record_digest(r), "record": r},
                            sort_keys=True)
                 for r in recs]
        text = "\n".join(body) + "\n"

        def make_name() -> str:
            # fresh stamp per attempt: a rival writer's collision re-draws
            self._seg_counter += 1
            return (f"seg-{bucket}-{int(time.time() * 1e6)}-"
                    f"{self.owner}-{self._seg_counter}.jsonl")

        name = guarded_store_write(
            self.dir,
            lambda: publish_sealed(self.segments_path, make_name, text),
            where="serve.store.publish_segment")
        meta = {"bucket": bucket, "records": len(recs),
                "bytes": len(text), "created_at": header["created_at"],
                "source": source, "sealed": True}
        self.segment_info[name] = {**meta, "listed": False,
                                   "damaged": False}
        return name, meta

    def flush(self) -> None:
        """Publish one new segment per *dirty* bucket and index them in
        the manifest — cost proportional to the records mutated since
        the last flush, never to the corpus (module docstring)."""
        if self.dir is None:
            return
        # the "store merge" leg of a request's cross-process trace: a
        # drain daemon flushes under the work item's ambient context, so
        # this span carries the originating query's trace_id
        with get_tracer().span("serve.store.flush",
                               backend="segmented") as sp:
            os.makedirs(self.dir, exist_ok=True)
            by_bucket: Dict[str, List[Record]] = {}
            for exact, key in self._dirty:
                rec = self.entries.get(exact, {}).get(key)
                if rec is not None:
                    by_bucket.setdefault(rec.get("bucket") or "unbucketed",
                                         []).append(rec)
            added: Dict[str, Dict[str, Any]] = {}
            for bucket in sorted(by_bucket):
                name, meta = self._publish_segment(
                    bucket, by_bucket[bucket], source="flush")
                added[name] = meta
            sp.set("segments", len(added))
            sp.set("dirty_records", sum(len(v) for v in by_bucket.values()))
            if added or not os.path.exists(self.manifest_path):

                def mutate(doc):
                    doc["segments"].update(added)
                    return doc

                self._mutate_manifest(mutate)
                for name in added:
                    self.segment_info[name]["listed"] = True
            self._dirty.clear()
        get_metrics().counter("serve.store.flushed").inc()

    # -- stats ---------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        st = super().stats()
        by_bucket: Dict[str, Dict[str, Any]] = {}
        for name, info in self.segment_info.items():
            b = by_bucket.setdefault(info["bucket"], {
                "segments": 0, "records": 0, "bytes": 0, "live": 0})
            b["segments"] += 1
            b["records"] += info.get("records", 0)
            b["bytes"] += info.get("bytes", 0)
            b["live"] += 1 if info.get("listed") else 0
        admission = {"verified": 0, "unsound": 0, "unstamped": 0}
        for rec in self.records():
            if rec.get("flags", {}).get("unsound"):
                admission["unsound"] += 1
            elif rec.get("verified_at_admission"):
                admission["verified"] += 1
            else:
                admission["unstamped"] += 1
        compactions = list((self.manifest_doc or {}).get("compactions", []))
        st.update({
            "backend": "segmented",
            "segments": {
                "count": len(self.segment_info),
                "bytes": sum(i.get("bytes", 0)
                             for i in self.segment_info.values()),
                "orphans": len(self.orphan_segments),
                "missing": len(self.missing_segments),
                "quarantined": len(self.quarantined_segments),
                "newer_skipped": len(self.newer_segments),
            },
            "by_bucket": dict(sorted(by_bucket.items())),
            "checksum_failed": self.checksum_failed,
            "salvaged": self.salvaged,
            "admission": admission,
            "compactions": len(compactions),
            "last_compaction": compactions[-1] if compactions else None,
            "dirty": len(self._dirty),
        })
        return st


class Compactor:
    """The offline segment compactor (module docstring): merge each
    multi-segment bucket via the commutative record merge, publish, index,
    then reclaim — ``SIGKILL``-safe at every instant, lease-exclusive via
    serve/lease.py.  ``crash_after`` is the chaos hook (the CLI's hidden
    ``--crash-after``): ``"segment"`` SIGKILLs this process after the
    first merged segment is published but *before* the manifest lands,
    ``"manifest"`` after the manifest lands but *before* the inputs are
    reclaimed — the two windows a real ``kill -9`` could hit."""

    def __init__(self, store_dir: str, owner: str = "",
                 min_segments: int = 2, lease_ttl_secs: float = 60.0,
                 grace_secs: float = 60.0,
                 log: Optional[Callable[[str], None]] = None,
                 crash_after: Optional[str] = None):
        self.dir = store_dir
        self.owner = _owner_token(
            owner or f"{socket.gethostname()}-{os.getpid()}")
        self.min_segments = max(2, int(min_segments))
        self.lease_ttl_secs = float(lease_ttl_secs)
        self.grace_secs = float(grace_secs)
        self._log = log
        self.crash_after = crash_after

    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def _crash(self, point: str) -> None:
        if self.crash_after == point:  # pragma: no cover — chaos only
            os.kill(os.getpid(), signal.SIGKILL)

    def _gc_tmp(self, now: float) -> int:
        """Collect stale ``*.tmp`` droppings a SIGKILLed writer left
        (never acknowledged — their writer died before the publish, so
        removing them removes nothing a reader could have seen)."""
        n = 0
        for d in (self.dir, os.path.join(self.dir, SEGMENTS_DIR)):
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if not name.endswith(".tmp"):
                    continue
                path = os.path.join(d, name)
                try:
                    if now - os.path.getmtime(path) > self.grace_secs:
                        os.unlink(path)
                        n += 1
                except OSError:
                    continue
        return n

    def run(self) -> Dict[str, Any]:
        """One compaction pass; returns the summary dict the CLI prints.
        A held lease skips (another compactor is live); an expired one is
        reclaimed through the shared protocol."""
        reg = get_metrics()
        summary: Dict[str, Any] = {
            "store": self.dir, "owner": self.owner,
            "buckets_compacted": 0, "segments_reclaimed": 0,
            "orphans_adopted": 0, "tmp_collected": 0, "records": 0,
            "lease_lost": False, "skipped": None,
        }
        if not os.path.isdir(self.dir):
            summary["skipped"] = "missing-store"
            return summary
        lease = LeaseFile(os.path.join(self.dir, COMPACT_LEASE),
                          self.owner, ttl_secs=self.lease_ttl_secs,
                          log=self._log)
        info = lease.claim()
        if info is None:
            reg.counter("serve.compaction.contended").inc()
            summary["skipped"] = "lease-held"
            return summary
        if info.reclaimed:
            self._note(f"compact: reclaimed expired lease (owner "
                       f"{info.prev_owner}, {info.age_s}s stale)")
        reg.counter("serve.compaction.runs").inc()
        tr = get_tracer()
        try:
            with tr.span("serve.compaction", store=self.dir,
                         owner=self.owner):
                # loading salvages damage + quarantines; flushing
                # persists the salvage (and creates a missing manifest)
                store = SegmentedStore(self.dir, tenant="compactor",
                                       log=self._log)
                store.flush()
                summary["records"] = len(store)
                man = store._read_manifest() or {"segments": {}}
                listed = man.get("segments", {})
                by_bucket: Dict[str, List[str]] = {}
                # compact (and later reclaim) ONLY the segments this
                # pass actually loaded into memory — a rival writer may
                # publish a new segment between our load and now, and a
                # fresh scan here would reclaim it without its records
                # ever entering the merged output (permanent loss, not
                # a superset); the unseen segment just waits for the
                # next pass
                for name, info in store.segment_info.items():
                    by_bucket.setdefault(info["bucket"], []).append(name)
                for bucket in sorted(by_bucket):
                    names = sorted(by_bucket[bucket])
                    orphans = [n for n in names if n not in listed]
                    if len(names) >= self.min_segments:
                        self._compact_bucket(store, bucket, names, summary)
                    elif orphans:
                        self._adopt(store, orphans, summary)
                    if not lease.renew():
                        # a rival reclaimed us mid-pass (stall past the
                        # TTL): every published step is already
                        # consistent; just stop competing
                        summary["lease_lost"] = True
                        self._note("compact: lease lost mid-pass — "
                                   "stopping")
                        break
                summary["tmp_collected"] = self._gc_tmp(time.time())
        finally:
            lease.release()
        return summary

    def _compact_bucket(self, store: SegmentedStore, bucket: str,
                        names: List[str], summary: Dict[str, Any]) -> None:
        recs = [r for r in store.records() if r.get("bucket") == bucket]
        if not recs:
            return
        new_name, meta = store._publish_segment(bucket, recs,
                                                source="compact")
        self._crash("segment")  # chaos window 1: orphan output, inputs live

        def mutate(doc):
            for n in names:
                doc["segments"].pop(n, None)
            doc["segments"][new_name] = meta
            ledger = doc.setdefault("compactions", [])
            ledger.append({
                "at": time.time(), "owner": self.owner, "bucket": bucket,
                "inputs": names, "output": new_name,
                "records": len(recs),
            })
            del ledger[:-COMPACTION_HISTORY_CAP]
            return doc

        store._mutate_manifest(mutate)
        store.segment_info[new_name]["listed"] = True
        self._crash("manifest")  # chaos window 2: inputs orphaned on disk
        reclaimed = 0
        for n in names:
            try:
                os.unlink(os.path.join(store.segments_path, n))
                reclaimed += 1
            except OSError:
                pass
            store.segment_info.pop(n, None)
        summary["buckets_compacted"] += 1
        summary["segments_reclaimed"] += reclaimed
        reg = get_metrics()
        reg.counter("serve.compaction.buckets").inc()
        reg.counter("serve.compaction.reclaimed").inc(reclaimed)
        tr = get_tracer()
        if tr.enabled:
            tr.event("serve.compaction.bucket", bucket=bucket,
                     inputs=len(names), output=new_name, records=len(recs))
        self._note(f"compact: bucket {bucket[:12]} {len(names)} -> 1 "
                   f"segment(s), {len(recs)} record(s)")

    def _adopt(self, store: SegmentedStore, orphans: List[str],
               summary: Dict[str, Any]) -> None:
        """Index orphan segments (a flush or compaction that died after
        publish, before the manifest) without rewriting them — adoption
        is what turns 'loaded by scan' into 'listed', so the ledgered
        view converges back to the disk truth."""
        metas: Dict[str, Dict[str, Any]] = {}
        for name in orphans:
            path = os.path.join(store.segments_path, name)
            try:
                with open(path) as f:
                    header = json.loads(f.readline())
                size = os.path.getsize(path)
            except (OSError, ValueError):
                continue  # vanished or unreadable: the loader's problem
            metas[name] = {
                "bucket": header.get("bucket", segment_bucket_of(name)),
                "records": header.get("n_records", 0), "bytes": size,
                "created_at": header.get("created_at"),
                "source": "adopted", "sealed": True,
            }
        if not metas:
            return

        def mutate(doc):
            for name, meta in metas.items():
                doc["segments"].setdefault(name, meta)
            return doc

        store._mutate_manifest(mutate)
        summary["orphans_adopted"] += len(metas)
        get_metrics().counter("serve.compaction.adopted").inc(len(metas))
        self._note(f"compact: adopted {len(metas)} orphan segment(s)")
