"""Production traffic recorder + tail-sampled exemplars — the
watchtower's data plane (docs/observability.md "Watchtower").

The steering benchmark (serve/replay.py) drove a *synthetic* query mix;
the ROADMAP gap is "replay traces drawn from recorded production mixes
instead of the synthetic generator".  This module records the mix:

**Request log** (:class:`RequestLog`) — ``serve listen`` appends one
compact record per admitted request (timestamp, trace_id, tenant, tier,
fingerprint digests, ``resolve_us`` + per-phase breakdown, shed/timeout
outcome, and the verbatim request kwargs so the query is *re-issuable*)
into a sampled, size-bounded, checksummed JSONL log using the
sealed-segment publish discipline of serve/segments.py:

* records buffer in memory and publish as **sealed segments**
  (``req-<stamp>-<owner>-<n>.jsonl``): line 0 a header
  (``kind: "reqlog_segment"``, version, counts, cumulative
  dropped-by-sampling), each following line ``{"sha256", "record"}``
  checksummed over the record's canonical serialization — every line is
  self-certifying, salvage never trusts framing;
* publish is atomic (private temp, fsync, hard-link, directory fsync) —
  a reader can never observe a torn acknowledged segment; a SIGKILLed
  writer loses at most its unflushed buffer;
* **sampling** is deterministic per ``trace_id`` (a stable hash, never
  a process RNG — the solvers' seeded streams stay untouched), and what
  was dropped is *counted*, never silent (``position()`` +
  the segment headers + ``serve.reqlog.sampled_out``);
* **rotation with a retention cap**: the oldest sealed segments are
  reclaimed beyond ``retain_segments`` — a month of traffic costs a
  bounded directory, and the cap is visible in ``position()``.

:func:`read_request_log` is the salvage-on-damage reader the replay
harness (``serve/replay.py --from-recorded``) and the report CLI use:
bit-flipped lines are skipped and counted, truncated segments yield
their checksum-valid prefix, newer-version segments are skipped loudly
— same damage taxonomy as the segmented store, strictly read-only.

**Exemplars** (:class:`ExemplarStore`) — aggregate histograms say the
pct99 is bad; they cannot say *which request* made it bad.  The listen
loop keeps full span bundles only for *interesting* requests: the
slowest-K served per heartbeat window, plus **every** shed / timeout /
error / unverified answer immediately.  Each exemplar is a JSONL bundle
(``exemplar-<trace>-<reason>.jsonl``: line 0 a header with the request
record, then the tracer's span/event records carrying that trace_id) —
directly consumable by ``obs/export.py stitch`` (headers are skipped,
spans merge like any process bundle), bounded to ``cap`` files with
oldest-first eviction.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tenzing_tpu.fault.backoff import BackoffPolicy, retry_call
from tenzing_tpu.fault.errors import is_transient_io
from tenzing_tpu.obs.metrics import get_metrics
# THE per-line checksum, owner-token and sealed-publish helpers —
# shared with the segmented store so neither the checksum format nor
# the publish discipline can silently diverge between the two
from tenzing_tpu.serve.segments import _owner_token, record_digest
from tenzing_tpu.utils.atomic import publish_sealed

# transient-EIO retries for a segment publish — short and bounded: the
# recorder rides the heartbeat thread, not the request path
_PUBLISH_RETRY = BackoffPolicy(retries=2, base_secs=0.05, factor=4.0,
                               max_secs=0.5, jitter=0.25)

REQLOG_VERSION = 1
EXEMPLAR_VERSION = 1

RECORD_VERSION = 1          # the per-request record's "v" field
SAMPLE_BUCKETS = 1 << 16    # sampling quantum (per-trace hash space)


def is_reqlog_segment(name: str) -> bool:
    return name.startswith("req-") and name.endswith(".jsonl")


def sampled_in(trace_id: str, sample: float) -> bool:
    """Deterministic admission draw for one request: a stable hash of
    the trace_id against the sample rate.  Hash-based, not RNG-based —
    recording must never perturb the seeded solver streams, and the
    same trace must draw the same verdict on every host that sees it
    (a gateway retry is one request, not two coin flips)."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    h = hashlib.sha1(str(trace_id).encode()).digest()
    bucket = int.from_bytes(h[:4], "big") % SAMPLE_BUCKETS
    return bucket < int(sample * SAMPLE_BUCKETS)


class RequestLog:
    """The sampled, size-bounded, checksummed request log (module
    docstring).  Thread-safe: the listen loop appends from worker,
    watchdog and intake threads alike.  A full buffer rotates into a
    *pending* sealed batch without any I/O — the fsync-heavy publish
    runs from the heartbeat (:meth:`publish_pending` / :meth:`flush`),
    never on the request path, unless the pending backlog exceeds
    ``pending_batch_cap`` batches (extreme-storm backpressure: inline
    publish then beats unbounded memory)."""

    def __init__(self, directory: str, owner: str = "",
                 sample: float = 1.0, segment_records: int = 256,
                 retain_segments: int = 16, pending_batch_cap: int = 16,
                 log: Optional[Callable[[str], None]] = None):
        self.dir = directory
        self.owner = _owner_token(
            owner or f"{socket.gethostname()}-{os.getpid()}")
        self.sample = float(sample)
        self.segment_records = max(1, int(segment_records))
        self.retain_segments = max(1, int(retain_segments))
        self.pending_batch_cap = max(1, int(pending_batch_cap))
        self._log = log
        self._lock = threading.Lock()
        self._buffer: List[Dict[str, Any]] = []
        self._pending: List[List[Dict[str, Any]]] = []
        self._seg_counter = 0
        self.records_written = 0
        self.bytes_written = 0
        self.dropped_sampling = 0
        self.segments_published = 0
        self.segments_reclaimed = 0
        # count-and-drop bookkeeping (docs/robustness.md "Degraded
        # read-only mode"): a full/hostile filesystem costs records,
        # visibly, never the serving path — both surface in position()
        self.dropped_write = 0
        self.write_errors = 0
        self.last_segment: Optional[str] = None

    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def append(self, record: Dict[str, Any]) -> bool:
        """Record one request; False when the sampling draw dropped it
        (counted, never silent).  A full buffer rotates into a pending
        sealed batch with no I/O on this (request-path) thread."""
        if not sampled_in(record.get("trace_id") or "", self.sample):
            with self._lock:
                self.dropped_sampling += 1
            get_metrics().counter("serve.reqlog.sampled_out").inc()
            return False
        # coerce to plain JSON NOW (default=str absorbs stray bytes /
        # numpy scalars a caller smuggled into request kwargs): a
        # non-serializable record surfacing at segment-publish time
        # would throw away every other buffered record with it
        record = json.loads(json.dumps(record, sort_keys=True,
                                       default=str))
        overflow: Optional[List[List[Dict[str, Any]]]] = None
        with self._lock:
            self._buffer.append(record)
            if len(self._buffer) >= self.segment_records:
                self._pending.append(self._buffer)
                self._buffer = []
                if len(self._pending) > self.pending_batch_cap:
                    # backpressure: the heartbeat is not keeping up with
                    # an extreme storm — pay the publish inline rather
                    # than grow memory without bound
                    overflow, self._pending = self._pending, []
        get_metrics().counter("serve.reqlog.recorded").inc()
        for batch in overflow or []:
            self._publish(batch)
        return True

    def publish_pending(self) -> int:
        """Publish every full sealed batch (the cheap per-heartbeat
        hook; a no-op when nothing rotated since the last call)."""
        with self._lock:
            batches, self._pending = self._pending, []
        for batch in batches:
            self._publish(batch)
        return len(batches)

    def flush(self) -> Optional[str]:
        """Publish pending batches plus whatever is part-buffered (the
        drain / cadence hook); None when everything was already out."""
        n = self.publish_pending()
        with self._lock:
            recs, self._buffer = self._buffer, []
        if not recs:
            return self.last_segment if n else None
        return self._publish(recs)

    def _publish(self, recs: List[Dict[str, Any]]) -> Optional[str]:
        """Seal + atomically publish one segment, then apply retention
        (utils/atomic.py ``publish_sealed`` — the same discipline as
        the segmented store's segments).  Transient EIO retries through
        THE shared backoff; a publish that still fails (ENOSPC, dead
        disk) **counts and drops** the batch — recording must degrade,
        never wedge the loop or grow memory without bound.  Returns the
        published name, or None when the batch was dropped."""
        with self._lock:
            dropped = self.dropped_sampling
        header = {"kind": "reqlog_segment", "version": REQLOG_VERSION,
                  "n_records": len(recs), "owner": self.owner,
                  "created_at": time.time(),
                  # cumulative, so a reader can report recording
                  # coverage without the writer process being alive
                  "dropped_sampling": dropped}
        body = [json.dumps(header, sort_keys=True)]
        body += [json.dumps({"sha256": record_digest(r), "record": r},
                            sort_keys=True) for r in recs]
        text = "\n".join(body) + "\n"

        def make_name() -> str:
            with self._lock:
                self._seg_counter += 1
                n = self._seg_counter
            return (f"req-{int(time.time() * 1e6)}-"
                    f"{self.owner}-{n}.jsonl")

        try:
            name = retry_call(
                lambda: publish_sealed(self.dir, make_name, text),
                policy=_PUBLISH_RETRY, retry_on=is_transient_io,
                where="serve.reqlog.publish")
        except OSError as e:
            with self._lock:
                self.dropped_write += len(recs)
                self.write_errors += 1
            get_metrics().counter(
                "serve.reqlog.dropped_write").inc(len(recs))
            self._note(f"reqlog: dropped {len(recs)} record(s), publish "
                       f"failed ({e})")
            return None
        with self._lock:
            self.records_written += len(recs)
            self.bytes_written += len(text)
            self.segments_published += 1
            self.last_segment = name
        get_metrics().counter("serve.reqlog.segments").inc()
        self._retain()
        return name

    def _retain(self) -> None:
        """Reclaim the oldest sealed segments beyond the retention cap
        (names sort by their microsecond stamp — lexicographic order is
        publish order for one writer; cross-writer ties don't matter,
        retention is a bound, not an ordering contract)."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if is_reqlog_segment(n))
        except OSError:
            return
        n_excess = len(names) - self.retain_segments
        for name in names[:max(0, n_excess)]:
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                continue
            with self._lock:
                self.segments_reclaimed += 1
            get_metrics().counter("serve.reqlog.reclaimed").inc()

    def position(self) -> Dict[str, Any]:
        """Where the recorder stands — the block metric snapshots carry
        so the recorder is itself observable (ISSUE 13 satellite):
        current segment, bytes/records published, buffered backlog, and
        the dropped-by-sampling count."""
        with self._lock:
            return {
                "dir": self.dir,
                "sample": self.sample,
                "segment": self.last_segment,
                "segments": self.segments_published,
                "segments_reclaimed": self.segments_reclaimed,
                "records": self.records_written,
                "bytes": self.bytes_written,
                # buffered = everything acknowledged but not yet sealed
                # on disk: the open buffer plus rotated pending batches
                "buffered": (len(self._buffer)
                             + sum(len(b) for b in self._pending)),
                "dropped_sampling": self.dropped_sampling,
                "dropped_write": self.dropped_write,
                "write_errors": self.write_errors,
            }


def read_request_log(directory: str,
                     log: Optional[Callable[[str], None]] = None
                     ) -> Dict[str, Any]:
    """Salvage-on-damage read of a request-log directory (module
    docstring).  Returns ``{"records": [...], "segments", "damaged",
    "checksum_failed", "torn_lines", "newer_skipped",
    "dropped_sampling"}`` — records sorted by their ``ts`` stamp so
    inter-arrival reconstruction is order-correct.  Strictly read-only:
    damage is counted and reported, never quarantined (the writer owns
    its directory)."""

    def note(msg: str) -> None:
        if log is not None:
            log(msg)

    out: Dict[str, Any] = {"records": [], "segments": 0, "damaged": 0,
                           "checksum_failed": 0, "torn_lines": 0,
                           "newer_skipped": 0, "dropped_sampling": 0}
    # the header count is cumulative PER WRITER: max within an owner,
    # summed across owners (two loops recording into one directory must
    # not have one's coverage shadow the other's)
    dropped_by_owner: Dict[str, int] = {}
    try:
        names = sorted(n for n in os.listdir(directory)
                       if is_reqlog_segment(n))
    except OSError as e:
        raise OSError(f"request log {directory} unreadable: {e}") from e
    for name in names:
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue  # reclaimed between listdir and open
        damaged = False
        header: Dict[str, Any] = {}
        if lines:
            try:
                header = json.loads(lines[0])
                if not isinstance(header, dict) or \
                        header.get("kind") != "reqlog_segment":
                    raise ValueError("not a reqlog segment header")
            except ValueError:
                header, damaged = {}, True
        else:
            damaged = True
        if header.get("version", 0) > REQLOG_VERSION:
            out["newer_skipped"] += 1
            note(f"reqlog: segment {name} has newer version "
                 f"{header.get('version')!r}; skipped")
            continue
        own = str(header.get("owner", "?"))
        dropped_by_owner[own] = max(
            dropped_by_owner.get(own, 0),
            int(header.get("dropped_sampling") or 0))
        n_valid = 0
        for line in lines[1:]:
            if not line.strip():
                continue
            try:
                j = json.loads(line)
            except ValueError:
                out["torn_lines"] += 1
                damaged = True
                continue
            rec = j.get("record") if isinstance(j, dict) else None
            if not isinstance(rec, dict) or \
                    record_digest(rec) != j.get("sha256"):
                out["checksum_failed"] += 1
                damaged = True
                continue
            out["records"].append(rec)
            n_valid += 1
        n_expected = header.get("n_records")
        if isinstance(n_expected, int) and n_valid < n_expected:
            damaged = True
        if damaged:
            out["damaged"] += 1
            note(f"reqlog: segment {name} damaged; salvaged "
                 f"{n_valid} record(s)")
        out["segments"] += 1
    out["dropped_sampling"] = sum(dropped_by_owner.values())
    out["records"].sort(key=lambda r: (r.get("ts") or 0.0))
    return out


# -- tail-sampled exemplars --------------------------------------------------

# outcomes that make a request interesting unconditionally (module
# docstring): its full span bundle is written immediately, never
# subject to the slowest-K window
ALWAYS_KEEP = ("shed", "timeout", "error", "unverified")


class ExemplarStore:
    """Tail-sampled span bundles for the requests behind a bad pct99
    (module docstring).  ``offer`` every completed request; the
    heartbeat calls :meth:`roll` to close the current window and write
    the slowest ``k`` served exemplars; interesting outcomes write
    immediately.  Thread-safe, bounded to ``cap`` files."""

    def __init__(self, directory: str, k: int = 4, cap: int = 64,
                 immediate_per_window: int = 8, tracer=None,
                 log: Optional[Callable[[str], None]] = None):
        self.dir = directory
        self.k = max(0, int(k))
        self.cap = max(1, int(cap))
        # interesting outcomes write on the REQUEST path (intake /
        # watchdog thread), and a shed storm makes them anything but
        # rare — the per-window budget keeps overload from buying an
        # O(tracer-ring) snapshot + a file write per rejected request;
        # beyond it the storm is counted (suppressed), never amplified
        self.immediate_per_window = max(1, int(immediate_per_window))
        self._immediate_left = self.immediate_per_window
        self.suppressed = 0
        self._tracer = tracer
        self._log = log
        self._lock = threading.Lock()
        # the current window's served candidates: (resolve_us, record)
        self._window: List[Tuple[float, Dict[str, Any]]] = []
        self.written = 0
        self._seq = 0  # filename uniquifier (batch members share a trace)

    def offer(self, record: Dict[str, Any],
              interesting: Optional[str] = None) -> Optional[str]:
        """One completed request.  ``interesting`` (an
        :data:`ALWAYS_KEEP` reason) writes the bundle now — up to the
        per-window budget; otherwise the record becomes a slowest-K
        candidate for the current window."""
        if interesting is not None:
            with self._lock:
                if self._immediate_left <= 0:
                    self.suppressed += 1
                    over_budget = True
                else:
                    self._immediate_left -= 1
                    over_budget = False
            if over_budget:
                get_metrics().counter("serve.exemplars.suppressed").inc()
                return None
            return self._write(record, interesting)
        us = record.get("resolve_us")
        if us is None:
            return None
        with self._lock:
            self._window.append((float(us), record))
            # bound the candidate list between rolls: only the current
            # top-k can ever be written, so keep a small multiple
            if len(self._window) > max(32, 4 * self.k):
                self._window.sort(key=lambda t: -t[0])
                del self._window[max(32, 4 * self.k):]
        return None

    def roll(self) -> List[str]:
        """Close the window: write the slowest-K served candidates seen
        since the last roll and refill the immediate-write budget (the
        heartbeat hook).  ONE tracer snapshot serves the whole roll —
        never one per exemplar."""
        with self._lock:
            window, self._window = self._window, []
            self._immediate_left = self.immediate_per_window
        window.sort(key=lambda t: -t[0])
        top = window[:self.k]
        if not top:
            return []
        by_trace = self._trace_records_many(
            [str(rec.get("trace_id") or "no-trace") for _, rec in top])
        out = []
        for _, rec in top:
            tid = str(rec.get("trace_id") or "no-trace")
            p = self._write(rec, "slow", trace_recs=by_trace.get(tid, []))
            if p is not None:
                out.append(p)
        return out

    def _trace_records_many(self, trace_ids: List[str]
                            ) -> Dict[str, List[Dict[str, Any]]]:
        """The tracer's span/event records bucketed by ``trace_id`` —
        ONE O(ring) snapshot shared by every requested trace (a roll
        writes K exemplars from a single scan; the immediate path pays
        one scan per write, bounded by the per-window budget)."""
        wanted = set(trace_ids)
        out: Dict[str, List[Dict[str, Any]]] = {t: [] for t in wanted}
        tracer = self._tracer
        if tracer is None:
            from tenzing_tpu.obs.tracer import get_tracer
            tracer = get_tracer()
        if not wanted or not getattr(tracer, "enabled", False):
            return out
        spans, events, open_spans = tracer.snapshot(block=False,
                                                    flush_open=True)
        for r in spans + open_spans + events:
            j = r.to_json()
            tid = (j.get("attrs") or {}).get("trace_id")
            if tid in wanted:
                out[tid].append(j)
        for recs in out.values():
            recs.sort(key=lambda r: r.get("ts_us", 0.0))
        return out

    def _write(self, record: Dict[str, Any], reason: str,
               trace_recs: Optional[List[Dict[str, Any]]] = None
               ) -> Optional[str]:
        trace_id = str(record.get("trace_id") or "no-trace")
        if trace_recs is None:
            trace_recs = self._trace_records_many([trace_id])[trace_id]
        with self._lock:
            self._seq += 1
            seq = self._seq
        # the sequence uniquifies the name: every member of a shed or
        # errored batch shares the pending's one trace_id, and N bundles
        # overwriting one file would silently lose N-1 of them
        name = (f"exemplar-{_owner_token(trace_id)[:16]}-{reason}"
                f"-{seq}.jsonl")
        path = os.path.join(self.dir, name)
        header = {"kind": "exemplar", "version": EXEMPLAR_VERSION,
                  "reason": reason, "trace_id": trace_id,
                  "written_at": time.time(), "record": record}
        lines = [json.dumps(header, sort_keys=True, default=str)]
        lines += [json.dumps(r, sort_keys=True, default=str)
                  for r in trace_recs]
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write("\n".join(lines) + "\n")
            os.replace(tmp, path)
        except OSError as e:
            if self._log is not None:
                self._log(f"exemplar write failed ({e})")
            return None
        with self._lock:
            self.written += 1
        get_metrics().counter("serve.exemplars.written").inc()
        self._evict()
        return path

    def _evict(self) -> None:
        """Oldest-first eviction beyond ``cap`` (mtime order: exemplar
        names key on trace_id, so name order is meaningless here)."""
        try:
            entries = [(os.path.getmtime(os.path.join(self.dir, n)), n)
                       for n in os.listdir(self.dir)
                       if n.startswith("exemplar-") and
                       n.endswith(".jsonl")]
        except OSError:
            return
        entries.sort()
        for _, name in entries[:max(0, len(entries) - self.cap)]:
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                continue


def read_exemplars(directory: str) -> List[Dict[str, Any]]:
    """The exemplar headers found in ``directory`` (newest first) —
    what the report CLI renders as "the worst requests behind the
    pct99"; span lines stay on disk for ``obs/export.py stitch``."""
    out: List[Tuple[float, Dict[str, Any]]] = []
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith("exemplar-") and n.endswith(".jsonl")]
    except OSError:
        return []
    for name in names:
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                header = json.loads(f.readline())
                n_lines = sum(1 for line in f if line.strip())
        except (OSError, ValueError):
            continue
        if not isinstance(header, dict) or header.get("kind") != "exemplar":
            continue
        header["path"] = path
        header["n_trace_records"] = max(0, n_lines)
        out.append((float(header.get("written_at") or 0.0), header))
    out.sort(key=lambda t: -t[0])
    return [h for _, h in out]
