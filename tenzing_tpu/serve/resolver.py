"""Tiered request resolution: exact hit / near miss / cold.

The serving path's answer policy, in strictly-cheaper-first order
(docs/serving.md):

* **exact** — the store holds a schedule for the request's exact
  fingerprint digest: deserialize it against the request's graph and
  re-verify through the independent
  :class:`~tenzing_tpu.verify.ScheduleVerifier` (the PR-4 pair of eyes —
  a store poisoned by a bad merge or a stale graph variant must never
  serve an under-synchronized schedule).  Zero compiles, zero
  measurements: resolution never builds an executor, and the provenance
  block says so explicitly.  An entry that fails re-verification is
  flagged, *not served*, and resolution falls through.
* **near** — no exact entry, but the bucket (same bucketed shape / mesh
  / engines) has neighbors: answer with the best neighbor's schedule,
  priced by the PR-2 surrogate under an **uncertainty gate** — a
  prediction whose ensemble spread exceeds ``near_max_sigma`` (log
  space) is not an answer, it is a guess, and the request falls through
  to cold.  Served predictions carry ``was_predicted: true`` provenance
  (the same honesty rule the learned screen's ``fid=model`` dump rows
  follow: a prediction must never masquerade as a measurement), and the
  request's fingerprint is enqueued for background refinement while the
  answering entry is flagged ``needs_refinement``.
* **cold** — nothing to answer from: enqueue a checkpointed
  :class:`~tenzing_tpu.bench.driver.DriverRequest` work item
  (serve/store.py ``WorkQueue``) for a driver to drain, and say so.

Every resolution lands a ``serve.query`` span, a ``serve.<tier>``
counter, and a ``serve.resolve_us`` latency observation — plus a
per-tier ``serve.resolve_us.<tier>`` series and a **per-phase
breakdown** (``Resolution.phase_us``: fingerprint canonicalization,
exact-cache probe, store walk) — the profile the ROADMAP's
tens-of-µs exact-tier item steers by (docs/observability.md).

Resolution runs under a cross-process trace context (obs/context.py):
the caller's (serve/listen.py mints one per request at ingress), or one
minted here for context-less callers (the one-shot ``serve query``
CLI).  The context stamps every span/event on the path and rides the
cold tier's work-item envelope, so the daemon drain a cold query causes
is linkable back to the query (docs/observability.md "Fleet telemetry
plane").
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from tenzing_tpu.obs import context as obs_context
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.serve.fingerprint import WorkloadFingerprint, fingerprint_of
from tenzing_tpu.serve.store import Record, ScheduleStore, WorkQueue


@dataclass
class Resolution:
    """One resolved request.  ``provenance`` always carries
    ``compiles: 0`` / ``measurements: 0`` — the serving tiers never
    touch an executor; a number in here is either a stored measurement
    (exact) or an explicitly-marked prediction (near)."""

    tier: str  # "exact" | "near" | "cold"
    fingerprint: WorkloadFingerprint
    record: Optional[Record] = None
    sequence: Optional[Any] = None  # Sequence, resolved against the request
    pct50_us: Optional[float] = None
    vs_naive: Optional[float] = None
    provenance: Dict[str, Any] = field(default_factory=dict)
    work_item: Optional[str] = None  # cold: the queued item's path
    # per-phase latency breakdown (µs): fingerprint / cache_probe /
    # store_walk (+ serialize, added by the transport) — the exact-tier
    # profile serve/replay.py aggregates into SERVE_BENCH documents
    phase_us: Dict[str, float] = field(default_factory=dict)
    trace_id: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "tier": self.tier,
            "fingerprint": self.fingerprint.to_json(),
            "provenance": self.provenance,
        }
        if self.phase_us:
            out["phase_us"] = self.phase_us
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.record is not None:
            out["key"] = self.record["key"]
            out["ops"] = self.record["ops"]
        if self.pct50_us is not None:
            out["pct50_us"] = self.pct50_us
        if self.vs_naive is not None:
            out["vs_naive"] = self.vs_naive
        if self.work_item is not None:
            out["work_item"] = self.work_item
        return out


class Resolver:
    """The tier policy over one :class:`ScheduleStore` (see module
    docstring).

    ``model`` is a loaded :class:`~tenzing_tpu.learn.RidgeEnsemble` (the
    PR-2 surrogate) — without one the near tier is disabled and bucket
    neighbors fall through to cold: an unpriced neighbor is not an
    answer.  ``graph_builder`` defaults to the driver's device-free
    :func:`~tenzing_tpu.bench.driver.graph_for`; graphs/verifiers are
    cached per exact digest because structurally-identical requests
    dominate serving traffic."""

    def __init__(self, store: ScheduleStore, queue: Optional[WorkQueue] = None,
                 model=None, near_max_sigma: float = 0.75,
                 verify: bool = True,
                 graph_builder: Optional[Callable] = None,
                 log: Optional[Callable[[str], None]] = None,
                 serve_cache: bool = True,
                 legacy_verify: bool = False):
        self.store = store
        self.queue = queue
        self.model = model
        self.near_max_sigma = float(near_max_sigma)
        self.verify = verify
        # serve_cache=False disables the exact-tier sealed-record cache;
        # legacy_verify=True additionally ignores admission stamps and
        # re-verifies every exact hit — together they replay the pre-PR
        # resolution path exactly (the trace-replay benchmark's baseline,
        # serve/replay.py; never the serving configuration)
        self.serve_cache = serve_cache
        self.legacy_verify = legacy_verify
        self._graph_builder = graph_builder
        # per-exact-digest caches, BOUNDED: the digests are derived from
        # client-controlled shape parameters, and a long-lived server
        # sweeping shapes (one graph + verifier + surrogate each) must
        # not grow without limit — insertion-order eviction is enough
        # because serving traffic concentrates on few fingerprints
        self.cache_cap = 32
        # the exact-tier answer cache is the serving hot path (one dict
        # probe per hit) and its entries are small (a record reference +
        # a materialized Sequence): it earns a much larger bound
        self.exact_cache_cap = 4096
        self._graphs: Dict[str, Tuple[Any, Dict[str, int]]] = {}
        self._verifiers: Dict[str, Any] = {}
        # exact digest -> (record, sequence, provenance) of the admitted
        # best answer; validity keyed on the store's generation counter
        # (any record landing anywhere invalidates wholesale — coarse,
        # but merges are rare and wrong answers are forever)
        self._exact_cache: Dict[str, Tuple[Record, Any, Dict[str, Any]]] = {}
        self._exact_cache_gen = -1
        # (model, surrogate) per exact digest: the surrogate's
        # canonical-key prediction cache must survive across queries of
        # a hot fingerprint (re-featurizing the same neighbors per
        # request is O(schedule length) on the serve.resolve_us path);
        # keyed with the model so a retrain invalidates
        self._surrogates: Dict[str, Tuple[Any, Any]] = {}
        self._log = log

    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def _cache_put(self, cache: Dict[str, Any], key: str, value,
                   cap: Optional[int] = None) -> None:
        if key in cache:
            # re-put of a present key must update in place: evicting an
            # oldest entry for it would shrink the cache by one per
            # refresh (and could evict the very entry being refreshed)
            cache[key] = value
            return
        cap = self.cache_cap if cap is None else cap
        while len(cache) >= cap:
            cache.pop(next(iter(cache)))  # oldest insertion
        cache[key] = value

    def _graph(self, req, fp: WorkloadFingerprint):
        got = self._graphs.get(fp.exact_digest)
        if got is None:
            builder = self._graph_builder
            if builder is None:
                from tenzing_tpu.bench.driver import graph_for as builder
            got = builder(req)
            self._cache_put(self._graphs, fp.exact_digest, got)
        return got

    def _verifier(self, graph, fp: WorkloadFingerprint):
        v = self._verifiers.get(fp.exact_digest)
        if v is None:
            from tenzing_tpu.verify import ScheduleVerifier

            v = ScheduleVerifier(graph)
            self._cache_put(self._verifiers, fp.exact_digest, v)
        return v

    def _materialize(self, rec: Record, graph) -> Optional[Any]:
        """The record's ops resolved against the *request's* graph; None
        when they no longer resolve (recorded against a different
        structural variant) — a store answer the request cannot execute
        is no answer."""
        from tenzing_tpu.core.serdes import sequence_from_json

        try:
            return sequence_from_json(rec["ops"], graph)
        except Exception as e:
            self._note(f"serve: record {rec['key'][:8]} does not resolve "
                       f"({type(e).__name__}: {str(e)[:120]})")
            return None

    # -- tiers ---------------------------------------------------------------
    def _try_exact(self, req, fp: WorkloadFingerprint,
                   phases: Dict[str, float]) -> Optional[Resolution]:
        reg = get_metrics()
        t0 = time.perf_counter()
        with get_tracer().span("serve.cache_probe") as psp:
            if self.serve_cache:
                hit = self._exact_cache.get(fp.exact_digest)
                if hit is not None and \
                        hit[0].get("flags", {}).get("unsound"):
                    # belt-and-braces behind the generation check: a
                    # record flagged between the generation bump and this
                    # probe (or by a caller holding the same dict) must
                    # never be served
                    self._exact_cache.pop(fp.exact_digest, None)
                    hit = None
                if hit is not None:
                    # the hot path: one dict probe, zero
                    # materializations, zero verifier invocations — the
                    # record was admitted (verified + sealed) when it
                    # entered the cache
                    rec, seq, prov = hit
                    phases["cache_probe"] = round(
                        (time.perf_counter() - t0) * 1e6, 2)
                    psp.set("hit", True)
                    reg.counter("serve.exact_cache.hits").inc()
                    return Resolution(
                        tier="exact", fingerprint=fp, record=rec,
                        sequence=seq, pct50_us=rec.get("pct50_us"),
                        vs_naive=rec.get("vs_naive"),
                        provenance=dict(prov, cache_hit=True))
            psp.set("hit", False)
        phases["cache_probe"] = round((time.perf_counter() - t0) * 1e6, 2)
        t_walk = time.perf_counter()
        records = self.store.exact_records(fp.exact_digest)
        # the walk phase covers everything past the probe (store listing,
        # materialization, verification fallback) — the cold/near paths
        # overwrite nothing, so an exact miss still reports what the
        # exact tier spent before falling through
        try:
            return self._walk_exact(req, fp, records, reg)
        finally:
            phases["store_walk"] = round(
                (time.perf_counter() - t_walk) * 1e6, 2)

    def _walk_exact(self, req, fp: WorkloadFingerprint,
                    records, reg) -> Optional[Resolution]:
        if not records:
            return None
        if self.serve_cache:
            reg.counter("serve.exact_cache.misses").inc()
        graph = None
        # best-first WALK, not best-only: one unsound or unresolvable
        # best record must not permanently block a sound runner-up under
        # the same exact digest (the near tier excludes the requester's
        # own digest, so falling through here would skip it entirely)
        for rec in records:
            if rec.get("flags", {}).get("unsound"):
                # flagged at admission (or by a prior discovery): never
                # served, and never worth re-verifying — the verdict is
                # deterministic
                continue
            if graph is None:
                graph, _ = self._graph(req, fp)
            seq = self._materialize(rec, graph)
            if seq is None:
                continue
            admission_stamped = (bool(rec.get("verified_at_admission"))
                                 and not self.legacy_verify)
            verified = None
            verifier_calls = 0
            if admission_stamped:
                # verified once when it was merged into the store, under
                # this same fingerprint's (deterministic) graph — serving
                # it again needs no second opinion (docs/serving.md
                # "Admission-time verification")
                verified = True
            elif self.verify:
                verifier_calls = 1
                reg.counter("serve.verify_fallback").inc()
                verdict = self._verifier(graph, fp)(seq)
                verified = bool(verdict.ok)
                if not verified:
                    # an unsound stored schedule must never be served —
                    # flag it (visible in stats + the report CLI) and
                    # try the next-best record
                    self.store.flag(rec["exact"], rec["key"],
                                    unsound=True, needs_refinement=True)
                    get_metrics().counter("serve.store.unsound").inc()
                    self._note(f"serve: exact entry {rec['key'][:8]} "
                               "failed re-verification — flagged, "
                               "not served")
                    continue
                # the lazy-verified record is now as good as stamped for
                # this process's lifetime (in-memory only: persistence of
                # the stamp belongs to admission, not resolution); the
                # legacy replay path must not stamp — it would leak
                # new-path state into the baseline it exists to measure
                if not self.legacy_verify:
                    rec["verified_at_admission"] = True
            prov = {
                "verified": verified,
                "verified_at_admission": admission_stamped,
                "verifier_calls": verifier_calls,
                "cache_hit": False,
                "was_predicted": False,
                "compiles": 0,
                "measurements": 0,
                "source_exact": rec["exact"],
                **rec.get("provenance", {}),
            }
            if self.serve_cache and verified is not False:
                self._cache_put(self._exact_cache, fp.exact_digest,
                                (rec, seq, prov),
                                cap=self.exact_cache_cap)
            return Resolution(tier="exact", fingerprint=fp, record=rec,
                              sequence=seq, pct50_us=rec.get("pct50_us"),
                              vs_naive=rec.get("vs_naive"),
                              provenance=prov)
        return None

    def _try_near(self, req, fp: WorkloadFingerprint) -> Optional[Resolution]:
        if self.model is None:
            return None
        neighbors = self.store.bucket_records(
            fp.bucket_digest, exclude_exact=fp.exact_digest)
        if not neighbors:
            return None
        graph, nbytes = self._graph(req, fp)
        ent = self._surrogates.get(fp.exact_digest)
        if ent is None or ent[0] is not self.model:
            from tenzing_tpu.learn import SurrogateBenchmarker

            surrogate = SurrogateBenchmarker(self.model, nbytes=nbytes)
            self._cache_put(self._surrogates, fp.exact_digest,
                            (self.model, surrogate))
        else:
            surrogate = ent[1]
        for rec in neighbors:
            if rec.get("flags", {}).get("unsound"):
                continue  # same rule as the exact tier: known-bad, skip
            seq = self._materialize(rec, graph)
            if seq is None:
                continue
            mu, sigma = surrogate.predict(seq)
            if sigma > self.near_max_sigma:
                # uncertainty gate: the ensemble cannot price this
                # schedule for the requested shape — falling through to
                # cold is honest, serving a wide guess is not
                get_metrics().counter("serve.near_rejected").inc()
                self._note(f"serve: near candidate {rec['key'][:8]} "
                           f"rejected (sigma {sigma:.3f} > "
                           f"{self.near_max_sigma})")
                continue
            verified = None
            if self.verify:
                verified = bool(self._verifier(graph, fp)(seq).ok)
                if not verified:
                    # same treatment as the exact tier: counted, flagged
                    # for refinement, never served — a poisoned entry
                    # first discovered via a near miss must not be
                    # invisible to the serve.store.unsound dashboards
                    self.store.flag(rec["exact"], rec["key"],
                                    unsound=True, needs_refinement=True)
                    get_metrics().counter("serve.store.unsound").inc()
                    self._note(f"serve: near candidate {rec['key'][:8]} "
                               "failed re-verification — flagged, "
                               "not served")
                    continue
            # the label space is log(t / naive anchor): exp(-mu) is the
            # predicted paired ratio vs naive for the requested shape
            pred_vs = math.exp(-mu)
            self.store.flag(rec["exact"], rec["key"], needs_refinement=True)
            if self.queue is not None:
                # ensure, not enqueue: a hot near-miss fingerprint
                # re-resolves per request and must not rewrite an
                # identical work item each time (same reasoning as
                # flag()'s unchanged-short-circuit above)
                self.queue.ensure(fp, self._request_payload(req),
                                  reason="refine-near-miss",
                                  trace=obs_context.current())
            prov = {
                "verified": verified,
                "was_predicted": True,
                "uncertainty": round(float(sigma), 4),
                "compiles": 0,
                "measurements": 0,
                "source_exact": rec["exact"],
                "neighbor_vs_naive": rec.get("vs_naive"),
                **rec.get("provenance", {}),
            }
            return Resolution(tier="near", fingerprint=fp, record=rec,
                              sequence=seq, pct50_us=None,
                              vs_naive=round(pred_vs, 4), provenance=prov)
        return None

    def _cold(self, req, fp: WorkloadFingerprint) -> Resolution:
        path = None
        if self.queue is not None:
            # the ambient trace context rides the work-item envelope:
            # the daemon drain this item causes is linkable back to the
            # query that caused it (obs/context.py)
            path = self.queue.ensure(fp, self._request_payload(req),
                                     reason="cold",
                                     trace=obs_context.current())
        return Resolution(
            tier="cold", fingerprint=fp, work_item=path,
            provenance={"was_predicted": False, "compiles": 0,
                        "measurements": 0})

    @staticmethod
    def _request_payload(req) -> Dict[str, Any]:
        fn = getattr(req, "to_json", None)
        return fn() if callable(fn) else dict(vars(req))

    # -- entry ---------------------------------------------------------------
    def resolve(self, req) -> Resolution:
        """Resolve a :class:`~tenzing_tpu.bench.driver.DriverRequest`
        through the tiers, under the ambient trace context (one is
        minted here when the caller arrived without one — the resolver
        is the ingress of record for non-listen paths)."""
        ctx = obs_context.current() or obs_context.new_trace()
        with obs_context.use(ctx):
            return self._resolve(req, ctx)

    def _resolve(self, req, ctx) -> Resolution:
        reg = get_metrics()
        tr = get_tracer()
        t0 = time.perf_counter()
        gen = getattr(self.store, "generation", 0)
        if gen != self._exact_cache_gen:
            # any record landing anywhere (add/merge/load) invalidates
            # the whole answer cache: coarse, but merges are rare and a
            # stale answer would outlive the better record that beat it
            self._exact_cache.clear()
            self._exact_cache_gen = gen
        phases: Dict[str, float] = {}
        with tr.span("serve.query") as sp:
            # fingerprint canonicalization is the first per-hit phase the
            # ROADMAP's tens-of-µs item profiles — timed always (two
            # perf_counter reads), sub-spanned only when tracing is on
            t_fp = time.perf_counter()
            if tr.enabled:
                with tr.span("serve.fingerprint"):
                    fp = fingerprint_of(req)
            else:
                fp = fingerprint_of(req)
            phases["fingerprint"] = round(
                (time.perf_counter() - t_fp) * 1e6, 2)
            sp.set("workload", fp.workload)
            sp.set("exact", fp.exact_digest)
            sp.set("bucket", fp.bucket_digest)
            res = (self._try_exact(req, fp, phases)
                   or self._try_near(req, fp)
                   or self._cold(req, fp))
            sp.set("tier", res.tier)
        res.phase_us = phases
        res.trace_id = ctx.trace_id
        reg.counter(f"serve.{res.tier}").inc()
        dt_us = (time.perf_counter() - t0) * 1e6
        # windowed retention (obs/metrics.py): a live SLO block must
        # read the pct99 of CURRENT traffic — first-N retention would
        # freeze the series at whatever the process saw before the cap
        # filled and hide every post-warm-up regression
        reg.histogram("serve.resolve_us", window=True).observe(dt_us)
        # the per-tier series the SLO block and the follow view read:
        # exact-tier pct99 mixed with cold-tier enqueue latency would
        # steer the tens-of-µs target with the wrong number
        reg.histogram(f"serve.resolve_us.{res.tier}",
                      window=True).observe(dt_us)
        return res
