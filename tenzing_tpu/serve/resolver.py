"""Tiered request resolution: exact hit / near miss / cold.

The serving path's answer policy, in strictly-cheaper-first order
(docs/serving.md):

* **exact** — the store holds a schedule for the request's exact
  fingerprint digest: deserialize it against the request's graph and
  re-verify through the independent
  :class:`~tenzing_tpu.verify.ScheduleVerifier` (the PR-4 pair of eyes —
  a store poisoned by a bad merge or a stale graph variant must never
  serve an under-synchronized schedule).  Zero compiles, zero
  measurements: resolution never builds an executor, and the provenance
  block says so explicitly.  An entry that fails re-verification is
  flagged, *not served*, and resolution falls through.
* **near** — no exact entry, but the bucket (same bucketed shape / mesh
  / engines) has neighbors: answer with the best neighbor's schedule,
  priced by the PR-2 surrogate under an **uncertainty gate** — a
  prediction whose ensemble spread exceeds ``near_max_sigma`` (log
  space) is not an answer, it is a guess, and the request falls through
  to cold.  Served predictions carry ``was_predicted: true`` provenance
  (the same honesty rule the learned screen's ``fid=model`` dump rows
  follow: a prediction must never masquerade as a measurement), and the
  request's fingerprint is enqueued for background refinement while the
  answering entry is flagged ``needs_refinement``.
* **cold** — nothing to answer from: enqueue a checkpointed
  :class:`~tenzing_tpu.bench.driver.DriverRequest` work item
  (serve/store.py ``WorkQueue``) for a driver to drain, and say so.

Every resolution lands a ``serve.query`` span, a ``serve.<tier>``
counter, and a ``serve.resolve_us`` latency observation — plus a
per-tier ``serve.resolve_us.<tier>`` series and a **per-phase
breakdown** (``Resolution.phase_us``: fingerprint canonicalization,
exact-cache probe, store walk) — the profile the ROADMAP's
tens-of-µs exact-tier item steers by (docs/observability.md).

**The fast path** (docs/serving.md "Fast path"): the measured phase
profile says an exact hit spends its time on pure overhead —
serialization, fingerprint canonicalization, digest hashing — so all
three are compiled away:

* **Sealed-response memoization** — when a record enters the exact
  cache, the serialized response body is precomputed once per
  (record, fingerprint) with placeholder slots for the per-request
  fields; serving a hit is then a dict copy + two slot patches
  (``phase_us``, ``trace_id``), byte-identical to fresh serialization
  by construction (both go through the same ``Resolution.to_json``).
  Invalidated with the store-generation bump (which every record
  landing and every flag mutation performs) and on cache eviction —
  ``serve.memo.{hits,misses,invalidations}`` count the economics.
* **Fingerprint canonicalization cache** — resolutions arriving with a
  verbatim request-kwargs tuple (:func:`fp_cache_key`) probe a bounded
  cache of already-canonicalized fingerprints (digests precomputed),
  collapsing shape resolution + canonical JSON + sha1 to a dict probe
  (``serve.fp_cache.{hits,misses}``).  The recorded-traffic mix is
  dominated by repeated shape buckets, so the hit rate is the serve
  rate.
* **Lock-free concurrent reads** — :meth:`Resolver.resolve_fast`
  resolves exact hits against an immutable snapshot of the exact cache
  (an atomically-replaced ``(generation, dict)`` pair) without any
  lock: the listen loop's workers serve exact hits concurrently, and
  only store writes / cold enqueues / the near tier still serialize
  under the exclusive lock (serve/listen.py).  A snapshot whose
  generation lags the store falls through to the exclusive path, so a
  flag mutation or merge can never serve a stale answer.

Resolution runs under a cross-process trace context (obs/context.py):
the caller's (serve/listen.py mints one per request at ingress), or one
minted here for context-less callers (the one-shot ``serve query``
CLI).  The context stamps every span/event on the path and rides the
cold tier's work-item envelope, so the daemon drain a cold query causes
is linkable back to the query (docs/observability.md "Fleet telemetry
plane").
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from tenzing_tpu.fault.errors import StoreReadonlyError, is_unwritable_io
from tenzing_tpu.obs import context as obs_context
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.serve.fingerprint import WorkloadFingerprint, fingerprint_of
from tenzing_tpu.serve.store import (
    Record,
    ScheduleStore,
    WorkQueue,
    mark_store_unwritable,
    store_readonly,
)

# sealed-response slot sentinels: a memoized response carries these at
# the per-request fields' natural positions, so patching them in place
# preserves key order and the patched document is byte-identical to a
# fresh serialization of the same resolution (the correctness contract
# tests/test_serve_fastpath.py pins literally)
_PHASE_SLOT: Dict[str, float] = {"_slot": 0.0}
_TRACE_SLOT = "_slot"


# an fp-cache key retains the VERBATIM client kwargs for the cache's
# lifetime: entry-count bounds alone would let 4096 multi-megabyte
# string values (valid DriverRequest path fields) pin gigabytes in a
# long-lived serve loop, so oversized keys are simply uncacheable
_FP_KEY_MAX_CHARS = 2048


def fp_cache_key(kwargs: Any) -> Optional[Tuple]:
    """The fingerprint-cache key: the **verbatim request kwargs** as a
    sorted hashable tuple — no canonicalization, no shape resolution
    (that is exactly the work the cache exists to skip).  ``None`` when
    the kwargs are not a dict, carry an unhashable value, or are
    oversized (module comment above) — such a request simply resolves
    through the uncached path."""
    if not isinstance(kwargs, dict):
        return None
    try:
        key = tuple(sorted(kwargs.items()))
        hash(key)
    except TypeError:
        return None
    size = 0
    for k, v in key:
        size += len(k) + (len(v) if isinstance(v, str) else 8)
        if size > _FP_KEY_MAX_CHARS:
            return None
    return key


@dataclass
class Resolution:
    """One resolved request.  ``provenance`` always carries
    ``compiles: 0`` / ``measurements: 0`` — the serving tiers never
    touch an executor; a number in here is either a stored measurement
    (exact) or an explicitly-marked prediction (near)."""

    tier: str  # "exact" | "near" | "cold"
    fingerprint: WorkloadFingerprint
    record: Optional[Record] = None
    sequence: Optional[Any] = None  # Sequence, resolved against the request
    pct50_us: Optional[float] = None
    vs_naive: Optional[float] = None
    provenance: Dict[str, Any] = field(default_factory=dict)
    work_item: Optional[str] = None  # cold: the queued item's path
    # per-phase latency breakdown (µs): fingerprint / cache_probe /
    # store_walk (+ serialize, added by the transport) — the exact-tier
    # profile serve/replay.py aggregates into SERVE_BENCH documents
    phase_us: Dict[str, float] = field(default_factory=dict)
    trace_id: Optional[str] = None
    # the sealed response body (docs/serving.md "Fast path"): the
    # to_json document precomputed when the record entered the exact
    # cache, with slot sentinels where the per-request fields go —
    # serving is then a dict copy + slot patches instead of fingerprint
    # re-serialization and digest hashing
    memo: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        if self.memo is not None:
            # copy-and-patch: assigning to a present key keeps its
            # position, so the patched document's key order (and hence
            # its json.dumps bytes) matches a fresh serialization
            out = dict(self.memo)
            if self.phase_us:
                out["phase_us"] = self.phase_us
            else:
                out.pop("phase_us", None)
            if self.trace_id is not None:
                out["trace_id"] = self.trace_id
            else:
                out.pop("trace_id", None)
            return out
        out = {
            "tier": self.tier,
            "fingerprint": self.fingerprint.to_json(),
            "provenance": self.provenance,
        }
        if self.phase_us:
            out["phase_us"] = self.phase_us
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.record is not None:
            out["key"] = self.record["key"]
            out["ops"] = self.record["ops"]
        if self.pct50_us is not None:
            out["pct50_us"] = self.pct50_us
        if self.vs_naive is not None:
            out["vs_naive"] = self.vs_naive
        if self.work_item is not None:
            out["work_item"] = self.work_item
        return out


class Resolver:
    """The tier policy over one :class:`ScheduleStore` (see module
    docstring).

    ``model`` is a loaded :class:`~tenzing_tpu.learn.RidgeEnsemble` (the
    PR-2 surrogate) — without one the near tier is disabled and bucket
    neighbors fall through to cold: an unpriced neighbor is not an
    answer.  ``graph_builder`` defaults to the driver's device-free
    :func:`~tenzing_tpu.bench.driver.graph_for`; graphs/verifiers are
    cached per exact digest because structurally-identical requests
    dominate serving traffic."""

    def __init__(self, store: ScheduleStore, queue: Optional[WorkQueue] = None,
                 model=None, near_max_sigma: float = 0.75,
                 verify: bool = True,
                 graph_builder: Optional[Callable] = None,
                 log: Optional[Callable[[str], None]] = None,
                 serve_cache: bool = True,
                 legacy_verify: bool = False):
        self.store = store
        self.queue = queue
        self.model = model
        self.near_max_sigma = float(near_max_sigma)
        self.verify = verify
        # serve_cache=False disables the exact-tier sealed-record cache;
        # legacy_verify=True additionally ignores admission stamps and
        # re-verifies every exact hit — together they replay the pre-PR
        # resolution path exactly (the trace-replay benchmark's baseline,
        # serve/replay.py; never the serving configuration)
        self.serve_cache = serve_cache
        self.legacy_verify = legacy_verify
        self._graph_builder = graph_builder
        # per-exact-digest caches, BOUNDED: the digests are derived from
        # client-controlled shape parameters, and a long-lived server
        # sweeping shapes (one graph + verifier + surrogate each) must
        # not grow without limit — insertion-order eviction is enough
        # because serving traffic concentrates on few fingerprints
        self.cache_cap = 32
        # the exact-tier answer cache is the serving hot path (one dict
        # probe per hit) and its entries are small (a record reference +
        # a materialized Sequence): it earns a much larger bound
        self.exact_cache_cap = 4096
        self._graphs: Dict[str, Tuple[Any, Dict[str, int]]] = {}
        self._verifiers: Dict[str, Any] = {}
        # exact digest -> (record, sequence, provenance, sealed response
        # memo) of the admitted best answer; validity keyed on the
        # store's generation counter (any record landing anywhere — and
        # every flag mutation — invalidates wholesale: coarse, but
        # merges are rare and wrong answers are forever)
        self._exact_cache: Dict[
            str, Tuple[Record, Any, Dict[str, Any], Dict[str, Any]]] = {}
        self._exact_cache_gen = -1
        # the lock-free read path's view: an immutable (generation,
        # dict) pair replaced wholesale on every cache mutation —
        # readers grab the attribute once (atomic under the GIL) and
        # probe a dict no writer will ever mutate in place
        self._exact_snapshot: Tuple[int, Dict[str, Any]] = (-1, {})
        # verbatim-kwargs tuple -> canonicalized fingerprint with both
        # digests precomputed (docs/serving.md "Fast path"); bounded
        # like the exact cache — the key space is client-controlled
        self.fp_cache_cap = 4096
        self._fp_cache: Dict[Any, WorkloadFingerprint] = {}
        # (model, surrogate) per exact digest: the surrogate's
        # canonical-key prediction cache must survive across queries of
        # a hot fingerprint (re-featurizing the same neighbors per
        # request is O(schedule length) on the serve.resolve_us path);
        # keyed with the model so a retrain invalidates
        self._surrogates: Dict[str, Tuple[Any, Any]] = {}
        self._log = log

    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    def _cache_put(self, cache: Dict[str, Any], key: str, value,
                   cap: Optional[int] = None,
                   on_evict: Optional[Callable[[Any], None]] = None) -> None:
        if key in cache:
            # re-put of a present key must update in place: evicting an
            # oldest entry for it would shrink the cache by one per
            # refresh (and could evict the very entry being refreshed)
            cache[key] = value
            return
        cap = self.cache_cap if cap is None else cap
        while len(cache) >= cap:
            evicted = cache.pop(next(iter(cache)))  # oldest insertion
            if on_evict is not None:
                on_evict(evicted)
        cache[key] = value

    # -- fast path (docs/serving.md "Fast path") -----------------------------
    def _publish_snapshot(self) -> None:
        """Replace the lock-free readers' view after any exact-cache
        mutation.  The copy is bounded by ``exact_cache_cap`` and only
        paid on the mutation path (miss/invalidation) — never per hit."""
        self._exact_snapshot = (self._exact_cache_gen,
                                dict(self._exact_cache))

    def _seal_response(self, fp: WorkloadFingerprint, rec: Record, seq,
                       prov: Dict[str, Any]) -> Dict[str, Any]:
        """The memoized response body for a cache hit of this record:
        the full ``to_json`` document — fingerprint serialization and
        digest hashing paid HERE, once — with slot sentinels at the
        per-request fields' positions (patched per request by
        :meth:`Resolution.to_json`)."""
        sealed = Resolution(
            tier="exact", fingerprint=fp, record=rec, sequence=seq,
            pct50_us=rec.get("pct50_us"), vs_naive=rec.get("vs_naive"),
            provenance=dict(prov, cache_hit=True))
        # per-seal copy: aliasing the module-level sentinel into every
        # memo would make one in-place mutation corrupt all of them
        sealed.phase_us = dict(_PHASE_SLOT)
        sealed.trace_id = _TRACE_SLOT
        return sealed.to_json()

    def _cache_exact(self, fp: WorkloadFingerprint, rec: Record, seq,
                     prov: Dict[str, Any]) -> None:
        """Admit one record into the exact cache: seal its response
        memo, evict (counting the dropped memo as an invalidation), and
        publish a fresh snapshot for the lock-free readers."""
        memo = self._seal_response(fp, rec, seq, prov)
        self._cache_put(
            self._exact_cache, fp.exact_digest, (rec, seq, prov, memo),
            cap=self.exact_cache_cap,
            on_evict=lambda _: get_metrics().counter(
                "serve.memo.invalidations").inc())
        self._publish_snapshot()

    def _drop_exact(self, exact: str) -> None:
        """Invalidate one cached answer (e.g. a record flagged unsound
        by a caller holding the same dict) — counted, and republished so
        the lock-free readers stop seeing it immediately."""
        if self._exact_cache.pop(exact, None) is not None:
            get_metrics().counter("serve.memo.invalidations").inc()
            self._publish_snapshot()

    def _invalidate_exact_cache(self, gen: int) -> None:
        """The store-generation bump: every record landing and every
        flag mutation moves the generation, and the whole answer cache
        (records, sequences, sealed memos) dies with it."""
        if self._exact_cache:
            get_metrics().counter("serve.memo.invalidations").inc(
                len(self._exact_cache))
            self._exact_cache.clear()
        self._exact_cache_gen = gen
        self._publish_snapshot()

    def _fingerprint(self, req, fp_key: Optional[Tuple]):
        """:func:`fingerprint_of` through the canonicalization cache:
        a request arriving with a verbatim-kwargs key
        (:func:`fp_cache_key`) probes the bounded cache first; a miss
        canonicalizes once, precomputes both digests, and caches — the
        recorded-traffic mix repeats shape buckets, so the steady state
        is one dict probe."""
        if fp_key is not None:
            fp = self._fp_cache.get(fp_key)
            if fp is not None:
                get_metrics().counter("serve.fp_cache.hits").inc()
                return fp
        fp = fingerprint_of(req)
        if fp_key is not None:
            _ = (fp.exact_digest, fp.bucket_digest)  # warm both digests
            self._cache_put(self._fp_cache, fp_key, fp,
                            cap=self.fp_cache_cap)
            get_metrics().counter("serve.fp_cache.misses").inc()
        return fp

    def resolve_fast(self, fp_key: Optional[Tuple]) -> Optional[Resolution]:
        """The lock-free exact tier: fingerprint-cache probe + snapshot
        probe + memoized response, **no lock, no store access beyond one
        generation read** — safe to call from any number of threads
        concurrently (serve/listen.py's workers do).  ``None`` means
        "not servable lock-free" (cold fingerprint cache, stale
        snapshot, non-exact tier): the caller falls through to
        :meth:`resolve` under its exclusive lock, which repopulates
        every cache this path reads."""
        if fp_key is None:
            return None
        t0 = time.perf_counter()
        fp = self._fp_cache.get(fp_key)
        if fp is None:
            return None
        reg = get_metrics()
        phases: Dict[str, float] = {}
        phases["fingerprint"] = round((time.perf_counter() - t0) * 1e6, 2)
        t_probe = time.perf_counter()
        gen_snap, snap = self._exact_snapshot
        if gen_snap != getattr(self.store, "generation", 0):
            return None  # the exclusive path refreshes the snapshot
        hit = snap.get(fp.exact_digest)
        if hit is None:
            return None
        rec, seq, prov, memo = hit
        if rec.get("flags", {}).get("unsound"):
            # flagged by a caller holding the same record dict (a
            # store.flag goes through the generation bump and never
            # reaches here): let the exclusive path drop + re-walk
            return None
        phases["cache_probe"] = round(
            (time.perf_counter() - t_probe) * 1e6, 2)
        ctx = obs_context.current() or obs_context.new_trace()
        reg.counter("serve.fp_cache.hits").inc()
        reg.counter("serve.exact_cache.hits").inc()
        reg.counter("serve.memo.hits").inc()
        reg.counter("serve.exact").inc()
        res = Resolution(
            tier="exact", fingerprint=fp, record=rec, sequence=seq,
            pct50_us=rec.get("pct50_us"), vs_naive=rec.get("vs_naive"),
            provenance=dict(prov, cache_hit=True), memo=memo)
        res.phase_us = phases
        res.trace_id = ctx.trace_id
        dt_us = (time.perf_counter() - t0) * 1e6
        reg.histogram("serve.resolve_us", window=True).observe(dt_us)
        reg.histogram("serve.resolve_us.exact", window=True).observe(dt_us)
        tr = get_tracer()
        if tr.enabled:
            # emitted AFTER the fact so a fall-through never produces a
            # duplicate serve.query span next to the exclusive path's:
            # the span's own duration is therefore ~0 — the real
            # latency rides the resolve_us attribute (and phase_us on
            # the response), which is what timing analyses must read
            # for fast-path traffic
            with obs_context.use(ctx), tr.span("serve.query") as sp:
                sp.set("workload", fp.workload)
                sp.set("exact", fp.exact_digest)
                sp.set("tier", "exact")
                sp.set("fast_path", True)
                sp.set("resolve_us", round(dt_us, 2))
        return res

    def _graph(self, req, fp: WorkloadFingerprint):
        got = self._graphs.get(fp.exact_digest)
        if got is None:
            builder = self._graph_builder
            if builder is None:
                from tenzing_tpu.bench.driver import graph_for as builder
            got = builder(req)
            self._cache_put(self._graphs, fp.exact_digest, got)
        return got

    def _verifier(self, graph, fp: WorkloadFingerprint):
        v = self._verifiers.get(fp.exact_digest)
        if v is None:
            from tenzing_tpu.verify import ScheduleVerifier

            v = ScheduleVerifier(graph)
            self._cache_put(self._verifiers, fp.exact_digest, v)
        return v

    def _materialize(self, rec: Record, graph) -> Optional[Any]:
        """The record's ops resolved against the *request's* graph; None
        when they no longer resolve (recorded against a different
        structural variant) — a store answer the request cannot execute
        is no answer."""
        from tenzing_tpu.core.serdes import sequence_from_json

        try:
            return sequence_from_json(rec["ops"], graph)
        except Exception as e:
            self._note(f"serve: record {rec['key'][:8]} does not resolve "
                       f"({type(e).__name__}: {str(e)[:120]})")
            return None

    # -- tiers ---------------------------------------------------------------
    def _try_exact(self, req, fp: WorkloadFingerprint,
                   phases: Dict[str, float]) -> Optional[Resolution]:
        reg = get_metrics()
        t0 = time.perf_counter()
        with get_tracer().span("serve.cache_probe") as psp:
            if self.serve_cache:
                hit = self._exact_cache.get(fp.exact_digest)
                if hit is not None and \
                        hit[0].get("flags", {}).get("unsound"):
                    # belt-and-braces behind the generation check: a
                    # record flagged between the generation bump and this
                    # probe (or by a caller holding the same dict) must
                    # never be served
                    self._drop_exact(fp.exact_digest)
                    hit = None
                if hit is not None:
                    # the hot path: one dict probe, zero
                    # materializations, zero verifier invocations — the
                    # record was admitted (verified + sealed) when it
                    # entered the cache, and its response body was
                    # sealed with it (the memo the transport patches)
                    rec, seq, prov, memo = hit
                    phases["cache_probe"] = round(
                        (time.perf_counter() - t0) * 1e6, 2)
                    psp.set("hit", True)
                    reg.counter("serve.exact_cache.hits").inc()
                    reg.counter("serve.memo.hits").inc()
                    return Resolution(
                        tier="exact", fingerprint=fp, record=rec,
                        sequence=seq, pct50_us=rec.get("pct50_us"),
                        vs_naive=rec.get("vs_naive"),
                        provenance=dict(prov, cache_hit=True),
                        memo=memo)
            psp.set("hit", False)
        phases["cache_probe"] = round((time.perf_counter() - t0) * 1e6, 2)
        t_walk = time.perf_counter()
        records = self.store.exact_records(fp.exact_digest)
        # the walk phase covers everything past the probe (store listing,
        # materialization, verification fallback) — the cold/near paths
        # overwrite nothing, so an exact miss still reports what the
        # exact tier spent before falling through
        try:
            return self._walk_exact(req, fp, records, reg)
        finally:
            phases["store_walk"] = round(
                (time.perf_counter() - t_walk) * 1e6, 2)

    def _walk_exact(self, req, fp: WorkloadFingerprint,
                    records, reg) -> Optional[Resolution]:
        if not records:
            return None
        if self.serve_cache:
            reg.counter("serve.exact_cache.misses").inc()
        graph = None
        # best-first WALK, not best-only: one unsound or unresolvable
        # best record must not permanently block a sound runner-up under
        # the same exact digest (the near tier excludes the requester's
        # own digest, so falling through here would skip it entirely)
        for rec in records:
            if rec.get("flags", {}).get("unsound"):
                # flagged at admission (or by a prior discovery): never
                # served, and never worth re-verifying — the verdict is
                # deterministic
                continue
            if graph is None:
                graph, _ = self._graph(req, fp)
            seq = self._materialize(rec, graph)
            if seq is None:
                continue
            admission_stamped = (bool(rec.get("verified_at_admission"))
                                 and not self.legacy_verify)
            verified = None
            verifier_calls = 0
            if admission_stamped:
                # verified once when it was merged into the store, under
                # this same fingerprint's (deterministic) graph — serving
                # it again needs no second opinion (docs/serving.md
                # "Admission-time verification")
                verified = True
            elif self.verify:
                verifier_calls = 1
                reg.counter("serve.verify_fallback").inc()
                verdict = self._verifier(graph, fp)(seq)
                verified = bool(verdict.ok)
                if not verified:
                    # an unsound stored schedule must never be served —
                    # flag it (visible in stats + the report CLI) and
                    # try the next-best record
                    self.store.flag(rec["exact"], rec["key"],
                                    unsound=True, needs_refinement=True)
                    get_metrics().counter("serve.store.unsound").inc()
                    self._note(f"serve: exact entry {rec['key'][:8]} "
                               "failed re-verification — flagged, "
                               "not served")
                    continue
                # the lazy-verified record is now as good as stamped for
                # this process's lifetime (in-memory only: persistence of
                # the stamp belongs to admission, not resolution); the
                # legacy replay path must not stamp — it would leak
                # new-path state into the baseline it exists to measure
                if not self.legacy_verify:
                    rec["verified_at_admission"] = True
            prov = {
                "verified": verified,
                "verified_at_admission": admission_stamped,
                "verifier_calls": verifier_calls,
                "cache_hit": False,
                "was_predicted": False,
                "compiles": 0,
                "measurements": 0,
                "source_exact": rec["exact"],
                **rec.get("provenance", {}),
            }
            if self.serve_cache and verified is not False:
                # entering the cache seals the response memo: this
                # fresh serve paid full serialization (counted as the
                # memo miss), every cache hit after it is copy-and-patch
                reg.counter("serve.memo.misses").inc()
                self._cache_exact(fp, rec, seq, prov)
            return Resolution(tier="exact", fingerprint=fp, record=rec,
                              sequence=seq, pct50_us=rec.get("pct50_us"),
                              vs_naive=rec.get("vs_naive"),
                              provenance=prov)
        return None

    def _try_near(self, req, fp: WorkloadFingerprint) -> Optional[Resolution]:
        if self.model is None:
            return None
        neighbors = self.store.bucket_records(
            fp.bucket_digest, exclude_exact=fp.exact_digest)
        if not neighbors:
            return None
        graph, nbytes = self._graph(req, fp)
        ent = self._surrogates.get(fp.exact_digest)
        if ent is None or ent[0] is not self.model:
            from tenzing_tpu.learn import SurrogateBenchmarker

            surrogate = SurrogateBenchmarker(self.model, nbytes=nbytes)
            self._cache_put(self._surrogates, fp.exact_digest,
                            (self.model, surrogate))
        else:
            surrogate = ent[1]
        for rec in neighbors:
            if rec.get("flags", {}).get("unsound"):
                continue  # same rule as the exact tier: known-bad, skip
            seq = self._materialize(rec, graph)
            if seq is None:
                continue
            mu, sigma = surrogate.predict(seq)
            if sigma > self.near_max_sigma:
                # uncertainty gate: the ensemble cannot price this
                # schedule for the requested shape — falling through to
                # cold is honest, serving a wide guess is not
                get_metrics().counter("serve.near_rejected").inc()
                self._note(f"serve: near candidate {rec['key'][:8]} "
                           f"rejected (sigma {sigma:.3f} > "
                           f"{self.near_max_sigma})")
                continue
            verified = None
            if self.verify:
                verified = bool(self._verifier(graph, fp)(seq).ok)
                if not verified:
                    # same treatment as the exact tier: counted, flagged
                    # for refinement, never served — a poisoned entry
                    # first discovered via a near miss must not be
                    # invisible to the serve.store.unsound dashboards
                    self.store.flag(rec["exact"], rec["key"],
                                    unsound=True, needs_refinement=True)
                    get_metrics().counter("serve.store.unsound").inc()
                    self._note(f"serve: near candidate {rec['key'][:8]} "
                               "failed re-verification — flagged, "
                               "not served")
                    continue
            # the label space is log(t / naive anchor): exp(-mu) is the
            # predicted paired ratio vs naive for the requested shape
            pred_vs = math.exp(-mu)
            self.store.flag(rec["exact"], rec["key"], needs_refinement=True)
            if self.queue is not None:
                # ensure, not enqueue: a hot near-miss fingerprint
                # re-resolves per request and must not rewrite an
                # identical work item each time (same reasoning as
                # flag()'s unchanged-short-circuit above)
                self.queue.ensure(fp, self._request_payload(req),
                                  reason="refine-near-miss",
                                  trace=obs_context.current())
            prov = {
                "verified": verified,
                "was_predicted": True,
                "uncertainty": round(float(sigma), 4),
                "compiles": 0,
                "measurements": 0,
                "source_exact": rec["exact"],
                "neighbor_vs_naive": rec.get("vs_naive"),
                **rec.get("provenance", {}),
            }
            return Resolution(tier="near", fingerprint=fp, record=rec,
                              sequence=seq, pct50_us=None,
                              vs_naive=round(pred_vs, 4), provenance=prov)
        return None

    def _cold(self, req, fp: WorkloadFingerprint) -> Resolution:
        path = None
        if self.queue is not None:
            # the ambient trace context rides the work-item envelope:
            # the daemon drain this item causes is linkable back to the
            # query that caused it (obs/context.py)
            path = self.queue.ensure(fp, self._request_payload(req),
                                     reason="cold",
                                     trace=obs_context.current())
        return Resolution(
            tier="cold", fingerprint=fp, work_item=path,
            provenance={"was_predicted": False, "compiles": 0,
                        "measurements": 0})

    def _near_or_cold(self, req, fp: WorkloadFingerprint) -> Resolution:
        """The write-needing tiers, gated on the read-only latch
        (serve/store.py): near flags + enqueues, cold enqueues — none of
        that can land while the store is degraded, so both shed with
        :class:`StoreReadonlyError` (the listen loop converts it to a
        ``{"shed": true, "reason": "store_readonly"}`` response; exact
        hits above keep answering from the sealed cache throughout).  An
        ENOSPC-family OSError escaping a tier write trips the latch
        here, so the *next* request sheds before touching the disk."""
        ro = store_readonly(self.store.path)
        if ro is not None:
            get_metrics().counter("serve.shed.store_readonly").inc()
            raise StoreReadonlyError(
                f"store degraded read-only ({ro.get('error')})")
        try:
            return self._try_near(req, fp) or self._cold(req, fp)
        except OSError as e:
            if is_unwritable_io(e):
                mark_store_unwritable(self.store.path, e)
                get_metrics().counter("serve.shed.store_readonly").inc()
                raise StoreReadonlyError(str(e)) from e
            raise

    @staticmethod
    def _request_payload(req) -> Dict[str, Any]:
        fn = getattr(req, "to_json", None)
        return fn() if callable(fn) else dict(vars(req))

    # -- entry ---------------------------------------------------------------
    def resolve(self, req, fp_key: Optional[Tuple] = None) -> Resolution:
        """Resolve a :class:`~tenzing_tpu.bench.driver.DriverRequest`
        through the tiers, under the ambient trace context (one is
        minted here when the caller arrived without one — the resolver
        is the ingress of record for non-listen paths).  ``fp_key`` is
        the request's verbatim-kwargs tuple (:func:`fp_cache_key`) when
        the caller has one: it keys the fingerprint canonicalization
        cache and seeds :meth:`resolve_fast` for the next arrival."""
        ctx = obs_context.current() or obs_context.new_trace()
        with obs_context.use(ctx):
            return self._resolve(req, ctx, fp_key)

    def _resolve(self, req, ctx, fp_key: Optional[Tuple] = None) -> Resolution:
        reg = get_metrics()
        tr = get_tracer()
        t0 = time.perf_counter()
        gen = getattr(self.store, "generation", 0)
        if gen != self._exact_cache_gen:
            # any record landing anywhere (add/merge/load/flag)
            # invalidates the whole answer cache: coarse, but merges are
            # rare and a stale answer would outlive the better record
            # that beat it — counted per sealed memo dropped, and the
            # lock-free snapshot is republished empty
            self._invalidate_exact_cache(gen)
        phases: Dict[str, float] = {}
        with tr.span("serve.query") as sp:
            # fingerprint canonicalization is the first per-hit phase the
            # ROADMAP's tens-of-µs item profiles — timed always (two
            # perf_counter reads), sub-spanned only when tracing is on
            t_fp = time.perf_counter()
            if tr.enabled:
                with tr.span("serve.fingerprint"):
                    fp = self._fingerprint(req, fp_key)
            else:
                fp = self._fingerprint(req, fp_key)
            phases["fingerprint"] = round(
                (time.perf_counter() - t_fp) * 1e6, 2)
            sp.set("workload", fp.workload)
            sp.set("exact", fp.exact_digest)
            sp.set("bucket", fp.bucket_digest)
            res = self._try_exact(req, fp, phases)
            if res is None:
                res = self._near_or_cold(req, fp)
            sp.set("tier", res.tier)
        res.phase_us = phases
        res.trace_id = ctx.trace_id
        reg.counter(f"serve.{res.tier}").inc()
        dt_us = (time.perf_counter() - t0) * 1e6
        # windowed retention (obs/metrics.py): a live SLO block must
        # read the pct99 of CURRENT traffic — first-N retention would
        # freeze the series at whatever the process saw before the cap
        # filled and hide every post-warm-up regression
        reg.histogram("serve.resolve_us", window=True).observe(dt_us)
        # the per-tier series the SLO block and the follow view read:
        # exact-tier pct99 mixed with cold-tier enqueue latency would
        # steer the tens-of-µs target with the wrong number
        reg.histogram(f"serve.resolve_us.{res.tier}",
                      window=True).observe(dt_us)
        return res
