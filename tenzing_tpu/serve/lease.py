"""THE lease-file protocol: claim-by-hardlink, mtime-heartbeat,
reclaim-by-rename, grab-inspect-release — plus epoch fencing tokens.

One mutual-exclusion discipline for every long-running exclusive job in
the serving tree — extracted from serve/daemon.py (where it was born, PR
9, guarding per-item queue claims) when the segment compactor
(serve/segments.py) needed the identical protocol for its store-wide
compaction lease.  The invariants, each carried over verbatim:

* **Claim** — the owner payload (owner id, pid, host, claim time, a
  unique **nonce**) is fully written and fsynced to a private temp file,
  then *hard-linked* to the lease path: exactly one of any number of
  rivals wins the link (``FileExistsError`` for the rest), and a rival
  can never read a torn lease.
* **Heartbeat** — renewing bumps the lease file's **mtime**; a lease
  whose mtime is older than the TTL is *expired*.  Renewal re-reads the
  payload nonce first: inode numbers recycle the moment a file is
  unlinked, so "same path, same inode" does NOT mean "still our claim".
  A holder that lost its lease during a stall learns it from the failed
  renew and must abort its work instead of double-running.
* **Reclaim** — an expired lease is reclaimed by atomic rename (again:
  one winner among any number of contenders; the losers' rename gets
  ``ENOENT``), so a SIGKILLed holder's claim is never lost forever.
* **Release** — delete iff still ours, *atomically*.  A bare
  check-then-unlink has a stall window (``owns`` true, we pause past the
  TTL, a rival reclaims and publishes, our unlink deletes the rival's
  LIVE lease): instead the lease is *grabbed* by rename (one winner),
  inspected privately, and either deleted (ours) or re-published by hard
  link (a rival's — put it back).  If a third party claims during the
  grab window the re-link loses and the rival's own heartbeat detects
  the loss (nonce mismatch) and aborts — the designed recovery, never a
  silent double-run.

**Epoch fencing** (the hostile-filesystem hardening): mtime-TTL reclaim
trusts two things a shared NFS-like mount does not guarantee — the
observed mtime (1s-granularity coarsening / client-clock skew can age a
live rival's heartbeat into "expired") and the freshness of the nonce
re-read (an attribute-cached read can serve the *previous* lease payload
— our own — and tell a reclaimed zombie it still owns).  Both lies let
two holders drain one item: the documented double-run hole.  The fence
closes it at the *write* side:

* Every successful claim carries a monotonically-increasing **epoch**,
  allocated from and recorded into a registry directory next to the
  lease (``<path>.epochs/c-<N>``, created ``O_EXCL`` — the atomic
  winner-takes-all step again, directory entries rather than file
  content precisely so a stale *content* read cannot lie about them).
  The payload's ``epoch`` field and :attr:`ClaimInfo.epoch` report it.
* Before any effect lands — the daemon's store merge, a checkpoint
  journal append (fault/checkpoint.py) — the holder calls
  :meth:`LeaseFile.check_fence` / :func:`check_epoch`: if the registry
  shows an epoch newer than ours, a rival has claimed since; the write
  raises :class:`~tenzing_tpu.fault.errors.FencedWriteError` instead of
  landing stale.  A *vanished* registry with our marker gone means the
  rival already completed and cleaned up — equally fenced.

Expiry clocks and nonce re-reads go through the utils/atomic.py I/O seam
(``io_getmtime`` / ``read_json``) so fault/fsinject.py can inject
exactly the mtime coarsening/skew and stale reads the fence exists to
survive; the registry operations deliberately do not — O_EXCL create and
listdir are the layer chaos must not be able to lie to
(tests/test_lease_fencing.py drills both halves).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from tenzing_tpu.fault.errors import FencedWriteError
from tenzing_tpu.utils.atomic import io_getmtime, read_json

# the fencing registry rides next to the lease file; entries are
# c-<epoch> markers, one per *successful* claim, newest few kept
EPOCH_DIR_SUFFIX = ".epochs"
EPOCH_KEEP = 8


def epoch_registry_of(path: str) -> str:
    """The fencing registry directory of lease ``path``."""
    return path + EPOCH_DIR_SUFFIX


def issued_epoch(path: str) -> int:
    """The highest epoch any successful claim of ``path`` has recorded
    (0 when none / the registry is absent)."""
    best = 0
    try:
        names = os.listdir(epoch_registry_of(path))
    except OSError:
        return 0
    for n in names:
        if n.startswith("c-"):
            try:
                best = max(best, int(n[2:]))
            except ValueError:
                pass
    return best


def check_epoch(path: str, epoch: int) -> None:
    """Raise :class:`FencedWriteError` unless ``epoch`` is exactly the
    newest successful claim of lease ``path`` (see module docstring —
    newer means a rival reclaimed us; older/absent means the rival
    already completed and purged the registry).  THE one fence check,
    shared by the holder object, the daemon's merge gate, and the
    checkpoint journal's env-wired hook (fault/checkpoint.py)."""
    newest = issued_epoch(path)
    if newest != epoch:
        raise FencedWriteError(
            f"lease {os.path.basename(path)} epoch {epoch} fenced "
            f"(registry newest: {newest}) — a rival claim supersedes "
            "this holder; abandoning the write")


@dataclass
class ClaimInfo:
    """What :meth:`LeaseFile.claim` reports on success: whether the claim
    reclaimed an expired rival first (the caller's counter/telemetry
    decision, not the protocol's), whose, and the claim's fencing epoch
    (None when the registry could not record it — fencing degrades to
    the nonce checks, never blocks the claim)."""

    reclaimed: bool = False
    prev_owner: Optional[str] = None
    age_s: Optional[float] = None
    epoch: Optional[int] = None


class LeaseFile:
    """One lease path's view of the protocol (module docstring).  The
    object is single-claim: ``claim()`` then ``renew()``/``owns()`` until
    ``release()``; a fresh claim needs a fresh nonce but may reuse the
    object."""

    def __init__(self, path: str, owner: str,
                 ttl_secs: float = 60.0,
                 log: Optional[Callable[[str], None]] = None):
        self.path = path
        self.owner = owner
        self.ttl_secs = float(ttl_secs)
        self.nonce: Optional[str] = None
        self.epoch: Optional[int] = None
        self._log = log

    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    # -- fencing -------------------------------------------------------------
    def _record_epoch(self, epoch: int) -> bool:
        """Record a successful claim's epoch marker (O_EXCL — atomic) and
        trim the registry tail.  False when the marker could not land:
        the claim stands, fencing degrades to the nonce checks."""
        d = epoch_registry_of(self.path)
        try:
            os.makedirs(d, exist_ok=True)
            fd = os.open(os.path.join(d, f"c-{epoch}"),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
        except OSError:
            return False
        try:
            for n in os.listdir(d):
                if not n.startswith("c-"):
                    continue
                try:
                    k = int(n[2:])
                except ValueError:
                    continue
                if k <= epoch - EPOCH_KEEP:
                    try:
                        os.unlink(os.path.join(d, n))
                    except OSError:
                        pass
        except OSError:
            pass
        return True

    def check_fence(self) -> None:
        """Raise :class:`FencedWriteError` iff a rival claim supersedes
        this holder's epoch (module docstring).  A no-op for unfenced
        claims (epoch marker never landed): those fall back to the
        nonce-re-read protection alone."""
        if self.epoch is not None:
            check_epoch(self.path, self.epoch)

    def purge_epochs(self) -> None:
        """Drop the fencing registry — called by the *completing* holder
        after the guarded effect landed and the work item is gone (a
        later zombie is fenced by the registry's absence, and a fresh
        item at the same path restarts epochs from 1)."""
        d = epoch_registry_of(self.path)
        try:
            for n in os.listdir(d):
                try:
                    os.unlink(os.path.join(d, n))
                except OSError:
                    pass
            os.rmdir(d)
        except OSError:
            pass

    # -- claim ---------------------------------------------------------------
    def claim(self, extra: Optional[Dict[str, Any]] = None
              ) -> Optional[ClaimInfo]:
        """Claim the lease; ``None`` when a rival holds a fresh lease or
        wins either race.  ``extra`` keys ride in the payload (the daemon
        stamps the claimed item's exact digest)."""
        now = time.time()
        info = ClaimInfo()
        self.epoch = None
        try:
            # the expiry clock reads through the I/O seam: coarse or
            # skewed observed mtimes are exactly the chaos the fence
            # (below) exists to survive
            age = now - io_getmtime(self.path)
        except OSError:
            age = None  # no lease: go straight to the fresh claim
        if age is not None:
            if age <= self.ttl_secs:
                return None  # live rival
            # expired: reclaim by atomic rename — one winner among any
            # number of contenders (the losers' rename gets ENOENT)
            stale = (f"{self.path}.stale-{self.owner}-{os.getpid()}-"
                     f"{int(now * 1e6)}")
            try:
                os.rename(self.path, stale)
            except OSError:
                return None  # lost the reclaim race
            prev_owner = "?"
            try:
                with open(stale) as f:
                    prev_owner = json.load(f).get("owner", "?")
            except (OSError, ValueError):
                pass
            try:
                os.unlink(stale)
            except OSError:
                pass
            info = ClaimInfo(reclaimed=True, prev_owner=prev_owner,
                             age_s=round(age, 3))
        # fresh claim: publish-by-hard-link — the payload is fully
        # written and fsynced in a private temp file before the link, so
        # a rival never reads a torn lease, and the link itself is the
        # atomic winner-takes-all step
        epoch = issued_epoch(self.path) + 1
        nonce = (f"{self.owner}-{os.getpid()}-{threading.get_ident()}-"
                 f"{int(now * 1e6)}")
        payload = {"owner": self.owner, "pid": os.getpid(),
                   "host": socket.gethostname(),
                   "claimed_at": now, "ttl_s": self.ttl_secs,
                   "nonce": nonce, "epoch": epoch, **(extra or {})}
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        # thread id in the temp name: two same-owner holders embedded in
        # one process must not interleave writes to one temp file
        tmp = (f"{self.path}.{self.owner}.{os.getpid()}."
               f"{threading.get_ident()}.tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            try:
                os.link(tmp, self.path)
            except OSError:
                return None  # a rival landed first
            self.nonce = nonce
            # record the fence marker ONLY as the winner — losers must
            # never advance the registry past the live holder's epoch
            if self._record_epoch(epoch):
                self.epoch = epoch
                info.epoch = epoch
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return info

    # -- heartbeat -----------------------------------------------------------
    def owns(self) -> bool:
        if self.nonce is None:
            return False  # nothing claimed; never matches a nonce-less file
        try:
            # through the seam: an NFS-style stale read can serve OUR OWN
            # superseded payload here and lie to a reclaimed zombie —
            # which is why effects must also pass check_fence()
            return read_json(self.path).get("nonce") == self.nonce
        except (OSError, ValueError):
            return False

    def renew(self) -> bool:
        """Bump the lease mtime — but only while it is still OUR lease
        (nonce re-read; see module docstring).  False means a rival
        reclaimed it: the holder must abort, not double-run."""
        if not self.owns():
            return False
        try:
            os.utime(self.path, None)
            return True
        except OSError:
            return False

    # -- release -------------------------------------------------------------
    def release(self) -> bool:
        """Grab-inspect-release (module docstring); returns True iff the
        lease was ours and is now deleted.  Always clears the nonce —
        after a release attempt this object holds nothing."""
        self.epoch = None
        if self.nonce is None:
            return False
        grab = (f"{self.path}.release.{self.owner}.{os.getpid()}."
                f"{threading.get_ident()}")
        try:
            os.rename(self.path, grab)
        except OSError:
            self.nonce = None
            return False  # already gone (reclaimed + released by a rival)
        ours = False
        try:
            with open(grab) as f:
                ours = json.load(f).get("nonce") == self.nonce
        except (OSError, ValueError):
            pass
        if not ours:
            try:
                os.link(grab, self.path)  # a rival's live claim: restore it
            except OSError:
                pass
        try:
            os.unlink(grab)
        except OSError:
            pass
        self.nonce = None
        return ours
