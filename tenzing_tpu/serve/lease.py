"""THE lease-file protocol: claim-by-hardlink, mtime-heartbeat,
reclaim-by-rename, grab-inspect-release.

One mutual-exclusion discipline for every long-running exclusive job in
the serving tree — extracted from serve/daemon.py (where it was born, PR
9, guarding per-item queue claims) when the segment compactor
(serve/segments.py) needed the identical protocol for its store-wide
compaction lease.  The invariants, each carried over verbatim:

* **Claim** — the owner payload (owner id, pid, host, claim time, a
  unique **nonce**) is fully written and fsynced to a private temp file,
  then *hard-linked* to the lease path: exactly one of any number of
  rivals wins the link (``FileExistsError`` for the rest), and a rival
  can never read a torn lease.
* **Heartbeat** — renewing bumps the lease file's **mtime**; a lease
  whose mtime is older than the TTL is *expired*.  Renewal re-reads the
  payload nonce first: inode numbers recycle the moment a file is
  unlinked, so "same path, same inode" does NOT mean "still our claim".
  A holder that lost its lease during a stall learns it from the failed
  renew and must abort its work instead of double-running.
* **Reclaim** — an expired lease is reclaimed by atomic rename (again:
  one winner among any number of contenders; the losers' rename gets
  ``ENOENT``), so a SIGKILLed holder's claim is never lost forever.
* **Release** — delete iff still ours, *atomically*.  A bare
  check-then-unlink has a stall window (``owns`` true, we pause past the
  TTL, a rival reclaims and publishes, our unlink deletes the rival's
  LIVE lease): instead the lease is *grabbed* by rename (one winner),
  inspected privately, and either deleted (ours) or re-published by hard
  link (a rival's — put it back).  If a third party claims during the
  grab window the re-link loses and the rival's own heartbeat detects
  the loss (nonce mismatch) and aborts — the designed recovery, never a
  silent double-run.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class ClaimInfo:
    """What :meth:`LeaseFile.claim` reports on success: whether the claim
    reclaimed an expired rival first (the caller's counter/telemetry
    decision, not the protocol's), and whose."""

    reclaimed: bool = False
    prev_owner: Optional[str] = None
    age_s: Optional[float] = None


class LeaseFile:
    """One lease path's view of the protocol (module docstring).  The
    object is single-claim: ``claim()`` then ``renew()``/``owns()`` until
    ``release()``; a fresh claim needs a fresh nonce but may reuse the
    object."""

    def __init__(self, path: str, owner: str,
                 ttl_secs: float = 60.0,
                 log: Optional[Callable[[str], None]] = None):
        self.path = path
        self.owner = owner
        self.ttl_secs = float(ttl_secs)
        self.nonce: Optional[str] = None
        self._log = log

    def _note(self, msg: str) -> None:
        if self._log is not None:
            self._log(msg)

    # -- claim ---------------------------------------------------------------
    def claim(self, extra: Optional[Dict[str, Any]] = None
              ) -> Optional[ClaimInfo]:
        """Claim the lease; ``None`` when a rival holds a fresh lease or
        wins either race.  ``extra`` keys ride in the payload (the daemon
        stamps the claimed item's exact digest)."""
        now = time.time()
        info = ClaimInfo()
        try:
            age = now - os.path.getmtime(self.path)
        except OSError:
            age = None  # no lease: go straight to the fresh claim
        if age is not None:
            if age <= self.ttl_secs:
                return None  # live rival
            # expired: reclaim by atomic rename — one winner among any
            # number of contenders (the losers' rename gets ENOENT)
            stale = (f"{self.path}.stale-{self.owner}-{os.getpid()}-"
                     f"{int(now * 1e6)}")
            try:
                os.rename(self.path, stale)
            except OSError:
                return None  # lost the reclaim race
            prev_owner = "?"
            try:
                with open(stale) as f:
                    prev_owner = json.load(f).get("owner", "?")
            except (OSError, ValueError):
                pass
            try:
                os.unlink(stale)
            except OSError:
                pass
            info = ClaimInfo(reclaimed=True, prev_owner=prev_owner,
                             age_s=round(age, 3))
        # fresh claim: publish-by-hard-link — the payload is fully
        # written and fsynced in a private temp file before the link, so
        # a rival never reads a torn lease, and the link itself is the
        # atomic winner-takes-all step
        nonce = (f"{self.owner}-{os.getpid()}-{threading.get_ident()}-"
                 f"{int(now * 1e6)}")
        payload = {"owner": self.owner, "pid": os.getpid(),
                   "host": socket.gethostname(),
                   "claimed_at": now, "ttl_s": self.ttl_secs,
                   "nonce": nonce, **(extra or {})}
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        # thread id in the temp name: two same-owner holders embedded in
        # one process must not interleave writes to one temp file
        tmp = (f"{self.path}.{self.owner}.{os.getpid()}."
               f"{threading.get_ident()}.tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            try:
                os.link(tmp, self.path)
            except OSError:
                return None  # a rival landed first
            self.nonce = nonce
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return info

    # -- heartbeat -----------------------------------------------------------
    def owns(self) -> bool:
        if self.nonce is None:
            return False  # nothing claimed; never matches a nonce-less file
        try:
            with open(self.path) as f:
                return json.load(f).get("nonce") == self.nonce
        except (OSError, ValueError):
            return False

    def renew(self) -> bool:
        """Bump the lease mtime — but only while it is still OUR lease
        (nonce re-read; see module docstring).  False means a rival
        reclaimed it: the holder must abort, not double-run."""
        if not self.owns():
            return False
        try:
            os.utime(self.path, None)
            return True
        except OSError:
            return False

    # -- release -------------------------------------------------------------
    def release(self) -> bool:
        """Grab-inspect-release (module docstring); returns True iff the
        lease was ours and is now deleted.  Always clears the nonce —
        after a release attempt this object holds nothing."""
        if self.nonce is None:
            return False
        grab = (f"{self.path}.release.{self.owner}.{os.getpid()}."
                f"{threading.get_ident()}")
        try:
            os.rename(self.path, grab)
        except OSError:
            self.nonce = None
            return False  # already gone (reclaimed + released by a rival)
        ours = False
        try:
            with open(grab) as f:
                ours = json.load(f).get("nonce") == self.nonce
        except (OSError, ValueError):
            pass
        if not ours:
            try:
                os.link(grab, self.path)  # a rival's live claim: restore it
            except OSError:
                pass
        try:
            os.unlink(grab)
        except OSError:
            pass
        self.nonce = None
        return ours
