"""Self-healing fleet supervisor: the controller that ACTS on the
telemetry plane (docs/serving.md "Fleet supervisor").

Everything below it already existed as *signals*: ``backlog_summary``
computes arrival-vs-drain economics and recommends a fleet size, the
``stale_heartbeat`` rule spots wedged processes, the lease protocol
makes daemons work-steal safely, and the drain daemon survives SIGKILL
at item granularity.  This module closes the loop — one long-lived
controller (``python -m tenzing_tpu.serve.supervisor``) that owns the
whole serving fleet and applies Borg-style supervision to it:

* **members** — N drain daemons (``serve/fleet.py``'s argv + pipe-pump
  machinery, unchanged), an optional ``serve listen`` loop
  (``--listen-socket``), and a periodic offline ``serve compact`` pass
  over a segmented store.
* **autoscaling** — each tick consumes the clamped
  ``recommended_daemons`` from :func:`~tenzing_tpu.obs.alerts.
  backlog_summary` with hysteresis (the desire must persist
  ``scale_hold_ticks`` ticks), a cooldown between actions, and hard
  ``--min-daemons``/``--max-daemons`` bounds.  Scale-up adds one
  member; scale-down SIGTERMs the *youngest* member, whose in-flight
  item is protected by the daemon's own lease/checkpoint protocol
  (verified by the fleet's status-history audit).  Scale-up is
  suppressed while the backlog is poison-dominated — more daemons
  cannot drain quarantined poison faster.
* **self-healing** — a dead member (or one whose status-doc heartbeat
  is stale past the ``stale_heartbeat`` criterion: wedged, so it is
  SIGKILLed first) restarts through ``fault/backoff.py`` bounded
  exponential backoff.  K crash-restarts inside a sliding window trip
  a per-member :class:`CrashLoopBreaker`: the slot is quarantined
  (breaker **open**), a ``supervisor_crash_loop`` alert fires through
  the watchtower ledger, and the rest of the fleet degrades gracefully
  instead of flapping.  After a quarantine period the breaker goes
  **half_open** and admits one probe member; a healthy probe closes
  it, a dead one re-opens it.
* **SIGKILL-survivable** — the supervisor holds a single-controller
  lease (``serve/lease.py``; a second supervisor on the same queue
  exits immediately with rc 3) and stamps ``status-supervisor.json``
  heartbeats + metric snapshots like every other long-lived process.
  A successor *adopts* still-running members discovered from their
  live status docs (fresh heartbeat + live pid) instead of
  double-spawning; losing the lease renewal mid-run means a successor
  took over — the incumbent stands down WITHOUT touching the members
  it no longer owns (rc 4).
* **retention GC** — ``status-*/metrics-*/alerts-*`` documents and
  exemplar bundles of long-dead owners otherwise accumulate forever
  and every ``report --follow``/alerts tick rescans them; the
  supervisor sweeps artifacts whose owner said goodbye properly
  (``state: stopped``) longer than ``--gc-retention`` ago.  Live
  heartbeats — even stale ones, which are *evidence* for the
  ``stale_heartbeat`` page — are never touched.

Run it::

    python -m tenzing_tpu.serve.supervisor --queue QDIR --store STORE \
        --min-daemons 1 --max-daemons 4 [--listen-socket SOCK] \
        [--drain-exit] [--override mcts_iters=6 ...]

**Exit codes**: 0 = drained/stopped healthy, 1 = degraded (open
breaker, double-run, or a member dead at shutdown), 3 = another
supervisor holds the controller lease, 4 = lease lost mid-run (a
successor adopted the fleet).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tenzing_tpu.fault.backoff import BackoffPolicy
from tenzing_tpu.obs.alerts import Alert, AlertBook, backlog_summary
from tenzing_tpu.obs.metrics import MetricsSnapshotWriter, get_metrics
from tenzing_tpu.serve.fleet import (
    FleetOpts,
    _daemon_cmd,
    _ProcHandle,
    audit_completions,
)
from tenzing_tpu.serve.lease import LeaseFile
from tenzing_tpu.serve.store import WorkQueue
from tenzing_tpu.utils.atomic import atomic_dump_json

SUPERVISOR_VERSION = 1
LEASE_NAME = "supervisor.lease"       # NOT lease-*.json: item leases only
STATUS_NAME = "status-supervisor.json"
ALERTS_NAME = "alerts-supervisor.json"

RC_OK = 0
RC_DEGRADED = 1
RC_LEASE_HELD = 3
RC_LEASE_LOST = 4


# -- crash-loop circuit breaker ----------------------------------------------

class CrashLoopBreaker:
    """Per-member-slot crash-loop protection: ``closed`` (normal
    restarts-with-backoff) → ``open`` after ``max_restarts`` crash
    restarts inside a ``window_secs`` sliding window (the slot is
    quarantined, nothing is spawned) → ``half_open`` after
    ``quarantine_secs`` (exactly one probe member is admitted) →
    ``closed`` again if the probe stays healthy for ``probe_ok_secs``
    (or exits clean), back to ``open`` if the probe crashes."""

    def __init__(self, max_restarts: int = 3, window_secs: float = 60.0,
                 quarantine_secs: float = 120.0,
                 probe_ok_secs: float = 5.0):
        self.max_restarts = int(max_restarts)
        self.window_secs = float(window_secs)
        self.quarantine_secs = float(quarantine_secs)
        self.probe_ok_secs = float(probe_ok_secs)
        self.state = "closed"
        self.restarts: List[float] = []
        self.opened_at: Optional[float] = None
        self.probe_spawned = False

    def prune(self, now: float) -> None:
        self.restarts = [t for t in self.restarts
                         if now - t <= self.window_secs]

    def record_crash(self, now: float) -> str:
        """One crash restart; returns the state AFTER recording."""
        if self.state == "half_open":
            # the probe itself died: the slot is still poisoned
            self.state, self.opened_at = "open", now
            self.probe_spawned = False
            self.restarts.append(now)
            return self.state
        self.prune(now)
        self.restarts.append(now)
        if self.state == "closed" and \
                len(self.restarts) >= self.max_restarts:
            self.state, self.opened_at = "open", now
        return self.state

    def allow_spawn(self, now: float) -> bool:
        if self.state == "closed":
            return True
        if self.state == "open":
            if self.opened_at is not None and \
                    now - self.opened_at >= self.quarantine_secs:
                self.state = "half_open"
                self.probe_spawned = False
                return True
            return False
        return not self.probe_spawned  # half_open: one probe only

    def spawned(self, now: float) -> None:
        if self.state == "half_open":
            self.probe_spawned = True

    def note_healthy(self, now: float) -> None:
        """The member ran ``probe_ok_secs`` (or exited clean): a
        half-open probe succeeded — close and forget the window."""
        if self.state == "half_open" and self.probe_spawned:
            self.state = "closed"
            self.restarts, self.opened_at = [], None
            self.probe_spawned = False

    def to_json(self) -> Dict[str, Any]:
        return {"state": self.state,
                "restarts_in_window": len(self.restarts),
                "max_restarts": self.max_restarts,
                "window_s": self.window_secs,
                "opened_at": self.opened_at}


# -- member handles ----------------------------------------------------------

class AdoptedHandle:
    """A member inherited from a dead predecessor: we hold its pid (from
    its status doc), not its pipes.  Liveness is ``kill(pid, 0)``;
    signals go to the pid; the exit code is unknowable (``rc: None`` —
    clean-vs-crash is then decided from the member's own status doc)."""

    def __init__(self, owner: str, pid: int):
        self.owner = owner
        self.pid = int(pid)

    def alive(self) -> bool:
        try:
            os.kill(self.pid, 0)
            return True
        except OSError:
            return False

    def send_signal(self, sig: int) -> None:
        os.kill(self.pid, sig)

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        deadline = time.time() + (timeout or 0.0)
        while self.alive() and time.time() < deadline:
            time.sleep(0.05)
        return {"owner": self.owner, "rc": None, "adopted": True}


def _handle_alive(handle: Any) -> bool:
    if handle is None:
        return False
    proc = getattr(handle, "proc", None)
    if proc is not None:
        return proc.poll() is None
    fn = getattr(handle, "alive", None)
    if callable(fn):
        try:
            return bool(fn())
        except OSError:
            return False
    thread = getattr(handle, "thread", None)
    return thread.is_alive() if thread is not None else False


def _handle_rc(handle: Any) -> Optional[int]:
    proc = getattr(handle, "proc", None)
    if proc is not None:
        return proc.returncode
    return getattr(handle, "returncode", None)


def _handle_pid(handle: Any) -> Optional[int]:
    proc = getattr(handle, "proc", None)
    if proc is not None:
        return proc.pid
    pid = getattr(handle, "pid", None)
    return int(pid) if pid is not None else None


def _handle_signal(handle: Any, sig: int) -> None:
    try:
        proc = getattr(handle, "proc", None)
        if proc is not None:
            proc.send_signal(sig)
            return
        fn = getattr(handle, "send_signal", None)
        if callable(fn):
            fn(sig)
        elif sig in (signal.SIGTERM, signal.SIGINT) and \
                callable(getattr(handle, "stop", None)):
            handle.stop()  # in-process test members
    except (OSError, ValueError):
        pass


@dataclass
class MemberSlot:
    """One supervised member slot (slot index is stable: a quarantined
    slot keeps its index and breaker while empty)."""

    k: int
    owner: str
    kind: str = "daemon"              # "daemon" | "listen"
    handle: Any = None
    started_at: float = 0.0
    adopted: bool = False
    stopping: bool = False            # SIGTERM sent (scale-down/shutdown)
    wedged: bool = False              # SIGKILLed for heartbeat staleness
    restarts: int = 0                 # lifetime crash restarts
    clean_exits: int = 0
    backoff_i: int = 0
    next_spawn_at: float = 0.0
    last_rc: Optional[int] = None

    def state(self, breaker: CrashLoopBreaker) -> str:
        if self.handle is not None:
            return "stopping" if self.stopping else "running"
        if breaker.state in ("open", "half_open"):
            return "quarantined"
        return "restarting" if self.next_spawn_at else "empty"


# -- options -----------------------------------------------------------------

@dataclass
class SupervisorOpts:
    """Knobs of one supervisor run (CLI flags map 1:1)."""

    queue_dir: str
    store_path: str
    min_daemons: int = 1
    max_daemons: Optional[int] = None   # None -> ~os.cpu_count()
    owner_prefix: str = "fleet"
    owner: str = ""                     # supervisor id (default host-pid)
    tick_secs: float = 1.0
    heartbeat_secs: float = 2.0
    lease_ttl_secs: float = 30.0        # single-controller lease
    stale_secs: float = 60.0            # stale_heartbeat criterion
    # scaling policy
    scale_hold_ticks: int = 3           # hysteresis: ticks of persistence
    cooldown_secs: float = 15.0         # between scaling actions
    # restart policy
    backoff: BackoffPolicy = field(default_factory=lambda: BackoffPolicy(
        retries=1_000_000, base_secs=0.5, factor=2.0, max_secs=30.0,
        jitter=0.25))
    breaker_max_restarts: int = 3
    breaker_window_secs: float = 60.0
    breaker_quarantine_secs: float = 120.0
    breaker_probe_ok_secs: float = 5.0
    # member daemon knobs (FleetOpts pass-through)
    member_idle_exit_secs: Optional[float] = None   # None: never idle-exit
    member_poll_secs: float = 0.25
    member_lease_ttl_secs: float = 60.0
    member_heartbeat_secs: float = 1.0
    member_item_timeout_secs: Optional[float] = 3600.0
    topk: int = 3
    overrides: Dict[str, Any] = field(default_factory=dict)
    # test/CI chaos hook: replace the daemon argv ({owner} substituted)
    member_argv: Optional[List[str]] = None
    # optional listen-loop member
    listen_socket: Optional[str] = None
    listen_args: List[str] = field(default_factory=list)
    # periodic offline compaction (segmented stores; 0 disables)
    compact_interval_secs: float = 300.0
    # retention GC (0 disables)
    gc_interval_secs: float = 60.0
    gc_retention_secs: float = 3600.0
    # CI mode: exit once the queue is drained and every member has
    # idle-exited (or every slot is quarantined — the degraded exit)
    drain_exit: bool = False
    handle_signals: bool = True
    max_run_secs: Optional[float] = None  # hard wall-clock stop (tests)


def _store_base(store_path: str) -> str:
    """The directory the serve loop's status/metrics docs live in: the
    segmented store dir itself, or the monolithic json's parent."""
    if os.path.isdir(store_path) or not store_path.endswith(".json"):
        return store_path
    return os.path.dirname(os.path.abspath(store_path))


# -- retention GC ------------------------------------------------------------

_METRICS_RE = re.compile(r"^metrics-(.+)-(\d+)\.json$")
_STATUS_RE = re.compile(r"^status-(.+)\.json$")
_ALERTS_RE = re.compile(r"^alerts-(.+)\.json$")


def gc_stale_artifacts(dirs: List[str], retention_secs: float,
                       now: Optional[float] = None,
                       keep_owners: Optional[List[str]] = None,
                       log: Optional[Callable[[str], None]] = None,
                       ) -> Dict[str, int]:
    """One retention sweep over the fleet's telemetry artifacts.

    Removed: status docs in ``state: stopped``/``interrupted`` whose
    heartbeat is older than ``retention_secs`` (they said goodbye
    properly and nobody follows them anymore), metric-snapshot rings
    whose owner has no status doc left, alert ledgers with nothing
    firing and no writes inside the window, and exemplar bundles older
    than the window.  NEVER removed: anything owned by ``keep_owners``
    (the live fleet), any status doc that did *not* stop — a stale
    live heartbeat is the ``stale_heartbeat`` page's evidence — and
    anything younger than the window.  Returns per-class removal
    counts."""
    now = time.time() if now is None else now
    keep = set(keep_owners or [])
    counts = {"status": 0, "metrics": 0, "alerts": 0, "exemplars": 0}

    def _unlink(path: str, what: str) -> None:
        try:
            os.unlink(path)
            counts[what] += 1
        except OSError:
            pass

    for d in dict.fromkeys(d for d in dirs if d and os.path.isdir(d)):
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        for name in names:
            m = _STATUS_RE.match(name)
            if not m or m.group(1) in keep:
                continue
            path = os.path.join(d, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if doc.get("state") not in ("stopped", "interrupted"):
                continue
            try:
                age = now - float(doc.get("heartbeat_at") or 0)
            except (TypeError, ValueError):
                continue
            if age > retention_secs:
                _unlink(path, "status")
        # metric rings: orphaned once their owner's status doc is gone
        try:
            remaining = sorted(os.listdir(d))
        except OSError:
            remaining = []
        owners_left = {m.group(1)
                       for m in map(_STATUS_RE.match, remaining) if m}
        for name in names:
            m = _METRICS_RE.match(name)
            if not m or m.group(1) in keep or m.group(1) in owners_left:
                continue
            path = os.path.join(d, name)
            try:
                if now - os.path.getmtime(path) > retention_secs:
                    _unlink(path, "metrics")
            except OSError:
                pass
        for name in names:
            m = _ALERTS_RE.match(name)
            if not m or m.group(1) in keep:
                continue
            path = os.path.join(d, name)
            try:
                if now - os.path.getmtime(path) <= retention_secs:
                    continue
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            firing = any((e or {}).get("state") == "firing"
                         for e in (doc.get("alerts") or {}).values())
            if not firing:
                _unlink(path, "alerts")
        # exemplar bundles (serve/reqlog.py tail-sampled spans)
        for sub in (d, os.path.join(d, "exemplars"),
                    os.path.join(d, "reqlog", "exemplars")):
            if not os.path.isdir(sub):
                continue
            try:
                for name in sorted(os.listdir(sub)):
                    if not (name.startswith("exemplar-")
                            and name.endswith(".jsonl")):
                        continue
                    path = os.path.join(sub, name)
                    try:
                        if now - os.path.getmtime(path) > retention_secs:
                            _unlink(path, "exemplars")
                    except OSError:
                        pass
            except OSError:
                pass
    removed = sum(counts.values())
    if removed and log:
        log(f"supervisor: gc removed {removed} stale artifact(s) "
            f"({counts})")
    return counts


# -- the supervisor ----------------------------------------------------------

class Supervisor:
    """The controller (module docstring).  ``spawn(opts, slot)`` is
    injectable for tests — anything returning a handle with the
    :func:`_handle_alive`/``send_signal`` duck type; the default
    spawns real subprocess members via fleet.py's argv builder."""

    def __init__(self, opts: SupervisorOpts,
                 spawn: Optional[Callable[["SupervisorOpts", MemberSlot],
                                          Any]] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.opts = opts
        self.owner = opts.owner or \
            f"supervisor-{socket.gethostname()}-{os.getpid()}"
        self._spawn_fn = spawn or _subprocess_member_spawn
        self._log_fn = log
        self.queue = WorkQueue(opts.queue_dir)
        self.store_base = _store_base(opts.store_path)
        self.max_daemons = int(opts.max_daemons or os.cpu_count() or 4)
        self.started_at = time.time()
        self.slots: Dict[int, MemberSlot] = {}
        self.listen_slot: Optional[MemberSlot] = None
        self.breakers: Dict[str, CrashLoopBreaker] = {}
        self.counters: Dict[str, int] = {}
        self.gc_counts: Dict[str, int] = {"status": 0, "metrics": 0,
                                          "alerts": 0, "exemplars": 0}
        self.all_owners: List[str] = []
        self.lease = LeaseFile(
            os.path.join(opts.queue_dir, LEASE_NAME), self.owner,
            ttl_secs=opts.lease_ttl_secs, log=self._log)
        self.status_path = os.path.join(opts.queue_dir, STATUS_NAME)
        self._snapshots = MetricsSnapshotWriter(
            opts.queue_dir, "supervisor")
        self._book = AlertBook(
            os.path.join(opts.queue_dir, ALERTS_NAME), owner="supervisor",
            log=self._log)
        self._stop = False
        self._signals = 0
        self._desired = max(1, opts.min_daemons)
        self._pending_desired: Optional[int] = None
        self._pending_ticks = 0
        self._last_scale_at = 0.0
        self._last_heartbeat_at = 0.0
        self._last_gc_at = time.time()
        self._last_compact_at = time.time()
        self._compact_handle: Optional[_ProcHandle] = None
        self._last_summary: Dict[str, Any] = {}
        self._scaling_state: Dict[str, Any] = {}
        self._ticks = 0
        self._prev_handlers: Dict[int, Any] = {}

    # -- plumbing ------------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self._log_fn is not None:
            self._log_fn(msg)
        else:
            sys.stderr.write(msg + "\n")

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        get_metrics().counter(f"supervisor.{name}").inc(n)

    def _breaker_of(self, owner: str) -> CrashLoopBreaker:
        br = self.breakers.get(owner)
        if br is None:
            o = self.opts
            br = self.breakers[owner] = CrashLoopBreaker(
                max_restarts=o.breaker_max_restarts,
                window_secs=o.breaker_window_secs,
                quarantine_secs=o.breaker_quarantine_secs,
                probe_ok_secs=o.breaker_probe_ok_secs)
        return br

    def _status_doc_of(self, slot: MemberSlot) -> Optional[Dict[str, Any]]:
        d = self.store_base if slot.kind == "listen" else \
            self.opts.queue_dir
        try:
            with open(os.path.join(d, f"status-{slot.owner}.json")) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError):
            return None

    # -- member lifecycle ----------------------------------------------------

    def _spawn(self, slot: MemberSlot, now: float) -> None:
        br = self._breaker_of(slot.owner)
        slot.handle = self._spawn_fn(self.opts, slot)
        slot.started_at = now
        slot.next_spawn_at = 0.0
        slot.stopping = slot.wedged = False
        br.spawned(now)
        if slot.owner not in self.all_owners:
            self.all_owners.append(slot.owner)
        self._count("spawned")
        probe = " (breaker probe)" if br.state == "half_open" else ""
        self._log(f"supervisor: spawned {slot.owner} "
                  f"pid {_handle_pid(slot.handle)}{probe}")

    def _adopt(self, now: float) -> int:
        """Discover the predecessor's still-running members from their
        live status docs and adopt them instead of double-spawning."""
        adopted = 0
        pat = re.compile(
            rf"^status-({re.escape(self.opts.owner_prefix)}-(\d+))\.json$")
        try:
            names = sorted(os.listdir(self.opts.queue_dir))
        except OSError:
            names = []
        for name in names:
            m = pat.match(name)
            if not m:
                continue
            owner, k = m.group(1), int(m.group(2))
            slot = MemberSlot(k=k, owner=owner, kind="daemon")
            doc = self._status_doc_of(slot)
            if not self._adoptable(doc, now):
                continue
            slot.handle = AdoptedHandle(owner, int(doc["pid"]))
            slot.adopted = True
            slot.started_at = float(doc.get("started_at") or now)
            self.slots[k] = slot
            if owner not in self.all_owners:
                self.all_owners.append(owner)
            adopted += 1
            self._count("adopted")
            self._log(f"supervisor: adopted {owner} "
                      f"pid {doc['pid']} (uptime "
                      f"{doc.get('uptime_s', '?')}s)")
        if self.opts.listen_socket:
            slot = MemberSlot(k=-1, owner=self._listen_owner(),
                              kind="listen")
            doc = self._status_doc_of(slot)
            if self._adoptable(doc, now):
                slot.handle = AdoptedHandle(slot.owner, int(doc["pid"]))
                slot.adopted = True
                slot.started_at = float(doc.get("started_at") or now)
                self.listen_slot = slot
                adopted += 1
                self._count("adopted")
                self._log(f"supervisor: adopted {slot.owner} "
                          f"pid {doc['pid']}")
        return adopted

    def _adoptable(self, doc: Optional[Dict[str, Any]],
                   now: float) -> bool:
        if not doc or doc.get("state") in ("stopped", "interrupted"):
            return False
        try:
            hb_age = now - float(doc.get("heartbeat_at") or 0)
            pid = int(doc["pid"])
        except (KeyError, TypeError, ValueError):
            return False
        if hb_age > self.opts.stale_secs or pid == os.getpid():
            return False
        try:
            os.kill(pid, 0)
        except OSError:
            return False
        return True

    def _listen_owner(self) -> str:
        return f"{self.opts.owner_prefix}-listen"

    def _member_tick(self, slot: MemberSlot, now: float) -> None:
        """Reap/heal one slot: respawn after backoff, quarantine on a
        tripped breaker, SIGKILL a wedged heartbeat, reset backoff on a
        healthy run."""
        br = self._breaker_of(slot.owner)
        br.prune(now)
        if slot.handle is None:
            if slot.stopping or now < slot.next_spawn_at:
                return
            if self.opts.drain_exit and slot.kind == "daemon" and \
                    slot.clean_exits and not len(self.queue):
                return  # drained fleet: a clean-exited member stays down
            if br.allow_spawn(now):
                self._spawn(slot, now)
            return
        if _handle_alive(slot.handle):
            uptime = now - slot.started_at
            doc = self._status_doc_of(slot)
            hb_age = None
            if doc is not None:
                try:
                    hb_age = now - float(doc.get("heartbeat_at") or 0)
                except (TypeError, ValueError):
                    hb_age = None
            if not slot.stopping and not slot.wedged and \
                    hb_age is not None and hb_age > self.opts.stale_secs \
                    and uptime > self.opts.stale_secs:
                # alive but silent past the stale_heartbeat criterion:
                # wedged — kill it and let the death path restart it
                self._log(f"supervisor: {slot.owner} heartbeat "
                          f"{hb_age:.0f}s stale — killing wedged member")
                slot.wedged = True
                self._count("wedged")
                _handle_signal(slot.handle, signal.SIGKILL)
                return
            if uptime >= br.probe_ok_secs:
                slot.backoff_i = 0
                br.note_healthy(now)
            return
        # dead: clean exit, scale-down completion, or crash
        rc = _handle_rc(slot.handle)
        slot.last_rc = rc
        doc = self._status_doc_of(slot)
        said_goodbye = bool(doc) and \
            doc.get("state") in ("stopped", "interrupted")
        clean = (not slot.wedged) and \
            (rc == 0 or (rc is None and said_goodbye))
        slot.handle = None
        if slot.stopping:
            self._log(f"supervisor: {slot.owner} stopped (rc {rc})")
            self._reap_slot(slot)
            return
        if clean:
            slot.clean_exits += 1
            self._count("clean_exits")
            br.note_healthy(now)
            slot.backoff_i = 0
            slot.next_spawn_at = now + self.opts.tick_secs
            self._log(f"supervisor: {slot.owner} exited clean (rc {rc})")
            return
        slot.restarts += 1
        slot.wedged = False
        self._count("restarts")
        state = br.record_crash(now)
        if state == "open":
            slot.next_spawn_at = 0.0  # quarantined, not restarting
            self._count("quarantined")
            self._log(f"supervisor: {slot.owner} crash-looped "
                      f"({len(br.restarts)} restart(s) in "
                      f"{br.window_secs:.0f}s) — breaker OPEN, slot "
                      "quarantined")
            return
        delay = self.opts.backoff.delay(slot.backoff_i)
        slot.backoff_i += 1
        slot.next_spawn_at = now + delay
        self._log(f"supervisor: {slot.owner} died (rc {rc}) — restart "
                  f"in {delay:.1f}s (attempt {slot.restarts})")

    def _reap_slot(self, slot: MemberSlot) -> None:
        if slot.kind == "listen":
            self.listen_slot = None
        else:
            self.slots.pop(slot.k, None)

    # -- autoscaling ---------------------------------------------------------

    def _active_n(self) -> int:
        return sum(1 for s in self.slots.values()
                   if not s.stopping and
                   (s.handle is not None or s.next_spawn_at))

    def _poison_dominated(self) -> bool:
        poisoned = len(self.queue.poisoned())
        return poisoned > 0 and poisoned >= len(self.queue)

    def _scale_tick(self, now: float) -> None:
        # pass the IN-MEMORY open/half-open breaker owners explicitly: the
        # summary's own status-doc scan only sees breaker state as of the
        # last publish, and a member that crash-looped since then must not
        # count as drain capacity in the estimate this tick scales on
        bl = backlog_summary([self.store_base], [self.opts.queue_dir],
                             max_daemons=self.max_daemons,
                             quarantined_owners={
                                 o for o, b in self.breakers.items()
                                 if b.state in ("open", "half_open")})
        self._last_summary = bl
        desired = max(self.opts.min_daemons,
                      min(bl["recommended_daemons"], self.max_daemons))
        active = self._active_n()
        suppressed = False
        if desired > active and self._poison_dominated():
            desired, suppressed = active, True
        self._scaling_state = {
            "recommended": bl["recommended_daemons"],
            "desired": desired, "active": active,
            "suppressed_poison": suppressed,
            "last_action_at": self._last_scale_at or None}
        if desired == self._pending_desired:
            self._pending_ticks += 1
        else:
            self._pending_desired, self._pending_ticks = desired, 1
        if desired == active or \
                self._pending_ticks < self.opts.scale_hold_ticks or \
                now - self._last_scale_at < self.opts.cooldown_secs:
            return
        if desired > active:
            self._scale_up(now)
        else:
            self._scale_down(now)
        self._last_scale_at = now
        self._pending_ticks = 0

    def _scale_up(self, now: float) -> None:
        k = 0
        while k in self.slots:
            k += 1
        owner = f"{self.opts.owner_prefix}-{k}"
        slot = MemberSlot(k=k, owner=owner)
        self.slots[k] = slot
        self._count("scale_up")
        self._log(f"supervisor: scale-up -> spawning {owner} "
                  f"(active {self._active_n() - 1} < desired "
                  f"{self._pending_desired})")
        self._spawn(slot, now)

    def _scale_down(self, now: float) -> None:
        running = [s for s in self.slots.values()
                   if s.handle is not None and not s.stopping]
        if not running:
            return
        youngest = max(running, key=lambda s: s.started_at)
        youngest.stopping = True
        self._count("scale_down")
        self._log(f"supervisor: scale-down -> SIGTERM {youngest.owner} "
                  "(youngest; its in-flight item is lease-protected)")
        _handle_signal(youngest.handle, signal.SIGTERM)

    # -- periodic compaction -------------------------------------------------

    def _compact_tick(self, now: float) -> None:
        if self._compact_handle is not None:
            if _handle_alive(self._compact_handle):
                return
            rc = _handle_rc(self._compact_handle)
            self._count("compactions")
            if rc not in (0, None):
                self._count("compact_failures")
                self._log(f"supervisor: compact pass failed (rc {rc})")
            self._compact_handle = None
        if not self.opts.compact_interval_secs or \
                not os.path.isdir(self.opts.store_path) or \
                now - self._last_compact_at < \
                self.opts.compact_interval_secs:
            return
        self._last_compact_at = now
        cmd = [sys.executable, "-m", "tenzing_tpu.serve", "compact",
               "--store", self.opts.store_path,
               "--owner", f"{self.owner}-compact"]
        self._compact_handle = _ProcHandle(
            f"{self.owner}-compact",
            subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True))
        self._log("supervisor: compact pass started")

    # -- heartbeat / telemetry -----------------------------------------------

    def _member_json(self, slot: MemberSlot) -> Dict[str, Any]:
        return {"slot": slot.k, "owner": slot.owner, "kind": slot.kind,
                "state": slot.state(self._breaker_of(slot.owner)),
                "pid": _handle_pid(slot.handle),
                "adopted": slot.adopted, "restarts": slot.restarts,
                "started_at": round(slot.started_at, 3) or None,
                "last_rc": slot.last_rc}

    def _write_status(self, state: str) -> None:
        now = time.time()
        members = [self._member_json(s)
                   for _, s in sorted(self.slots.items())]
        if self.listen_slot is not None:
            members.append(self._member_json(self.listen_slot))
        doc = {
            "version": SUPERVISOR_VERSION,
            "kind": "supervisor",
            "owner": self.owner,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "started_at": self.started_at,
            "heartbeat_at": now,
            "uptime_s": round(now - self.started_at, 1),
            "state": state,
            "n_members": self._active_n(),
            "desired_n": self._pending_desired or self._desired,
            "members": members,
            "breakers": {o: b.to_json()
                         for o, b in sorted(self.breakers.items())
                         if b.state != "closed" or b.restarts},
            "scaling": dict(self._scaling_state,
                            cooldown_s=self.opts.cooldown_secs,
                            hold_ticks=self.opts.scale_hold_ticks,
                            min_daemons=self.opts.min_daemons,
                            max_daemons=self.max_daemons),
            "backlog": self._last_summary or None,
            "counters": dict(self.counters),
            "gc": dict(self.gc_counts),
        }
        try:
            atomic_dump_json(self.status_path, doc, prefix=".status.")
        except OSError as e:
            self._log(f"supervisor: status write failed ({e})")
        try:
            self._snapshots.write(state=state, extra={
                "counters": dict(self.counters),
                "n_members": self._active_n(),
                "uptime_s": round(now - self.started_at, 1)})
        except OSError:
            pass
        # the watchtower ledger: open/half-open breakers fire the
        # supervisor_crash_loop page until the slot recovers
        active = [Alert(
            "supervisor_crash_loop", owner, "page",
            {"state": b.state, "restarts": len(b.restarts)},
            {"max_restarts": b.max_restarts, "window_s": b.window_secs},
            f"member {owner!r} crash-looped; breaker {b.state}")
            for owner, b in sorted(self.breakers.items())
            if b.state in ("open", "half_open")]
        try:
            self._book.apply(active, now=now)
        except OSError:
            pass

    def _gc_tick(self, now: float) -> None:
        if not self.opts.gc_interval_secs or \
                now - self._last_gc_at < self.opts.gc_interval_secs:
            return
        self._last_gc_at = now
        keep = ["supervisor", self.owner] + \
            [s.owner for s in self.slots.values()]
        if self.listen_slot is not None:
            keep.append(self.listen_slot.owner)
        counts = gc_stale_artifacts(
            [self.opts.queue_dir, self.store_base],
            self.opts.gc_retention_secs, now=now, keep_owners=keep,
            log=self._log)
        for k, v in counts.items():
            if v:
                self.gc_counts[k] += v
                self._count(f"gc.{k}", v)

    # -- drain-exit / shutdown -----------------------------------------------

    def _drained(self) -> bool:
        """drain-exit: the queue is empty (no live work, no leases) and
        no member is running — either every slot idle-exited clean, or
        what remains is quarantined (the degraded exit)."""
        if not self.opts.drain_exit:
            return False
        if len(self.queue) or self.queue.leases():
            # members still draining (or a crashed member's lease is
            # aging toward reclaim — not drained either way)
            running = any(s.handle is not None
                          for s in self.slots.values())
            restartable = any(
                s.handle is None and not s.stopping and
                self._breaker_of(s.owner).state == "closed"
                for s in self.slots.values())
            if running or restartable:
                return False
            # nothing left that could drain it: all quarantined
            return bool(self.slots) and not running
        return not any(s.handle is not None or
                       (s.next_spawn_at and not s.clean_exits)
                       for s in self.slots.values())

    def _shutdown_members(self, grace_secs: float = 20.0) -> None:
        stoppers = [s for s in self.slots.values()
                    if s.handle is not None]
        if self.listen_slot is not None and \
                self.listen_slot.handle is not None:
            stoppers.append(self.listen_slot)
        for s in stoppers:
            s.stopping = True
            _handle_signal(s.handle, signal.SIGTERM)
        deadline = time.time() + grace_secs
        for s in stoppers:
            while _handle_alive(s.handle) and time.time() < deadline:
                time.sleep(0.1)
            if _handle_alive(s.handle):
                self._log(f"supervisor: {s.owner} ignored SIGTERM — "
                          "killing")
                _handle_signal(s.handle, signal.SIGKILL)
        if self._compact_handle is not None and \
                _handle_alive(self._compact_handle):
            _handle_signal(self._compact_handle, signal.SIGTERM)

    def _install_signals(self) -> None:
        if not self.opts.handle_signals:
            return

        def handler(signum, frame):
            self._signals += 1
            self._stop = True

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, handler)
            except (ValueError, OSError):
                pass

    def _restore_signals(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()

    def stop(self) -> None:
        """Programmatic twin of SIGTERM."""
        self._stop = True

    # -- the run loop --------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        o = self.opts
        now = time.time()
        if self.lease.claim(extra={"kind": "supervisor"}) is None:
            self._log("supervisor: controller lease is held by a live "
                      "rival — standing down")
            return self._summary("lease_held")
        self._install_signals()
        reason = "stopped"
        try:
            adopted = self._adopt(now)
            if adopted:
                self._log(f"supervisor: adopted {adopted} live "
                          "member(s) from a predecessor")
            # fill up to min_daemons with fresh members (adopted slots
            # count — adoption must not double-spawn)
            while self._active_n() < max(1, o.min_daemons):
                self._scale_up(time.time())
            if o.listen_socket and self.listen_slot is None:
                self.listen_slot = MemberSlot(
                    k=-1, owner=self._listen_owner(), kind="listen")
                self._spawn(self.listen_slot, time.time())
            self._write_status("supervising")
            while not self._stop:
                now = time.time()
                self._ticks += 1
                if not self.lease.renew():
                    self._count("lease_lost")
                    self._log("supervisor: lease renewal lost — a "
                              "successor owns the fleet; standing down "
                              "without touching its members")
                    reason = "lease_lost"
                    break
                for _, slot in sorted(self.slots.items()):
                    self._member_tick(slot, now)
                if self.listen_slot is not None:
                    self._member_tick(self.listen_slot, now)
                self._scale_tick(now)
                self._compact_tick(now)
                self._gc_tick(now)
                if now - self._last_heartbeat_at >= o.heartbeat_secs:
                    self._last_heartbeat_at = now
                    self._write_status("supervising")
                if self._drained():
                    reason = "drained"
                    break
                if o.max_run_secs is not None and \
                        now - self.started_at >= o.max_run_secs:
                    reason = "max_run_secs"
                    break
                time.sleep(o.tick_secs)
            else:
                reason = "signal"
        finally:
            self._restore_signals()
        if reason != "lease_lost":
            # successor owns the members on lease loss; otherwise they
            # are ours to stop
            if reason in ("signal", "stopped", "max_run_secs",
                          "drained"):
                self._shutdown_members()
            self._write_status("stopped")
            self.lease.release()
        return self._summary(reason)

    def _summary(self, reason: str) -> Dict[str, Any]:
        audit = audit_completions(self.opts.queue_dir,
                                  sorted(self.all_owners)) \
            if self.all_owners else {"completed_by": {},
                                     "double_runs": {},
                                     "audit_complete": True}
        doc = {
            "kind": "supervisor",
            "version": SUPERVISOR_VERSION,
            "owner": self.owner,
            "reason": reason,
            "wall_s": round(time.time() - self.started_at, 3),
            "ticks": self._ticks,
            "members": {s.owner: {"restarts": s.restarts,
                                  "clean_exits": s.clean_exits,
                                  "adopted": s.adopted,
                                  "last_rc": s.last_rc}
                        for s in list(self.slots.values()) +
                        ([self.listen_slot] if self.listen_slot else [])},
            "breakers": {o: b.to_json()
                         for o, b in sorted(self.breakers.items())},
            "counters": dict(self.counters),
            "gc": dict(self.gc_counts),
            "queue_after": len(self.queue),
            "double_runs": audit["double_runs"],
            "completed_by": audit["completed_by"],
            "audit_complete": audit["audit_complete"],
        }
        if audit["double_runs"]:
            self._log(f"supervisor: DOUBLE RUNS detected: "
                      f"{audit['double_runs']}")
        return doc


def _subprocess_member_spawn(opts: SupervisorOpts,
                             slot: MemberSlot) -> _ProcHandle:
    """The production spawner: fleet.py's daemon argv (one source of
    truth) with supervisor-specific lifetime knobs, or the listen
    loop's argv for the ``listen`` slot."""
    if slot.kind == "listen":
        cmd = [sys.executable, "-m", "tenzing_tpu.serve", "listen",
               "--store", opts.store_path, "--queue", opts.queue_dir,
               "--socket", opts.listen_socket or "",
               "--owner", slot.owner] + list(opts.listen_args)
    elif opts.member_argv:
        cmd = [a.replace("{owner}", slot.owner)
               for a in opts.member_argv]
    else:
        fo = FleetOpts(
            queue_dir=opts.queue_dir, store_path=opts.store_path,
            owner_prefix=opts.owner_prefix,
            idle_exit_secs=opts.member_idle_exit_secs
            if opts.member_idle_exit_secs is not None else 0.0,
            poll_secs=opts.member_poll_secs,
            lease_ttl_secs=opts.member_lease_ttl_secs,
            heartbeat_secs=opts.member_heartbeat_secs,
            item_timeout_secs=opts.member_item_timeout_secs,
            topk=opts.topk, overrides=opts.overrides)
        cmd = _daemon_cmd(fo, slot.k)
        if opts.member_idle_exit_secs is None:
            # a supervised member never idle-exits on its own — strip
            # the flag _daemon_cmd always emits
            i = cmd.index("--idle-exit")
            cmd = cmd[:i] + cmd[i + 2:]
    # own session: a signal aimed at the supervisor's group must not hit
    # members directly (the supervisor owns their shutdown), and chaos
    # tests can killpg one member (daemon + its drain child) without
    # touching the controller
    return _ProcHandle(slot.owner,
                       subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                        stderr=subprocess.PIPE,
                                        text=True, start_new_session=True))


def supervisor_exit_code(doc: Dict[str, Any]) -> int:
    """The CLI verdict: lease exclusivity codes trump, then the
    exactly-once contract and breaker state — a fleet that ends with a
    slot quarantined (or a proven double run) must not report
    success."""
    if doc.get("reason") == "lease_held":
        return RC_LEASE_HELD
    if doc.get("reason") == "lease_lost":
        return RC_LEASE_LOST
    if doc.get("double_runs"):
        return RC_DEGRADED
    if any((b or {}).get("state") in ("open", "half_open")
           for b in (doc.get("breakers") or {}).values()):
        return RC_DEGRADED
    return RC_OK


def main(argv: Optional[List[str]] = None) -> int:
    from tenzing_tpu.serve.daemon import parse_override

    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.serve.supervisor",
        description="Self-healing fleet supervisor: autoscaling drain "
                    "fleet with crash-loop breakers, adoption-on-"
                    "restart, and graceful degradation "
                    "(docs/serving.md 'Fleet supervisor').")
    ap.add_argument("--queue", required=True, metavar="DIR")
    ap.add_argument("--store", required=True, metavar="PATH")
    ap.add_argument("--min-daemons", type=int, default=1)
    ap.add_argument("--max-daemons", type=int, default=None,
                    help="hard fleet ceiling (default ~os.cpu_count(); "
                         "shared with the backlog recommendation clamp)")
    ap.add_argument("--owner-prefix", default="fleet")
    ap.add_argument("--owner", default=None,
                    help="supervisor id (default host-pid)")
    ap.add_argument("--tick", type=float, default=1.0, metavar="SECS")
    ap.add_argument("--heartbeat", type=float, default=2.0,
                    metavar="SECS")
    ap.add_argument("--lease-ttl", type=float, default=30.0,
                    metavar="SECS",
                    help="single-controller lease TTL (a successor "
                         "reclaims after this much supervisor silence)")
    ap.add_argument("--stale-secs", type=float, default=60.0,
                    help="member heartbeat staleness before a wedged "
                         "member is killed (the stale_heartbeat "
                         "criterion)")
    ap.add_argument("--scale-hold-ticks", type=int, default=3,
                    help="hysteresis: ticks a scaling desire must "
                         "persist before acting")
    ap.add_argument("--cooldown", type=float, default=15.0,
                    metavar="SECS", help="between scaling actions")
    ap.add_argument("--breaker-max-restarts", type=int, default=3)
    ap.add_argument("--breaker-window", type=float, default=60.0,
                    metavar="SECS")
    ap.add_argument("--breaker-quarantine", type=float, default=120.0,
                    metavar="SECS")
    ap.add_argument("--backoff-base", type=float, default=0.5,
                    metavar="SECS")
    ap.add_argument("--backoff-max", type=float, default=30.0,
                    metavar="SECS")
    ap.add_argument("--member-idle-exit", type=float, default=None,
                    metavar="SECS",
                    help="members exit after idling this long (default: "
                         "never — the supervisor owns their lifetime; "
                         "set it with --drain-exit for CI)")
    ap.add_argument("--member-poll", type=float, default=0.25,
                    metavar="SECS")
    ap.add_argument("--member-lease-ttl", type=float, default=60.0,
                    metavar="SECS")
    ap.add_argument("--member-heartbeat", type=float, default=1.0,
                    metavar="SECS")
    ap.add_argument("--item-timeout", type=float, default=3600.0,
                    metavar="SECS")
    ap.add_argument("--topk", type=int, default=3)
    ap.add_argument("--override", action="append", default=[],
                    metavar="K=V",
                    help="request-budget override for every member "
                         "(serve/daemon.py semantics)")
    ap.add_argument("--listen-socket", default=None, metavar="PATH",
                    help="also supervise a serve listen loop on this "
                         "unix socket")
    ap.add_argument("--listen-arg", action="append", default=[],
                    metavar="ARG",
                    help="extra argv appended to the listen member "
                         "(repeatable, e.g. --listen-arg=--busy-poll-us "
                         "--listen-arg=50)")
    ap.add_argument("--compact-interval", type=float, default=300.0,
                    metavar="SECS",
                    help="periodic offline compaction pass over a "
                         "segmented store (0 disables)")
    ap.add_argument("--gc-interval", type=float, default=60.0,
                    metavar="SECS")
    ap.add_argument("--gc-retention", type=float, default=3600.0,
                    metavar="SECS",
                    help="stale-artifact retention window (0 disables "
                         "the sweep)")
    ap.add_argument("--drain-exit", action="store_true",
                    help="exit once the queue is drained and every "
                         "member idle-exited (CI mode)")
    ap.add_argument("--max-run-secs", type=float, default=None,
                    help=argparse.SUPPRESS)
    # chaos hook for tests/CI: replace the member daemon argv entirely
    # ({owner} substituted) — not for operators
    ap.add_argument("--member-argv", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    try:
        overrides = dict(parse_override(s) for s in args.override)
    except ValueError as e:
        ap.error(str(e))
    member_argv = None
    if args.member_argv:
        try:
            member_argv = json.loads(args.member_argv)
            assert isinstance(member_argv, list)
        except (ValueError, AssertionError):
            ap.error("--member-argv: expected a JSON list of strings")
    opts = SupervisorOpts(
        queue_dir=args.queue, store_path=args.store,
        min_daemons=args.min_daemons, max_daemons=args.max_daemons,
        owner_prefix=args.owner_prefix, owner=args.owner or "",
        tick_secs=args.tick, heartbeat_secs=args.heartbeat,
        lease_ttl_secs=args.lease_ttl, stale_secs=args.stale_secs,
        scale_hold_ticks=args.scale_hold_ticks,
        cooldown_secs=args.cooldown,
        backoff=BackoffPolicy(retries=1_000_000,
                              base_secs=args.backoff_base,
                              factor=2.0, max_secs=args.backoff_max,
                              jitter=0.25),
        breaker_max_restarts=args.breaker_max_restarts,
        breaker_window_secs=args.breaker_window,
        breaker_quarantine_secs=args.breaker_quarantine,
        member_idle_exit_secs=args.member_idle_exit,
        member_poll_secs=args.member_poll,
        member_lease_ttl_secs=args.member_lease_ttl,
        member_heartbeat_secs=args.member_heartbeat,
        member_item_timeout_secs=args.item_timeout,
        topk=args.topk, overrides=overrides, member_argv=member_argv,
        listen_socket=args.listen_socket, listen_args=args.listen_arg,
        compact_interval_secs=args.compact_interval,
        gc_interval_secs=args.gc_interval,
        gc_retention_secs=args.gc_retention,
        drain_exit=args.drain_exit, max_run_secs=args.max_run_secs)
    doc = Supervisor(opts).run()
    sys.stdout.write(json.dumps(doc) + "\n")
    return supervisor_exit_code(doc)


if __name__ == "__main__":
    sys.exit(main())
