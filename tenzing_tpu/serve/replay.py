"""Trace-replay serving benchmark: ``python -m tenzing_tpu.serve.replay``.

The ROADMAP's serving metric — "drive ``serve.resolve_us`` pct99 down
100x under a replayed high-QPS trace" — needs a harness before it needs
optimizations.  This module is that harness (ISSUE 11 satellite): a
**seeded synthetic query trace** (shape/workload mix over the committed
halo/spmv corpora) replayed against two resolution paths over
identically-warmed stores:

* **monolithic-legacy** — the pre-PR path, replayed exactly: the
  monolithic JSON-document store, no exact-answer cache, admission
  stamps ignored, every exact hit re-materialized and re-verified
  (``Resolver(serve_cache=False, legacy_verify=True)``);
* **segmented** — the post-PR path end to end: segmented store,
  admission-time verification, the sealed in-memory exact cache, all
  driven through the real :class:`~tenzing_tpu.serve.listen.ServeLoop`
  at a paced target QPS, so shed/timeout behavior is measured, not
  assumed.

Both paths get one uncounted warmup pass per *distinct* request shape
(graph/verifier caches hot on both sides — the comparison isolates the
per-query serving work, not one-time graph construction).  Latencies are
grouped **by resolved tier**; the headline number is the exact tier's
pct99 ratio, the acceptance criterion the ISSUE pins (≥10x with zero
per-query verifier invocations).  Results land as one JSON document
(``SERVE_BENCH_r01.json`` committed at the repo root, alongside the
``BENCH_*`` series) and one summary line on stdout.

The trace is deterministic: ``random.Random(seed)`` draws workload and
tier-class per query from the requested mix; "near" shapes sit in the
warmed shape's bucket (power-of-two bucketing, serve/fingerprint.py),
"cold" shapes in other buckets — so the trace exercises the cache, the
near tier's surrogate pricing, and the cold tier's ensure-not-rewrite
path in one stream.

**Recorded traffic** (ISSUE 13 tentpole; docs/observability.md
"Watchtower"): ``--record DIR`` turns the segmented path's listen loop
into a production recorder (serve/reqlog.py), and ``--from-recorded
DIR`` replays the *empirical* mix instead of the synthetic generator —
request kwargs verbatim from the log, tier/workload mix and the paced
QPS reconstructed from the recorded stream's inter-arrival times
(``--qps`` still overrides).  The result document then carries a
``recorded`` block (source coverage, empirical mix, QPS estimate) so a
committed ``SERVE_BENCH_r*.json`` says which traffic it measured.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.utils.numeric import percentile

REPLAY_VERSION = 5
# raw exact-tier latency series retained in the result document (replay
# order preserved): the regression gate's noise-awareness runs the
# bench/randomness.py runs test over it — and 512 points bound the
# committed SERVE_BENCH file size
EXACT_SAMPLES_CAP = 512
# the synthetic generator's default pacing.  The original 500 QPS
# assumed more headroom than the reference host sustains — the
# recorded-mix baseline (SERVE_BENCH_r03.json) measured ~300 QPS
# effective on this host, so pacing faster just manufactures queueing
# the serving path never caused.  ``--qps`` overrides; ``--from-
# recorded`` paces at the recorded stream's inter-arrival estimate.
DEFAULT_QPS = 300.0

# per-workload shape knob: (field, near value, cold values) — "exact"
# queries use the warmed default shape; "near" sits in its power-of-two
# bucket with a different exact digest (halo: n 500 vs 512 both bucket
# 512; spmv: m 200000 vs 150000 both bucket 262144 with bw in bucket
# 32768), "cold" in other buckets.  Golden-checked against
# serve/fingerprint.py's shape_bucket boundaries.
_SHAPE_KNOBS: Dict[str, Tuple[str, int, List[int]]] = {
    "halo": ("halo_n", 500, [1024, 2048]),
    "spmv": ("m", 200000, [100000, 60000]),
}


def _req_kwargs(workload: str, kind: str, i: int = 0) -> Dict[str, Any]:
    field, near, colds = _SHAPE_KNOBS[workload]
    if kind == "exact":
        return {"workload": workload}
    if kind == "near":
        return {"workload": workload, field: near}
    # a couple of distinct cold shapes per workload: exercises more than
    # one cold digest without paying a fresh graph build per query (the
    # resolver's graph cache covers them)
    return {"workload": workload, field: colds[i % len(colds)]}


def build_trace(workloads: List[str], n: int, seed: int,
                mix: Dict[str, float]) -> List[Dict[str, Any]]:
    """The deterministic query stream: ``n`` request-kwarg dicts drawn
    from the workload set and the exact/near/cold mix."""
    rng = random.Random(seed)
    kinds = sorted(mix)
    weights = [mix[k] for k in kinds]
    out = []
    for i in range(n):
        wl = rng.choice(workloads)
        kind = rng.choices(kinds, weights=weights, k=1)[0]
        out.append({"kind": kind, "request": _req_kwargs(wl, kind, i)})
    return out


def trace_from_recorded(directory: str,
                        log=None) -> Tuple[List[Dict[str, Any]],
                                           Dict[str, Any]]:
    """The query trace reconstructed from a recorded request log
    (serve/reqlog.py; module docstring): every replayable record —
    query/batch with its verbatim request kwargs — becomes one trace
    entry in arrival order, its ``kind`` the tier it resolved to (or
    the shed/timeout outcome for requests that never resolved; offered
    load is offered load).  Returns ``(trace, info)`` where ``info`` is
    the ``recorded`` provenance block: empirical tier mix, workloads,
    the inter-arrival QPS estimate, and the log's coverage/damage
    tallies."""
    from tenzing_tpu.bench.driver import DriverRequest
    from tenzing_tpu.serve.reqlog import read_request_log

    data = read_request_log(directory, log=log)
    # an EMPTY kwargs dict stays in: {"op": "query"} with no body is a
    # valid all-defaults DriverRequest, and a log dominated by
    # default-shape queries must not silently reconstruct as empty
    recs = [r for r in data["records"]
            if r.get("op") in ("query", "batch")
            and isinstance(r.get("request"), dict)]
    trace: List[Dict[str, Any]] = []
    mix_n: Dict[str, int] = {}
    outcomes: Dict[str, int] = {}
    workloads: set = set()
    unreplayable = 0
    for r in list(recs):
        try:
            # the log records kwargs verbatim, validated or not — a shed
            # or errored request never reached DriverRequest, so an
            # off-schema record must be skipped (and counted), not crash
            # the whole replay at reconstruction time
            DriverRequest(**r["request"])
        except TypeError:
            unreplayable += 1
            recs.remove(r)
            continue
        kind = r.get("tier") or r.get("outcome") or "recorded"
        trace.append({"kind": kind, "request": r["request"]})
        mix_n[kind] = mix_n.get(kind, 0) + 1
        outcomes[r.get("outcome", "?")] = \
            outcomes.get(r.get("outcome", "?"), 0) + 1
        wl = r.get("workload") or r["request"].get("workload")
        if wl:
            workloads.add(wl)
    if not trace:
        raise ValueError(f"{directory}: no replayable request records")
    if unreplayable and log is not None:
        log(f"replay: skipped {unreplayable} unreplayable record(s) "
            "(off-schema request kwargs)")
    ts = [r["ts"] for r in recs if isinstance(r.get("ts"), (int, float))]
    qps = None
    if len(ts) >= 2 and ts[-1] > ts[0]:
        # 3 decimals, not 1: a trickle recorded over an hour must not
        # round to a falsy 0.0 and silently repace at the synthetic
        # default — slow truth beats fast fiction (--qps overrides)
        qps = round((len(ts) - 1) / (ts[-1] - ts[0]), 3)
    n = len(recs)
    info = {
        "dir": directory,
        "records": n,
        "mix": {k: round(v / n, 4) for k, v in sorted(mix_n.items())},
        "outcomes": dict(sorted(outcomes.items())),
        "workloads": sorted(workloads),
        "qps_estimate": qps,
        "unreplayable": unreplayable,
        "segments": data["segments"],
        "damaged_segments": data["damaged"],
        "checksum_failed": data["checksum_failed"],
        "dropped_sampling": data["dropped_sampling"],
    }
    return trace, info


def _series(lat_by_tier: Dict[str, List[float]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for tier, xs in sorted(lat_by_tier.items()):
        if not xs:
            continue
        s = sorted(xs)
        out[tier] = {
            "count": len(s),
            "pct50_us": round(percentile(s, 50), 1),
            "pct99_us": round(percentile(s, 99), 1),
            "max_us": round(s[-1], 1),
            "mean_us": round(sum(s) / len(s), 1),
        }
    return out


def _phase_series(phase_lat: Dict[str, List[float]]) -> Dict[str, Any]:
    """Per-phase latency summary (fingerprint / cache_probe /
    store_walk / serialize — resolver + transport phase stamps): THE
    exact-tier profile the ROADMAP's tens-of-µs item optimizes against
    (docs/serving.md 'Trace-replay benchmark')."""
    out: Dict[str, Any] = {}
    for phase, xs in sorted(phase_lat.items()):
        if not xs:
            continue
        s = sorted(xs)
        out[phase] = {
            "count": len(s),
            "pct50_us": round(percentile(s, 50), 2),
            "pct99_us": round(percentile(s, 99), 2),
            "sum_us": round(sum(s), 1),
        }
    return out


def _warm_stores(workdir: str, csv_globs: Dict[str, List[str]],
                 topk: int, log) -> Dict[str, Any]:
    """Warm a monolithic and a segmented store identically from the
    given corpora; returns paths + per-workload warm summaries."""
    from tenzing_tpu.bench.driver import DriverRequest
    from tenzing_tpu.serve.service import ScheduleService

    mono_path = os.path.join(workdir, "mono.json")
    seg_path = os.path.join(workdir, "seg")
    summaries: Dict[str, Any] = {}
    # one surrogate per store (the near tier's pricing model): train it
    # from the richest corpus only — a later warm with train=True would
    # overwrite it with the last workload's model
    primary = "halo" if "halo" in csv_globs else sorted(csv_globs)[0]
    for store_path, tag in ((mono_path, "mono"), (seg_path, "seg")):
        svc = ScheduleService(store_path,
                              queue_dir=os.path.join(workdir, f"q-{tag}"),
                              tenant=f"replay-{tag}", log=log)
        for wl, globs in sorted(csv_globs.items()):
            s = svc.warm(DriverRequest(workload=wl), globs, topk=topk,
                         train=(wl == primary))
            summaries.setdefault(wl, {})[tag] = {
                "added": s["added"], "rows": s["rows"],
                "admission": s.get("admission"),
            }
    return {"mono": mono_path, "seg": seg_path, "warm": summaries}


def _replay_legacy(mono_path: str, queue_dir: str, model_path: str,
                   trace: List[Dict[str, Any]], log) -> Dict[str, Any]:
    """The pre-PR path, sequentially (process-per-query never had a
    queue to shed from): per-query materialize + verify, no cache."""
    from tenzing_tpu.bench.driver import DriverRequest
    from tenzing_tpu.serve.resolver import Resolver
    from tenzing_tpu.serve.store import ScheduleStore, WorkQueue

    store = ScheduleStore(mono_path, log=log)
    model = None
    if os.path.exists(model_path):
        from tenzing_tpu.learn import FEATURE_NAMES, RidgeEnsemble

        model = RidgeEnsemble.load(model_path,
                                   expect_features=list(FEATURE_NAMES))
    resolver = Resolver(store, queue=WorkQueue(queue_dir), model=model,
                        serve_cache=False, legacy_verify=True, log=log)
    reqs = [DriverRequest(**t["request"]) for t in trace]
    for kw in {json.dumps(t["request"], sort_keys=True)
               for t in trace}:
        resolver.resolve(DriverRequest(**json.loads(kw)))  # warmup
    fallback0 = get_metrics().counter("serve.verify_fallback").value
    lat: Dict[str, List[float]] = {}
    phases: Dict[str, List[float]] = {}
    t_start = time.perf_counter()
    for req in reqs:
        t0 = time.perf_counter()
        res = resolver.resolve(req)
        lat.setdefault(res.tier, []).append(
            (time.perf_counter() - t0) * 1e6)
        if res.tier == "exact":
            for phase, us in res.phase_us.items():
                phases.setdefault(phase, []).append(us)
    wall = time.perf_counter() - t_start
    return {
        "mode": "monolithic-legacy",
        "resolve_us": _series(lat),
        "phases_us": _phase_series(phases),
        "verifier_calls": get_metrics().counter(
            "serve.verify_fallback").value - fallback0,
        "wall_s": round(wall, 3),
        "qps_effective": round(len(reqs) / wall, 1) if wall else None,
    }


def _replay_segmented(seg_path: str, queue_dir: str,
                      trace: List[Dict[str, Any]], qps: float,
                      max_pending: int, workers: int,
                      request_timeout: float, log,
                      record_dir: Optional[str] = None,
                      busy_poll_us: float = 0.0) -> Dict[str, Any]:
    """The post-PR path through the real ServeLoop, paced at the target
    QPS — shed and timeout counts are measured behavior.  With
    ``record_dir`` the loop additionally records the replayed traffic
    (serve/reqlog.py) — the round-trip source for ``--from-recorded``."""
    from tenzing_tpu.bench.driver import DriverRequest
    from tenzing_tpu.serve.listen import ListenOpts, ServeLoop
    from tenzing_tpu.serve.service import ScheduleService

    svc = ScheduleService(seg_path, queue_dir=queue_dir,
                          tenant="replay-seg", log=log)
    for kw in {json.dumps(t["request"], sort_keys=True) for t in trace}:
        svc.query(DriverRequest(**json.loads(kw)))  # warmup
    reg = get_metrics()
    fallback0 = reg.counter("serve.verify_fallback").value
    # fast-path economics (docs/serving.md "Fast path"): deltas over
    # the replay window for the memo and fingerprint caches — the CI
    # gate asserts the memo actually served (hits > 0)
    fast0 = {name: reg.counter(name).value for name in (
        "serve.memo.hits", "serve.memo.misses",
        "serve.memo.invalidations",
        "serve.fp_cache.hits", "serve.fp_cache.misses")}
    loop = ServeLoop(svc, ListenOpts(
        max_pending=max_pending, workers=workers,
        request_timeout_secs=request_timeout,
        status_path=os.path.join(seg_path, "status-replay.json"),
        owner="replay", handle_signals=False,
        busy_poll_us=busy_poll_us,
        record_dir=record_dir), log=log)
    loop.start()
    results: List[Dict[str, Any]] = []
    lock = threading.Lock()

    def respond(doc: Dict[str, Any]) -> None:
        with lock:
            results.append(doc)

    t_start = time.perf_counter()
    for i, t in enumerate(trace):
        target = t_start + i / qps
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        loop.submit({"op": "query", "id": i, "request": t["request"]},
                    respond)
    loop.drain(timeout=max(30.0, request_timeout * 2))
    wall = time.perf_counter() - t_start
    lat: Dict[str, List[float]] = {}
    phases: Dict[str, List[float]] = {}
    exact_samples: List[float] = []
    shed = timeouts = errors = cache_hits = 0
    for doc in results:
        if doc.get("shed"):
            shed += 1
        elif doc.get("timed_out"):
            timeouts += 1
        elif not doc.get("ok"):
            errors += 1
        else:
            r = doc["result"]
            lat.setdefault(r["tier"], []).append(r["resolve_us"])
            if r["tier"] == "exact":
                # the exact tier's per-phase profile + a bounded raw
                # series (replay order) for the noise-aware regression
                # gate (obs/report.py check_regression, serve family)
                for phase, us in (r.get("phase_us") or {}).items():
                    phases.setdefault(phase, []).append(us)
                if len(exact_samples) < EXACT_SAMPLES_CAP:
                    exact_samples.append(r["resolve_us"])
            if r.get("provenance", {}).get("cache_hit"):
                cache_hits += 1
    out_reqlog = (loop.summary().get("reqlog")
                  if record_dir is not None else None)
    fast = {name: reg.counter(name).value - v0
            for name, v0 in fast0.items()}
    memo_served = fast["serve.memo.hits"] + fast["serve.memo.misses"]
    fp_probed = fast["serve.fp_cache.hits"] + fast["serve.fp_cache.misses"]
    return {
        "mode": "segmented",
        "busy_poll_us": busy_poll_us,
        **({"reqlog": out_reqlog} if out_reqlog else {}),
        "resolve_us": _series(lat),
        "phases_us": _phase_series(phases),
        "exact_samples_us": exact_samples,
        "memo": {
            "hits": fast["serve.memo.hits"],
            "misses": fast["serve.memo.misses"],
            "invalidations": fast["serve.memo.invalidations"],
            "hit_rate": (round(fast["serve.memo.hits"] / memo_served, 4)
                         if memo_served else None),
        },
        "fp_cache": {
            "hits": fast["serve.fp_cache.hits"],
            "misses": fast["serve.fp_cache.misses"],
            "hit_rate": (round(fast["serve.fp_cache.hits"] / fp_probed, 4)
                         if fp_probed else None),
        },
        "verifier_calls": reg.counter(
            "serve.verify_fallback").value - fallback0,
        "shed": shed,
        "timeouts": timeouts,
        "errors": errors,
        "exact_cache_hits": cache_hits,
        "wall_s": round(wall, 3),
        "qps_effective": round(len(trace) / wall, 1) if wall else None,
        "counters": dict(loop.counters),
    }


def run_replay(csv_globs: Dict[str, List[str]], n: int = 1200,
               qps: float = DEFAULT_QPS, seed: int = 7,
               mix: Optional[Dict[str, float]] = None, topk: int = 3,
               workdir: Optional[str] = None, keep_workdir: bool = False,
               max_pending: int = 256, workers: int = 2,
               request_timeout: float = 30.0,
               record_dir: Optional[str] = None,
               trace: Optional[List[Dict[str, Any]]] = None,
               recorded: Optional[Dict[str, Any]] = None,
               pacing: Optional[Dict[str, Any]] = None,
               fleet_scaling: Optional[Dict[str, Any]] = None,
               noise_samples: int = 64,
               busy_poll_us: float = 0.0,
               log=None) -> Dict[str, Any]:
    """The whole benchmark; returns the result document (see module
    docstring).  ``trace`` (with its ``recorded`` provenance block, from
    :func:`trace_from_recorded`) replaces the synthetic generator;
    ``record_dir`` records the segmented path's traffic;
    ``fleet_scaling`` embeds a drain-fleet scaling measurement
    (serve/fleet.py) so one SERVE_BENCH document carries both halves of
    the serving story (resolution latency + drain throughput)."""
    mix = mix or {"exact": 0.8, "near": 0.15, "cold": 0.05}
    workloads = sorted(csv_globs)
    # measure the host's latency floors BEFORE the replay warms anything:
    # the quietest read of what a scheduler wake costs here, recorded so
    # the regression gate can tell a slower host from a slower server
    # (obs/noise.py, docs/observability.md "Causal analysis")
    host_noise = None
    if noise_samples > 0:
        from tenzing_tpu.obs.noise import probe_host_noise
        host_noise = probe_host_noise(samples=noise_samples)
        if log:
            w = host_noise["timer_wake_us"]
            s = host_noise["hot_spin_us"]
            log(f"replay: host noise floors — timer-wake p50 "
                f"{w['p50_us']:.1f}us p99 {w['p99_us']:.1f}us, hot-spin "
                f"p50 {s['p50_us']:.1f}us p99 {s['p99_us']:.1f}us")
    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="tz_serve_replay.")
    try:
        stores = _warm_stores(workdir, csv_globs, topk, log)
        if trace is None:
            trace = build_trace(workloads, n, seed, mix)
        else:
            n = len(trace)
            mix = (recorded or {}).get("mix", mix)
        legacy = _replay_legacy(
            stores["mono"], os.path.join(workdir, "q-mono"),
            stores["mono"] + ".model.json", trace, log)
        seg = _replay_segmented(
            stores["seg"], os.path.join(workdir, "q-seg"), trace, qps,
            max_pending, workers, request_timeout, log,
            record_dir=record_dir, busy_poll_us=busy_poll_us)
        speedup = None
        le = legacy["resolve_us"].get("exact")
        se = seg["resolve_us"].get("exact")
        if le and se and se["pct99_us"] > 0:
            speedup = round(le["pct99_us"] / se["pct99_us"], 2)
        return {
            "kind": "serve_trace_replay",
            "version": REPLAY_VERSION,
            "n": n, "qps": qps, "seed": seed, "mix": mix,
            "workloads": workloads,
            # pacing provenance: where the paced rate came from and
            # what this host is known to sustain (the r03 measurement
            # the default is clamped to) — a committed SERVE_BENCH says
            # not just how fast it went but why it was paced that way
            "pacing": dict({"qps": qps, "default_qps": DEFAULT_QPS,
                            "sustained_note":
                                "~300 QPS measured effective on the "
                                "SERVE_BENCH_r03 host; the synthetic "
                                "default is clamped to it"},
                           **(pacing or {})),
            "warm": stores["warm"],
            **({"host_noise": host_noise} if host_noise else {}),
            **({"recorded": recorded} if recorded else {}),
            "monolithic": legacy,
            "segmented": seg,
            **({"fleet_scaling": fleet_scaling} if fleet_scaling else {}),
            "exact_pct99_speedup": speedup,
        }
    finally:
        if own_workdir and not keep_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.serve.replay",
        description="Replay a synthetic high-QPS query trace against the "
                    "legacy monolithic and the segmented serving paths "
                    "(docs/serving.md 'Trace-replay benchmark').")
    ap.add_argument("--halo-csv", nargs="*", default=None, metavar="GLOB",
                    help="halo recorded databases (default: the "
                         "committed experiments/halo_search_tpu_r[45]* "
                         "corpus)")
    ap.add_argument("--spmv-csv", nargs="*", default=None, metavar="GLOB",
                    help="spmv recorded databases (default: the "
                         "committed experiments/spmv_search_tpu.csv)")
    ap.add_argument("--n", type=int, default=1200,
                    help="queries in the trace")
    ap.add_argument("--qps", type=float, default=None,
                    help="paced submission rate for the segmented path "
                         f"(default {DEFAULT_QPS:.0f} — the rate the "
                         "r03 host sustains — or the recorded stream's "
                         "inter-arrival estimate under --from-recorded)")
    ap.add_argument("--fleet-json", default=None, metavar="PATH",
                    help="embed a drain-fleet scaling document "
                         "(python -m tenzing_tpu.serve.fleet --out) as "
                         "the result's fleet_scaling section")
    ap.add_argument("--record", default=None, metavar="DIR",
                    help="record the segmented path's replayed traffic "
                         "into this request-log directory "
                         "(serve/reqlog.py)")
    ap.add_argument("--from-recorded", dest="from_recorded", default=None,
                    metavar="DIR",
                    help="replay the empirical mix reconstructed from a "
                         "recorded request log instead of the synthetic "
                         "generator (docs/observability.md 'Watchtower')")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--mix", default="exact=0.8,near=0.15,cold=0.05",
                    help="tier-class mix, k=v comma list")
    ap.add_argument("--topk", type=int, default=3,
                    help="winners warmed per workload")
    ap.add_argument("--max-pending", type=int, default=256)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--busy-poll-us", type=float, default=0.0,
                    help="segmented-path worker busy-poll window in µs "
                         "(serve listen --busy-poll-us; 0 = blocking "
                         "waits) — recorded in the result's segmented "
                         "block")
    ap.add_argument("--request-timeout", type=float, default=30.0)
    ap.add_argument("--noise-samples", type=int, default=64,
                    help="host-noise floor probe samples stamped into "
                         "the result's host_noise block (0 disables; "
                         "obs/noise.py)")
    ap.add_argument("--workdir", default=None,
                    help="keep stores/queues here (default: temp, "
                         "removed)")
    ap.add_argument("--out", default=None,
                    help="write the result document here (e.g. "
                         "SERVE_BENCH_r01.json)")
    args = ap.parse_args(argv)
    mix: Dict[str, float] = {}
    for part in args.mix.split(","):
        k, _, v = part.partition("=")
        mix[k.strip()] = float(v)
    csv_globs: Dict[str, List[str]] = {}
    halo = (args.halo_csv if args.halo_csv is not None
            else ["experiments/halo_search_tpu_r[45]*.csv"])
    spmv = (args.spmv_csv if args.spmv_csv is not None
            else ["experiments/spmv_search_tpu.csv"])
    if halo:
        csv_globs["halo"] = halo
    if spmv:
        csv_globs["spmv"] = spmv
    def log(m):
        sys.stderr.write(m + "\n")

    trace = recorded = None
    qps = args.qps if args.qps is not None else DEFAULT_QPS
    pacing_source = "override" if args.qps is not None else "default"
    if args.from_recorded:
        try:
            trace, recorded = trace_from_recorded(args.from_recorded,
                                                  log=log)
        except (OSError, ValueError) as e:
            sys.stderr.write(f"replay: {e}\n")
            return 2
        est = recorded.get("qps_estimate")
        if args.qps is None and est is not None and est > 0:
            # pace like the recorded stream unless the operator says so
            qps = est
            pacing_source = "recorded-estimate"
        sys.stderr.write(
            f"replay: recorded trace {recorded['records']} request(s), "
            f"mix {recorded['mix']}, qps~{recorded['qps_estimate']}\n")
    fleet_scaling = None
    if args.fleet_json:
        try:
            with open(args.fleet_json) as f:
                fleet_scaling = json.load(f)
        except (OSError, ValueError) as e:
            sys.stderr.write(f"replay: unreadable --fleet-json "
                             f"{args.fleet_json} ({e})\n")
            return 2
    doc = run_replay(csv_globs, n=args.n, qps=qps, seed=args.seed,
                     mix=mix, topk=args.topk, workdir=args.workdir,
                     keep_workdir=args.workdir is not None,
                     max_pending=args.max_pending, workers=args.workers,
                     request_timeout=args.request_timeout,
                     record_dir=args.record, trace=trace,
                     recorded=recorded,
                     pacing={"source": pacing_source},
                     fleet_scaling=fleet_scaling,
                     noise_samples=args.noise_samples,
                     busy_poll_us=args.busy_poll_us, log=log)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        sys.stderr.write(f"replay: {args.out}\n")
    sys.stdout.write(json.dumps({
        "exact_pct99_speedup": doc["exact_pct99_speedup"],
        "monolithic_exact": doc["monolithic"]["resolve_us"].get("exact"),
        "segmented_exact": doc["segmented"]["resolve_us"].get("exact"),
        "segmented_verifier_calls": doc["segmented"]["verifier_calls"],
        "memo_hit_rate": doc["segmented"]["memo"]["hit_rate"],
        "fp_cache_hit_rate": doc["segmented"]["fp_cache"]["hit_rate"],
        "shed": doc["segmented"]["shed"],
        "timeouts": doc["segmented"]["timeouts"],
        **({"recorded_mix": doc["recorded"]["mix"]}
           if "recorded" in doc else {}),
        **({"reqlog": doc["segmented"]["reqlog"]}
           if "reqlog" in doc["segmented"] else {}),
    }) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
