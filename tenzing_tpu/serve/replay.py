"""Trace-replay serving benchmark: ``python -m tenzing_tpu.serve.replay``.

The ROADMAP's serving metric — "drive ``serve.resolve_us`` pct99 down
100x under a replayed high-QPS trace" — needs a harness before it needs
optimizations.  This module is that harness (ISSUE 11 satellite): a
**seeded synthetic query trace** (shape/workload mix over the committed
halo/spmv corpora) replayed against two resolution paths over
identically-warmed stores:

* **monolithic-legacy** — the pre-PR path, replayed exactly: the
  monolithic JSON-document store, no exact-answer cache, admission
  stamps ignored, every exact hit re-materialized and re-verified
  (``Resolver(serve_cache=False, legacy_verify=True)``);
* **segmented** — the post-PR path end to end: segmented store,
  admission-time verification, the sealed in-memory exact cache, all
  driven through the real :class:`~tenzing_tpu.serve.listen.ServeLoop`
  at a paced target QPS, so shed/timeout behavior is measured, not
  assumed.

Both paths get one uncounted warmup pass per *distinct* request shape
(graph/verifier caches hot on both sides — the comparison isolates the
per-query serving work, not one-time graph construction).  Latencies are
grouped **by resolved tier**; the headline number is the exact tier's
pct99 ratio, the acceptance criterion the ISSUE pins (≥10x with zero
per-query verifier invocations).  Results land as one JSON document
(``SERVE_BENCH_r01.json`` committed at the repo root, alongside the
``BENCH_*`` series) and one summary line on stdout.

The trace is deterministic: ``random.Random(seed)`` draws workload and
tier-class per query from the requested mix; "near" shapes sit in the
warmed shape's bucket (power-of-two bucketing, serve/fingerprint.py),
"cold" shapes in other buckets — so the trace exercises the cache, the
near tier's surrogate pricing, and the cold tier's ensure-not-rewrite
path in one stream.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.utils.numeric import percentile

REPLAY_VERSION = 2
# raw exact-tier latency series retained in the result document (replay
# order preserved): the regression gate's noise-awareness runs the
# bench/randomness.py runs test over it — and 512 points bound the
# committed SERVE_BENCH file size
EXACT_SAMPLES_CAP = 512

# per-workload shape knob: (field, near value, cold values) — "exact"
# queries use the warmed default shape; "near" sits in its power-of-two
# bucket with a different exact digest (halo: n 500 vs 512 both bucket
# 512; spmv: m 200000 vs 150000 both bucket 262144 with bw in bucket
# 32768), "cold" in other buckets.  Golden-checked against
# serve/fingerprint.py's shape_bucket boundaries.
_SHAPE_KNOBS: Dict[str, Tuple[str, int, List[int]]] = {
    "halo": ("halo_n", 500, [1024, 2048]),
    "spmv": ("m", 200000, [100000, 60000]),
}


def _req_kwargs(workload: str, kind: str, i: int = 0) -> Dict[str, Any]:
    field, near, colds = _SHAPE_KNOBS[workload]
    if kind == "exact":
        return {"workload": workload}
    if kind == "near":
        return {"workload": workload, field: near}
    # a couple of distinct cold shapes per workload: exercises more than
    # one cold digest without paying a fresh graph build per query (the
    # resolver's graph cache covers them)
    return {"workload": workload, field: colds[i % len(colds)]}


def build_trace(workloads: List[str], n: int, seed: int,
                mix: Dict[str, float]) -> List[Dict[str, Any]]:
    """The deterministic query stream: ``n`` request-kwarg dicts drawn
    from the workload set and the exact/near/cold mix."""
    rng = random.Random(seed)
    kinds = sorted(mix)
    weights = [mix[k] for k in kinds]
    out = []
    for i in range(n):
        wl = rng.choice(workloads)
        kind = rng.choices(kinds, weights=weights, k=1)[0]
        out.append({"kind": kind, "request": _req_kwargs(wl, kind, i)})
    return out


def _series(lat_by_tier: Dict[str, List[float]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for tier, xs in sorted(lat_by_tier.items()):
        if not xs:
            continue
        s = sorted(xs)
        out[tier] = {
            "count": len(s),
            "pct50_us": round(percentile(s, 50), 1),
            "pct99_us": round(percentile(s, 99), 1),
            "max_us": round(s[-1], 1),
            "mean_us": round(sum(s) / len(s), 1),
        }
    return out


def _phase_series(phase_lat: Dict[str, List[float]]) -> Dict[str, Any]:
    """Per-phase latency summary (fingerprint / cache_probe /
    store_walk / serialize — resolver + transport phase stamps): THE
    exact-tier profile the ROADMAP's tens-of-µs item optimizes against
    (docs/serving.md 'Trace-replay benchmark')."""
    out: Dict[str, Any] = {}
    for phase, xs in sorted(phase_lat.items()):
        if not xs:
            continue
        s = sorted(xs)
        out[phase] = {
            "count": len(s),
            "pct50_us": round(percentile(s, 50), 2),
            "pct99_us": round(percentile(s, 99), 2),
            "sum_us": round(sum(s), 1),
        }
    return out


def _warm_stores(workdir: str, csv_globs: Dict[str, List[str]],
                 topk: int, log) -> Dict[str, Any]:
    """Warm a monolithic and a segmented store identically from the
    given corpora; returns paths + per-workload warm summaries."""
    from tenzing_tpu.bench.driver import DriverRequest
    from tenzing_tpu.serve.service import ScheduleService

    mono_path = os.path.join(workdir, "mono.json")
    seg_path = os.path.join(workdir, "seg")
    summaries: Dict[str, Any] = {}
    # one surrogate per store (the near tier's pricing model): train it
    # from the richest corpus only — a later warm with train=True would
    # overwrite it with the last workload's model
    primary = "halo" if "halo" in csv_globs else sorted(csv_globs)[0]
    for store_path, tag in ((mono_path, "mono"), (seg_path, "seg")):
        svc = ScheduleService(store_path,
                              queue_dir=os.path.join(workdir, f"q-{tag}"),
                              tenant=f"replay-{tag}", log=log)
        for wl, globs in sorted(csv_globs.items()):
            s = svc.warm(DriverRequest(workload=wl), globs, topk=topk,
                         train=(wl == primary))
            summaries.setdefault(wl, {})[tag] = {
                "added": s["added"], "rows": s["rows"],
                "admission": s.get("admission"),
            }
    return {"mono": mono_path, "seg": seg_path, "warm": summaries}


def _replay_legacy(mono_path: str, queue_dir: str, model_path: str,
                   trace: List[Dict[str, Any]], log) -> Dict[str, Any]:
    """The pre-PR path, sequentially (process-per-query never had a
    queue to shed from): per-query materialize + verify, no cache."""
    from tenzing_tpu.bench.driver import DriverRequest
    from tenzing_tpu.serve.resolver import Resolver
    from tenzing_tpu.serve.store import ScheduleStore, WorkQueue

    store = ScheduleStore(mono_path, log=log)
    model = None
    if os.path.exists(model_path):
        from tenzing_tpu.learn import FEATURE_NAMES, RidgeEnsemble

        model = RidgeEnsemble.load(model_path,
                                   expect_features=list(FEATURE_NAMES))
    resolver = Resolver(store, queue=WorkQueue(queue_dir), model=model,
                        serve_cache=False, legacy_verify=True, log=log)
    reqs = [DriverRequest(**t["request"]) for t in trace]
    for kw in {json.dumps(t["request"], sort_keys=True)
               for t in trace}:
        resolver.resolve(DriverRequest(**json.loads(kw)))  # warmup
    fallback0 = get_metrics().counter("serve.verify_fallback").value
    lat: Dict[str, List[float]] = {}
    phases: Dict[str, List[float]] = {}
    t_start = time.perf_counter()
    for req in reqs:
        t0 = time.perf_counter()
        res = resolver.resolve(req)
        lat.setdefault(res.tier, []).append(
            (time.perf_counter() - t0) * 1e6)
        if res.tier == "exact":
            for phase, us in res.phase_us.items():
                phases.setdefault(phase, []).append(us)
    wall = time.perf_counter() - t_start
    return {
        "mode": "monolithic-legacy",
        "resolve_us": _series(lat),
        "phases_us": _phase_series(phases),
        "verifier_calls": get_metrics().counter(
            "serve.verify_fallback").value - fallback0,
        "wall_s": round(wall, 3),
        "qps_effective": round(len(reqs) / wall, 1) if wall else None,
    }


def _replay_segmented(seg_path: str, queue_dir: str,
                      trace: List[Dict[str, Any]], qps: float,
                      max_pending: int, workers: int,
                      request_timeout: float, log) -> Dict[str, Any]:
    """The post-PR path through the real ServeLoop, paced at the target
    QPS — shed and timeout counts are measured behavior."""
    from tenzing_tpu.bench.driver import DriverRequest
    from tenzing_tpu.serve.listen import ListenOpts, ServeLoop
    from tenzing_tpu.serve.service import ScheduleService

    svc = ScheduleService(seg_path, queue_dir=queue_dir,
                          tenant="replay-seg", log=log)
    for kw in {json.dumps(t["request"], sort_keys=True) for t in trace}:
        svc.query(DriverRequest(**json.loads(kw)))  # warmup
    fallback0 = get_metrics().counter("serve.verify_fallback").value
    loop = ServeLoop(svc, ListenOpts(
        max_pending=max_pending, workers=workers,
        request_timeout_secs=request_timeout,
        status_path=os.path.join(seg_path, "status-replay.json"),
        owner="replay", handle_signals=False), log=log)
    loop.start()
    results: List[Dict[str, Any]] = []
    lock = threading.Lock()

    def respond(doc: Dict[str, Any]) -> None:
        with lock:
            results.append(doc)

    t_start = time.perf_counter()
    for i, t in enumerate(trace):
        target = t_start + i / qps
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        loop.submit({"op": "query", "id": i, "request": t["request"]},
                    respond)
    loop.drain(timeout=max(30.0, request_timeout * 2))
    wall = time.perf_counter() - t_start
    lat: Dict[str, List[float]] = {}
    phases: Dict[str, List[float]] = {}
    exact_samples: List[float] = []
    shed = timeouts = errors = cache_hits = 0
    for doc in results:
        if doc.get("shed"):
            shed += 1
        elif doc.get("timed_out"):
            timeouts += 1
        elif not doc.get("ok"):
            errors += 1
        else:
            r = doc["result"]
            lat.setdefault(r["tier"], []).append(r["resolve_us"])
            if r["tier"] == "exact":
                # the exact tier's per-phase profile + a bounded raw
                # series (replay order) for the noise-aware regression
                # gate (obs/report.py check_regression, serve family)
                for phase, us in (r.get("phase_us") or {}).items():
                    phases.setdefault(phase, []).append(us)
                if len(exact_samples) < EXACT_SAMPLES_CAP:
                    exact_samples.append(r["resolve_us"])
            if r.get("provenance", {}).get("cache_hit"):
                cache_hits += 1
    return {
        "mode": "segmented",
        "resolve_us": _series(lat),
        "phases_us": _phase_series(phases),
        "exact_samples_us": exact_samples,
        "verifier_calls": get_metrics().counter(
            "serve.verify_fallback").value - fallback0,
        "shed": shed,
        "timeouts": timeouts,
        "errors": errors,
        "exact_cache_hits": cache_hits,
        "wall_s": round(wall, 3),
        "qps_effective": round(len(trace) / wall, 1) if wall else None,
        "counters": dict(loop.counters),
    }


def run_replay(csv_globs: Dict[str, List[str]], n: int = 1200,
               qps: float = 500.0, seed: int = 7,
               mix: Optional[Dict[str, float]] = None, topk: int = 3,
               workdir: Optional[str] = None, keep_workdir: bool = False,
               max_pending: int = 256, workers: int = 2,
               request_timeout: float = 30.0,
               log=None) -> Dict[str, Any]:
    """The whole benchmark; returns the result document (see module
    docstring)."""
    mix = mix or {"exact": 0.8, "near": 0.15, "cold": 0.05}
    workloads = sorted(csv_globs)
    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="tz_serve_replay.")
    try:
        stores = _warm_stores(workdir, csv_globs, topk, log)
        trace = build_trace(workloads, n, seed, mix)
        legacy = _replay_legacy(
            stores["mono"], os.path.join(workdir, "q-mono"),
            stores["mono"] + ".model.json", trace, log)
        seg = _replay_segmented(
            stores["seg"], os.path.join(workdir, "q-seg"), trace, qps,
            max_pending, workers, request_timeout, log)
        speedup = None
        le = legacy["resolve_us"].get("exact")
        se = seg["resolve_us"].get("exact")
        if le and se and se["pct99_us"] > 0:
            speedup = round(le["pct99_us"] / se["pct99_us"], 2)
        return {
            "kind": "serve_trace_replay",
            "version": REPLAY_VERSION,
            "n": n, "qps": qps, "seed": seed, "mix": mix,
            "workloads": workloads,
            "warm": stores["warm"],
            "monolithic": legacy,
            "segmented": seg,
            "exact_pct99_speedup": speedup,
        }
    finally:
        if own_workdir and not keep_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.serve.replay",
        description="Replay a synthetic high-QPS query trace against the "
                    "legacy monolithic and the segmented serving paths "
                    "(docs/serving.md 'Trace-replay benchmark').")
    ap.add_argument("--halo-csv", nargs="*", default=None, metavar="GLOB",
                    help="halo recorded databases (default: the "
                         "committed experiments/halo_search_tpu_r[45]* "
                         "corpus)")
    ap.add_argument("--spmv-csv", nargs="*", default=None, metavar="GLOB",
                    help="spmv recorded databases (default: the "
                         "committed experiments/spmv_search_tpu.csv)")
    ap.add_argument("--n", type=int, default=1200,
                    help="queries in the trace")
    ap.add_argument("--qps", type=float, default=500.0,
                    help="paced submission rate for the segmented path")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--mix", default="exact=0.8,near=0.15,cold=0.05",
                    help="tier-class mix, k=v comma list")
    ap.add_argument("--topk", type=int, default=3,
                    help="winners warmed per workload")
    ap.add_argument("--max-pending", type=int, default=256)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--request-timeout", type=float, default=30.0)
    ap.add_argument("--workdir", default=None,
                    help="keep stores/queues here (default: temp, "
                         "removed)")
    ap.add_argument("--out", default=None,
                    help="write the result document here (e.g. "
                         "SERVE_BENCH_r01.json)")
    args = ap.parse_args(argv)
    mix: Dict[str, float] = {}
    for part in args.mix.split(","):
        k, _, v = part.partition("=")
        mix[k.strip()] = float(v)
    csv_globs: Dict[str, List[str]] = {}
    halo = (args.halo_csv if args.halo_csv is not None
            else ["experiments/halo_search_tpu_r[45]*.csv"])
    spmv = (args.spmv_csv if args.spmv_csv is not None
            else ["experiments/spmv_search_tpu.csv"])
    if halo:
        csv_globs["halo"] = halo
    if spmv:
        csv_globs["spmv"] = spmv
    doc = run_replay(csv_globs, n=args.n, qps=args.qps, seed=args.seed,
                     mix=mix, topk=args.topk, workdir=args.workdir,
                     keep_workdir=args.workdir is not None,
                     max_pending=args.max_pending, workers=args.workers,
                     request_timeout=args.request_timeout,
                     log=lambda m: sys.stderr.write(m + "\n"))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        sys.stderr.write(f"replay: {args.out}\n")
    sys.stdout.write(json.dumps({
        "exact_pct99_speedup": doc["exact_pct99_speedup"],
        "monolithic_exact": doc["monolithic"]["resolve_us"].get("exact"),
        "segmented_exact": doc["segmented"]["resolve_us"].get("exact"),
        "segmented_verifier_calls": doc["segmented"]["verifier_calls"],
        "shed": doc["segmented"]["shed"],
        "timeouts": doc["segmented"]["timeouts"],
    }) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
