"""``python -m tenzing_tpu.serve`` — the schedule-serving CLI.

Subcommands (docs/serving.md; each prints ONE JSON line on stdout, the
same machine-readable discipline as the bench driver):

* ``warm``  — mine recorded search databases into the store (and train
  the near tier's surrogate):
  ``python -m tenzing_tpu.serve warm --store S --workload halo
  --csv 'experiments/halo_search_tpu_r[45]*.csv'``
* ``query`` — resolve one request through the exact/near/cold tiers:
  ``python -m tenzing_tpu.serve query --store S --workload halo
  --queue QDIR``
* ``merge`` — fold other stores in (commutative, lossless):
  ``python -m tenzing_tpu.serve merge --store S --from OTHER.json``
* ``stats`` — store/queue occupancy:
  ``python -m tenzing_tpu.serve stats --store S --queue QDIR``
* ``listen`` — the long-lived service loop (serve/listen.py): batched
  JSONL queries over stdin or a unix socket, bounded queue with explicit
  load-shedding, per-request watchdog, graceful SIGTERM drain,
  ``status-<owner>.json`` heartbeat.  ``python -m tenzing_tpu.serve
  --listen ...`` is accepted as a spelling of the same mode.
* ``compact`` — one offline compaction pass over a **segmented** store
  directory (serve/segments.py): merge multi-segment buckets, adopt
  orphans, reclaim — crash-consistent, lease-exclusive.
* ``backup`` / ``restore`` / ``fsck`` — disaster recovery
  (serve/dr.py, docs/robustness.md "Disaster recovery"): point-in-time
  hard-linked generations with a checksummed catalog, superset-safe
  merge-restore, and a deep read-only integrity walk whose exit code
  CI gates on (0 clean / 1 damaged / 2 unreadable).

``--store`` accepts both backends: a ``*.json`` path is the legacy
monolithic store, anything else a segmented store directory
(serve/store.py ``open_store``).

Shape flags (``--halo-n`` / ``--m`` / ``--spmv-bw`` / ``--moe-tokens`` /
``--lanes`` / ``--smoke``) mirror the bench CLI: a query is exactly a
:class:`~tenzing_tpu.bench.driver.DriverRequest`, which is also what a
cold query's work item serializes — ``bench.py`` and a queue drainer
answer the same request the same way.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from tenzing_tpu.bench.driver import DriverRequest
from tenzing_tpu.serve.service import ScheduleService


def _request_of(args) -> DriverRequest:
    return DriverRequest(
        workload=args.workload, smoke=args.smoke, halo_n=args.halo_n,
        m=args.m, spmv_bw=args.spmv_bw, moe_tokens=args.moe_tokens,
        lanes=args.lanes)


def _service_of(args) -> ScheduleService:
    return ScheduleService(
        args.store, queue_dir=args.queue, model_path=args.model,
        tenant=args.tenant, verify=not getattr(args, "no_verify", False),
        near_max_sigma=getattr(args, "near_max_sigma", 0.75),
        log=lambda m: sys.stderr.write(m + "\n"))


def _emit(doc) -> None:
    sys.stdout.write(json.dumps(doc) + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--listen" in argv:
        # the ISSUE/docs spelling `python -m tenzing_tpu.serve --listen`
        # is the listen subcommand
        argv = ["listen"] + [a for a in argv if a != "--listen"]
    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.serve",
        description="Schedule-serving store/resolver CLI (docs/serving.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--store", required=True,
                       help="store JSON path (created on first flush)")
        p.add_argument("--queue", default=None, metavar="DIR",
                       help="cold/refinement work-queue directory")
        p.add_argument("--model", default=None,
                       help="surrogate model JSON (default: "
                            "<store>.model.json)")
        p.add_argument("--tenant", default="local",
                       help="provenance tenant tag for records added "
                            "through this process")
        p.add_argument("--trace-out", default=None, metavar="PATH",
                       help="enable tracing; write this process's "
                            "telemetry JSONL bundle here (stitch fleet "
                            "bundles with python -m tenzing_tpu.obs."
                            "export)")

    def request_flags(p):
        p.add_argument("--workload",
                       choices=("halo", "spmv", "attn", "moe"),
                       default="halo")
        p.add_argument("--smoke", action="store_true",
                       help="the tiny CPU config's fingerprint")
        p.add_argument("--halo-n", type=int, default=512)
        p.add_argument("--m", type=int, default=None)
        p.add_argument("--spmv-bw", type=int, default=None)
        p.add_argument("--moe-tokens", type=int, default=8192)
        p.add_argument("--lanes", type=int, default=None)

    pw = sub.add_parser("warm", help="mine recorded corpora into the store")
    common(pw)
    request_flags(pw)
    pw.add_argument("--csv", nargs="+", required=True, metavar="GLOB",
                    help="recorded search databases (bench.py --dump-csv)")
    pw.add_argument("--bench", nargs="*", default=None, metavar="GLOB",
                    help="driver JSON verdicts to stamp as provenance")
    pw.add_argument("--topk", type=int, default=3,
                    help="distinct winners to store per warm")
    pw.add_argument("--no-train", action="store_true",
                    help="skip training the near-tier surrogate")

    pq = sub.add_parser("query", help="resolve one request")
    common(pq)
    request_flags(pq)
    pq.add_argument("--no-verify", action="store_true",
                    help="skip exact-hit re-verification (not "
                         "recommended; docs/serving.md)")
    pq.add_argument("--near-max-sigma", type=float, default=0.75,
                    help="near-miss uncertainty gate (log-space ensemble "
                         "spread ceiling)")

    pm = sub.add_parser("merge", help="merge other stores into --store")
    common(pm)
    pm.add_argument("--from", dest="from_stores", nargs="+", required=True,
                    metavar="STORE", help="store files to fold in")

    ps = sub.add_parser("stats", help="store/queue occupancy")
    common(ps)

    pl = sub.add_parser("listen",
                        help="long-lived service loop (docs/serving.md "
                             "'Listen mode')")
    common(pl)
    pl.add_argument("--socket", default=None, metavar="PATH",
                    help="serve a unix domain socket instead of "
                         "stdin/stdout JSONL")
    pl.add_argument("--max-pending", type=int, default=64,
                    help="bounded request queue; beyond this, shed with "
                         "retry_after")
    pl.add_argument("--workers", type=int, default=2,
                    help="resolution worker threads")
    pl.add_argument("--request-timeout", type=float, default=10.0,
                    metavar="SECS",
                    help="per-request watchdog (0 disables)")
    pl.add_argument("--tenant-max-pending", type=int, default=None,
                    help="per-tenant in-flight cap: an over-cap tenant "
                         "is shed with reason tenant_cap before the "
                         "global bound fills (default max-pending/2; "
                         "0 disables)")
    pl.add_argument("--shed-retry-after", type=float, default=0.5,
                    metavar="SECS",
                    help="retry_after hint carried by shed responses")
    pl.add_argument("--busy-poll-us", type=float, default=0.0,
                    metavar="US",
                    help="worker busy-poll window: spin this many µs "
                         "for the next request before blocking — buys "
                         "back the OS wake floor on the exact-tier "
                         "tail at the cost of an idle-spinning core "
                         "(0 = blocking waits)")
    pl.add_argument("--heartbeat", type=float, default=2.0, metavar="SECS",
                    help="status-document rewrite interval")
    pl.add_argument("--idle-exit", type=float, default=None, metavar="SECS",
                    help="socket mode: exit after this much silence (CI)")
    pl.add_argument("--owner", default=None,
                    help="worker id for the status doc (default host-pid)")
    pl.add_argument("--status", default=None, metavar="PATH",
                    help="status JSON path (default "
                         "status-<owner>.json next to the store)")
    pl.add_argument("--no-verify", action="store_true",
                    help="skip lazy re-verification of unstamped records")
    pl.add_argument("--near-max-sigma", type=float, default=0.75,
                    help="near-miss uncertainty gate")
    pl.add_argument("--slo-target-us", type=float, default=None,
                    help="exact-tier pct99 objective for the SLO block "
                         "in metric snapshots (docs/observability.md)")
    pl.add_argument("--slo-baseline", default=None, metavar="PATH",
                    help="committed SERVE_BENCH_r*.json anchoring the "
                         "SLO burn direction")
    pl.add_argument("--metrics-ring", type=int, default=8,
                    help="metric-snapshot files kept per owner")
    pl.add_argument("--record", default=None, metavar="DIR",
                    help="record admitted traffic into this request-log "
                         "directory (serve/reqlog.py; replay it with "
                         "python -m tenzing_tpu.serve.replay "
                         "--from-recorded DIR)")
    pl.add_argument("--record-sample", type=float, default=1.0,
                    help="request-log sampling rate (deterministic per "
                         "trace_id; dropped requests are counted)")
    pl.add_argument("--record-retain", type=int, default=16,
                    help="sealed request-log segments kept (rotation)")
    pl.add_argument("--exemplar-k", type=int, default=4,
                    help="slowest-K span bundles kept per heartbeat "
                         "window (shed/timeout/error always kept)")
    pl.add_argument("--exemplar-cap", type=int, default=64,
                    help="exemplar bundles kept before oldest-first "
                         "eviction")

    pc = sub.add_parser("compact",
                        help="one offline compaction pass over a "
                             "segmented store directory")
    pc.add_argument("--store", required=True,
                    help="segmented store directory (serve/segments.py)")
    pc.add_argument("--owner", default=None,
                    help="compactor id for the lease (default host-pid)")
    pc.add_argument("--min-segments", type=int, default=2,
                    help="segments per bucket before a merge-rewrite")
    pc.add_argument("--lease-ttl", type=float, default=60.0, metavar="SECS",
                    help="compaction lease TTL (expired leases reclaim)")
    pc.add_argument("--grace", type=float, default=60.0, metavar="SECS",
                    help="age before stale temp droppings are collected")
    # chaos hook for the crash-consistency tests/CI: SIGKILL this process
    # at a chosen publish boundary — not for operators
    pc.add_argument("--crash-after", choices=("segment", "manifest"),
                    default=None, help=argparse.SUPPRESS)

    pb = sub.add_parser("backup",
                        help="one point-in-time backup generation "
                             "(docs/robustness.md 'Disaster recovery')")
    pb.add_argument("--store", required=True,
                    help="store path (segmented directory or *.json)")
    pb.add_argument("--out", default=None, metavar="DIR",
                    help="generations root (default <store>/backups)")
    pb.add_argument("--note", default="",
                    help="free-form tag stamped into the catalog")

    pr = sub.add_parser("restore",
                        help="catalog-verified point-in-time restore "
                             "(verbatim into an empty store, "
                             "superset-safe merge into a live one)")
    pr.add_argument("--store", required=True)
    pr.add_argument("--from", dest="generation", default=None,
                    metavar="GEN",
                    help="generation directory (default: the latest "
                         "under <store>/backups)")
    pr.add_argument("--out", default=None, metavar="DIR",
                    help="generations root searched when --from is "
                         "omitted")
    pr.add_argument("--force", action="store_true",
                    help="restore the intact files of a generation "
                         "that fails catalog verification")

    pf = sub.add_parser("fsck",
                        help="deep read-only integrity walk; exit 0 "
                             "clean / 1 damaged / 2 unreadable")
    pf.add_argument("--store", required=True)
    pf.add_argument("--adopt", action="store_true",
                    help="index orphan segments into the manifest "
                         "(the only write fsck can do)")
    pf.add_argument("--stamp", action="store_true",
                    help="record the verdict to <store>/fsck.json for "
                         "report --follow")
    pf.add_argument("--no-backups", action="store_true",
                    help="skip the backup-generation census")

    args = ap.parse_args(argv)
    trace_out = getattr(args, "trace_out", None)
    if trace_out:
        from tenzing_tpu import obs

        obs.configure(enabled=True)
    if args.cmd in ("backup", "restore", "fsck"):
        from tenzing_tpu.serve import dr

        log = lambda m: sys.stderr.write(m + "\n")  # noqa: E731
        try:
            if args.cmd == "backup":
                _emit(dr.backup_store(args.store, out_dir=args.out,
                                      note=args.note, log=log))
                return 0
            if args.cmd == "restore":
                gen = args.generation or dr.latest_generation(
                    args.out or dr.backups_root(args.store))
                if gen is None:
                    raise dr.DrError(
                        f"no backup generations found for {args.store}")
                _emit(dr.restore_store(args.store, gen,
                                       force=args.force, log=log))
                return 0
            doc = dr.fsck_store(args.store, adopt=args.adopt,
                                stamp=args.stamp,
                                check_backups=not args.no_backups,
                                log=log)
            _emit(doc)
            return doc["rc"]
        except dr.DrError as e:
            sys.stderr.write(f"serve {args.cmd}: {e}\n")
            return 2
    if args.cmd == "compact":
        from tenzing_tpu.serve.segments import Compactor

        _emit(Compactor(args.store, owner=args.owner or "",
                        min_segments=args.min_segments,
                        lease_ttl_secs=args.lease_ttl,
                        grace_secs=args.grace,
                        log=lambda m: sys.stderr.write(m + "\n"),
                        crash_after=args.crash_after).run())
        return 0
    svc = _service_of(args)
    if args.cmd == "warm":
        _emit(svc.warm(_request_of(args), args.csv,
                       bench_globs=args.bench, topk=args.topk,
                       train=not args.no_train))
    elif args.cmd == "query":
        _emit(svc.query(_request_of(args)).to_json())
    elif args.cmd == "merge":
        out = [svc.merge(p) for p in args.from_stores]
        _emit({"merged": out, "records": len(svc.store)})
    elif args.cmd == "stats":
        _emit(svc.stats())
    elif args.cmd == "listen":
        from tenzing_tpu.serve.listen import ListenOpts, ServeLoop

        opts = ListenOpts(
            max_pending=args.max_pending, workers=args.workers,
            tenant_max_pending=args.tenant_max_pending,
            request_timeout_secs=args.request_timeout or 0.0,
            shed_retry_after_secs=args.shed_retry_after,
            busy_poll_us=args.busy_poll_us,
            heartbeat_secs=args.heartbeat,
            idle_exit_secs=args.idle_exit, owner=args.owner or "",
            status_path=args.status, socket_path=args.socket,
            slo_target_us=args.slo_target_us,
            slo_baseline=args.slo_baseline,
            metrics_ring=args.metrics_ring, trace_out=trace_out,
            record_dir=args.record, record_sample=args.record_sample,
            record_retain=args.record_retain,
            exemplar_k=args.exemplar_k, exemplar_cap=args.exemplar_cap)
        loop = ServeLoop(svc, opts,
                         log=lambda m: sys.stderr.write(m + "\n"))
        if args.socket:
            _emit(loop.serve_socket(args.socket))
        else:
            _emit(loop.serve_stdin())
        return 0
    if trace_out:
        # one-shot subcommands archive their bundle after the verdict
        # line (the listen loop writes its own on drain)
        from tenzing_tpu import obs

        obs.write_jsonl(obs.get_tracer(), trace_out)
        sys.stderr.write(f"trace bundle: {trace_out}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
