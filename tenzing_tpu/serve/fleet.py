"""Horizontal drain fleet: N daemons work-stealing one WorkQueue.

The drain daemon (serve/daemon.py) was built so that rivals are safe by
construction: leased claims admit exactly one winner per item, expired
leases reclaim atomically, and the store merge is commutative and
flock-serialized.  That means scaling drain throughput horizontally is
*zero daemon changes* — just run N of them against one queue directory
and let the lease protocol arbitrate.  This module is the launcher and
the measurement harness that proves it (docs/serving.md "Drain fleet"):

* **launch** — spawn N daemon subprocesses (``python -m
  tenzing_tpu.serve.daemon``) on one queue/store, each with its own
  ``--owner`` (``<prefix>-<k>``) and optional ``--trace-out`` bundle,
  wait for all of them (``--idle-exit`` ends a drained fleet), and
  collect each daemon's one-line JSON summary.
* **double-run audit** — the exactly-once contract, checked from the
  evidence the daemons already publish: every ``status-<owner>.json``
  history entry with outcome ``completed`` maps its item's exact digest
  to the completing owner; an item completed more than once across the
  fleet is a ``double_runs`` entry.  (The audit window is each daemon's
  bounded status history — complete for smoke-sized queues, a sampled
  audit beyond it; ``audit_complete`` says which.)
* **drain-rate scaling** — :func:`measure_scaling` replays the SAME
  work items against fleets of growing N (each rung gets a fresh queue
  copy and a fresh store, so rungs are independent), and reports
  items/second per rung plus the speedup over the single-daemon rung —
  the ``fleet_scaling`` section a SERVE_BENCH document embeds
  (``serve/replay.py --fleet-json``).
* **stitched traces** — with ``--trace-dir`` every daemon writes its
  telemetry bundle and asks its drain children to archive theirs under
  each item's ``ckpt-<exact>/trace/``; the harness stitches all of them
  (obs/export.py) and reports, per work item that carried a trace
  context, whether its ``trace_id`` spans a ``daemon.drain`` — the
  PR-12 cross-process linkage, now across a whole fleet.

Run it::

    python -m tenzing_tpu.serve.fleet --queue QDIR --store STORE \
        --n 2 --idle-exit 3 [--override mcts_iters=6 ...]

or measure scaling (treats --queue as a read-only item template,
fresh queue copy + store per rung)::

    python -m tenzing_tpu.serve.fleet --queue QDIR \
        --scale 1,2 --workdir WDIR --out fleet.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tenzing_tpu.serve.store import WorkQueue

FLEET_VERSION = 1


@dataclass
class FleetOpts:
    """Knobs of one fleet launch (CLI flags map 1:1; the daemon knobs
    pass straight through to every member)."""

    queue_dir: str
    store_path: str
    n: int = 2
    owner_prefix: str = "fleet"
    idle_exit_secs: float = 3.0       # a drained fleet exits by itself
    poll_secs: float = 0.25
    lease_ttl_secs: float = 60.0
    heartbeat_secs: float = 1.0
    item_timeout_secs: Optional[float] = 3600.0
    topk: int = 3
    overrides: Dict[str, Any] = field(default_factory=dict)
    trace_dir: Optional[str] = None   # per-daemon bundles + stitch here
    wait_timeout_secs: float = 1800.0


def _daemon_cmd(opts: FleetOpts, k: int) -> List[str]:
    """The member daemon's argv — one place, so the subprocess launcher
    and anyone reproducing a member by hand agree."""
    cmd = [sys.executable, "-m", "tenzing_tpu.serve.daemon",
           "--queue", opts.queue_dir, "--store", opts.store_path,
           "--owner", f"{opts.owner_prefix}-{k}",
           "--idle-exit", str(opts.idle_exit_secs),
           "--poll", str(opts.poll_secs),
           "--lease-ttl", str(opts.lease_ttl_secs),
           "--heartbeat", str(opts.heartbeat_secs),
           "--topk", str(opts.topk)]
    if opts.item_timeout_secs is not None:
        # 0 passes through: the daemon documents "0 disables" — mapping
        # it to flag-omission would silently reinstate the 3600s default
        cmd += ["--item-timeout", str(opts.item_timeout_secs)]
    for key, v in opts.overrides.items():
        cmd += ["--override", f"{key}={json.dumps(v)}"]
    if opts.trace_dir:
        cmd += ["--trace-out",
                os.path.join(opts.trace_dir, f"daemon-{k}.jsonl")]
    return cmd


class _ProcHandle:
    """One spawned member: ``wait()`` returns its summary dict (the
    daemon's one JSON stdout line), with ``rc`` and a truncated stderr
    tail on failure so a dead member is evidence, not a mystery.

    The pipes are pumped from a background thread STARTING AT SPAWN —
    ``wait()`` is called on the members one at a time, and a member
    whose unread stderr filled the 64 KiB pipe buffer mid-drain would
    otherwise block in ``write()`` until its turn, age its lease past
    the TTL, and hand its item to a rival: a harness-made double-run
    on exactly the property the harness exists to prove."""

    def __init__(self, owner: str, proc: subprocess.Popen):
        self.owner = owner
        self.proc = proc
        self._out: Optional[str] = None
        self._err: Optional[str] = None

        def pump():
            self._out, self._err = proc.communicate()

        self._pump = threading.Thread(target=pump, daemon=True,
                                      name=f"fleet-pump-{owner}")
        self._pump.start()

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        self._pump.join(timeout=timeout)
        if self._pump.is_alive():
            self.proc.kill()
            self._pump.join(timeout=10)
            return {"owner": self.owner, "rc": -9,
                    "error": "fleet wait timeout — member killed",
                    "stderr": (self._err or "")[-2000:]}
        doc: Dict[str, Any] = {"owner": self.owner,
                               "rc": self.proc.returncode}
        for line in reversed((self._out or "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc.update(json.loads(line))
                    break
                except ValueError:
                    continue
        if self.proc.returncode != 0:
            doc.setdefault("stderr", (self._err or "")[-2000:])
        return doc


def _subprocess_spawn(opts: FleetOpts, k: int) -> _ProcHandle:
    if opts.trace_dir:
        os.makedirs(opts.trace_dir, exist_ok=True)
    return _ProcHandle(
        f"{opts.owner_prefix}-{k}",
        subprocess.Popen(_daemon_cmd(opts, k), stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True))


def stub_spawner(drain_secs: float) -> Callable:
    """A spawner whose members are real in-process :class:`DrainDaemon`
    threads with a fixed-cost stub drain (``time.sleep``) — the whole
    lease/claim/status/merge protocol runs for real, only the search is
    replaced by a constant.  This measures what the FLEET layer adds:
    drains dominated by device/tunnel wait (the TPU regime) scale like
    this curve, while compute-bound CPU drains on a small host saturate
    the cores instead (``--stub-drain-secs`` documents which was
    measured — a stub curve must never masquerade as a real-drain
    measurement)."""
    from tenzing_tpu.serve.daemon import DaemonOpts, DrainDaemon

    def runner(item_path, payload, timeout):
        time.sleep(drain_secs)
        return {"metric": "stub", "value": 1.0, "unit": "us"}

    class _ThreadHandle:
        def __init__(self, daemon):
            self.summary: Optional[Dict[str, Any]] = None

            def go():
                self.summary = daemon.run()

            self.thread = threading.Thread(target=go, daemon=True)
            self.thread.start()

        def wait(self, timeout=None):
            self.thread.join(timeout=timeout)
            if self.summary is None:
                return {"rc": -1, "error": "member never finished"}
            return dict(self.summary, rc=0)

    def spawn(opts: FleetOpts, k: int):
        d = DrainDaemon(DaemonOpts(
            queue_dir=opts.queue_dir, store_path=opts.store_path,
            owner=f"{opts.owner_prefix}-{k}", handle_signals=False,
            in_process=True, idle_exit_secs=opts.idle_exit_secs,
            poll_secs=opts.poll_secs,
            lease_ttl_secs=opts.lease_ttl_secs,
            heartbeat_secs=opts.heartbeat_secs,
            backoff_base_secs=0.01),
            runner=runner, log=lambda m: None)
        return _ThreadHandle(d)

    return spawn


def audit_completions(queue_dir: str,
                      owners: List[str]) -> Dict[str, Any]:
    """The exactly-once audit over the fleet's status documents: which
    owner completed which exact digest, and any digest completed more
    than once (``double_runs``).  ``audit_complete`` is False when any
    member's history hit its bounded-doc window (the audit is then a
    sample, not a proof — still worth printing)."""
    completed_by: Dict[str, List[str]] = {}
    complete = True
    for owner in owners:
        path = os.path.join(queue_dir, f"status-{owner}.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            complete = False
            continue
        history = doc.get("history", [])
        if doc.get("counters", {}).get("completed", 0) > len(
                [h for h in history if h.get("outcome") == "completed"]):
            complete = False  # history window smaller than completions
        for h in history:
            if h.get("outcome") == "completed":
                completed_by.setdefault(h.get("exact", "?"),
                                        []).append(owner)
    double = {exact: owners_ for exact, owners_ in completed_by.items()
              if len(owners_) > 1}
    return {"completed_by": {k: sorted(v)
                             for k, v in sorted(completed_by.items())},
            "double_runs": dict(sorted(double.items())),
            "audit_complete": complete}


def _item_traces(queue: WorkQueue) -> Dict[str, Optional[str]]:
    """exact digest -> the trace_id its envelope carries (None when the
    enqueuer had no ambient context)."""
    out: Dict[str, Optional[str]] = {}
    for path, payload in queue.items():
        out[WorkQueue.exact_of(path)] = (
            payload.get("trace") or {}).get("trace_id")
    return out


def _stitch_fleet(opts: FleetOpts,
                  item_traces: Dict[str, Optional[str]],
                  log: Callable[[str], None]) -> Optional[Dict[str, Any]]:
    """Stitch every member bundle + every drain child's archived bundle
    into one Perfetto file; report per-item whether its trace_id made it
    through a ``daemon.drain`` span — the stitched-trace-per-item check
    the fleet smoke gates on."""
    import glob as _glob

    from tenzing_tpu.obs.export import stitch

    paths = sorted(
        _glob.glob(os.path.join(opts.trace_dir, "daemon-*.jsonl")))
    paths += sorted(_glob.glob(
        os.path.join(opts.queue_dir, "ckpt-*", "trace", "trace.jsonl")))
    if not paths:
        return None
    out_path = os.path.join(opts.trace_dir, "fleet.json")
    try:
        summary = stitch(paths, out_path=out_path)
    except (OSError, ValueError) as e:
        log(f"fleet: stitch failed ({e})")
        return None
    traces = summary.get("traces", {})
    items = {}
    for exact, tid in item_traces.items():
        if tid is None:
            items[exact] = {"trace_id": None, "stitched": None}
            continue
        t = traces.get(tid) or {}
        items[exact] = {
            "trace_id": tid,
            "stitched": "daemon.drain" in (t.get("names") or []),
            "n_processes": t.get("n_processes"),
        }
    return {"out": out_path, "bundles": len(paths), "items": items}


def run_fleet(opts: FleetOpts,
              spawn: Optional[Callable[[FleetOpts, int], Any]] = None,
              log: Optional[Callable[[str], None]] = None,
              drain_label: str = "real") -> Dict[str, Any]:
    """Launch N members on one queue, wait, audit, measure (module
    docstring).  ``spawn(opts, k)`` is injectable for tests (anything
    with a ``wait() -> summary dict``); the default spawns real daemon
    subprocesses."""
    log = log or (lambda m: sys.stderr.write(m + "\n"))
    spawn = spawn or _subprocess_spawn
    queue = WorkQueue(opts.queue_dir)
    item_traces = _item_traces(queue)
    depth_before = len(item_traces)
    owners = [f"{opts.owner_prefix}-{k}" for k in range(opts.n)]
    log(f"fleet: launching {opts.n} daemon(s) on {opts.queue_dir} "
        f"({depth_before} item(s))")
    t0 = time.time()
    handles = [spawn(opts, k) for k in range(opts.n)]
    # one SHARED deadline: members run concurrently, so waiting them in
    # turn must not grant each a fresh full timeout (n hung members
    # would otherwise block n * wait_timeout before the fleet reports)
    deadline = t0 + opts.wait_timeout_secs
    summaries = [h.wait(timeout=max(1.0, deadline - time.time()))
                 for h in handles]
    wall = time.time() - t0
    drained = sum(s.get("counters", {}).get("completed", 0)
                  for s in summaries)
    audit = audit_completions(opts.queue_dir, owners)
    doc: Dict[str, Any] = {
        "kind": "drain_fleet",
        "version": FLEET_VERSION,
        # what kind of drain was measured: "real" (driver searches) or
        # "stub:<secs>" (fixed-cost protocol measurement, stub_spawner)
        "drain": drain_label,
        "n_daemons": opts.n,
        "items_before": depth_before,
        "drained": drained,
        "queue_after": len(queue),
        "wall_s": round(wall, 3),
        "drain_rate_per_s": round(drained / wall, 4) if wall else None,
        "double_runs": audit["double_runs"],
        "completed_by": audit["completed_by"],
        "audit_complete": audit["audit_complete"],
        "daemons": [{
            "owner": s.get("owner"),
            "rc": s.get("rc", 0),
            "drained": s.get("drained"),
            "counters": s.get("counters"),
            **({"error": s["error"]} if "error" in s else {}),
        } for s in summaries],
    }
    if opts.trace_dir:
        stitched = _stitch_fleet(opts, item_traces, log)
        if stitched is not None:
            doc["stitched"] = stitched
    if audit["double_runs"]:
        log(f"fleet: DOUBLE RUNS detected: {audit['double_runs']}")
    log(f"fleet: drained {drained}/{depth_before} in {wall:.1f}s "
        f"({doc['drain_rate_per_s']}/s) across {opts.n} daemon(s)")
    return doc


def copy_queue_items(src_queue: str, dst_queue: str) -> int:
    """Copy the work items (and ONLY the items — no leases, failure
    sidecars, checkpoints, or status docs) of one queue into a fresh
    directory: the per-rung reset :func:`measure_scaling` needs so every
    rung drains identical, untouched work."""
    os.makedirs(dst_queue, exist_ok=True)
    n = 0
    for name in sorted(os.listdir(src_queue)):
        if name.startswith("work-") and name.endswith(".json"):
            shutil.copy2(os.path.join(src_queue, name),
                         os.path.join(dst_queue, name))
            n += 1
    return n


def measure_scaling(opts: FleetOpts, ns: List[int], workdir: str,
                    log: Optional[Callable[[str], None]] = None,
                    spawn: Optional[Callable] = None,
                    drain_label: str = "real") -> Dict[str, Any]:
    """Drain-rate scaling vs fleet size: for each N in ``ns``, copy the
    source queue's items into a fresh queue, point the fleet at a fresh
    store, run it, and record the rate.  The speedup of each rung over
    the N=1 rung is the scaling curve; the lease protocol's overhead is
    whatever keeps it below N."""
    log = log or (lambda m: sys.stderr.write(m + "\n"))
    rungs: List[Dict[str, Any]] = []
    for n in ns:
        qdir = os.path.join(workdir, f"q-n{n}")
        copied = copy_queue_items(opts.queue_dir, qdir)
        rung_opts = FleetOpts(
            **{**opts.__dict__,
               "queue_dir": qdir,
               "store_path": os.path.join(workdir, f"store-n{n}"),
               "n": n,
               "owner_prefix": f"{opts.owner_prefix}-n{n}",
               "trace_dir": (os.path.join(opts.trace_dir, f"n{n}")
                             if opts.trace_dir else None)})
        log(f"fleet: scaling rung n={n} ({copied} item(s))")
        rungs.append(run_fleet(rung_opts, spawn=spawn, log=log,
                               drain_label=drain_label))
    base = next((r for r in rungs if r["n_daemons"] == 1), None)
    base_rate = (base or {}).get("drain_rate_per_s")
    for r in rungs:
        rate = r.get("drain_rate_per_s")
        r["speedup_vs_n1"] = (round(rate / base_rate, 3)
                              if rate and base_rate else None)
    return {
        "kind": "drain_fleet_scaling",
        "version": FLEET_VERSION,
        "drain": drain_label,
        "ns": list(ns),
        "rungs": rungs,
        "double_runs_total": sum(len(r["double_runs"]) for r in rungs),
    }


def fleet_exit_code(doc: Dict[str, Any]) -> int:
    """The CLI's verdict: nonzero on a double run (the exactly-once
    contract) OR on any member that died with a nonzero rc — a
    half-dead fleet must not report success to the cron/script gating
    on it.  Undrained items are data, not failure (a transient-failing
    item legitimately stays queued for a later pass — it is visible in
    ``queue_after`` and the member counters)."""
    if doc.get("kind") == "drain_fleet_scaling":
        if doc.get("double_runs_total"):
            return 1
        members = [d for r in doc.get("rungs", [])
                   for d in r.get("daemons", [])]
    else:
        if doc.get("double_runs"):
            return 1
        members = doc.get("daemons", [])
    return 1 if any(d.get("rc") not in (0, None) for d in members) else 0


def main(argv: Optional[List[str]] = None) -> int:
    from tenzing_tpu.serve.daemon import parse_override

    ap = argparse.ArgumentParser(
        prog="python -m tenzing_tpu.serve.fleet",
        description="Launch N drain daemons work-stealing one queue, "
                    "audit exactly-once completion, measure drain-rate "
                    "scaling (docs/serving.md 'Drain fleet').")
    ap.add_argument("--queue", required=True, metavar="DIR",
                    help="work-queue directory (the scaling mode treats "
                         "it as a read-only item template)")
    ap.add_argument("--store", metavar="PATH",
                    help="schedule store to re-warm (required unless "
                         "--scale, which uses per-rung stores)")
    ap.add_argument("--n", type=int, default=2,
                    help="fleet size (ignored under --scale)")
    ap.add_argument("--scale", default=None, metavar="N1,N2,...",
                    help="measure drain-rate scaling across these fleet "
                         "sizes (fresh queue copy + store per rung)")
    ap.add_argument("--workdir", default=None, metavar="DIR",
                    help="scaling mode: where per-rung queues/stores "
                         "live (required with --scale)")
    ap.add_argument("--owner-prefix", default="fleet")
    ap.add_argument("--idle-exit", type=float, default=3.0, metavar="SECS")
    ap.add_argument("--poll", type=float, default=0.25, metavar="SECS")
    ap.add_argument("--lease-ttl", type=float, default=60.0,
                    metavar="SECS")
    ap.add_argument("--heartbeat", type=float, default=1.0, metavar="SECS")
    ap.add_argument("--item-timeout", type=float, default=3600.0,
                    metavar="SECS")
    ap.add_argument("--topk", type=int, default=3)
    ap.add_argument("--override", action="append", default=[],
                    metavar="K=V",
                    help="request-budget override for every member "
                         "(serve/daemon.py semantics)")
    ap.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="per-daemon telemetry bundles + the stitched "
                         "fleet trace land here")
    ap.add_argument("--stub-drain-secs", type=float, default=None,
                    metavar="SECS",
                    help="replace the real drain with a fixed-cost "
                         "sleep (in-process members, full lease "
                         "protocol): measures the fleet layer itself — "
                         "the device-wait-dominated regime — and marks "
                         "the result 'drain: stub:<secs>'")
    ap.add_argument("--wait-timeout", type=float, default=1800.0,
                    metavar="SECS")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the result document here (embeddable "
                         "via serve/replay.py --fleet-json)")
    args = ap.parse_args(argv)
    try:
        overrides = dict(parse_override(s) for s in args.override)
    except ValueError as e:
        ap.error(str(e))
    if args.scale and not args.workdir:
        ap.error("--scale requires --workdir")
    if not args.scale and not args.store:
        ap.error("--store is required (unless --scale)")
    if args.stub_drain_secs is not None and args.trace_dir:
        # stub members are threads sharing ONE process tracer: per-member
        # bundles would all dump the same records, and no drain children
        # exist — a silent empty stitch would misread as a stitch bug
        ap.error("--trace-dir requires real subprocess members "
                 "(omit --stub-drain-secs)")
    opts = FleetOpts(
        queue_dir=args.queue, store_path=args.store or "",
        n=args.n, owner_prefix=args.owner_prefix,
        idle_exit_secs=args.idle_exit, poll_secs=args.poll,
        lease_ttl_secs=args.lease_ttl, heartbeat_secs=args.heartbeat,
        item_timeout_secs=args.item_timeout, topk=args.topk,
        overrides=overrides, trace_dir=args.trace_dir,
        wait_timeout_secs=args.wait_timeout)
    spawn = None
    drain_label = "real"
    if args.stub_drain_secs is not None:
        spawn = stub_spawner(args.stub_drain_secs)
        drain_label = f"stub:{args.stub_drain_secs}s"
    if args.scale:
        ns = [int(x) for x in args.scale.split(",") if x.strip()]
        doc = measure_scaling(opts, ns, args.workdir, spawn=spawn,
                              drain_label=drain_label)
    else:
        doc = run_fleet(opts, spawn=spawn, drain_label=drain_label)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
    sys.stdout.write(json.dumps(doc) + "\n")
    return fleet_exit_code(doc)


if __name__ == "__main__":
    sys.exit(main())
