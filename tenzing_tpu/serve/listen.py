"""The long-lived serving loop: ``python -m tenzing_tpu.serve listen``.

Process-per-query was fine for smoke tests; a fleet front door is a
process that stays up.  This module wraps one
:class:`~tenzing_tpu.serve.service.ScheduleService` in a bounded,
load-shedding request loop (docs/serving.md "Listen mode"):

* **Transports** — newline-delimited JSON over **stdin/stdout** (the
  default: trivially driveable from a shell, a pipe, or a supervisor) or
  a **unix domain socket** (``--socket PATH``; any number of concurrent
  connections, one reader thread each, responses interleaved per
  connection under a write lock).  One request per line, one response
  line per request, matched by the client-chosen ``id``.
* **Protocol** — ``{"op": "query", "id": ..., "request": {DriverRequest
  fields}}`` resolves one request; ``{"op": "batch", "requests": [...]}``
  resolves many in one trip (one queue slot, one response line — the
  batched API that amortizes transport overhead at fleet rates);
  ``stats`` and ``ping`` round out liveness probing.
* **Bounded queue + explicit shedding** — at most ``--max-pending``
  requests wait; beyond that the loop answers **immediately** with
  ``{"shed": true, "retry_after": <secs>}`` and counts ``serve.shed``
  — a server that cannot keep up says so in microseconds instead of
  letting every client time out in line (the same honesty rule as the
  near tier's uncertainty gate: a non-answer now beats a bad answer
  later).
* **Per-tenant fair admission** — ``--tenant-max-pending`` (default
  half the global bound, 0 disables) caps each tenant's own in-flight
  count: an over-cap tenant sheds with ``{"shed": true, "reason":
  "tenant_cap"}`` *before* its burst can fill the global bound and
  starve everyone else; the per-tenant ``serve.shed.<tenant>``
  counters are the fairness measurement.  Untagged requests see only
  the global bound.
* **Split resolve lock** (docs/serving.md "Fast path") — workers try
  the resolver's lock-free snapshot path first (exact hits resolve
  CONCURRENTLY, memoized response and all); only the fallback — store
  walks, flag writes, cold enqueues, the near tier — serializes under
  the exclusive lock, so exact-tier pct99 at high QPS is bounded by
  the hit's own microseconds, not queue depth.
* **Per-request watchdog** — a request older than
  ``--request-timeout`` is answered with a classified timeout
  (``error_class: transient`` — the fault taxonomy of
  fault/errors.py, the caller may retry) even while the worker that
  picked it up is still grinding; the worker's late result is
  discarded.  Store-lock contention inside resolution is already
  bounded by the segmented store's backoff
  (:class:`~tenzing_tpu.fault.errors.StoreLockTimeout`).
* **Graceful drain** — SIGTERM/SIGINT stops intake, drains everything
  already queued, stamps the status document ``stopped``, and exits; a
  second signal abandons the drain.
* **Status/heartbeat** — ``status-<owner>.json`` next to the store,
  atomically rewritten every ``--heartbeat`` seconds with state, queue
  depth, per-tier served counts, shed/timeout tallies — the same
  liveness-probe contract as the drain daemon's status document, and
  the report CLI renders both.
* **Telemetry plane** (docs/observability.md "Fleet telemetry plane")
  — every request is minted a cross-process **trace context** at
  ingress (obs/context.py; a client-supplied ``trace`` key is adopted
  instead, so an upstream gateway's ids survive): the context stamps
  every span/event resolution emits, rides a cold query's work-item
  envelope into the drain daemon, and is echoed back as ``trace_id``
  on every response.  The heartbeat additionally publishes **metric
  snapshots** (obs/metrics.py ``MetricsSnapshotWriter`` — a bounded
  ring of atomic documents next to the status doc) carrying per-tier /
  per-tenant latency histograms, queue-age and shed-rate gauges, and
  an SLO block (exact pct99 vs ``--slo-target-us`` and vs the
  committed SERVE_BENCH baseline); the ``metrics`` protocol verb
  answers the same document on demand.

* **Watchtower** (docs/observability.md "Watchtower") — ``--record
  DIR`` turns the loop into a production traffic recorder
  (serve/reqlog.py): one sampled, checksummed, rotation-capped log
  record per admitted request (verbatim kwargs, tier, digests, latency
  phases, shed/timeout outcome — the empirical mix ``serve/replay.py
  --from-recorded`` replays), full span bundles for the interesting
  requests (slowest-K per heartbeat window, every
  shed/timeout/error/unverified) under ``DIR/exemplars/``, per-tenant
  shed/timeout counters, and a ``reqlog`` position block in every
  metric snapshot so the recorder is itself observable.

Every response carries ``resolve_us`` (the resolution's own latency,
excluding queue wait) so a replaying client can build the latency
distribution the ROADMAP's pct99 metric tracks without trusting the
server's aggregates.
"""

from __future__ import annotations

import json
import os
import queue as _queue
import signal
import socket as _socket
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tenzing_tpu.fault.errors import StoreReadonlyError, classify_error
from tenzing_tpu.obs import context as obs_context
from tenzing_tpu.serve.resolver import fp_cache_key
from tenzing_tpu.serve.store import probe_store_writable, store_readonly
from tenzing_tpu.obs.metrics import (
    MetricsSnapshotWriter,
    SloConfig,
    baseline_pct99_from,
    get_metrics,
)
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.utils.atomic import atomic_dump_json

STATUS_VERSION = 1
_OPS = ("query", "batch", "stats", "ping", "metrics")


@dataclass
class ListenOpts:
    """Knobs of one :class:`ServeLoop` (CLI flags map 1:1)."""

    max_pending: int = 64            # bounded queue: beyond this, shed
    workers: int = 2                 # resolution worker threads
    request_timeout_secs: float = 10.0   # per-request watchdog
    shed_retry_after_secs: float = 0.5   # the hint shed responses carry
    heartbeat_secs: float = 2.0      # status rewrite interval
    idle_exit_secs: Optional[float] = None  # exit after idling (CI)
    owner: str = ""                  # default: <host>-<pid>
    status_path: Optional[str] = None
    socket_path: Optional[str] = None
    handle_signals: bool = True
    # busy-poll worker mode (docs/serving.md "Busy-poll workers"):
    # each worker spins on get_nowait() for up to this many µs before
    # falling back to the blocking wait — buys back the OS timer-wake
    # floor on the exact-tier tail (obs/noise.py measures that floor)
    # at the cost of burning a core while idle.  0 = blocking waits.
    busy_poll_us: float = 0.0
    # -- telemetry plane (docs/observability.md) --
    slo_target_us: Optional[float] = None    # exact-tier pct99 objective
    slo_baseline: Optional[str] = None       # SERVE_BENCH_r*.json path
    metrics_ring: int = 8                    # snapshot files per owner
    trace_out: Optional[str] = None          # JSONL bundle written on drain
    # distinct per-tenant histogram labels admitted before new tenants
    # aggregate under "other" — per-tenant series must not let a
    # client-controlled string grow the registry without bound
    tenant_cap: int = 16
    # per-tenant fair admission (docs/serving.md): at most this many
    # in-flight requests per tenant (batch members charged to their
    # effective tenants) — an over-cap tenant is shed with reason
    # "tenant_cap" BEFORE its burst can fill the global max_pending
    # bound and starve everyone else.  None derives
    # max(1, max_pending // 2), enforced work-conservingly (only once a
    # second distinct tenant is seen); 0 disables the cap; an explicit
    # value always applies.  Requests without a tenant tag see only the
    # global bound.
    tenant_max_pending: Optional[int] = None
    # -- watchtower: production traffic recording (serve/reqlog.py) --
    record_dir: Optional[str] = None     # enables the request log
    record_sample: float = 1.0           # deterministic per-trace draw
    record_segment_records: int = 256    # records per sealed segment
    record_retain: int = 16              # sealed segments kept (rotation)
    record_flush_secs: float = 30.0      # heartbeat-side publish cadence
    exemplar_k: int = 4                  # slowest-K bundles per window
    exemplar_cap: int = 64               # exemplar files kept


class _Pending:
    """One in-flight request: complete-once semantics — whoever gets
    there first (worker result, watchdog timeout, shutdown shed) writes
    the response; everyone else's attempt is a no-op."""

    __slots__ = ("rid", "payload", "respond", "enqueued_at", "deadline",
                 "ctx", "_done", "_lock")

    def __init__(self, rid, payload: Dict[str, Any],
                 respond: Callable[[Dict[str, Any]], None],
                 deadline: Optional[float], ctx=None):
        self.rid = rid
        self.payload = payload
        self.respond = respond
        self.enqueued_at = time.time()
        self.deadline = deadline
        self.ctx = ctx  # the request's TraceContext (minted at ingress)
        self._done = False
        self._lock = threading.Lock()

    def complete(self, doc: Dict[str, Any]) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
        out = dict(doc)
        if self.rid is not None:
            out["id"] = self.rid
        if self.ctx is not None and "trace_id" not in out:
            # every response names its trace — shed and watchdog answers
            # included, so a client can correlate even its non-answers
            out["trace_id"] = self.ctx.trace_id
        try:
            self.respond(out)
        except Exception:
            pass  # a vanished client must not take the loop down
        return True

    @property
    def done(self) -> bool:
        return self._done


class ServeLoop:
    """See module docstring.  Embeddable: tests drive :meth:`submit` /
    :meth:`start` / :meth:`drain` directly; the CLI runs
    :meth:`serve_stdin` or :meth:`serve_socket`."""

    def __init__(self, service, opts: Optional[ListenOpts] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.service = service
        self.opts = opts or ListenOpts()
        self.owner = self.opts.owner or \
            f"{_socket.gethostname()}-{os.getpid()}"
        self._log_fn = log
        self.counters: Dict[str, int] = {
            k: 0 for k in ("requests", "batches", "served_exact",
                           "served_near", "served_cold", "shed",
                           "timeouts", "errors", "malformed", "signals")}
        # socket mode bumps counters from one reader thread per
        # connection plus the workers and the watchdog — unlocked
        # dict += would lose counts under interleaving, and these are
        # the economics the status doc and the replay benchmark read
        self._count_lock = threading.Lock()
        self.started_at = time.time()
        self._stop = threading.Event()       # stop intake, drain
        self._abandon = threading.Event()    # second signal: stop now
        self._queue: "_queue.Queue[_Pending]" = _queue.Queue(
            maxsize=max(1, self.opts.max_pending))
        self._live: "set[_Pending]" = set()
        self._live_lock = threading.Lock()
        # per-tenant in-flight counts, maintained under _live_lock by
        # _live_add/_live_discard: the fair-admission check is O(1) and
        # ATOMIC with registration — concurrent submits from many
        # connection threads cannot race past the cap between a count
        # and an add
        self._tenant_live: Dict[str, int] = {}
        # resolution is serialized: the resolver's caches and the store
        # flag/enqueue writes are not thread-safe, and the hot path is a
        # dict probe — worker concurrency buys queueing, not resolution
        self._resolve_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._prev_handlers: Dict[int, Any] = {}
        self.last_request_at = time.time()
        store_path = getattr(self.service.store, "path", None)
        # the readonly-probe target (and the status doc's "store" key)
        self._store_path = store_path if isinstance(store_path, str) \
            else None
        base = (os.path.dirname(os.path.abspath(store_path))
                if isinstance(store_path, str) and store_path.endswith(
                    ".json")
                else store_path if isinstance(store_path, str)
                else ".")
        self.status_path = self.opts.status_path or os.path.join(
            base, f"status-{self.owner}.json")
        # the streaming metrics exporter (obs/metrics.py): a bounded
        # ring of snapshot documents next to the status doc, written on
        # every heartbeat and answered by the `metrics` protocol verb
        baseline = (baseline_pct99_from(self.opts.slo_baseline)
                    if self.opts.slo_baseline else None)
        self._snapshots = MetricsSnapshotWriter(
            os.path.dirname(os.path.abspath(self.status_path)), self.owner,
            ring=self.opts.metrics_ring,
            slo=SloConfig(target_us=self.opts.slo_target_us,
                          baseline_pct99_us=baseline))
        # tenants admitted to their own latency series; the cap guards
        # the registry against client-controlled label cardinality
        self._tenants: "set[str]" = set()
        self._shed_window = (time.time(), 0)  # (window start, sheds then)
        # -- watchtower (serve/reqlog.py): the production traffic
        # recorder and the tail-sampled exemplar store, both opt-in via
        # record_dir — every admitted request lands one sampled,
        # checksummed log record; interesting requests (slowest-K per
        # window, every shed/timeout/error/unverified) keep their full
        # span bundle keyed by trace_id
        self._reqlog = None
        self._exemplars = None
        self._last_record_flush = time.time()
        if self.opts.record_dir:
            from tenzing_tpu.serve.reqlog import ExemplarStore, RequestLog

            self._reqlog = RequestLog(
                self.opts.record_dir, owner=self.owner,
                sample=self.opts.record_sample,
                segment_records=self.opts.record_segment_records,
                retain_segments=self.opts.record_retain, log=self._log)
            self._exemplars = ExemplarStore(
                os.path.join(self.opts.record_dir, "exemplars"),
                k=self.opts.exemplar_k, cap=self.opts.exemplar_cap,
                log=self._log)

    def _log(self, msg: str) -> None:
        if self._log_fn is not None:
            self._log_fn(msg)
        else:
            sys.stderr.write(f"serve[{self.owner}]: {msg}\n")

    def _bump(self, key: str, n: int = 1) -> None:
        with self._count_lock:
            self.counters[key] = self.counters.get(key, 0) + n

    # -- status --------------------------------------------------------------
    def _write_status(self, state: str) -> None:
        doc = {
            "version": STATUS_VERSION,
            "kind": "serve_loop",
            "owner": self.owner,
            "pid": os.getpid(),
            "host": _socket.gethostname(),
            "started_at": self.started_at,
            "heartbeat_at": time.time(),
            "uptime_s": round(time.time() - self.started_at, 1),
            "state": state,
            "queue_depth": self._queue.qsize(),
            "in_flight": len(self._live),
            "counters": dict(self.counters),
            "store": getattr(self.service.store, "path", None),
            "store_readonly": store_readonly(self._store_path),
            "socket": self.opts.socket_path,
        }
        try:
            atomic_dump_json(self.status_path, doc, prefix=".status.")
        except OSError as e:
            self._log(f"status write failed ({e})")

    # -- intake --------------------------------------------------------------
    def submit(self, payload: Dict[str, Any],
               respond: Callable[[Dict[str, Any]], None]) -> None:
        """One parsed request line: enqueue, or shed immediately when
        the bounded queue is full / the loop is draining."""
        rid = payload.get("id") if isinstance(payload, dict) else None
        self._bump("requests")
        self.last_request_at = time.time()
        if not isinstance(payload, dict) or \
                payload.get("op", "query") not in _OPS:
            self._bump("malformed")
            _Pending(rid, {}, respond, None).complete({
                "ok": False, "error": "malformed request "
                f"(op must be {'|'.join(_OPS)})",
                "error_class": "deterministic"})
            return
        # ingress: mint (or adopt the client's) cross-process trace
        # context — THE id that follows this request through resolution,
        # a cold enqueue, the daemon drain, and the store merge
        ctx = (obs_context.from_json(payload.get("trace"))
               or obs_context.new_trace())
        deadline = (time.time() + self.opts.request_timeout_secs
                    if self.opts.request_timeout_secs else None)
        pending = _Pending(rid, payload, respond, deadline, ctx=ctx)
        if self._stop.is_set():
            self._shed(pending, reason="draining")
            return
        # per-tenant fair admission, atomic with live registration: the
        # tenant's own in-flight count is bounded below the global one,
        # so one tenant's burst sheds against its own cap while everyone
        # else still has queue room.  Registered live BEFORE the
        # enqueue: a worker that grabs the item instantly must find it
        # registered, or the discard would lose to the add and leak a
        # ghost into the watchdog's view.
        admitted, over_tenant = self._live_add(pending)
        if not admitted:
            self._shed(pending, reason="tenant_cap", tenant=over_tenant)
            return
        try:
            self._queue.put_nowait(pending)
        except _queue.Full:
            self._live_discard(pending)
            self._shed(pending, reason="queue-full")
            return

    def _shed(self, pending: _Pending, reason: str,
              tenant: Optional[str] = None) -> None:
        self._bump("shed")
        reg = get_metrics()
        reg.counter("serve.shed").inc()
        # per-tenant shed economics (ISSUE 13 satellite): the fairness
        # measurement the ROADMAP's per-tenant admission item needs —
        # capped to "other" exactly like the latency series.  ``tenant``
        # names the over-cap tenant for tenant_cap sheds (an untagged
        # batch shed for a MEMBER tenant must charge that tenant, not
        # nobody); other reasons attribute to the payload tenant.
        label = self._tenant_label(tenant if tenant is not None
                                   else self._tenant_of(pending.payload))
        if label is not None:
            reg.counter(f"serve.shed.{label}").inc()
        tr = get_tracer()
        if tr.enabled:
            tr.event("serve.shed", reason=reason,
                     depth=self._queue.qsize())
        doc = {
            "ok": False, "shed": True, "reason": reason,
            "retry_after": self.opts.shed_retry_after_secs,
            "error_class": "transient"}
        if pending.complete(doc):
            self._record(pending, doc)

    def _tenant_pending_cap(self) -> int:
        """The effective per-tenant in-flight bound (opts docstring):
        configured, or half the global bound; 0 = disabled."""
        cap = self.opts.tenant_max_pending
        if cap is None:
            return max(1, self.opts.max_pending // 2)
        return max(0, cap)

    @classmethod
    def _tenant_weights(cls, payload: Any) -> Dict[str, int]:
        """tenant -> request count a payload charges against the
        fair-admission cap.  Tenant tags are guarded to strings (client
        input — a non-string tenant must not crash admission on an
        unhashable dict key; it admits uncapped like an untagged
        request, same rule as ``_tenant_label``).  A batch charges each
        MEMBER to its own effective tenant — the same ``r.get("tenant",
        payload_tenant)`` rule execution and telemetry apply — so
        neither one batch slot nor member-level tagging can smuggle
        sub-requests past the starvation bound.  Pure payload
        arithmetic: add and discard recompute it identically, so no
        per-pending state is needed."""
        base = cls._tenant_of(payload)
        if not isinstance(base, str):
            base = None
        if not (isinstance(payload, dict)
                and payload.get("op") == "batch"):
            return {base: 1} if base else {}
        reqs = payload.get("requests")
        if not isinstance(reqs, list) or not reqs:
            return {base: 1} if base else {}
        weights: Dict[str, int] = {}
        for r in reqs:
            t = r.get("tenant", base) if isinstance(r, dict) else base
            if not isinstance(t, str):
                t = None
            if t:
                weights[t] = weights.get(t, 0) + 1
        return weights

    def _live_add(self, pending: _Pending):
        """Register a request in the live set, enforcing the per-tenant
        cap atomically in the same critical section.  Returns
        ``(admitted, over_tenant)``: ``(False, <tenant>)`` means that
        tenant is over cap and the request was NOT registered (the
        caller sheds with reason ``tenant_cap``, charged to that
        tenant; a batch admits or sheds whole — it occupies one queue
        slot).

        The DERIVED default cap (no explicit ``tenant_max_pending``) is
        work-conserving: it only bites once a second distinct tenant
        has been seen (``self._tenants`` — shed and resolution labeling
        both register tenants, so a starved newcomer activates the cap
        within one round-trip).  Fairness between tenants is vacuous
        with one tenant, and halving a sole tenant's capacity against
        nobody would be pure waste.  An explicit cap always applies."""
        weights = self._tenant_weights(pending.payload)
        for t in weights:
            # register at submission so a starved newcomer activates
            # the derived cap immediately, not only after it resolves
            self._tenant_label(t)
        cap = self._tenant_pending_cap()
        if cap and self.opts.tenant_max_pending is None and \
                len(self._tenants) < 2:
            cap = 0
        with self._live_lock:
            if cap:
                for tenant, weight in weights.items():
                    if self._tenant_live.get(tenant, 0) + weight > cap:
                        return False, tenant
            self._live.add(pending)
            for tenant, weight in weights.items():
                self._tenant_live[tenant] = \
                    self._tenant_live.get(tenant, 0) + weight
        return True, None

    def _live_discard(self, pending: _Pending) -> None:
        """Remove from the live set, keeping the per-tenant counts
        exact: both the worker and the watchdog discard the same
        pending, so only the acquisition that actually removes it may
        decrement."""
        with self._live_lock:
            if pending not in self._live:
                return
            self._live.discard(pending)
            for tenant, weight in \
                    self._tenant_weights(pending.payload).items():
                n = self._tenant_live.get(tenant, 0) - weight
                if n > 0:
                    self._tenant_live[tenant] = n
                else:
                    self._tenant_live.pop(tenant, None)

    # -- workers -------------------------------------------------------------
    @staticmethod
    def _tenant_of(payload: Any) -> Optional[str]:
        """The request's tenant tag — THE one extraction shed, timeout
        and recording all share (a payload is client input: any shape)."""
        return payload.get("tenant") if isinstance(payload, dict) else None

    def _tenant_label(self, tenant: Optional[str]) -> Optional[str]:
        """The bounded per-tenant histogram label: the first
        ``tenant_cap`` distinct tenants get their own series, later ones
        aggregate under ``other`` (still measured, never unbounded)."""
        if not tenant or not isinstance(tenant, str):
            return None
        if tenant in self._tenants:
            return tenant
        if len(self._tenants) < max(0, self.opts.tenant_cap):
            self._tenants.add(tenant)
            return tenant
        return "other"

    def _resolve_one(self, request: Dict[str, Any],
                     tenant: Optional[str] = None) -> Dict[str, Any]:
        # the split lock (docs/serving.md "Fast path"): exact hits
        # resolve lock-free against the resolver's immutable snapshot —
        # workers serve them CONCURRENTLY — and only the fallback
        # (store writes, cold enqueues, the near tier, cache refills)
        # takes the exclusive lock.  pct99 at high QPS is then bounded
        # by the hit's own microseconds, not by queue depth times the
        # slowest request ahead of it.
        # embedded/stub services without a resolver attribute keep the
        # pre-split behavior: everything through the exclusive lock
        resolver = getattr(self.service, "resolver", None)
        key = (fp_cache_key(request if request else {})
               if resolver is not None else None)
        t0 = time.perf_counter()
        res = resolver.resolve_fast(key) if resolver is not None else None
        if res is not None:
            dt_us = (time.perf_counter() - t0) * 1e6
        else:
            from tenzing_tpu.bench.driver import DriverRequest

            with self._resolve_lock:
                # timed inside the lock: resolve_us is the resolution's
                # own latency (the serve.resolve_us series), not
                # queue/lock wait
                t0 = time.perf_counter()
                req = DriverRequest(**(request or {}))
                res = (self.service.query(req, fp_key=key)
                       if resolver is not None
                       else self.service.query(req))
                dt_us = (time.perf_counter() - t0) * 1e6
        # response serialization is a real per-hit phase (the ROADMAP's
        # tens-of-µs item profiles it): timed + sub-spanned like the
        # resolver's fingerprint/cache-probe phases
        tr = get_tracer()
        t_ser = time.perf_counter()
        if tr.enabled:
            with tr.span("serve.serialize", tier=res.tier):
                out = res.to_json()
        else:
            out = res.to_json()
        ser_us = round((time.perf_counter() - t_ser) * 1e6, 2)
        out.setdefault("phase_us", {})["serialize"] = ser_us
        out["resolve_us"] = round(dt_us, 1)
        self._bump(f"served_{res.tier}")
        label = self._tenant_label(tenant)
        if label is not None:
            reg = get_metrics()
            reg.counter(f"serve.tenant.{label}.{res.tier}").inc()
            # small WINDOWED cap (obs/metrics.py): one long-lived loop
            # serves many tenants, and the snapshot percentiles must
            # cover the recent window, not the first 4096 ever seen
            reg.histogram(f"serve.tenant.{label}.resolve_us",
                          max_raw=4096, window=True).observe(dt_us)
        return out

    def _handle(self, pending: _Pending) -> Dict[str, Any]:
        payload = pending.payload
        op = payload.get("op", "query")
        if op == "ping":
            return {"ok": True, "pong": True, "owner": self.owner}
        if op == "metrics":
            # the on-demand twin of the heartbeat's snapshot documents
            return {"ok": True, "metrics": self._snapshots.build(
                state="serving", extra=self._snapshot_extra())}
        if op == "stats":
            with self._resolve_lock:
                return {"ok": True, "stats": self.service.stats()}
        tenant = payload.get("tenant")
        if op == "batch":
            reqs = payload.get("requests") or []
            self._bump("batches")
            get_metrics().counter("serve.listen.batches").inc()
            results = []
            for r in reqs:
                req = r.get("request", r) if isinstance(r, dict) else {}
                t = r.get("tenant", tenant) if isinstance(r, dict) else tenant
                try:
                    results.append(self._resolve_one(req, tenant=t))
                except StoreReadonlyError as e:
                    # degraded read-only: this member needed a store
                    # write (near/cold) — shed it explicitly; exact
                    # members of the same batch still answer above
                    self._bump("shed")
                    results.append(self._readonly_shed_doc(e))
                except Exception as e:
                    results.append({"error": str(e)[:500],
                                    "error_class": classify_error(e)})
            return {"ok": True, "results": results}
        try:
            return {"ok": True,
                    "result": self._resolve_one(payload.get("request") or {},
                                                tenant=tenant)}
        except StoreReadonlyError as e:
            self._bump("shed")
            return self._readonly_shed_doc(e)

    def _readonly_shed_doc(self, exc: BaseException) -> Dict[str, Any]:
        """The store-readonly shed response (docs/robustness.md
        "Degraded read-only mode"): transient by classification — the
        latch clears when a probe write lands, so retry-later is the
        honest hint.  Exact-tier traffic never sees this: the sealed
        cache keeps answering throughout the outage."""
        get_metrics().counter("serve.shed").inc()
        return {"ok": False, "shed": True, "reason": "store_readonly",
                "retry_after": self.opts.shed_retry_after_secs,
                "error": str(exc)[:300], "error_class": "transient"}

    def _next_pending(self):
        """One queue fetch: a bounded ``get_nowait()`` spin first
        (``busy_poll_us``), then the blocking wait.  A request landing
        during the spin window is picked up at sub-microsecond latency
        instead of paying the condition-variable wake floor; a quiet
        window degrades to exactly the old blocking behavior."""
        spin_s = self.opts.busy_poll_us / 1e6
        if spin_s > 0 and not self._stop.is_set():
            deadline = time.perf_counter() + spin_s
            while True:
                try:
                    return self._queue.get_nowait()
                except _queue.Empty:
                    if self._stop.is_set() or \
                            time.perf_counter() >= deadline:
                        break
        return self._queue.get(timeout=0.1)

    def _worker(self) -> None:
        while True:
            try:
                pending = self._next_pending()
            except _queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                if pending.done:
                    continue  # timed out while queued: already answered
                try:
                    # the request's trace context is ambient for the
                    # whole handling: resolution spans, a cold enqueue's
                    # envelope, and any store flush all stamp it
                    with obs_context.use(pending.ctx):
                        doc = self._handle(pending)
                except Exception as e:
                    self._bump("errors")
                    get_metrics().counter("serve.listen.errors").inc()
                    doc = {"ok": False, "error": str(e)[:500],
                           "error_class": classify_error(e)}
                # a late result loses to the watchdog silently: the
                # client already got its transient-classified timeout
                if pending.complete(doc):
                    self._record(pending, doc)
            finally:
                self._live_discard(pending)
                self._queue.task_done()

    def _watchdog(self) -> None:
        while not self._abandon.is_set():
            now = time.time()
            with self._live_lock:
                overdue = [p for p in self._live
                           if p.deadline is not None and now > p.deadline
                           and not p.done]
            for p in overdue:
                doc = {
                    "ok": False, "timed_out": True,
                    "error": (f"request exceeded "
                              f"{self.opts.request_timeout_secs}s "
                              "watchdog"),
                    "error_class": "transient",
                    "retry_after": self.opts.shed_retry_after_secs}
                if p.complete(doc):
                    self._bump("timeouts")
                    reg = get_metrics()
                    reg.counter("serve.listen.timeouts").inc()
                    # per-tenant timeout twin of serve.shed.<tenant>
                    label = self._tenant_label(self._tenant_of(p.payload))
                    if label is not None:
                        reg.counter(f"serve.timeout.{label}").inc()
                    self._record(p, doc)
                self._live_discard(p)
            # sleep on ABANDON, not stop: once stop is set (the whole
            # drain window) a stop.wait would return instantly and this
            # loop would spin a core while contending _live_lock
            if self._abandon.wait(0.05):
                return
            if self._stop.is_set() and not self._live and \
                    self._queue.empty():
                return

    # -- watchtower recording (serve/reqlog.py) ------------------------------
    def _record(self, pending: _Pending, doc: Dict[str, Any]) -> None:
        """Append this completed request to the production traffic log
        and offer it to the exemplar store — one record per resolved
        request (batch members each get their own), carrying the
        verbatim request kwargs so ``serve/replay.py --from-recorded``
        can re-issue the exact query stream."""
        if self._reqlog is None:
            return
        from tenzing_tpu.serve.reqlog import RECORD_VERSION

        payload = (pending.payload
                   if isinstance(pending.payload, dict) else {})
        op = payload.get("op", "query")
        if op not in ("query", "batch"):
            return
        trace_id = pending.ctx.trace_id if pending.ctx is not None else None
        tenant = self._tenant_of(payload)
        if doc.get("shed"):
            outcome = "shed"
        elif doc.get("timed_out"):
            outcome = "timeout"
        elif not doc.get("ok"):
            outcome = "error"
        else:
            outcome = "served"
        if op == "batch":
            reqs = payload.get("requests") or []
            results = doc.get("results") or [None] * len(reqs)
            triples = []
            for r, res in zip(reqs, results):
                req = r.get("request", r) if isinstance(r, dict) else {}
                t = (r.get("tenant", tenant)
                     if isinstance(r, dict) else tenant)
                triples.append((req, t, res))
        else:
            triples = [(payload.get("request") or {}, tenant,
                        doc.get("result"))]
        for req, t, res in triples:
            res = res if isinstance(res, dict) else {}
            # the whole-request outcome, refined per batch member: a
            # batch answered ok can still carry individual errors
            out = outcome
            if out == "served" and "tier" not in res:
                out = "error"
            rec: Dict[str, Any] = {
                "v": RECORD_VERSION,
                "ts": pending.enqueued_at,
                "trace_id": trace_id,
                "tenant": t,
                "op": op,
                "outcome": out,
                "request": req,
            }
            if out in ("error", "timeout", "shed"):
                rec["error_class"] = (res.get("error_class")
                                      or doc.get("error_class"))
            if "tier" in res:
                fp = res.get("fingerprint") or {}
                rec.update({
                    "tier": res.get("tier"),
                    "workload": fp.get("workload"),
                    "exact": fp.get("exact"),
                    "bucket": fp.get("bucket_digest"),
                    "resolve_us": res.get("resolve_us"),
                    "phase_us": res.get("phase_us"),
                })
            interesting = None
            if out in ("shed", "timeout", "error"):
                interesting = out
            elif (res.get("provenance") or {}).get("verified") is False:
                interesting = "unverified"
            try:
                # recording must never take the serving path down: a
                # full disk (or a record a caller made unserializable)
                # costs the record, not the response — and never the
                # worker/watchdog thread it would otherwise kill
                self._reqlog.append(rec)
                if self._exemplars is not None:
                    self._exemplars.offer(rec, interesting=interesting)
            except Exception as e:
                self._log(f"request-log append failed "
                          f"({type(e).__name__}: {e})")

    def _record_tick(self) -> None:
        """The heartbeat's recording housekeeping: publish the buffered
        log records every ``record_flush_secs`` (a SIGKILLed loop then
        loses at most one cadence window) and close the exemplar
        window (slowest-K per heartbeat window)."""
        if self._reqlog is None:
            return
        now = time.time()
        try:
            # sealed batches rotate on the request path with zero I/O;
            # THIS thread pays their fsyncs every heartbeat, and the
            # partial buffer every record_flush_secs
            self._reqlog.publish_pending()
            if now - self._last_record_flush >= \
                    self.opts.record_flush_secs:
                self._last_record_flush = now
                self._reqlog.flush()
        except OSError as e:
            self._log(f"request-log flush failed ({e})")
        if self._exemplars is not None:
            self._exemplars.roll()

    def _snapshot_extra(self) -> Dict[str, Any]:
        """The loop-level block metric snapshots carry beside the raw
        registry: the counters the status doc publishes, the derived
        queue-age / shed-rate gauges, the loop's uptime, and — so the
        recorder is itself observable — the request-log position."""
        out: Dict[str, Any] = {
            "counters": dict(self.counters),
            "queue_depth": self._queue.qsize(),
            "in_flight": len(self._live),
            "uptime_s": round(time.time() - self.started_at, 1)}
        ro = store_readonly(self._store_path)
        if ro is not None:
            # the store_unwritable alert rule keys on this block
            # (obs/alerts.py): present while degraded, absent once the
            # heartbeat's probe write lands — fire-then-resolve
            out["store_readonly"] = ro
        if self._reqlog is not None:
            out["reqlog"] = self._reqlog.position()
        return out

    def _observe_gauges(self) -> None:
        reg = get_metrics()
        reg.gauge("serve.queue_depth").set(float(self._queue.qsize()))
        # queue age: the oldest still-unanswered request's wait so far —
        # depth says how many, age says how badly they are aging
        now = time.time()
        with self._live_lock:
            oldest = min((p.enqueued_at for p in self._live
                          if not p.done), default=None)
        reg.gauge("serve.queue_age_s").set(
            round(now - oldest, 3) if oldest is not None else 0.0)
        # shed rate over the last heartbeat window (sheds/sec)
        t0, sheds0 = self._shed_window
        sheds = self.counters.get("shed", 0)
        dt = max(1e-6, now - t0)
        reg.gauge("serve.shed_rate").set(round((sheds - sheds0) / dt, 4))
        self._shed_window = (now, sheds)

    def _heartbeat(self) -> None:
        while not self._stop.wait(self.opts.heartbeat_secs):
            if self._store_path is not None and \
                    store_readonly(self._store_path) is not None:
                # degraded read-only: one tiny probe write per heartbeat
                # (through the same atomic seam real writes use) clears
                # the latch the moment the filesystem recovers — near/
                # cold resolution resumes without operator action
                if probe_store_writable(self._store_path):
                    self._log("store writable again — resuming "
                              "near/cold tiers")
            self._write_status("serving")
            self._observe_gauges()
            try:
                self._snapshots.write(state="serving",
                                      extra=self._snapshot_extra())
            except OSError as e:
                self._log(f"metrics snapshot failed ({e})")
            self._record_tick()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self.opts.trace_out:
            from tenzing_tpu.obs.tracer import configure

            configure(enabled=True)
        for i in range(max(1, self.opts.workers)):
            t = threading.Thread(target=self._worker,
                                 name=f"serve-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        for fn, name in ((self._watchdog, "serve-watchdog"),
                         (self._heartbeat, "serve-heartbeat")):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        self._write_status("serving")

    def stop(self) -> None:
        """Stop intake; workers drain what is queued (the programmatic
        twin of SIGTERM)."""
        self._stop.set()

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for queued + in-flight work to finish; True when fully
        drained."""
        self._stop.set()
        deadline = time.time() + timeout
        while time.time() < deadline and not self._abandon.is_set():
            with self._live_lock:
                live = len(self._live)
            if live == 0 and self._queue.empty():
                break
            time.sleep(0.02)
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.time()))
        ok = self._queue.empty()
        # seal the recording before the final snapshot so the position
        # block in the "stopped" snapshot reflects the published truth
        if self._exemplars is not None:
            self._exemplars.roll()
        if self._reqlog is not None:
            try:
                self._reqlog.flush()
            except OSError as e:
                self._log(f"request-log flush failed ({e})")
        self._write_status("stopped")
        self._observe_gauges()
        try:
            self._snapshots.write(state="stopped",
                                  extra=self._snapshot_extra())
        except OSError as e:
            self._log(f"metrics snapshot failed ({e})")
        if self.opts.trace_out:
            # the loop's own telemetry bundle — one leg of the stitched
            # fleet trace (obs/export.py stitch)
            from tenzing_tpu.obs.export import write_jsonl
            from tenzing_tpu.obs.tracer import get_tracer as _gt

            try:
                write_jsonl(_gt(), self.opts.trace_out)
                self._log(f"trace bundle: {self.opts.trace_out}")
            except OSError as e:
                self._log(f"trace bundle failed ({e})")
        return ok

    def _on_signal(self, signum, frame) -> None:
        self.counters["signals"] += 1
        if self.counters["signals"] >= 2:
            self._abandon.set()
        self._stop.set()

    def _install_signals(self) -> None:
        if not self.opts.handle_signals:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev_handlers[sig] = signal.signal(sig, self._on_signal)

    def _restore_signals(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (OSError, ValueError):
                pass
        self._prev_handlers.clear()

    def summary(self) -> Dict[str, Any]:
        out = {"owner": self.owner, "counters": dict(self.counters),
               "status": self.status_path,
               "wall_s": round(time.time() - self.started_at, 3)}
        if self._reqlog is not None:
            out["reqlog"] = self._reqlog.position()
        if self._exemplars is not None:
            out["exemplars"] = self._exemplars.written
        return out

    # -- transports ----------------------------------------------------------
    def serve_stdin(self, stdin=None, stdout=None) -> Dict[str, Any]:
        """JSONL over stdin/stdout until EOF or a signal; then drain."""
        stdin = stdin if stdin is not None else sys.stdin
        stdout = stdout if stdout is not None else sys.stdout
        out_lock = threading.Lock()

        def respond(doc: Dict[str, Any]) -> None:
            with out_lock:
                stdout.write(json.dumps(doc) + "\n")
                stdout.flush()

        self._install_signals()
        self.start()
        self._log(f"listening on stdin (status {self.status_path})")
        try:
            for line in stdin:
                if self._stop.is_set():
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                except ValueError as e:
                    self._bump("malformed")
                    respond({"ok": False,
                             "error": f"bad json: {str(e)[:200]}",
                             "error_class": "deterministic"})
                    continue
                self.submit(payload, respond)
        finally:
            self.drain()
            self._restore_signals()
        return self.summary()

    def serve_socket(self, path: Optional[str] = None) -> Dict[str, Any]:
        """JSONL over a unix domain socket until a signal (or
        ``idle_exit_secs`` of silence); concurrent connections each get
        a reader thread; responses serialize per connection."""
        path = path or self.opts.socket_path
        try:
            os.unlink(path)
        except OSError:
            pass
        srv = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(16)
        srv.settimeout(0.25)
        self._install_signals()
        self.start()
        self._log(f"listening on {path} (status {self.status_path})")
        conn_threads: List[threading.Thread] = []

        def client(conn: _socket.socket) -> None:
            wlock = threading.Lock()

            def respond(doc: Dict[str, Any]) -> None:
                data = (json.dumps(doc) + "\n").encode()
                with wlock:
                    conn.sendall(data)

            buf = b""
            try:
                conn.settimeout(0.25)
                while not self._abandon.is_set():
                    try:
                        chunk = conn.recv(1 << 16)
                    except _socket.timeout:
                        if self._stop.is_set():
                            break
                        continue
                    except OSError:
                        break
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if not line.strip():
                            continue
                        try:
                            payload = json.loads(line)
                        except ValueError as e:
                            self._bump("malformed")
                            respond({"ok": False,
                                     "error": f"bad json: {str(e)[:200]}",
                                     "error_class": "deterministic"})
                            continue
                        self.submit(payload, respond)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

        try:
            while not self._stop.is_set():
                if (self.opts.idle_exit_secs is not None
                        and not self._live and self._queue.empty()
                        and time.time() - self.last_request_at
                        >= self.opts.idle_exit_secs):
                    self._log(f"idle for {self.opts.idle_exit_secs}s — "
                              "exiting")
                    break
                try:
                    conn, _ = srv.accept()
                except _socket.timeout:
                    continue
                except OSError:
                    break
                # prune dead readers so days of short-lived connections
                # don't accumulate one Thread object each
                conn_threads[:] = [t for t in conn_threads if t.is_alive()]
                t = threading.Thread(target=client, args=(conn,),
                                     daemon=True)
                t.start()
                conn_threads.append(t)
        finally:
            try:
                srv.close()
            except OSError:
                pass
            self.drain()
            for t in conn_threads:
                t.join(timeout=1.0)
            try:
                os.unlink(path)
            except OSError:
                pass
            self._restore_signals()
        return self.summary()
