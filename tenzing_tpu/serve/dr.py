"""Disaster recovery for the serve plane: ``serve backup``,
``serve restore``, ``serve fsck`` (docs/robustness.md "Disaster
recovery").

The segmented store already survives torn writes, bit flips and
``kill -9`` (serve/segments.py) — what it cannot survive is the disk
itself: an ``rm -rf``, a dead volume, a fat-fingered migration.  This
module closes that gap with three small, composable tools:

* **backup** — one point-in-time generation under
  ``<store>/backups/gen-<stamp>-<pid>/`` (or ``--out`` elsewhere).
  Sealed segments are *immutable by contract* (writers only ever
  publish new files and unlink old ones), so a backup hard-links them
  — O(1) per segment, no byte copying on the same filesystem — and
  snapshots the manifest bytes.  A checksummed ``catalog.json``
  (sha256 per captured file) is published LAST: a generation without a
  catalog is an aborted backup and restore refuses it.  Concurrent
  writers are safe by the same publish-then-reclaim ordering the
  loader relies on: when a compactor reclaims a segment mid-snapshot,
  the re-list picks up its published output — the captured set is
  always a **consistent superset of some instant's acknowledged
  records** (never a torn segment, never a lost record).
* **restore** — catalog-verified, point-in-time, **superset-safe**.
  Into an empty/absent store the generation's files are linked/copied
  back verbatim — byte-identical with the snapshot.  Into a live store
  it *merge-restores* through the same commutative
  :func:`~tenzing_tpu.serve.store.merge_records` algebra every other
  writer uses: records written after the snapshot survive, records
  lost since the snapshot come back, nothing is clobbered.
* **fsck** — a deep, read-only integrity walk: every record's sha256
  re-verified against its segment line (the loader's salvage machinery
  with ``quarantine_corrupt=False`` — report, never move evidence),
  manifest-vs-disk reconciliation (orphans / missing), a census of
  quarantined ``*.corrupt-*`` files, stale temp droppings and backup
  generations (catalog spot-check).  ``--adopt`` additionally indexes
  orphan segments into the manifest (the only write it can do);
  ``--stamp`` records the verdict to ``<store>/fsck.json`` for the
  report CLI's follow view.  Exit codes are the CI contract: 0 =
  clean, 1 = damage found, 2 = unreadable/usage — a committed corpus
  gates on 0.

Both store backends are covered: a ``*.json`` path is the monolithic
store (backup = checksummed byte copy), anything else the segmented
directory.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

from tenzing_tpu.serve.store import file_digest, store_readonly

BACKUPS_DIR = "backups"
CATALOG_NAME = "catalog.json"
CATALOG_VERSION = 1
FSCK_STAMP = "fsck.json"
FSCK_VERSION = 1
# the compactor's publish strictly precedes its reclaim, so one
# re-list after a vanished link target always finds the output — the
# bound is paranoia, not protocol
SNAPSHOT_PASSES = 5

RC_CLEAN = 0
RC_DAMAGED = 1
RC_UNREADABLE = 2


class DrError(RuntimeError):
    """A backup/restore precondition failed (missing generation, torn
    catalog, checksum mismatch): the operation refused to run — exit 2,
    never a half-applied restore."""


def _is_monolithic(store_path: str) -> bool:
    return store_path.endswith(".json") and not os.path.isdir(store_path)


def backups_root(store_path: str) -> str:
    """Where a store's generations live by default: inside the store
    directory (the segment scan only reads ``segments/``, so backups
    are invisible to loads) or next to a monolithic file."""
    if _is_monolithic(store_path):
        return os.path.abspath(store_path) + ".backups"
    return os.path.join(store_path, BACKUPS_DIR)


def list_generations(root: str) -> List[str]:
    try:
        return sorted(n for n in os.listdir(root)
                      if n.startswith("gen-")
                      and os.path.isdir(os.path.join(root, n)))
    except OSError:
        return []


def latest_generation(root: str) -> Optional[str]:
    gens = list_generations(root)
    return os.path.join(root, gens[-1]) if gens else None


def _link_or_copy(src: str, dst: str) -> str:
    """Hard-link (same filesystem: O(1), and sealed segments are
    immutable so sharing the inode is safe) with a byte-copy fallback
    for ``--out`` on another device."""
    try:
        os.link(src, dst)
        return "linked"
    except FileExistsError:
        return "linked"
    except OSError:
        shutil.copy2(src, dst)
        return "copied"


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# -- backup ------------------------------------------------------------------

def backup_store(store_path: str, out_dir: Optional[str] = None,
                 note: str = "",
                 log: Optional[Callable[[str], None]] = None
                 ) -> Dict[str, Any]:
    """One point-in-time generation (module docstring).  Returns the
    catalog doc plus the generation path; raises :class:`DrError` when
    there is nothing to back up."""
    from tenzing_tpu.serve.segments import (
        MANIFEST_NAME,
        SEGMENTS_DIR,
        is_segment_name,
    )

    store_path = os.path.abspath(store_path)
    root = out_dir or backups_root(store_path)
    gen_name = f"gen-{int(time.time() * 1e6)}-{os.getpid()}"
    gen_dir = os.path.join(root, gen_name)
    files: Dict[str, Dict[str, Any]] = {}
    captured = {"linked": 0, "copied": 0}

    if _is_monolithic(store_path):
        if not os.path.exists(store_path):
            raise DrError(f"nothing to back up: {store_path} is absent")
        os.makedirs(gen_dir, exist_ok=True)
        dst = os.path.join(gen_dir, "store.json")
        # a monolithic store is REPLACED atomically, never appended:
        # the link captures exactly one published version
        captured[_link_or_copy(store_path, dst)] += 1
        files["store.json"] = {"sha256": file_digest(dst),
                               "bytes": os.path.getsize(dst)}
        backend = "monolithic"
    else:
        seg_src = os.path.join(store_path, SEGMENTS_DIR)
        if not os.path.isdir(store_path):
            raise DrError(f"nothing to back up: {store_path} is absent")
        seg_dst = os.path.join(gen_dir, SEGMENTS_DIR)
        os.makedirs(seg_dst, exist_ok=True)
        done: set = set()
        for _pass in range(SNAPSHOT_PASSES):
            vanished = 0
            try:
                names = sorted(n for n in os.listdir(seg_src)
                               if is_segment_name(n))
            except OSError:
                names = []
            for name in names:
                if name in done:
                    continue
                src = os.path.join(seg_src, name)
                try:
                    how = _link_or_copy(src, os.path.join(seg_dst, name))
                except OSError:
                    # reclaimed between listdir and link: the
                    # compactor's published output shows up on re-list
                    vanished += 1
                    continue
                done.add(name)
                captured[how] += 1
            if not vanished:
                break
            if log:
                log(f"backup: {vanished} segment(s) reclaimed "
                    "mid-snapshot; re-listing")
        for name in sorted(done):
            dst = os.path.join(seg_dst, name)
            files[f"{SEGMENTS_DIR}/{name}"] = {
                "sha256": file_digest(dst),
                "bytes": os.path.getsize(dst)}
        man_src = os.path.join(store_path, MANIFEST_NAME)
        if os.path.exists(man_src):
            man_dst = os.path.join(gen_dir, MANIFEST_NAME)
            # manifests mutate (atomic replace): byte-copy the snapshot
            # instead of sharing the inode
            shutil.copy2(man_src, man_dst)
            files[MANIFEST_NAME] = {"sha256": file_digest(man_dst),
                                    "bytes": os.path.getsize(man_dst)}
        _fsync_dir(seg_dst)
        backend = "segmented"

    catalog = {
        "kind": "backup", "version": CATALOG_VERSION,
        "created_at": time.time(), "store": store_path,
        "backend": backend, "note": note,
        "n_files": len(files),
        "bytes": sum(f["bytes"] for f in files.values()),
        "captured": captured,
        "files": files,
    }
    # published LAST: a generation without a catalog is an aborted
    # backup, and restore refuses it
    from tenzing_tpu.utils.atomic import atomic_dump_json

    atomic_dump_json(os.path.join(gen_dir, CATALOG_NAME), catalog,
                     prefix=".catalog.")
    _fsync_dir(gen_dir)
    if log:
        log(f"backup: {gen_name}: {len(files)} file(s), "
            f"{catalog['bytes']} bytes ({captured['linked']} linked, "
            f"{captured['copied']} copied)")
    return dict(catalog, generation=gen_dir)


def load_catalog(gen_dir: str) -> Dict[str, Any]:
    path = os.path.join(gen_dir, CATALOG_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise DrError(f"generation {gen_dir}: no readable catalog "
                      f"({e}) — aborted backup?") from e
    if not isinstance(doc, dict) or doc.get("kind") != "backup" or \
            not isinstance(doc.get("files"), dict):
        raise DrError(f"generation {gen_dir}: catalog is not a backup "
                      "catalog")
    if doc.get("version", 0) > CATALOG_VERSION:
        raise DrError(f"generation {gen_dir}: catalog version "
                      f"{doc.get('version')!r} > {CATALOG_VERSION}")
    return doc


def verify_backup(gen_dir: str) -> Dict[str, Any]:
    """Deep-check one generation against its catalog: every captured
    file present with matching sha256/size.  Returns a verdict doc
    (never raises on damage — the caller decides)."""
    cat = load_catalog(gen_dir)
    missing: List[str] = []
    mismatched: List[str] = []
    for rel, meta in sorted(cat["files"].items()):
        path = os.path.join(gen_dir, rel)
        if not os.path.exists(path):
            missing.append(rel)
            continue
        try:
            if file_digest(path) != meta.get("sha256"):
                mismatched.append(rel)
        except OSError:
            missing.append(rel)
    return {"generation": gen_dir, "checked": len(cat["files"]),
            "missing": missing, "mismatched": mismatched,
            "ok": not missing and not mismatched,
            "catalog": cat}


# -- restore -----------------------------------------------------------------

def _store_is_empty(store_path: str) -> bool:
    from tenzing_tpu.serve.segments import (
        MANIFEST_NAME,
        SEGMENTS_DIR,
        is_segment_name,
    )

    if _is_monolithic(store_path):
        return not os.path.exists(store_path)
    if not os.path.isdir(store_path):
        return True
    if os.path.exists(os.path.join(store_path, MANIFEST_NAME)):
        return False
    try:
        seg = os.listdir(os.path.join(store_path, SEGMENTS_DIR))
    except OSError:
        return True
    return not any(is_segment_name(n) for n in seg)


def restore_store(store_path: str, gen_dir: str, force: bool = False,
                  log: Optional[Callable[[str], None]] = None
                  ) -> Dict[str, Any]:
    """Point-in-time restore (module docstring): catalog-verified
    first; verbatim into an empty store (byte-identical with the
    snapshot), commutative merge-restore into a live one (superset of
    both sides).  ``force`` restores from a generation that fails
    verification — the intact files still restore; the damaged ones
    are reported, not silently skipped."""
    from tenzing_tpu.serve.store import open_store

    store_path = os.path.abspath(store_path)
    verdict = verify_backup(gen_dir)
    damaged = sorted(set(verdict["missing"]) | set(verdict["mismatched"]))
    if not verdict["ok"] and not force:
        raise DrError(
            f"generation {gen_dir} fails verification "
            f"(missing {verdict['missing']!r}, mismatched "
            f"{verdict['mismatched']!r}); --force restores the intact "
            "files anyway")
    cat = verdict["catalog"]
    intact = [rel for rel in sorted(cat["files"])
              if rel not in damaged]

    if _store_is_empty(store_path):
        # verbatim: link/copy the generation back — byte-identical
        restored = 0
        for rel in intact:
            dst = os.path.join(store_path, rel) \
                if not _is_monolithic(store_path) \
                else store_path
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            _link_or_copy(os.path.join(gen_dir, rel), dst)
            restored += 1
        if not _is_monolithic(store_path):
            _fsync_dir(os.path.join(store_path, "segments"))
            _fsync_dir(store_path)
        if log:
            log(f"restore: {restored} file(s) restored verbatim into "
                f"empty store {store_path}")
        return {"kind": "restore", "mode": "verbatim",
                "generation": gen_dir, "store": store_path,
                "files_restored": restored, "records_merged": None,
                "damaged_skipped": damaged}

    # live store: merge-restore through the commutative record algebra
    if _is_monolithic(store_path):
        src = open_store(os.path.join(gen_dir, "store.json"),
                         quarantine_corrupt=False, _count_metrics=False)
    else:
        # the generation IS a store layout (segments/ + manifest.json)
        src = open_store(gen_dir, quarantine_corrupt=False,
                         _count_metrics=False)
    dest = open_store(store_path)
    n = dest.merge_from(src)
    dest.flush()
    if log:
        log(f"restore: merged {n} snapshot record(s) into live store "
            f"{store_path} (superset-safe)")
    return {"kind": "restore", "mode": "merge",
            "generation": gen_dir, "store": store_path,
            "files_restored": None, "records_merged": n,
            "records_after": len(dest), "damaged_skipped": damaged}


# -- fsck --------------------------------------------------------------------

def _census(directory: str) -> Dict[str, List[str]]:
    """Quarantine/dropping census of one directory (non-recursive)."""
    out: Dict[str, List[str]] = {"quarantined": [], "tmp": []}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if ".corrupt-" in name:
            out["quarantined"].append(name)
        elif name.startswith(".") and not name.startswith(".."):
            out["tmp"].append(name)
    return out


def fsck_store(store_path: str, adopt: bool = False, stamp: bool = False,
               check_backups: bool = True,
               log: Optional[Callable[[str], None]] = None
               ) -> Dict[str, Any]:
    """The deep integrity walk (module docstring).  Read-only by
    default; ``adopt`` indexes orphan segments into the manifest,
    ``stamp`` writes the verdict to ``<store>/fsck.json``."""
    from tenzing_tpu.serve.segments import SegmentedStore
    from tenzing_tpu.serve.store import STORE_VERSION, ScheduleStore

    store_path = os.path.abspath(store_path)
    now = time.time()
    doc: Dict[str, Any] = {"kind": "fsck", "version": FSCK_VERSION,
                           "store": store_path, "checked_at": now,
                           "errors": [], "warnings": []}

    if _is_monolithic(store_path):
        doc["backend"] = "monolithic"
        try:
            with open(store_path) as f:
                raw = json.load(f)
            if raw.get("version") != STORE_VERSION:
                doc["errors"].append(
                    f"store version {raw.get('version')!r} != "
                    f"{STORE_VERSION}")
            elif not isinstance(raw.get("entries"), dict):
                doc["errors"].append("entries is not an object")
        except FileNotFoundError:
            doc["errors"].append("store file is absent")
        except (OSError, ValueError) as e:
            doc["errors"].append(f"unreadable store: {e}")
        store = ScheduleStore(store_path if os.path.exists(store_path)
                              else None, quarantine_corrupt=False,
                              _count_metrics=False, log=log)
        doc["records"] = len(store)
        doc["skipped_records"] = store.skipped
        if store.skipped:
            doc["warnings"].append(
                f"{store.skipped} record(s) failed validation")
        census = _census(os.path.dirname(store_path) or ".")
        doc["quarantine_census"] = [
            n for n in census["quarantined"]
            if n.startswith(os.path.basename(store_path))]
    else:
        doc["backend"] = "segmented"
        if not os.path.isdir(store_path):
            doc["errors"].append("store directory is absent")
            store = None
        else:
            # quarantine_corrupt=False: fsck reports damage, it never
            # moves evidence — re-running it is always safe
            store = SegmentedStore(store_path, quarantine_corrupt=False,
                                   _count_metrics=False, log=log)
        if store is not None:
            doc.update({
                "records": len(store),
                "segments": len(store.segment_info),
                "orphan_segments": list(store.orphan_segments),
                "missing_segments": list(store.missing_segments),
                "newer_segments": list(store.newer_segments),
                "checksum_failed": store.checksum_failed,
                "salvaged": store.salvaged,
                "skipped_records": store.skipped,
                "damaged_segments": sorted(
                    n for n, i in store.segment_info.items()
                    if i.get("damaged")),
                "manifest_ok": store.manifest_doc is not None,
            })
            if store.checksum_failed:
                doc["errors"].append(
                    f"{store.checksum_failed} record(s) failed their "
                    "sha256 (bit flips)")
            if doc["damaged_segments"]:
                doc["errors"].append(
                    f"{len(doc['damaged_segments'])} damaged "
                    "segment(s) (torn/truncated; valid records "
                    "salvaged)")
            if store.missing_segments:
                doc["errors"].append(
                    f"{len(store.missing_segments)} segment(s) listed "
                    "in the manifest but missing on disk")
            if store.manifest_doc is None and store.segment_info:
                doc["warnings"].append(
                    "manifest unreadable/absent; corpus recovered "
                    "from the segment scan")
            if store.orphan_segments:
                doc["warnings"].append(
                    f"{len(store.orphan_segments)} orphan segment(s) "
                    "(published, not indexed)")
            if adopt and store.orphan_segments:
                adopted = {
                    name: dict(store.segment_info[name],
                               source="fsck-adopt", adopted_at=now)
                    for name in store.orphan_segments
                    if name in store.segment_info}

                def mutate(man):
                    for name, meta in adopted.items():
                        man["segments"].setdefault(name, {
                            k: meta.get(k)
                            for k in ("bucket", "records", "bytes",
                                      "source", "adopted_at")})
                    return man

                store._mutate_manifest(mutate)
                doc["adopted_orphans"] = sorted(adopted)
                if log:
                    log(f"fsck: adopted {len(adopted)} orphan "
                        "segment(s) into the manifest")
            seg_census = _census(os.path.join(store_path, "segments"))
            top_census = _census(store_path)
            doc["quarantine_census"] = sorted(
                seg_census["quarantined"] + top_census["quarantined"])
            doc["tmp_droppings"] = sorted(
                seg_census["tmp"] + top_census["tmp"])

    ro = store_readonly(store_path)
    if ro is not None:
        doc["store_readonly"] = ro
        doc["warnings"].append(
            f"store is latched read-only ({ro.get('error')})")

    if check_backups:
        root = backups_root(store_path)
        gens = list_generations(root)
        backups: List[Dict[str, Any]] = []
        for name in gens:
            gd = os.path.join(root, name)
            try:
                cat = load_catalog(gd)
                backups.append({"generation": name, "ok": True,
                                "created_at": cat.get("created_at"),
                                "n_files": cat.get("n_files"),
                                "bytes": cat.get("bytes")})
            except DrError as e:
                backups.append({"generation": name, "ok": False,
                                "error": str(e)})
                doc["warnings"].append(
                    f"backup {name}: unreadable catalog (aborted "
                    "backup?)")
        doc["backups"] = backups

    doc["ok"] = not doc["errors"]
    doc["rc"] = fsck_exit_code(doc)
    if stamp:
        from tenzing_tpu.utils.atomic import atomic_dump_json

        stamp_path = store_path + "." + FSCK_STAMP \
            if _is_monolithic(store_path) \
            else os.path.join(store_path, FSCK_STAMP)
        try:
            atomic_dump_json(stamp_path, doc, prefix=".fsck.")
        except OSError as e:
            doc["warnings"].append(f"fsck stamp not written ({e})")
    return doc


def fsck_exit_code(doc: Dict[str, Any]) -> int:
    """The CI gate: 0 clean, 1 damage found (the store still serves —
    salvage recovered what it could — but someone must look), 2 the
    tree could not be read at all."""
    errors = doc.get("errors") or []
    if any("absent" in e or "unreadable" in e.lower() for e in errors):
        return RC_UNREADABLE
    return RC_DAMAGED if errors else RC_CLEAN
