"""Independent schedule-soundness verification (docs/robustness.md,
"Schedule soundness").

The search stack's correctness story used to rest entirely on the
:class:`~tenzing_tpu.core.event_synchronizer.EventSynchronizer` that *built*
the schedules; this package is the separate pair of eyes: a static
happens-before reconstruction over a complete schedule that proves every
graph data dependency ordered and classifies anything unordered as the
cross-lane RAW/WAR/WAW race it is — wired as a guard into the resilient
measurement stack and all three solvers' accept points, and backing the
driver's final result-integrity gate (``bench.py``: winner re-executed vs
naive, outputs compared, ``verified`` stamped into the JSON).
"""

from tenzing_tpu.verify.soundness import (
    ScheduleVerifier,
    Soundness,
    Violation,
    happens_before_masks,
    project_graph,
    report_unsound,
    verify_schedule,
)

__all__ = [
    "ScheduleVerifier",
    "Soundness",
    "Violation",
    "happens_before_masks",
    "project_graph",
    "report_unsound",
    "verify_schedule",
]
