"""Independent schedule-soundness verification.

The search only ever emits schedules the :class:`EventSynchronizer` declared
legal, and ``remove_redundant_syncs`` then prunes sync ops it proves
removable — so until this module existed, the only thing standing between a
sync-insertion (or pruning) bug and a silently under-synchronized "fastest"
schedule was the very logic being checked.  A data race *benchmarks faster*:
the broken candidate would win.  Collective-synthesis systems treat an
independent checker as table stakes (TACCL / GC3 both pair schedule search
with a separate correctness pass over the synthesized plan; PAPERS.md).

This verifier reconstructs the happens-before relation of a complete
schedule **from scratch**, using only the documented token semantics of the
five sync ops (core/sync_ops.py module table, mirrored by the executor's
token chains in runtime/executor.py) — deliberately *not* reusing any
``EventSynchronizer`` internals:

* **lane program order** — ops bound to the same lane are chained; ops on
  different lanes share no chain unless a sync joins them;
* **host chain** — host ops (CpuOp, Start/Finish) run in program order, and
  every device op is ordered after the host dispatch point (the executor
  joins the host token into each device op — CUDA dispatch semantics);
* **sync edges** — ``EventRecord(lane, e)`` snapshots the lane chain into
  event ``e`` (without advancing the lane: the executor's
  ``record_event`` is a pure snapshot); ``WaitEvent(lane, e)`` /
  ``EventSync(e)`` join the snapshot into the lane / host chain;
  ``LaneSync`` / ``LaneWait`` join whole lane chains into host / another
  lane.

Against that relation it checks, per :func:`verify_schedule`:

1. **every graph data dependency is ordered** — each edge of the evolved
   graph whose endpoints both execute must be happens-before ordered.  The
   evolved graph (compounds expanded, choices resolved to the executed
   alternatives) is reconstructed by :func:`project_graph` from the original
   choice graph plus the executed op names — pure :class:`Graph` surgery,
   no solver state.  A violated edge whose endpoints also conflict on a
   declared buffer is classified as the matching **cross-lane RAW/WAR/WAW
   race** on that resource (``race:raw`` etc.); a violated edge with no
   buffer conflict stays a plain ``dep`` violation.  Buffer-name conflicts
   *outside* the graph relation are deliberately not racy: the graph is the
   ground truth for required ordering (e.g. the six halo unpacks all write
   disjoint regions of ``U`` and are legitimately concurrent).
2. **dangling records/waits and unreachable syncs** — an ``EventRecord``
   nobody consumes, a ``WaitEvent``/``EventSync`` on a never-recorded
   event, and a wait placed *before* its record (which therefore observes
   nothing) are reported as warnings: they do not break ordering by
   themselves (the dependency check decides that) but every one of them is
   sync the redundant-sync pass should have deleted or a corruption
   artifact.
3. **structural integrity** — an executable op of the evolved graph that is
   missing from the schedule, executed twice, or executed unbound is an
   error: such a schedule cannot have come from the synthesizer.

The verdict is a structured :class:`Soundness` with a **minimal witness**
per violation: the earliest unordered (pred, op) pair and, for races, the
conflicting buffer — small enough to paste into a bug report, precise
enough to replay.

Cost: one forward scan builds the chains, one bitset pass closes
reachability (Python ints as bitmasks — O(n·E/64) words), and the graph
projection is cached per structural variant — verifying a ~100-op schedule
is microseconds next to the milliseconds its measurement costs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    ChoiceOp,
    CompoundOp,
    DeviceOp,
    OpBase,
)
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import (
    EventRecord,
    EventSync,
    LaneSync,
    LaneWait,
    SyncOp,
    WaitEvent,
)


@dataclass(frozen=True)
class Violation:
    """One soundness violation: the earliest pair the happens-before
    relation fails to order (``kind``: ``dep`` or ``race:raw``/``race:war``/
    ``race:waw``), or a structural defect (``missing_op``/``duplicate_op``/
    ``unbound_op``)."""

    kind: str
    a: str  # desc of the op that must come first ("" for structural)
    b: str  # desc of the op that must come after / the defective op
    a_pos: int = -1
    b_pos: int = -1
    resource: Optional[str] = None  # conflicting buffer for race:* kinds

    def witness(self) -> str:
        if self.a_pos < 0:
            return f"{self.kind}: {self.b}"
        res = f" on {self.resource!r}" if self.resource else ""
        return (f"{self.kind}{res}: {self.a} [pos {self.a_pos}] not "
                f"happens-before {self.b} [pos {self.b_pos}]")

    def to_json(self) -> dict:
        return {"kind": self.kind, "a": self.a, "b": self.b,
                "a_pos": self.a_pos, "b_pos": self.b_pos,
                "resource": self.resource}


@dataclass
class Soundness:
    """The structured verdict of :func:`verify_schedule`."""

    ok: bool
    violations: List[Violation] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)
    n_ops: int = 0
    n_edges_checked: int = 0

    def witness(self) -> str:
        """The minimal witness: the first (earliest-position) violation."""
        if self.ok:
            return "sound"
        return self.violations[0].witness()

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "violations": [v.to_json() for v in self.violations],
            "warnings": list(self.warnings),
            "n_ops": self.n_ops,
            "n_edges_checked": self.n_edges_checked,
        }


def happens_before_masks(ops: List[OpBase],
                         warnings: Optional[List[str]] = None) -> List[int]:
    """``reach[i]`` = bitmask of positions that happen-before-or-equal
    position ``i``, reconstructed from lane/host program order and the five
    sync ops' token semantics (module docstring).  Every edge points from an
    earlier to a later position, so one forward pass closes the relation."""
    lane_head: Dict[int, int] = {}  # lane id -> last position on its chain
    ev_src: Dict[int, int] = {}  # event id -> position of latest record
    host_head: Optional[int] = None
    reach: List[int] = []

    def w(msg: str) -> None:
        if warnings is not None:
            warnings.append(msg)

    for i, op in enumerate(ops):
        preds: List[Optional[int]] = []
        if isinstance(op, EventRecord):
            # snapshot: event token := lane token; the lane chain itself
            # does not advance (executor record_event)
            preds.append(lane_head.get(op.lane().id))
            ev_src[op.event().id] = i
        elif isinstance(op, WaitEvent):
            src = ev_src.get(op.event().id)
            if src is None:
                w(f"dangling wait: {op.desc()} [pos {i}] waits on an event "
                  "recorded later or never")
            preds.append(src)
            preds.append(lane_head.get(op.lane().id))
            lane_head[op.lane().id] = i
        elif isinstance(op, EventSync):
            src = ev_src.get(op.event().id)
            if src is None:
                w(f"dangling wait: {op.desc()} [pos {i}] syncs an event "
                  "recorded later or never")
            preds.append(src)
            preds.append(host_head)
            host_head = i
        elif isinstance(op, LaneSync):
            preds.append(lane_head.get(op.lane().id))
            preds.append(host_head)
            host_head = i
        elif isinstance(op, LaneWait):
            preds.append(lane_head.get(op.waitee().id))
            preds.append(lane_head.get(op.waiter().id))
            lane_head[op.waiter().id] = i
        elif isinstance(op, BoundDeviceOp):
            # dispatch semantics: a device op joins its lane chain AND the
            # host chain at its dispatch point (runtime/executor.py
            # trace_default: tok_in = join(lane, host))
            preds.append(lane_head.get(op.lane().id))
            preds.append(host_head)
            lane_head[op.lane().id] = i
        else:
            # host op (CpuOp/Start/Finish): host program order only
            preds.append(host_head)
            host_head = i
        m = 1 << i
        for p in preds:
            if p is not None:
                m |= reach[p]
        reach.append(m)

    # dangling records: an event snapshot nobody ever consumes
    consumed = {op.event().id for op in ops
                if isinstance(op, (WaitEvent, EventSync))}
    for i, op in enumerate(ops):
        if isinstance(op, EventRecord) and op.event().id not in consumed:
            w(f"dangling record: {op.desc()} [pos {i}] is never waited on")
    return reach


def _resolved_choice(choice: ChoiceOp, names: frozenset) -> Optional[OpBase]:
    """The alternative of ``choice`` whose (possibly nested) ops were
    executed, found by name — the same name-anchored resolution the serdes
    layer uses, reimplemented over public surfaces only.

    The descent into a compound alternative skips its start/finish
    sentinels: every sub-graph carries the same ``start``/``finish`` NoOp
    names and every executed schedule contains them, so counting them as
    mentions would make EVERY compound alternative match and resolve each
    such choice to its first compound alternative regardless of what
    actually executed (observed as chunked-count misprojection: a
    ``.chunked.c4`` schedule projected as the ``.c2`` expansion, a false
    ``missing_op``)."""

    def mentions(op: OpBase) -> bool:
        if op.name() in names:
            return True
        if isinstance(op, CompoundOp):
            sub = op.graph()
            sentinels = (id(sub.start()), id(sub.finish()))
            return any(mentions(v) for v in sub.vertices()
                       if id(v) not in sentinels)
        if isinstance(op, ChoiceOp):
            return any(mentions(c) for c in op.choices())
        return False

    for c in choice.choices():
        if mentions(c):
            return c
    return None


def project_graph(graph: Graph, names: frozenset) -> Tuple[Graph, List[str]]:
    """The evolved graph a schedule executing ``names`` was built from:
    every CompoundOp inlined, every ChoiceOp replaced by the alternative the
    executed names identify.  Returns (graph, notes) — a choice none of
    whose alternatives was executed is left unresolved and noted (its edges
    then simply contribute no checks)."""
    notes: List[str] = []
    g = graph
    for _ in range(10_000):  # fixed point; bounded defensively
        comps = [v for v in g.vertices() if isinstance(v, CompoundOp)]
        if comps:
            g = g.clone_but_expand(comps[0])
            continue
        choices = [v for v in g.vertices() if isinstance(v, ChoiceOp)]
        progressed = False
        for c in choices:
            pick = _resolved_choice(c, names)
            if pick is not None:
                g = g.clone_but_replace(pick, c)
                progressed = True
                break
            notes.append(
                f"unresolved choice {c.name()!r}: no executed "
                "alternative found")
            # a pruned-out subtree contributes no deps; strip the vertex so
            # the loop terminates
            g = _drop_vertex(g, c)
            progressed = True
            break
        if not progressed:
            return g, notes
    raise RuntimeError("project_graph did not converge")  # pragma: no cover


def _drop_vertex(g: Graph, v: OpBase) -> Graph:
    """Clone ``g`` without vertex ``v`` (predecessors re-wired to
    successors, preserving the transitive relation through the hole)."""
    out = g.clone()
    vv = out.vertex(v)
    preds = list(out.preds_[vv])
    succs = list(out.succs_[vv])
    del out.succs_[vv]
    del out.preds_[vv]
    del out._canon[vv.eq_key()]
    for u in out.succs_:
        out.succs_[u] = [s for s in out.succs_[u] if s != vv]
        out.preds_[u] = [p for p in out.preds_[u] if p != vv]
    for p in preds:
        for s in succs:
            out.then(p, s)
    return out


def _conflict(a: OpBase, b: OpBase) -> Optional[Tuple[str, str]]:
    """(hazard kind, buffer) when ``a`` then ``b`` conflict on a declared
    resource — RAW preferred over WAW over WAR when several apply."""
    ar = set(a.reads() if hasattr(a, "reads") else [])
    aw = set(a.writes() if hasattr(a, "writes") else [])
    br = set(b.reads() if hasattr(b, "reads") else [])
    bw = set(b.writes() if hasattr(b, "writes") else [])
    raw = aw & br
    if raw:
        return "race:raw", sorted(raw)[0]
    waw = aw & bw
    if waw:
        return "race:waw", sorted(waw)[0]
    war = ar & bw
    if war:
        return "race:war", sorted(war)[0]
    return None


def verify_schedule(order: Sequence,
                    graph: Optional[Graph] = None,
                    projection_cache: Optional[Dict] = None) -> Soundness:
    """Verify one complete schedule (see module docstring).  ``graph`` is
    the workload's (choice) graph; without it only the happens-before
    reconstruction, structural checks and dangling-sync warnings run —
    dependency/race checking needs the graph's ground-truth relation.
    ``projection_cache`` (a plain dict, e.g. :class:`ScheduleVerifier`'s)
    memoizes the evolved-graph projection per structural variant — the one
    non-trivial cost, shared by every schedule resolving the same
    choices."""
    ops = list(order)
    warnings: List[str] = []
    violations: List[Violation] = []

    # structural: no unbound device ops, no duplicated executable ops
    pos: Dict[Tuple, int] = {}
    for i, op in enumerate(ops):
        if isinstance(op, DeviceOp) and not isinstance(op, BoundDeviceOp):
            violations.append(Violation(
                kind="unbound_op", a="", b=op.desc(), b_pos=i))
            continue
        if isinstance(op, SyncOp):
            continue
        k = op.eq_key()
        if k in pos:
            violations.append(Violation(
                kind="duplicate_op", a=op.desc(), b=op.desc(),
                a_pos=pos[k], b_pos=i))
        else:
            pos[k] = i

    reach = happens_before_masks(ops, warnings)

    n_edges = 0
    if graph is not None and not violations:
        names = frozenset(op.name() for op in ops
                          if not isinstance(op, SyncOp))
        hit = (projection_cache.get(names)
               if projection_cache is not None else None)
        if hit is None:
            hit = project_graph(graph, names)
            if projection_cache is not None:
                projection_cache[names] = hit
        evolved, notes = hit
        warnings.extend(notes)
        for u in evolved.vertices():
            if isinstance(u, (ChoiceOp, CompoundOp)):
                continue  # unresolved leftovers contribute nothing
            ku = u.eq_key()
            pu = pos.get(ku)
            if pu is None:
                violations.append(Violation(
                    kind="missing_op", a="", b=u.desc()))
                continue
            for v in evolved.succs(u):
                if isinstance(v, (ChoiceOp, CompoundOp)):
                    continue
                pv = pos.get(v.eq_key())
                if pv is None:
                    continue  # reported once as missing_op above/below
                n_edges += 1
                if pu != pv and not (reach[pv] >> pu) & 1:
                    kind, res = "dep", None
                    c = _conflict(ops[pu], ops[pv])
                    if c is not None:
                        kind, res = c
                    violations.append(Violation(
                        kind=kind, a=ops[pu].desc(), b=ops[pv].desc(),
                        a_pos=pu, b_pos=pv, resource=res))

    violations.sort(key=lambda v: (v.b_pos if v.b_pos >= 0 else 1 << 60,
                                   v.a_pos))
    return Soundness(ok=not violations, violations=violations,
                     warnings=warnings, n_ops=len(ops),
                     n_edges_checked=n_edges)


class ScheduleVerifier:
    """The deployable guard: ``verifier(order) -> Soundness`` bound to one
    workload graph, with verdicts cached by schedule identity and graph
    projections cached per structural variant (the expensive part — one
    clone chain per distinct choice resolution, shared by every schedule in
    that variant via :func:`verify_schedule`'s internal projection being
    re-run but the verdict cache making repeats free).

    Non-:class:`~tenzing_tpu.core.sequence.Sequence` orders (e.g. the
    CallableRunner's plain string names) are vacuously sound — there is no
    schedule to check."""

    def __init__(self, graph: Graph):
        self.graph = graph
        self._verdicts: Dict[Tuple, Soundness] = {}
        self._projections: Dict = {}
        self.checked = 0
        self.unsound = 0

    def __call__(self, order) -> Soundness:
        if not isinstance(order, Sequence):
            return Soundness(ok=True)
        from tenzing_tpu.core.sequence import canonical_key

        key = canonical_key(order)
        got = self._verdicts.get(key)
        if got is None:
            got = verify_schedule(order, self.graph,
                                  projection_cache=self._projections)
            self._verdicts[key] = got
            self.checked += 1
            if not got.ok:
                self.unsound += 1
        return got


def report_unsound(where: str, order, verdict: Soundness) -> None:
    """The one ``verify.unsound`` observability emission every guard site
    shares: a counter plus a structured trace event carrying the schedule
    id and the minimal witness."""
    from tenzing_tpu.bench.benchmarker import schedule_id
    from tenzing_tpu.obs.metrics import get_metrics
    from tenzing_tpu.obs.tracer import get_tracer

    get_metrics().counter("verify.unsound").inc()
    tr = get_tracer()
    if tr.enabled:
        tr.event("verify.unsound", where=where, schedule=schedule_id(order),
                 witness=verdict.witness(),
                 n_violations=len(verdict.violations))
