"""Distributed search fleet: sharded solvers + fused measurement rounds.

The ROADMAP's "distribute the search itself" scale-out.  One **measurement
owner** (the driver process — it already owns the compiled executor, the
benchmark stack, and the prefetcher) serves N **search worker** processes:

* Workers run the solvers — hill-climb jobs from the driver's climb
  configs, or MCTS/DFS shards over rank-agreed disjoint subtrees
  (``MctsOpts.subtree`` / ``DfsOpts.subtree``).  A worker never touches
  jax: it rebuilds the choice graph device-free (``driver.graph_for``),
  verifies its own candidates, and measures through a
  :class:`FleetBenchmarker` proxy that speaks a file protocol to the
  owner.
* The owner packs up to K pending candidate requests into ONE fused
  device round — ``EmpiricalBenchmarker.benchmark_batch_times`` with
  per-request ``group_seeds``, so each worker's paired 2-schedule batch
  keeps the exact permutation stream (and therefore the exact accept
  decisions) it would have had measuring alone — and answers every
  request from that round.  ``prefetch`` hints forward to the owner's
  ``PrefetchingBenchmarker``: round i+1's candidates compile in the
  background while round i occupies the device.
* Worker liveness reuses the serve plane's lease protocol
  (``serve/lease.py``): each job is claimed by hard-link, heartbeated by
  mtime, and a SIGKILLed worker's job lease expires so a surviving
  worker re-adopts the subtree (``search.fleet.reclaimed_subtrees``).
  Incumbents and visit statistics exchange through the file-backed
  control plane (``parallel.control_plane.FileControlPlane``) —
  monotonic snapshots and a winner-takes-all claim registry keep
  subtrees *dynamically* disjoint without any blocking rendezvous.

Fleet directory layout (one ``tempfile.mkdtemp`` per run)::

    spec.json            request + bench opts + fleet shape (owner writes)
    jobs/job-<k>.json    one solver job (owner writes)
    jobs/job-<k>.lease   worker's claim, lease-protocol heartbeat
    jobs/job-<k>.done.json  the job's sims/final/wall (worker writes)
    jobs/busy-r<rank>    "this worker is inside a job" marker
    mq/req-r<rank>-<n>.json  measurement request (worker writes)
    mq/res-<id>.json     the answer (owner writes)
    ctrl/                FileControlPlane snapshots + claim registry
    owner.hb             owner heartbeat (workers abort if it goes stale)
    stop                 owner's shutdown flag

``--search-workers 1 --measure-batch 1`` short-circuits to
:func:`run_serialized` — the same jobs executed inline with the exact
legacy ``hill_climb`` invocation (same seeds, same benchmark stack, same
prefer policies), so the backward-compat path is bit-identical to the
pre-fleet climb loop by construction.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult
from tenzing_tpu.core.sequence import canonical_key
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.parallel.control_plane import FileControlPlane
from tenzing_tpu.serve.lease import LeaseFile
from tenzing_tpu.utils.atomic import atomic_dump_json, read_json


def claim_key(seq) -> str:
    """Cross-process claim-registry key of a schedule: a digest of its
    canonical (bijection-equivalence) form — ``eq_key`` tuples are pure
    strings/ints, so the repr is identical in every worker process."""
    return hashlib.sha256(repr(canonical_key(seq)).encode()).hexdigest()[:32]


def _opts_to_json(opts: BenchOpts) -> Dict[str, Any]:
    return {"n_iters": opts.n_iters, "max_retries": opts.max_retries,
            "target_secs": opts.target_secs}


def _opts_from_json(j: Dict[str, Any]) -> BenchOpts:
    return BenchOpts(n_iters=int(j["n_iters"]),
                     max_retries=int(j["max_retries"]),
                     target_secs=float(j["target_secs"]))


def _result_to_json(res: BenchResult) -> Dict[str, Any]:
    return res.to_json()


def _result_from_json(j: Dict[str, Any]) -> BenchResult:
    return BenchResult(
        pct01=j["pct01"], pct10=j["pct10"], pct50=j["pct50"],
        pct90=j["pct90"], pct99=j["pct99"], stddev=j["stddev"],
        times=list(j["times"]) if j.get("times") is not None else None,
        fetch_overhead=j.get("fetch_overhead"))


@dataclass
class FleetJob:
    """One solver job — the unit of lease-claimed, reclaimable work.

    ``prefer`` names a module-level policy in ``bench.driver`` (the
    closures the legacy climb loop used, lifted so a worker process can
    reconstruct them): ``halo_alias`` / ``moe_bf16`` / ``recorded`` (with
    ``chosen``, the recorded winner's suffix menu) / ``generic_xla``.
    ``kind`` selects the solver: ``climb`` (hill_climb, the driver's
    default), ``mcts`` or ``dfs`` (subtree-sharded via ``subtree``)."""

    index: int
    budget: int
    seed: int
    lanes: int = 2
    phases: Tuple[str, ...] = ("",)
    prefer: str = "generic_xla"
    chosen: Optional[Dict[str, str]] = None
    kind: str = "climb"
    subtree: Optional[Tuple[int, int]] = None

    def to_json(self) -> Dict[str, Any]:
        return {"index": self.index, "budget": self.budget,
                "seed": self.seed, "lanes": self.lanes,
                "phases": list(self.phases), "prefer": self.prefer,
                "chosen": self.chosen, "kind": self.kind,
                "subtree": list(self.subtree) if self.subtree else None}

    @staticmethod
    def from_json(j: Dict[str, Any]) -> "FleetJob":
        return FleetJob(
            index=int(j["index"]), budget=int(j["budget"]),
            seed=int(j["seed"]), lanes=int(j.get("lanes", 2)),
            phases=tuple(j.get("phases") or ("",)),
            prefer=j.get("prefer", "generic_xla"),
            chosen=j.get("chosen"), kind=j.get("kind", "climb"),
            subtree=tuple(j["subtree"]) if j.get("subtree") else None)


def resolve_prefer(job: FleetJob):
    """The job's choice-preference policy, reconstructed from its name —
    the same module-level functions the serialized path uses, so worker
    and inline execution agree decision-for-decision."""
    from tenzing_tpu.bench import driver as _driver

    if job.prefer == "halo_alias":
        return _driver.halo_alias_prefer
    if job.prefer == "moe_bf16":
        return _driver.moe_bf16_prefer
    if job.prefer == "recorded":
        return _driver.recorded_prefer(dict(job.chosen or {}))
    return _driver.generic_xla_prefer


@dataclass
class FleetJobResult:
    index: int
    sims: List = field(default_factory=list)      # SimResult entries
    final: Optional[object] = None                # SimResult | None
    wall_s: float = 0.0
    worker: Optional[str] = None
    reclaimed: bool = False
    failed: Optional[str] = None


@dataclass
class FleetResult:
    jobs: List[FleetJobResult] = field(default_factory=list)
    stats: Dict[str, Any] = field(default_factory=dict)

    def sims(self) -> List:
        return [s for jr in self.jobs for s in jr.sims]

    def finals(self) -> List:
        return [jr.final for jr in self.jobs if jr.final is not None]


class SharedSearchState:
    """The worker side of the fleet's incumbent/visit-stat exchange
    (``LocalOpts.shared``): schedule claims through the control plane's
    winner-takes-all registry, incumbent snapshots published on every
    accepted move.  The "allreduce" is monotonic-snapshot: every rank
    eventually reads every other rank's latest, and the min-reduction
    happens in the reader (:meth:`global_best`)."""

    def __init__(self, cp: FileControlPlane):
        self.cp = cp
        self.claimed = 0
        self.claim_misses = 0
        self._best: Optional[float] = None

    def claim(self, seq) -> bool:
        ok = self.cp.claim("visited", claim_key(seq))
        if ok:
            self.claimed += 1
        else:
            self.claim_misses += 1
            get_metrics().counter("search.fleet.claim_misses").inc()
        return ok

    def note_incumbent(self, cost_s: float, seq) -> None:
        if self._best is not None and cost_s >= self._best:
            return
        self._best = cost_s
        from tenzing_tpu.core.serdes import sequence_to_json

        self.cp.publish("incumbent", {
            "cost_s": cost_s, "seq": sequence_to_json(seq),
            "claimed": self.claimed, "claim_misses": self.claim_misses})

    def global_best(self) -> Optional[Tuple[int, float]]:
        """(rank, cost_s) of the best incumbent any rank has published."""
        best = None
        for rank, snap in self.cp.gather("incumbent").items():
            try:
                c = float(snap["cost_s"])
            except (KeyError, TypeError, ValueError):
                continue
            if best is None or c < best[1]:
                best = (rank, c)
        return best


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


class FleetBenchmarker:
    """The worker's benchmarker: every ``benchmark`` /
    ``benchmark_batch_times`` / ``prefetch`` call becomes a request file
    the measurement owner answers.  Exposes exactly the protocol surface
    the solvers probe for (``hill_climb`` finds
    ``benchmark_batch_times`` by getattr; ``LocalOpts.prefetch`` needs
    ``.prefetch``), so a worker-side solver runs unmodified."""

    def __init__(self, fleet_dir: str, rank: int, graph,
                 timeout_secs: float = 900.0,
                 owner_stale_secs: float = 60.0):
        self.dir = fleet_dir
        self.rank = int(rank)
        self.graph = graph
        self.timeout_secs = timeout_secs
        self.owner_stale_secs = owner_stale_secs
        self._n = 0

    def _submit(self, kind: str, orders, opts: Optional[BenchOpts],
                seed: int) -> str:
        from tenzing_tpu.core.serdes import sequence_to_json

        self._n += 1
        rid = f"r{self.rank}-{self._n}"
        atomic_dump_json(
            os.path.join(self.dir, "mq", f"req-{rid}.json"),
            {"id": rid, "kind": kind,
             "orders": [sequence_to_json(o) for o in orders],
             "seed": int(seed),
             "opts": _opts_to_json(opts if opts is not None else BenchOpts())})
        return rid

    def _await(self, rid: str) -> Dict[str, Any]:
        res_path = os.path.join(self.dir, "mq", f"res-{rid}.json")
        hb = os.path.join(self.dir, "owner.hb")
        deadline = time.time() + self.timeout_secs
        while True:
            if os.path.exists(res_path):
                out = read_json(res_path)
                try:
                    os.unlink(res_path)
                except OSError:
                    pass
                err = out.get("error")
                if err is not None:
                    self._raise(err)
                return out
            if os.path.exists(os.path.join(self.dir, "stop")):
                raise RuntimeError("fleet owner requested stop mid-request")
            try:
                stale = time.time() - os.path.getmtime(hb)
            except OSError:
                stale = 0.0
            if stale > self.owner_stale_secs:
                raise RuntimeError(
                    f"fleet owner heartbeat stale ({stale:.0f}s) — "
                    "measurement owner presumed dead")
            if time.time() > deadline:
                raise RuntimeError(f"fleet measurement request {rid} timed "
                                   f"out after {self.timeout_secs:.0f}s")
            time.sleep(0.005)

    @staticmethod
    def _raise(err: Dict[str, Any]):
        from tenzing_tpu.fault.errors import DeviceLostError

        msg = f"[owner] {err.get('type', '?')}: {err.get('msg', '')}"
        if err.get("class") == "device_lost":
            raise DeviceLostError(msg)
        raise RuntimeError(msg)

    # -- the benchmarker protocol -------------------------------------------
    def benchmark(self, order, opts: Optional[BenchOpts] = None) -> BenchResult:
        rid = self._submit("single", [order], opts, 0)
        return _result_from_json(self._await(rid)["result"])

    def benchmark_batch_times(self, orders, opts: Optional[BenchOpts] = None,
                              seed: int = 0, times_out=None):
        rid = self._submit("batch", orders, opts, seed)
        times = [list(ts) for ts in self._await(rid)["times"]]
        if times_out is not None:
            for dst, src in zip(times_out, times):
                dst.clear()
                dst.extend(src)
            return times_out
        return times

    def prefetch(self, orders) -> int:
        """Fire-and-forget compile hints — the owner forwards them to its
        ``PrefetchingBenchmarker`` so the *next* round's candidates
        compile while the current round holds the device."""
        orders = [o for o in orders]
        if orders:
            self._submit("hint", orders, None, 0)
        return len(orders)


def _renewer(lease: LeaseFile, stop: threading.Event,
             lost: threading.Event, period: float) -> threading.Thread:
    def loop():
        while not stop.wait(period):
            if not lease.renew():
                lost.set()
                return

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


def _run_job(job: FleetJob, graph, proxy: FleetBenchmarker,
             shared: SharedSearchState, opts: BenchOpts, verify: bool):
    """Execute one solver job against the proxy; returns (sims, final)."""
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.verify import ScheduleVerifier

    platform = Platform.make_n_lanes(job.lanes)
    verifier = ScheduleVerifier(graph) if verify else None
    if job.kind == "mcts":
        from tenzing_tpu.solve.mcts.mcts import MctsOpts, explore

        r = explore(graph, platform, proxy,
                    MctsOpts(n_iters=job.budget, bench_opts=opts,
                             seed=job.seed, verify=verifier,
                             subtree=job.subtree, prefetch=proxy))
        return r.sims, r.best()
    if job.kind == "dfs":
        from tenzing_tpu.solve.dfs import DfsOpts, explore

        r = explore(graph, platform, proxy,
                    DfsOpts(max_seqs=job.budget, bench_opts=opts,
                            batch=True, batch_seed=job.seed,
                            verify=verifier, subtree=job.subtree))
        return r.sims, r.best()
    from tenzing_tpu.solve.local import LocalOpts, hill_climb

    r = hill_climb(
        graph, platform, proxy, job.phases, prefer=resolve_prefer(job),
        opts=LocalOpts(budget=job.budget, bench_opts=opts, seed=job.seed,
                       paired=True, verify=verifier, prefetch=proxy,
                       shared=shared))
    return r.sims, r.final


def worker_main(fleet_dir: str, rank: int) -> int:
    """The worker process: claim jobs by lease (adopting expired rivals'),
    run the solver against the measurement proxy, publish incumbents, and
    write each job's ``done`` doc.  Returns a process exit code."""
    from tenzing_tpu.core.serdes import sequence_to_json

    spec = read_json(os.path.join(fleet_dir, "spec.json"))
    from tenzing_tpu.bench.driver import DriverRequest, graph_for

    graph, _ = graph_for(DriverRequest(**spec["request"]))
    opts = _opts_from_json(spec["bench_opts"])
    ttl = float(spec.get("lease_ttl", 15.0))
    wid = f"worker-r{rank}"
    jobs = [FleetJob.from_json(read_json(p)) for p in sorted(
        os.path.join(fleet_dir, "jobs", n)
        for n in os.listdir(os.path.join(fleet_dir, "jobs"))
        if n.startswith("job-") and n.endswith(".json")
        and ".done." not in n)]
    proxy = FleetBenchmarker(fleet_dir, rank, graph)
    cp = FileControlPlane(os.path.join(fleet_dir, "ctrl"), rank,
                          int(spec.get("n_workers", 1)))
    shared = SharedSearchState(cp)
    busy_marker = os.path.join(fleet_dir, "jobs", f"busy-r{rank}")

    def done_path(j: FleetJob) -> str:
        return os.path.join(fleet_dir, "jobs", f"job-{j.index}.done.json")

    def stopped() -> bool:
        return os.path.exists(os.path.join(fleet_dir, "stop"))

    ran = 0
    while not stopped():
        claimed = None
        for j in jobs:
            if os.path.exists(done_path(j)):
                continue
            lease = LeaseFile(
                os.path.join(fleet_dir, "jobs", f"job-{j.index}.lease"),
                owner=wid, ttl_secs=ttl)
            info = lease.claim()
            if info is not None:
                claimed = (j, lease, info)
                break
        if claimed is None:
            if all(os.path.exists(done_path(j)) for j in jobs):
                break
            # every remaining job is leased by a live rival: wait for it
            # to finish — or for its lease to expire so we can adopt it
            time.sleep(min(1.0, ttl / 4))
            continue
        j, lease, info = claimed
        if info.reclaimed:
            sys.stderr.write(
                f"fleet {wid}: adopted job {j.index} from "
                f"{info.prev_owner} (lease {info.age_s}s stale)\n")
        with open(busy_marker, "w") as f:
            f.write(str(j.index))
        stop_renew, lost = threading.Event(), threading.Event()
        _renewer(lease, stop_renew, lost, max(0.2, ttl / 3))
        t0 = time.time()
        doc: Dict[str, Any] = {
            "index": j.index, "worker": wid,
            "reclaimed": bool(info.reclaimed)}
        try:
            sims, final = _run_job(j, graph, proxy, shared, opts,
                                   verify=bool(spec.get("verify", True)))
            doc["sims"] = [
                {"seq": sequence_to_json(s.order),
                 "result": _result_to_json(s.result)} for s in sims]
            doc["final"] = (
                {"seq": sequence_to_json(final.order),
                 "result": _result_to_json(final.result)}
                if final is not None else None)
            ran += 1
        except BaseException as e:  # a failed job must not stall the fleet
            doc["failed"] = f"{type(e).__name__}: {str(e)[:300]}"
            sys.stderr.write(f"fleet {wid}: job {j.index} failed "
                             f"({doc['failed']})\n")
        finally:
            stop_renew.set()
            doc["wall_s"] = round(time.time() - t0, 3)
            try:
                os.unlink(busy_marker)
            except OSError:
                pass
        if lost.is_set() or not lease.owns():
            # a rival adopted this job during a stall: its (deterministic,
            # same-seed) result supersedes ours — do not double-publish
            sys.stderr.write(
                f"fleet {wid}: lost job {j.index} lease mid-run; "
                "dropping result\n")
            continue
        atomic_dump_json(done_path(j), doc)
        lease.release()
    return 0


# ---------------------------------------------------------------------------
# owner side
# ---------------------------------------------------------------------------


@dataclass
class _Pending:
    rid: str
    orders: List
    seed: int
    opts_key: Tuple
    opts: BenchOpts
    at: float


class MeasureOwner:
    """The measurement owner's serve loop: drain worker requests, fuse up
    to ``measure_batch`` candidate orders into one grouped device round,
    answer each request, forward prefetch hints — and keep the fleet's
    ``search.fleet.*`` counters honest."""

    def __init__(self, fleet_dir: str, graph, bench, measure_batch: int,
                 prefetcher=None, grace_secs: float = 0.75, log=None):
        self.dir = fleet_dir
        self.graph = graph
        self.bench = bench
        self.k = max(1, int(measure_batch))
        self.prefetcher = prefetcher
        self.grace = grace_secs
        self.log = log or (lambda m: sys.stderr.write(m + "\n"))
        # batch resolution, exactly hill_climb's probe: the caching layer
        # does not forward the batch protocol, its .inner (journaling ->
        # resilient -> ... -> empirical) does
        self.batcher = getattr(bench, "benchmark_batch_times", None)
        if self.batcher is None:
            inner = getattr(bench, "inner", None)
            self.batcher = getattr(inner, "benchmark_batch_times", None)
        if self.batcher is None:
            raise RuntimeError(
                "fleet owner needs a benchmark stack exposing "
                "benchmark_batch_times")
        self.rounds = 0
        self.fused_orders = 0
        self.singles = 0
        self.hints = 0
        self._queue: List[_Pending] = []

    # -- protocol plumbing ---------------------------------------------------
    def _respond(self, rid: str, doc: Dict[str, Any]) -> None:
        atomic_dump_json(os.path.join(self.dir, "mq", f"res-{rid}.json"), doc)

    def _error_doc(self, e: BaseException) -> Dict[str, Any]:
        from tenzing_tpu.fault.errors import classify_error

        return {"error": {"type": type(e).__name__,
                          "class": classify_error(e),
                          "msg": str(e)[:300]}}

    def heartbeat(self) -> None:
        hb = os.path.join(self.dir, "owner.hb")
        with open(hb, "w") as f:
            f.write(str(os.getpid()))

    def drain(self, busy_workers: int) -> None:
        """One serve tick: ingest new requests (hints and singles answered
        immediately — a single is a worker's blocking incumbent measure),
        then fire a fused round if the packing rule says so."""
        from tenzing_tpu.core.serdes import sequence_from_json

        mq = os.path.join(self.dir, "mq")
        try:
            names = sorted(n for n in os.listdir(mq)
                           if n.startswith("req-"))
        except OSError:
            names = []
        for name in names:
            path = os.path.join(mq, name)
            try:
                req = read_json(path)
            except (OSError, ValueError):
                continue
            try:
                os.unlink(path)
            except OSError:
                pass
            try:
                orders = [sequence_from_json(oj, self.graph)
                          for oj in req["orders"]]
            except Exception as e:
                self._respond(req.get("id", name), self._error_doc(e))
                continue
            kind = req.get("kind", "batch")
            if kind == "hint":
                self.hints += len(orders)
                get_metrics().counter("search.fleet.hints").inc(len(orders))
                if self.prefetcher is not None:
                    self.prefetcher.prefetch(orders)
                continue
            opts = _opts_from_json(req["opts"])
            if kind == "single":
                self.singles += 1
                get_metrics().counter("search.fleet.singles").inc()
                try:
                    res = self.bench.benchmark(orders[0], opts)
                    self._respond(req["id"], {"result": _result_to_json(res)})
                except BaseException as e:
                    self._respond(req["id"], self._error_doc(e))
                    self._check_fatal(e)
                continue
            self._queue.append(_Pending(
                rid=req["id"], orders=orders, seed=int(req.get("seed", 0)),
                opts_key=(opts.n_iters, opts.max_retries, opts.target_secs),
                opts=opts, at=time.time()))
        self._maybe_fire(busy_workers)

    def _maybe_fire(self, busy_workers: int) -> None:
        if not self._queue:
            return
        # pack arrival-order requests sharing one fidelity (opts) until the
        # round holds K orders; a single oversized request rides alone
        head_key = self._queue[0].opts_key
        packed: List[_Pending] = []
        orders_n = 0
        for p in self._queue:
            if p.opts_key != head_key:
                continue
            if packed and orders_n + len(p.orders) > self.k:
                break
            packed.append(p)
            orders_n += len(p.orders)
            if orders_n >= self.k:
                break
        oldest = min(p.at for p in packed)
        # fire when the round is full, every busy worker has a request
        # pending (nothing more can arrive until we answer), or the oldest
        # request has waited out the grace window
        if (orders_n < self.k and len(packed) < max(1, busy_workers)
                and time.time() - oldest < self.grace):
            return
        for p in packed:
            self._queue.remove(p)
        all_orders = [o for p in packed for o in p.orders]
        group_seeds = [(len(p.orders), p.seed) for p in packed]
        self.rounds += 1
        self.fused_orders += len(all_orders)
        reg = get_metrics()
        reg.counter("search.fleet.rounds").inc()
        reg.counter("search.fleet.fused_orders").inc(len(all_orders))
        reg.gauge("search.fleet.batch_occupancy").set(self.occupancy())
        tr = get_tracer()
        if tr.enabled:
            tr.event("fleet.round", n_requests=len(packed),
                     n_orders=len(all_orders), k=self.k)
        try:
            times = self.batcher(all_orders, packed[0].opts,
                                 seed=packed[0].seed,
                                 group_seeds=group_seeds)
        except BaseException as e:
            for p in packed:
                self._respond(p.rid, self._error_doc(e))
            self._check_fatal(e)
            return
        off = 0
        for p in packed:
            self._respond(p.rid, {
                "times": [list(ts)
                          for ts in times[off:off + len(p.orders)]]})
            off += len(p.orders)

    def _check_fatal(self, e: BaseException) -> None:
        from tenzing_tpu.fault.errors import DeviceLostError

        if isinstance(e, (KeyboardInterrupt, SystemExit, DeviceLostError)):
            raise e

    def occupancy(self) -> float:
        return (self.fused_orders / (self.rounds * self.k)
                if self.rounds else 0.0)


def _spawn_worker(fleet_dir: str, rank: int) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "tenzing_tpu.search.fleet",
         fleet_dir, str(rank)],
        stdout=sys.stderr, stderr=sys.stderr)


def _load_done(fleet_dir: str, graph, jobs: List[FleetJob]
               ) -> List[FleetJobResult]:
    from tenzing_tpu.core.serdes import sequence_from_json
    from tenzing_tpu.solve.mcts.mcts import SimResult

    def sim_of(sj):
        return SimResult(order=sequence_from_json(sj["seq"], graph),
                         result=_result_from_json(sj["result"]))

    out = []
    for j in jobs:
        path = os.path.join(fleet_dir, "jobs", f"job-{j.index}.done.json")
        jr = FleetJobResult(index=j.index)
        try:
            doc = read_json(path)
        except (OSError, ValueError):
            jr.failed = "no result (worker never completed the job)"
            out.append(jr)
            continue
        jr.worker = doc.get("worker")
        jr.reclaimed = bool(doc.get("reclaimed"))
        jr.wall_s = float(doc.get("wall_s", 0.0))
        jr.failed = doc.get("failed")
        if jr.failed is None:
            jr.sims = [sim_of(sj) for sj in doc.get("sims", [])]
            if doc.get("final") is not None:
                jr.final = sim_of(doc["final"])
        out.append(jr)
    return out


def run_fleet(graph, request_json: Dict[str, Any], jobs: List[FleetJob],
              bench, opts: BenchOpts, n_workers: int, measure_batch: int,
              prefetcher=None, verify: bool = True,
              fleet_dir: Optional[str] = None, lease_ttl: float = 15.0,
              grace_secs: float = 0.75, max_restarts: int = 2,
              log=None) -> FleetResult:
    """Drive ``jobs`` across ``n_workers`` subprocess solvers with this
    process as the measurement owner; blocks until every job has a done
    doc (or the fleet is irrecoverably dead) and returns the merged
    results + the ``perf.distributed`` stats block."""
    log = log or (lambda m: sys.stderr.write(m + "\n"))
    own_dir = fleet_dir is None
    fleet_dir = fleet_dir or tempfile.mkdtemp(prefix="tenzing-fleet-")
    for sub in ("jobs", "mq", "ctrl"):
        os.makedirs(os.path.join(fleet_dir, sub), exist_ok=True)
    atomic_dump_json(os.path.join(fleet_dir, "spec.json"), {
        "request": request_json, "bench_opts": _opts_to_json(opts),
        "n_workers": int(n_workers), "measure_batch": int(measure_batch),
        "lease_ttl": lease_ttl, "verify": bool(verify)})
    for j in jobs:
        atomic_dump_json(
            os.path.join(fleet_dir, "jobs", f"job-{j.index}.json"),
            j.to_json())
    owner = MeasureOwner(fleet_dir, graph, bench, measure_batch,
                         prefetcher=prefetcher, grace_secs=grace_secs,
                         log=log)
    owner.heartbeat()
    t0 = time.time()
    procs: Dict[int, subprocess.Popen] = {
        r: _spawn_worker(fleet_dir, r) for r in range(n_workers)}
    restarts = 0
    worker_exits = 0

    def all_done() -> bool:
        return all(os.path.exists(os.path.join(
            fleet_dir, "jobs", f"job-{j.index}.done.json")) for j in jobs)

    def busy_workers() -> int:
        live = {r for r, p in procs.items() if p.poll() is None}
        n = 0
        try:
            for name in os.listdir(os.path.join(fleet_dir, "jobs")):
                if name.startswith("busy-r"):
                    try:
                        if int(name[6:]) in live:
                            n += 1
                    except ValueError:
                        pass
        except OSError:
            pass
        return n

    last_hb = 0.0
    try:
        while not all_done():
            now = time.time()
            if now - last_hb > 1.0:
                owner.heartbeat()
                last_hb = now
            live = [r for r, p in procs.items() if p.poll() is None]
            for r, p in list(procs.items()):
                rc = p.poll()
                if rc is not None and rc != 0:
                    worker_exits += 1
                    del procs[r]
            if not live and not all_done():
                if restarts >= max_restarts:
                    log("fleet: no live workers and restart budget "
                        "exhausted — finishing with partial results")
                    break
                restarts += 1
                log(f"fleet: all workers dead with jobs remaining — "
                    f"restart {restarts}/{max_restarts}")
                r = max(procs.keys(), default=-1) + 1 + n_workers
                procs[r] = _spawn_worker(fleet_dir, r)
            owner.drain(busy_workers())
            time.sleep(0.005)
        owner.drain(busy_workers())  # answer any final in-flight requests
    finally:
        with open(os.path.join(fleet_dir, "stop"), "w") as f:
            f.write("done")
        deadline = time.time() + 10.0
        for p in procs.values():
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.02)
            if p.poll() is None:
                p.kill()
    wall = time.time() - t0
    job_results = _load_done(fleet_dir, graph, jobs)
    reclaimed = sum(1 for jr in job_results if jr.reclaimed)
    get_metrics().counter("search.fleet.reclaimed_subtrees").inc(reclaimed)
    cp = FileControlPlane(os.path.join(fleet_dir, "ctrl"), -1,
                          n_workers)
    incumbents = {r: snap.get("cost_s")
                  for r, snap in cp.gather("incumbent").items()}
    candidates = sum(len(jr.sims) for jr in job_results)
    distinct, best = _coverage(job_results)
    stats = {
        "workers": int(n_workers),
        "measure_batch": owner.k,
        "jobs": len(jobs),
        "failed_jobs": sum(1 for jr in job_results if jr.failed),
        "wall_s": round(wall, 3),
        "candidates": candidates,
        "distinct_candidates": distinct,
        "best_cost_us": best,
        "candidates_per_s": round(candidates / wall, 3) if wall else 0.0,
        "rounds": owner.rounds,
        "singles": owner.singles,
        "hints": owner.hints,
        "batch_occupancy": round(owner.occupancy(), 3),
        "reclaimed_subtrees": reclaimed,
        "worker_exits": worker_exits,
        "worker_restarts": restarts,
        "claimed_keys": cp.claim_count("visited"),
        "job_wall_s": [jr.wall_s for jr in job_results],
        "scaling_factor": (
            round(sum(jr.wall_s for jr in job_results) / wall, 2)
            if wall else 0.0),
        "incumbent_costs_s": incumbents,
    }
    if own_dir:
        import shutil

        shutil.rmtree(fleet_dir, ignore_errors=True)
    return FleetResult(jobs=job_results, stats=stats)


def _coverage(job_results: List[FleetJobResult]):
    """(distinct canonical candidates measured, best pct50 in us) across
    every job's sims — the equal-coverage numbers the BENCH comparison
    between serialized and fused runs is normalized against (the
    serialized path re-measures cross-job duplicate neighbors; the fleet's
    claim registry measures each distinct candidate once)."""
    keys = set()
    best = None
    for jr in job_results:
        for s in jr.sims:
            keys.add(claim_key(s.order))
            if best is None or s.result.pct50 < best:
                best = s.result.pct50
    return len(keys), (round(best * 1e6, 3) if best is not None else None)


def run_serialized(graph, jobs: List[FleetJob], bench, opts: BenchOpts,
                   surrogate=None, ckpt=None, verifier=None,
                   prefetcher=None) -> FleetResult:
    """The ``--search-workers 1 --measure-batch 1`` backward-compat path:
    the same jobs executed inline, one ``hill_climb`` per job with the
    exact legacy invocation (same benchmark stack, prescreen, checkpoint,
    verifier, prefetcher and seeds as the pre-fleet climb loop) — bit-
    identical incumbents by construction, and the serialized wall-clock
    baseline the BENCH doc compares fused rounds against."""
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.solve.local import LocalOpts, hill_climb

    out = FleetResult()
    t_all = time.time()
    for j in jobs:
        t0 = time.time()
        jr = FleetJobResult(index=j.index, worker="inline")
        try:
            r = hill_climb(
                graph, Platform.make_n_lanes(j.lanes), bench, j.phases,
                prefer=resolve_prefer(j),
                opts=LocalOpts(budget=j.budget, bench_opts=opts,
                               seed=j.seed, paired=True,
                               prescreen=surrogate, checkpoint=ckpt,
                               verify=verifier, prefetch=prefetcher))
            jr.sims, jr.final = r.sims, r.final
        except RuntimeError as e:
            jr.failed = f"{type(e).__name__}: {str(e)[:300]}"
        jr.wall_s = round(time.time() - t0, 3)
        out.jobs.append(jr)
    wall = time.time() - t_all
    candidates = sum(len(jr.sims) for jr in out.jobs)
    distinct, best = _coverage(out.jobs)
    out.stats = {
        "workers": 1, "measure_batch": 1, "jobs": len(jobs),
        "failed_jobs": sum(1 for jr in out.jobs if jr.failed),
        "wall_s": round(wall, 3),
        "candidates": candidates,
        "distinct_candidates": distinct,
        "best_cost_us": best,
        "candidates_per_s": round(candidates / wall, 3) if wall else 0.0,
        "rounds": 0, "singles": 0, "hints": 0,
        "batch_occupancy": None, "reclaimed_subtrees": 0,
        "worker_exits": 0, "worker_restarts": 0,
        "job_wall_s": [jr.wall_s for jr in out.jobs],
        "scaling_factor": 1.0,
        "incumbent_costs_s": {},
    }
    return out


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(worker_main(sys.argv[1], int(sys.argv[2])))
