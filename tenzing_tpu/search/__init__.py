"""Distributed search: a fleet of solver processes over one device owner.

``search.fleet`` scales the *search* itself — N worker processes run
hill-climb/MCTS/DFS over disjoint subtrees and submit candidates to a
single measurement owner that fuses K schedules per device round
(``EmpiricalBenchmarker.benchmark_batch_times`` group seeds) — the
ROADMAP's "distribute the search itself" scale-out, driven from
``bench/driver.py`` behind ``--search-workers N --measure-batch K``.
"""
