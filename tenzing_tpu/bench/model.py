"""Analytic cost-model benchmarker: device-free schedule quality.

VERDICT r4 item 5: on the virtual CPU mesh, wall-clock is meaningless, so
multi-chip schedule quality was only ever validated for *numerics*.  This
module adds the missing yardstick — a deterministic machine model that maps a
schedule to a modeled makespan, usable anywhere a Benchmarker is (DFS, MCTS,
hill-climb, CsvBenchmarker precedent: the reference searches entirely offline
against recorded timings, benchmarker.cpp:169-223; this is the same idea with
a roofline cost model instead of a recording).

Machine model (the executor's token-lane semantics, abstracted):

* Each ``Lane`` is a serial queue with its own clock (the executor's
  token-lane encoding, runtime/executor.py).
* ``BoundDeviceOp``: starts at max(lane clock, readiness of every buffer in
  ``op.reads()``); runs for its modeled duration (HBM roofline: bytes moved /
  ``hbm_bw``, plus ``flop_time`` when the op declares FLOPs via
  ``cost_flops()``); its writes become ready at completion.
* Transfer posts (``CommStart`` subclasses, Rdma ops): occupy a serial
  *engine* queue — ``"ici"`` for permute/all-to-all/psum/rdma (per-hop
  latency + bytes/``ici_bw``), ``"pcie"`` for host spill/fetch — starting at
  max(engine clock, source readiness).  They do NOT block any lane: posting
  is free, which is exactly the overlap freedom the search exploits.
* ``AwaitTransfer``/``MultiAwait``: host-blocking join — every lane clock
  advances to the awaited buffer's readiness (the fully-synchronous naive
  discipline pays for this; post-all-await-late schedules don't).
* Sync ops: ``EventRecord`` stamps, ``WaitEvent`` joins, ``LaneWait`` joins
  two lanes, ``LaneSync``/``EventSync`` join the host (all lanes observe).

Costs derive from buffer byte sizes (a ``{name: nbytes}`` map, typically
built from the actual buffer dict) — per-op special-casing lives in the
``cost_fn`` hook, not here.  Defaults are TPU v5e single-chip figures
(819 GB/s HBM, 197 TFLOP/s bf16 — bench/roofline.py) with a v5p-class
90 GB/s/link ICI, 1 us hop latency, and a 30 GB/s PCIe-class host path;
override via ``ModelEnv`` for other generations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult
from tenzing_tpu.core.operation import BoundDeviceOp
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import (
    EventRecord,
    EventSync,
    LaneSync,
    LaneWait,
    WaitEvent,
)


# Engine classification of transfer-post op kinds — shared with the learned
# surrogate's featurizer (learn/features.py), which must bucket comm bytes by
# the SAME engine the analytic model queues them on.
ICI_KINDS = ("permute_start", "all_to_all_start", "psum_start",
             "rdma_copy_start", "rdma_shift_start")
PCIE_KINDS = ("host_spill_start", "host_fetch_start")


@dataclass(frozen=True)
class ModelEnv:
    """Machine parameters of the analytic model."""

    hbm_bw: float = 819e9  # bytes/s on-device (v5e HBM, bench/roofline.py)
    ici_bw: float = 90e9  # bytes/s per ICI link (v5p-class, public spec)
    ici_lat: float = 1e-6  # per-hop post latency
    pcie_bw: float = 30e9  # host staging path
    op_overhead: float = 2e-6  # fixed dispatch cost per device op
    flops_peak: float = 197e12  # bf16 MXU peak (bench/roofline.py)


class AnalyticBenchmarker:
    """Deterministic modeled makespan of a schedule (drop-in Benchmarker).

    ``nbytes``: buffer name -> byte size (readiness/transfer costing).
    ``cost_fn`` (optional): ``op -> seconds | None`` — return a duration to
    override the default roofline estimate for that op, or None to fall
    through.
    """

    def __init__(self, nbytes: Dict[str, int], env: Optional[ModelEnv] = None,
                 cost_fn: Optional[Callable] = None):
        self.nbytes = dict(nbytes)
        self.env = env if env is not None else ModelEnv()
        self.cost_fn = cost_fn

    # -- op classification ------------------------------------------------

    @staticmethod
    def _io(op, which: str):
        fn = getattr(op, which, None)
        return list(fn()) if callable(fn) else []

    def _bytes_of(self, names) -> float:
        return float(sum(self.nbytes.get(n, 0) for n in names))

    def _device_duration(self, op) -> float:
        if self.cost_fn is not None:
            got = self.cost_fn(op)
            if got is not None:
                return got
        env = self.env
        moved = self._bytes_of(self._io(op, "reads")) + self._bytes_of(
            self._io(op, "writes"))
        t = env.op_overhead + moved / env.hbm_bw
        flops = getattr(op, "cost_flops", None)
        if callable(flops):
            t += flops() / env.flops_peak
        return t

    def _transfer(self, op):
        """(engine, duration) for a transfer-post op, else None."""
        kind = getattr(op, "KIND", "")
        env = self.env
        src = self._io(op, "reads")
        size = self._bytes_of(src)
        if kind in PCIE_KINDS:
            return "pcie", size / env.pcie_bw
        if kind in ICI_KINDS:
            # psum/all_to_all move ~one full buffer per hop in a ring model;
            # a single modeled hop keeps the model simple and monotone
            return "ici", env.ici_lat + size / env.ici_bw
        return None

    # -- simulation -------------------------------------------------------

    def makespan(self, order: Sequence) -> float:
        lane_t: Dict[int, float] = {}
        event_t: Dict[int, float] = {}
        engine_t: Dict[str, float] = {}
        ready: Dict[str, float] = {}

        def all_join(t: float) -> None:
            for k in lane_t:
                lane_t[k] = max(lane_t[k], t)

        host_t = 0.0
        for op in order:
            if isinstance(op, EventRecord):
                event_t[op.event().id] = lane_t.get(op.lane().id, 0.0)
            elif isinstance(op, WaitEvent):
                lid = op.lane().id
                lane_t[lid] = max(lane_t.get(lid, 0.0),
                                  event_t.get(op.event().id, 0.0))
            elif isinstance(op, LaneWait):
                w = op.waiter().id
                lane_t[w] = max(lane_t.get(w, 0.0),
                                lane_t.get(op.waitee().id, 0.0))
            elif isinstance(op, LaneSync):
                host_t = max(host_t, lane_t.get(op.lane().id, 0.0))
                all_join(host_t)
            elif isinstance(op, EventSync):
                host_t = max(host_t, event_t.get(op.event().id, 0.0))
                all_join(host_t)
            elif isinstance(op, BoundDeviceOp):
                lid = op.lane().id
                start = max(
                    lane_t.get(lid, 0.0),
                    max((ready.get(n, 0.0)
                         for n in self._io(op, "reads")), default=0.0),
                )
                end = start + self._device_duration(op)
                lane_t[lid] = end
                for n in self._io(op, "writes"):
                    ready[n] = end
            else:
                kind = getattr(op, "KIND", "")
                xfer = self._transfer(op)
                if xfer is not None:
                    eng, dur = xfer
                    start = max(
                        engine_t.get(eng, 0.0),
                        max((ready.get(n, 0.0)
                             for n in self._io(op, "reads")), default=0.0),
                    )
                    end = start + dur
                    engine_t[eng] = end
                    for n in self._io(op, "writes"):
                        ready[n] = end
                elif kind in ("await_transfer", "multi_await"):
                    t = max((ready.get(n, 0.0)
                             for n in self._io(op, "reads")), default=0.0)
                    host_t = max(host_t, t)
                    all_join(host_t)
                elif kind not in ("start", "finish", "noop") and (
                        self._io(op, "reads") or self._io(op, "writes")):
                    # any other data-carrying host op: host-serial
                    t = max(
                        host_t,
                        max((ready.get(n, 0.0)
                             for n in self._io(op, "reads")), default=0.0),
                    ) + self._device_duration(op)
                    host_t = t
                    for n in self._io(op, "writes"):
                        ready[n] = t
                # Start/Finish/NoOp and io-less ops: no cost
        tail = [host_t]
        tail += list(lane_t.values())
        tail += list(engine_t.values())
        tail += list(ready.values())
        return max(tail)

    def benchmark(self, order: Sequence,
                  opts: Optional[BenchOpts] = None) -> BenchResult:
        return BenchResult.from_times([self.makespan(order)])
