"""Empirical and recorded benchmarking of candidate schedules.

Parity target: reference ``include/tenzing/benchmarker.hpp`` /
``src/benchmarker.cpp``:

* ``Benchmark.Result`` = percentiles 01/10/50/90/99 + stddev of per-iteration
  wall time (benchmarker.hpp:14-22).
* ``EmpiricalBenchmarker`` — adaptive inner loop grows samples-per-measurement
  until one measurement takes >= 10 ms (benchmarker.cpp:83-119); barrier before,
  wall-clock around the loop, **max across hosts** (benchmarker.cpp:101,145);
  nIters measurements; reject the whole set if the runs-test flags non-random
  structure and retry up to maxRetries (benchmarker.cpp:129-155).
* ``CsvBenchmarker`` — replays a recorded ``idx|pct...|stddev|json-op...`` CSV
  database, answering queries by bijection-equivalence matching of the query
  sequence against stored rows (benchmarker.cpp:169-223): search-algorithm
  experiments need no device at all.

TPU note: the executor compiles a schedule to one XLA program; ``run_once`` must
call the compiled function AND ``block_until_ready`` so a measurement fences the
device (SURVEY.md §7.2 "Measurement fidelity").  Compile time is excluded: the
callable is built once per schedule before timing starts.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Protocol, Tuple

from tenzing_tpu.bench.randomness import is_random
from tenzing_tpu.core.resources import Equivalence
from tenzing_tpu.core.sequence import Sequence, get_equivalence
from tenzing_tpu.parallel.control_plane import ControlPlane, default_control_plane
from tenzing_tpu.utils.numeric import percentile, stddev


@dataclass
class BenchResult:
    """Percentile statistics of per-iteration wall time in seconds
    (reference Benchmark::Result, benchmarker.hpp:14-22)."""

    pct01: float = 0.0
    pct10: float = 0.0
    pct50: float = 0.0
    pct90: float = 0.0
    pct99: float = 0.0
    stddev: float = 0.0

    @staticmethod
    def from_times(times: List[float]) -> "BenchResult":
        s = sorted(times)
        return BenchResult(
            pct01=percentile(s, 1),
            pct10=percentile(s, 10),
            pct50=percentile(s, 50),
            pct90=percentile(s, 90),
            pct99=percentile(s, 99),
            stddev=stddev(s),
        )

    def to_json(self) -> dict:
        return {
            "pct01": self.pct01,
            "pct10": self.pct10,
            "pct50": self.pct50,
            "pct90": self.pct90,
            "pct99": self.pct99,
            "stddev": self.stddev,
        }


@dataclass
class BenchOpts:
    """reference Benchmark::Opts (benchmarker.hpp:24-30)."""

    n_iters: int = 1000
    max_retries: int = 10
    target_secs: float = 0.01  # adaptive floor per measurement (benchmarker.cpp:85)


class ScheduleRunner(Protocol):
    """Anything that turns a schedule into a zero-arg fenced run callable —
    provided by runtime.executor."""

    def prepare(self, order: Sequence) -> Callable[[], None]: ...


class EmpiricalBenchmarker:
    """Times a schedule on the real device (reference EmpiricalBenchmarker)."""

    def __init__(
        self,
        runner: ScheduleRunner,
        control_plane: Optional[ControlPlane] = None,
    ):
        self.runner = runner
        self.cp = control_plane if control_plane is not None else default_control_plane()

    # reference measure(), benchmarker.cpp:83-119
    def _measure(self, run_once: Callable[[], None], n_samples: int, opts: BenchOpts) -> Tuple[float, int]:
        """One measurement: time >= target_secs of work; returns (secs-per-sample,
        possibly-grown n_samples)."""
        while True:
            self.cp.barrier()
            t0 = time.perf_counter()
            for _ in range(n_samples):
                run_once()
            elapsed = time.perf_counter() - t0
            elapsed = self.cp.allreduce_max(elapsed)
            if elapsed >= opts.target_secs:
                return elapsed / n_samples, n_samples
            grow = max(n_samples * 2, int(n_samples * 1.5 * opts.target_secs / max(elapsed, 1e-9)))
            n_samples = min(grow, 1_000_000)

    # reference benchmark(), benchmarker.cpp:121-167
    def benchmark(self, order: Sequence, opts: Optional[BenchOpts] = None) -> BenchResult:
        opts = opts if opts is not None else BenchOpts()
        run_once = self.runner.prepare(order)
        run_once()  # warmup: compile + first dispatch excluded from timing
        n_samples = 1
        for attempt in range(opts.max_retries):
            times: List[float] = []
            for _ in range(opts.n_iters):
                # _measure already max-reduces each elapsed across hosts
                t, n_samples = self._measure(run_once, n_samples, opts)
                times.append(t)
            if is_random(times) or attempt == opts.max_retries - 1:
                return BenchResult.from_times(times)
        raise AssertionError("unreachable")  # pragma: no cover


# -- recorded-timings replay (reference CsvBenchmarker, benchmarker.cpp:169-223) --

CSV_DELIM = "|"


def result_row(idx: int, res: BenchResult, order: Sequence) -> str:
    """One CSV row: ``idx|pct01|pct10|pct50|pct90|pct99|stddev|op-json|...``
    (reference mcts.cpp:13-31 / dfs.cpp:84-105 dump format)."""
    import json

    cells = [
        str(idx),
        repr(res.pct01),
        repr(res.pct10),
        repr(res.pct50),
        repr(res.pct90),
        repr(res.pct99),
        repr(res.stddev),
    ] + [
        # '|' can only occur inside JSON strings; the \\u007c escape keeps the
        # cell valid JSON while making the row safely splittable on the delimiter
        json.dumps(op.to_json()).replace(CSV_DELIM, "\\u007c")
        for op in order
    ]
    return CSV_DELIM.join(cells)


class CsvBenchmarker:
    """Answers benchmark queries from a recorded database by equivalence-matching
    the query sequence against stored schedules — search experiments with no
    device in the loop (reference benchmarker.cpp:169-223)."""

    def __init__(self, rows: List[str], graph):
        from tenzing_tpu.core.serdes import op_from_json
        import json

        self.entries: List[Tuple[Sequence, BenchResult]] = []
        for row in rows:
            if not row.strip():
                continue
            cells = row.split(CSV_DELIM)
            res = BenchResult(
                pct01=float(cells[1]),
                pct10=float(cells[2]),
                pct50=float(cells[3]),
                pct90=float(cells[4]),
                pct99=float(cells[5]),
                stddev=float(cells[6]),
            )
            ops = [op_from_json(json.loads(c), graph) for c in cells[7:]]
            self.entries.append((Sequence(ops), res))

    @classmethod
    def from_file(cls, path: str, graph) -> "CsvBenchmarker":
        with open(path) as f:
            return cls(f.read().splitlines(), graph)

    def benchmark(self, order: Sequence, opts: Optional[BenchOpts] = None) -> BenchResult:
        for stored, res in self.entries:
            if get_equivalence(stored, order):
                return res
        raise KeyError(
            f"no recorded schedule equivalent to: {order.desc()}"
        )
