"""Empirical and recorded benchmarking of candidate schedules.

Parity target: reference ``include/tenzing/benchmarker.hpp`` /
``src/benchmarker.cpp``:

* ``Benchmark.Result`` = percentiles 01/10/50/90/99 + stddev of per-iteration
  wall time (benchmarker.hpp:14-22).
* ``EmpiricalBenchmarker`` — adaptive inner loop grows samples-per-measurement
  until one measurement takes >= 10 ms (benchmarker.cpp:83-119); barrier before,
  wall-clock around the loop, **max across hosts** (benchmarker.cpp:101,145);
  nIters measurements; reject the whole set if the runs-test flags non-random
  structure and retry up to maxRetries (benchmarker.cpp:129-155).
* ``CsvBenchmarker`` — replays a recorded ``idx|pct...|stddev|json-op...`` CSV
  database, answering queries by bijection-equivalence matching of the query
  sequence against stored rows (benchmarker.cpp:169-223): search-algorithm
  experiments need no device at all.

TPU note (SURVEY.md §7.2 "Measurement fidelity"): the executor compiles a
schedule to one XLA program, and the sample loop runs *inside* that program
(``prepare_n``), fenced by a device->host fetch of one reduced scalar.  Through
a remote-tunnel PJRT backend, ``block_until_ready`` returns before execution
finishes (measured on the v5e tunnel: timing flat in work size; only
``device_get`` round-trips), so each measurement is
``wall(run_n(n)) - fetch_overhead`` with the overhead calibrated per
benchmarker from trivial fetches — the per-measurement analog of the
reference's MPI_Barrier + MPI_Wtime bracketing.  Compile time is excluded: the
callable is built once per schedule before timing starts.
"""

from __future__ import annotations

import random as _random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from tenzing_tpu.bench.randomness import is_random
from tenzing_tpu.core.sequence import Sequence, canonical_key
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer, short_digest
from tenzing_tpu.parallel.control_plane import ControlPlane, default_control_plane
from tenzing_tpu.utils.numeric import percentile, stddev


def schedule_id(order) -> str:
    """Short stable id of a schedule for telemetry correlation:
    ``obs.tracer.short_digest`` of its serialized form (works for Sequence
    orders and the CallableRunner's plain string names alike).  Deterministic
    across processes — multi-host trace bundles and archived JSONL agree on
    ids without coordination.  Memoized on the sequence (``Sequence.cached``,
    invalidated on mutation): every benchmark/cache/verify/journal/injection
    layer derives the id of the same order, and each derivation used to
    re-serialize the whole schedule to JSON."""
    if isinstance(order, str):
        return order

    def derive() -> str:
        try:
            from tenzing_tpu.core.serdes import sequence_to_json_str

            payload = sequence_to_json_str(order)
        except Exception:
            payload = repr(order)
        return short_digest(payload)

    if isinstance(order, Sequence):
        return order.cached("schedule_id", derive)
    return derive()


def candidate_failed(where: str, order, exc: BaseException) -> None:
    """Structured record of a candidate schedule that failed to compile/run:
    a ``search.candidate_failed`` trace event carrying the schedule id, the
    exception class, and the fault taxonomy class (fault/errors.py —
    transient flake vs deterministic broken candidate vs device loss), plus
    a counter — failed candidates are attributable in the trace instead of
    vanishing into a stderr note.  Shared by every solver's reject path
    (hill-climb, MCTS rollout/confirm, DFS)."""
    # lazy import: fault.resilient imports this module, so a top-level
    # import here would cycle
    from tenzing_tpu.fault.errors import classify_error

    get_metrics().counter("search.candidate_failed").inc()
    tr = get_tracer()
    if tr.enabled:
        tr.event("search.candidate_failed", where=where,
                 schedule=schedule_id(order), error=type(exc).__name__,
                 error_class=classify_error(exc),
                 message=str(exc)[:200])


@dataclass
class BenchResult:
    """Percentile statistics of per-iteration wall time in seconds
    (reference Benchmark::Result, benchmarker.hpp:14-22)."""

    pct01: float = 0.0
    pct10: float = 0.0
    pct50: float = 0.0
    pct90: float = 0.0
    pct99: float = 0.0
    stddev: float = 0.0
    # provenance for offline re-derivation (ISSUE 1 satellite): the raw
    # per-sample series the percentiles were computed from, and the
    # calibrated fetch-overhead correction the empirical benchmarker
    # subtracted per measurement.  Excluded from equality/repr: two results
    # are "the same measurement" by their statistics, and replayed results
    # (CsvBenchmarker) legitimately carry no raw series.
    times: Optional[List[float]] = field(default=None, compare=False,
                                         repr=False)
    fetch_overhead: Optional[float] = field(default=None, compare=False,
                                            repr=False)

    @staticmethod
    def from_times(times: List[float]) -> "BenchResult":
        s = sorted(times)
        return BenchResult(
            pct01=percentile(s, 1),
            pct10=percentile(s, 10),
            pct50=percentile(s, 50),
            pct90=percentile(s, 90),
            pct99=percentile(s, 99),
            stddev=stddev(s),
            times=list(times),
        )

    def to_json(self) -> dict:
        out = {
            "pct01": self.pct01,
            "pct10": self.pct10,
            "pct50": self.pct50,
            "pct90": self.pct90,
            "pct99": self.pct99,
            "stddev": self.stddev,
        }
        if self.times is not None:
            out["times"] = list(self.times)
        if self.fetch_overhead is not None:
            out["fetch_overhead"] = self.fetch_overhead
        return out


@dataclass
class BenchOpts:
    """reference Benchmark::Opts (benchmarker.hpp:24-30)."""

    n_iters: int = 1000
    max_retries: int = 10
    target_secs: float = 0.01  # adaptive floor per measurement (benchmarker.cpp:85)


class ScheduleRunner(Protocol):
    """Anything that turns a schedule into a fenced run callable — provided by
    runtime.executor.  ``prepare_n`` (preferred) returns ``run_n(n)`` repeating
    the schedule n times inside one program; ``prepare`` a run-once callable."""

    def prepare(self, order: Sequence) -> Callable[[], None]: ...


class EmpiricalBenchmarker:
    """Times a schedule on the real device (reference EmpiricalBenchmarker)."""

    def __init__(
        self,
        runner: ScheduleRunner,
        control_plane: Optional[ControlPlane] = None,
    ):
        self.runner = runner
        self.cp = control_plane if control_plane is not None else default_control_plane()
        self._overhead: Optional[float] = None

    def _fetch_overhead(self) -> float:
        """Median wall time of a trivial compiled fetch: dispatch + tunnel RTT.
        Subtracted from every measurement (each measurement is exactly one
        fetch-fenced call)."""
        if self._overhead is None:
            import jax
            import jax.numpy as jnp

            f = jax.jit(lambda x: x + 1.0)
            x = jnp.zeros(())
            jax.device_get(f(x))  # compile
            ts = []
            for _ in range(7):
                t0 = time.perf_counter()
                jax.device_get(f(x))
                ts.append(time.perf_counter() - t0)
            ts.sort()
            self._overhead = ts[len(ts) // 2]
        return self._overhead

    def _runner_for(self, order: Sequence) -> Tuple[Callable[[int], None], int]:
        """(run_n, fences_per_call_of_n): the prepare_n path fences once per
        measurement; the prepare() fallback fences once per sample, so the
        overhead subtraction must scale with n."""
        prep_n = getattr(self.runner, "prepare_n", None)
        if prep_n is not None:
            return prep_n(order), 0  # 0: one fence per run_n call, any n
        run_once = self.runner.prepare(order)

        def run_n(n: int) -> None:
            for _ in range(n):
                run_once()

        return run_n, 1  # 1: one fence per sample

    # reference measure(), benchmarker.cpp:83-119
    def _measure(
        self,
        run_n: Callable[[int], None],
        n_samples: int,
        opts: BenchOpts,
        fences_per_sample: int = 0,
    ) -> Tuple[float, int]:
        """One measurement: >= target_secs of device work past the fetch
        overhead; returns (secs-per-sample, possibly-grown n_samples)."""
        overhead = self._fetch_overhead()
        while True:
            self.cp.barrier()
            t0 = time.perf_counter()
            run_n(n_samples)
            wall = time.perf_counter() - t0
            cost = overhead * (fences_per_sample * n_samples if fences_per_sample else 1)
            elapsed = wall - cost
            elapsed = self.cp.allreduce_max(elapsed)
            if elapsed >= opts.target_secs:
                return elapsed / n_samples, n_samples
            # growth ratio from the raw wall time: overhead subtraction can
            # push elapsed to <= 0 at small n, and a ratio computed from a
            # near-zero denominator would jump n straight to the cap
            grow = max(
                n_samples * 2,
                int(n_samples * 1.5 * opts.target_secs / max(wall, 1e-9)),
            )
            if n_samples >= 1_000_000:
                # the cap is reached and elapsed still misses the floor: the
                # work is either folded away by the compiler or cheaper than
                # the fence overhead at any n.  Return the RAW wall time per
                # sample — an honest fence-dominated upper bound — rather
                # than the overhead-subtracted residual, which can be ~0 or
                # negative and would flow into paired ratios as a fabricated
                # astronomic speedup.  Max-reduced across hosts like every
                # other return from _measure (the benchmark() invariant).
                return self.cp.allreduce_max(wall) / n_samples, n_samples
            n_samples = min(grow, 1_000_000)

    # reference benchmark(), benchmarker.cpp:121-167
    def benchmark(self, order: Sequence, opts: Optional[BenchOpts] = None) -> BenchResult:
        opts = opts if opts is not None else BenchOpts()
        tr = get_tracer()
        sid = schedule_id(order) if tr.enabled else None
        with tr.span("bench.benchmark", schedule=sid, n_iters=opts.n_iters,
                     target_secs=opts.target_secs) as sp:
            run_n, fences = self._runner_for(order)
            with tr.span("bench.warm", schedule=sid):
                run_n(1)  # warmup: compile + first dispatch excluded
            n_samples = 1
            for attempt in range(opts.max_retries):
                times: List[float] = []
                for _ in range(opts.n_iters):
                    # _measure already max-reduces each elapsed across hosts
                    t, n_samples = self._measure(run_n, n_samples, opts, fences)
                    times.append(t)
                if is_random(times) or attempt == opts.max_retries - 1:
                    res = BenchResult.from_times(times)
                    res.fetch_overhead = self._overhead
                    sp.set("pct50", res.pct50)
                    sp.set("n_samples", n_samples)
                    sp.set("fetch_overhead", self._overhead)
                    sp.set("attempts", attempt + 1)
                    reg = get_metrics()
                    reg.counter("bench.benchmarks").inc()
                    reg.counter("bench.measurements").inc(len(times))
                    if attempt:
                        reg.counter("bench.runs_test_retries").inc(attempt)
                    return res
        raise AssertionError("unreachable")  # pragma: no cover

    # reference batch benchmark(), benchmarker.cpp:21-76: measure a SET of
    # schedules, visiting them in a fresh random permutation each iteration so
    # slow system drift decorrelates from schedule identity.
    def benchmark_batch_times(
        self,
        orders: List[Sequence],
        opts: Optional[BenchOpts] = None,
        seed: int = 0,
        times_out: Optional[List[List[float]]] = None,
        group_seeds: Optional[List[Tuple[int, int]]] = None,
    ) -> List[List[float]]:
        """Raw per-iteration times, aligned by iteration index: ``times[i][k]``
        is schedule i's secs-per-sample in iteration k, and iteration k visits
        every schedule once (shuffled) — so ``times[a][k] / times[b][k]`` is a
        *paired* comparison in which common-mode drift cancels (see
        utils.numeric.paired_speedup).

        ``times_out`` (a list of ``len(orders)`` empty lists) is filled in
        place as measurements land, so a signal handler can snapshot partial
        data from a long batch (the DFS partial-dump contract, trap.py).

        ``group_seeds`` — ``[(n_orders, seed), ...]`` partitioning ``orders``
        into consecutive groups, each shuffled by its OWN persistent
        ``Random(group_seed)``: a group's per-iteration visit order depends
        only on its own ``(group_orders, group_seed)``, bit-identical to a
        solo ``benchmark_batch_times(group_orders, seed=group_seed)`` call.
        This is how the search fleet's measurement owner fuses K candidate
        pairs from different worker processes into one device round without
        perturbing any worker's reproducibility (search/fleet.py) — the
        global permutation of the old single-seed path would entangle every
        group's visit order with its co-scheduled strangers.  ``None`` means
        one group ``(len(orders), seed)`` — exactly the historical
        behavior."""
        opts = opts if opts is not None else BenchOpts()
        groups = (list(group_seeds) if group_seeds is not None
                  else [(len(orders), seed)])
        if (any(n <= 0 for n, _ in groups)
                or sum(n for n, _ in groups) != len(orders)):
            raise ValueError(
                "group_seeds must partition orders into non-empty runs: "
                f"{groups} vs {len(orders)} orders")
        # one persistent RNG per group: reproducibility is per-group, never
        # a function of what else shares the device round
        group_rngs = [_random.Random(s) for _, s in groups]
        group_spans: List[range] = []
        at = 0
        for n, _ in groups:
            group_spans.append(range(at, at + n))
            at += n
        # validate before the (expensive) compile-all warmup; non-empty inner
        # lists would shift iteration indices and silently break the paired
        # -comparison alignment
        if times_out is not None and (
            len(times_out) != len(orders) or any(ts for ts in times_out)
        ):
            raise ValueError("times_out must have one EMPTY list per order")
        tr = get_tracer()
        with tr.span("bench.batch", n_orders=len(orders),
                     n_iters=opts.n_iters, seed=seed,
                     n_groups=len(groups)) as sp:
            runners = [self._runner_for(o) for o in orders]
            with tr.span("bench.batch_warm", n_orders=len(orders)):
                for r, _ in runners:
                    r(1)  # warmup/compile all before timing any
            n_samples = [1] * len(orders)
            times: List[List[float]] = (
                times_out if times_out is not None else [[] for _ in orders]
            )
            for _ in range(opts.n_iters):
                for span, rng in zip(group_spans, group_rngs):
                    perm = list(span)
                    rng.shuffle(perm)  # seeded: identical order on every host
                    for i in perm:
                        run_n, fences = runners[i]
                        t, n_samples[i] = self._measure(
                            run_n, n_samples[i], opts, fences)
                        times[i].append(t)
            sp.set("fetch_overhead", self._overhead)
            get_metrics().counter("bench.measurements").inc(
                opts.n_iters * len(orders))
        return times

    def benchmark_batch(
        self,
        orders: List[Sequence],
        opts: Optional[BenchOpts] = None,
        seed: int = 0,
    ) -> List[BenchResult]:
        return [
            BenchResult.from_times(ts)
            for ts in self.benchmark_batch_times(orders, opts, seed)
        ]


class CallableRunner:
    """ScheduleRunner over *named zero-arg callables* — external baselines
    (one fused ``jax.nn.dot_product_attention`` call, a single-jit XLA MoE)
    measured with the SAME protocol as searched schedules, including the
    decorrelated paired batch: the "order" is just the callable's name.  Each
    callable must be fully fenced (end with a ``jax.device_get``), mirroring
    the executor's fetch-fenced runners.

    CAUTION: one fence per *sample* — through a high-RTT tunnel where the
    per-call round trip rivals the calibrated fetch overhead, the adaptive
    floor may never converge (elapsed-past-overhead stays ~0 while n_samples
    doubles).  Fast kernels through a tunnel should use
    :class:`RepeatCallableRunner` instead."""

    def __init__(self, fns: Dict[str, Callable[[], None]]):
        self.fns = dict(fns)

    def prepare(self, name: str) -> Callable[[], None]:
        return self.fns[name]


class RepeatCallableRunner:
    """ScheduleRunner over named ``run_n(n)`` callables: each invocation runs
    n samples inside ONE fenced dispatch (the executor's ``prepare_n``
    discipline), so a measurement costs one tunnel round trip regardless of
    n and the adaptive floor converges for arbitrarily fast kernels.  The
    callable must keep the n iterations live (loop-carried data dependence —
    e.g. ``runtime.executor.datatie`` — or XLA hoists the loop-invariant
    body and times one execution)."""

    def __init__(self, run_ns: Dict[str, Callable[[int], None]]):
        self.run_ns = dict(run_ns)

    def prepare_n(self, name: str) -> Callable[[int], None]:
        return self.run_ns[name]

    def prepare(self, name: str) -> Callable[[], None]:
        run_n = self.run_ns[name]
        return lambda: run_n(1)


class CachingBenchmarker:
    """Equivalence-keyed cache in front of any benchmarker: a schedule equal to
    an already-benchmarked one up to lane/event renaming reuses the recorded
    result instead of recompiling and re-timing (the CsvBenchmarker lookup,
    benchmarker.cpp:169-223, applied online; VERDICT r1 weak #5 — MCTS
    re-benchmarked identical rollouts).

    Lookup is an O(1) dict hit on (opts, ``canonical_key``) — the canonical
    form under lane/event renaming is equal exactly when the pairwise
    bijection check succeeds (core/sequence.py canonical_key) — and a result
    recorded under one BenchOpts is never returned for another."""

    def __init__(self, inner):
        self.inner = inner
        self._cache: dict = {}
        self.hits = 0
        self.misses = 0
        # a cache in front of a rank-coherent benchmarker is itself rank
        # -coherent: hits are local (identical on every rank — the broadcast
        # order and the restored journal agree rank-to-rank) and misses
        # inherit the inner agreement protocol (fault/resilient.py)
        self.rank_coherent = getattr(inner, "rank_coherent", False)

    @staticmethod
    def _key(order: Sequence, opts: Optional[BenchOpts]) -> Tuple:
        ok = (opts.n_iters, opts.max_retries, opts.target_secs) if opts else None
        return (ok, canonical_key(order))

    @property
    def hit_rate(self) -> float:
        """Fraction of queries answered from the cache (0.0 when unqueried)."""
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def benchmark(self, order: Sequence, opts: Optional[BenchOpts] = None) -> BenchResult:
        key = self._key(order, opts)
        hit = key in self._cache
        if hit:
            self.hits += 1
            res = self._cache[key]
        else:
            res = self.inner.benchmark(order, opts)
            self._cache[key] = res
            self.misses += 1
        reg = get_metrics()
        reg.counter("bench.cache.hits" if hit else "bench.cache.misses").inc()
        reg.gauge("bench.cache.hit_rate").set(self.hit_rate)
        tr = get_tracer()
        if tr.enabled:
            tr.event("bench.cache", hit=hit, schedule=schedule_id(order),
                     pct50=res.pct50)
        return res


# -- recorded-timings replay (reference CsvBenchmarker, benchmarker.cpp:169-223) --

CSV_DELIM = "|"


def split_fidelity(cells: List[str]) -> Tuple[str, int]:
    """(fidelity, ops_start_index) of a split CSV row — THE parsing rule for
    the optional ``fid=<tag>`` cell between the stats and the ops (legacy
    rows have none and are "full").  Every reader of the dump format
    (CsvBenchmarker, postprocess, replay) must use this one definition so
    they cannot drift on which rows count as full-fidelity."""
    if len(cells) > 7 and cells[7].startswith("fid="):
        return cells[7][4:], 8
    return "full", 7


def result_row(idx: int, res: BenchResult, order: Sequence,
               fidelity: Optional[str] = None) -> str:
    """One CSV row: ``idx|pct01|pct10|pct50|pct90|pct99|stddev|op-json|...``
    (reference mcts.cpp:13-31 / dfs.cpp:84-105 dump format).  ``fidelity``
    (e.g. "screen" for a cheap multi-fidelity measurement) inserts a
    ``fid=<tag>`` cell before the ops — readable by CsvBenchmarker, invisible
    to rows that omit it, so legacy databases parse unchanged.  The tag has
    no escape mechanism, so one containing the cell delimiter would silently
    truncate and leave its tail masquerading as a malformed op cell —
    rejected here instead."""
    import json

    if fidelity is not None and CSV_DELIM in fidelity:
        raise ValueError(
            f"fidelity tag {fidelity!r} contains the CSV delimiter")

    cells = [
        str(idx),
        # float() first: a numpy scalar's repr ("np.float64(...)") would not
        # parse back, and CsvBenchmarker(strict=False) would silently skip
        # the row; plain-float repr round-trips exactly
        repr(float(res.pct01)),
        repr(float(res.pct10)),
        repr(float(res.pct50)),
        repr(float(res.pct90)),
        repr(float(res.pct99)),
        repr(float(res.stddev)),
    ] + ([f"fid={fidelity}"] if fidelity is not None else []) + [
        # '|' can only occur inside JSON strings; the \\u007c escape keeps the
        # cell valid JSON while making the row safely splittable on the delimiter
        json.dumps(op.to_json()).replace(CSV_DELIM, "\\u007c")
        for op in order
    ]
    return CSV_DELIM.join(cells)


class CsvBenchmarker:
    """Answers benchmark queries from a recorded database by equivalence-matching
    the query sequence against stored schedules — search experiments with no
    device in the loop (reference benchmarker.cpp:169-223).

    ``strict=False`` skips rows whose ops cannot be resolved against ``graph``
    (recorded against a different structural variant — e.g. a naive baseline
    dumped from the pre-choice graph); skipped row indices are kept in
    ``self.skipped`` so callers can see what the database did not cover.

    ``normalize=True`` matches queries modulo ``remove_redundant_syncs`` (both
    sides cleaned before the canonical-key lookup).  The peephole rules only delete
    sync ops with no execution effect, so normalized-equal schedules are the
    same program — this lets a database recorded by the DFS solver (raw
    terminal sequences) answer queries from the MCTS solver (which cleans
    every rollout before benchmarking), the offline replay-search workflow of
    the reference's mcts_csv drivers."""

    def __init__(self, rows: List[str], graph, strict: bool = True,
                 normalize: bool = False):
        from tenzing_tpu.core.serdes import op_from_json
        import json

        from tenzing_tpu.core.schedule import remove_redundant_syncs

        self._normalize = remove_redundant_syncs if normalize else (lambda s: s)
        self.entries: List[Tuple[Sequence, BenchResult]] = []
        self.fidelities: List[str] = []  # parallel to entries; "full" legacy
        self._by_canonical: dict = {}  # canonical(normalized seq) -> result
        self.skipped: List[int] = []
        for i, row in enumerate(rows):
            if not row.strip():
                continue
            cells = row.split(CSV_DELIM)
            try:
                res = BenchResult(
                    pct01=float(cells[1]),
                    pct10=float(cells[2]),
                    pct50=float(cells[3]),
                    pct90=float(cells[4]),
                    pct99=float(cells[5]),
                    stddev=float(cells[6]),
                )
                fid, ops_at = split_fidelity(cells)
                ops = [op_from_json(json.loads(c), graph) for c in cells[ops_at:]]
            except (KeyError, TypeError, ValueError, IndexError):
                # malformed row (e.g. dump truncated mid-write) or ops recorded
                # against a different structural variant
                if strict:
                    raise
                self.skipped.append(i)
                continue
            seq = Sequence(ops)
            self.entries.append((seq, res))
            self.fidelities.append(fid)
            # first FULL row wins for duplicate schedules (e.g. a search-time
            # row superseded by a final-batch row earlier in the file).
            # Screen-fidelity rows never answer benchmark queries: their
            # ~1 ms-floor numbers are bookkeeping, and letting one shadow a
            # full-floor twin would replay ~100x off-regime measurements.
            if fid == "full":
                self._by_canonical.setdefault(
                    canonical_key(self._normalize(seq)), res)

    @classmethod
    def from_file(cls, path: str, graph, strict: bool = True,
                  normalize: bool = False) -> "CsvBenchmarker":
        with open(path) as f:
            return cls(f.read().splitlines(), graph, strict=strict,
                       normalize=normalize)

    def benchmark(self, order: Sequence, opts: Optional[BenchOpts] = None) -> BenchResult:
        res = self._by_canonical.get(canonical_key(self._normalize(order)))
        if res is None:
            raise KeyError(
                f"no recorded schedule equivalent to: {order.desc()}"
            )
        return res
