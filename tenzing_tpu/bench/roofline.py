"""FLOPs/bytes cost models and achieved-fraction-of-peak reporting.

VERDICT r2 weak #3: every reported win was relative to this framework's own
serialized naive order; nothing computed FLOPs/bytes or fraction of peak, so
"actually fast" vs "faster than our own strawman" was unproven.  This module
is the absolute yardstick: per-workload arithmetic/byte counts and the
achieved fraction of the chip's peak compute and HBM bandwidth (the reference
publishes no numbers at all — SURVEY.md §6 — so this exceeds parity).

Peaks are TPU v5e (single chip) from the public spec sheet: 197 TFLOP/s bf16
on the MXU, 819 GB/s HBM.  f32 matmuls lower to the MXU with bf16-truncated
operands on this platform (probed: xla_allow_excess_precision,
experiments/device_numerics.py), so bf16 peak is the honest denominator for
both precisions; utilization of a byte-bound workload should be read against
``hbm_frac`` instead.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

# TPU v5e single-chip peaks (public spec)
V5E_PEAK_BF16_FLOPS = 197e12
V5E_PEAK_HBM_BYTES = 819e9

# On-core VMEM budget a fused-region tile's working set must fit (v5e has
# 128 MiB of VMEM per core; leave headroom for Pallas double-buffering and
# spills — the prune is a can-this-possibly-help filter, not a compiler)
V5E_VMEM_BYTES = 96 * 2**20
# Per-tile traffic floor below which the grid-step overhead (program
# prologue, DMA issue latency) dominates any pipelining win a finer tiling
# could buy — measured kernels in this repo stop scaling well under ~1 MiB
# of traffic per grid step
MIN_TILE_BYTES = 1 * 2**20

# Per-dispatch overhead floor for chunk pruning: splitting an op into n
# chunks adds n-1 separately dispatched programs, and the stepped-timeline
# attribution numbers (obs/attrib, the MPK baseline measurement) put one
# extra dispatch in the tens of microseconds on the v5e tunnel
CHUNK_DISPATCH_US = 25.0
# Staging-path bandwidth for hidden-comm bounds: the async host round-trip
# DMA regime measured for the halo/MoE staged transfers (order of
# magnitude; the bound is a can-it-help filter, not a performance model)
V5E_XFER_GBS = 16.0
# Menu cap on chunk counts: beyond 4 partials the added dispatches always
# dominate on the shapes this repo measures, and every extra count grows
# the solvers' decision space linearly
MENU_CHUNK_CAP = 4


@dataclass(frozen=True)
class Cost:
    """Arithmetic + memory traffic of one workload iteration.

    ``hbm_bytes`` counts device-memory traffic (reads + writes of the live
    tensors, not counting cache-resident reuse); ``xfer_bytes`` counts bytes
    through the slower staging path (host round trip / PCIe), which has its
    own (unpublished, measured) bandwidth."""

    flops: float
    hbm_bytes: float
    xfer_bytes: float = 0.0

    def utilization(self, seconds: float) -> Dict[str, float]:
        """Achieved fractions of peak for a measured iteration time."""
        return {
            "seconds": seconds,
            "tflops": self.flops / seconds / 1e12,
            "mxu_frac": self.flops / seconds / V5E_PEAK_BF16_FLOPS,
            "hbm_gbs": self.hbm_bytes / seconds / 1e9,
            "hbm_frac": self.hbm_bytes / seconds / V5E_PEAK_HBM_BYTES,
            "xfer_gbs": self.xfer_bytes / seconds / 1e9,
        }


def attention_cost(batch: int, seq: int, head_dim: int, bytes_per_el: int = 4) -> Cost:
    """Dense softmax attention, one head group: QK^T and PV are each
    2*b*n^2*d FLOPs (softmax's exp/sum is O(b*n^2), negligible).  HBM traffic
    = read Q,K,V + write O (the n^2 score matrix stays blocked in VMEM in
    every implementation compared)."""
    flops = 4.0 * batch * seq * seq * head_dim
    hbm = 4.0 * batch * seq * head_dim * bytes_per_el
    return Cost(flops=flops, hbm_bytes=hbm)


def moe_cost(tokens: int, d_model: int, d_ff: int, bytes_per_el: int = 4,
             staged: bool = False, n_experts: int = 8) -> Cost:
    """Top-1 routed MoE layer: every token through one gelu MLP —
    2*t*d*dff (up) + 2*t*dff*d (down) FLOPs.  HBM: read X, expert weights
    (each expert pair read once per chunk visit — counted once, the
    capacity-padded lower bound), write Y.  ``staged=True`` adds the
    dispatch/combine round trips through the staging path (4 crossings:
    slot table out+back for dispatch and combine)."""
    flops = 4.0 * tokens * d_model * d_ff
    weights = 2.0 * n_experts * d_model * d_ff * bytes_per_el
    hbm = (2.0 * tokens * d_model) * bytes_per_el + weights
    xfer = 4.0 * tokens * d_model * bytes_per_el if staged else 0.0
    return Cost(flops=flops, hbm_bytes=hbm, xfer_bytes=xfer)


def halo_cost(nq: int, lx: int, ly: int, lz: int, radius: int,
              bytes_per_el: int = 4, staged: bool = True) -> Cost:
    """3D 6-face halo exchange, one iteration: byte-bound, zero FLOPs.  Per
    face: pack (read face + write buf), unpack (read buf + write shell) =
    4 face-bytes of HBM traffic; the transfer adds 2 crossings of the staging
    path per face (spill + fetch) when host-staged."""
    faces = 2 * (lx * ly + ly * lz + lx * lz) * radius * nq
    face_bytes = float(faces) * bytes_per_el
    return Cost(
        flops=0.0,
        hbm_bytes=4.0 * face_bytes,
        xfer_bytes=(2.0 * face_bytes if staged else 0.0),
    )


def prune_tilings(cost: Cost, tile_counts, vmem_bytes: int = V5E_VMEM_BYTES,
                  min_tile_bytes: int = MIN_TILE_BYTES,
                  full_bytes: float = 0.0):
    """Tile counts of a fused region (runtime/fused.py) that could possibly
    help, from the structurally-valid candidates ``tile_counts``:

    * ``t == 1`` (the un-tiled single-block kernel) always survives — it is
      the fallback every region must admit;
    * ``t > 1`` is dropped when the per-tile share of the TILED traffic
      falls under ``min_tile_bytes`` (grid-step overhead dominates — a
      finer tiling cannot help) or the per-tile working set exceeds
      ``vmem_bytes`` (the tile cannot fit on-core, so the kernel would
      spill or fail to compile — a coarser tiling is required, not this
      one).

    ``full_bytes`` is the traffic of the region's FULL-VIEW buffers (the
    ``fuse_tiling`` entries declared ``None`` — e.g. a fused attention
    fold's K/V block, or a gathered x): those are re-presented whole to
    every grid step, so they do not shrink with ``t`` — the per-tile
    working set is ``(hbm_bytes - full_bytes) / t + full_bytes``, not
    ``hbm_bytes / t``.

    This is the analytic can-it-help filter the tile *decision nodes*
    (``FuseTileChoice``) are built from: the searchable menu is the pruned
    set, so the solvers never spend measurements on tilings the roofline
    already rules out.
    """
    full = min(max(0.0, float(full_bytes)), cost.hbm_bytes)
    tiled_total = cost.hbm_bytes - full
    out = []
    for t in sorted({int(t) for t in tile_counts}):
        if t < 1:
            continue
        if t == 1:
            out.append(t)
            continue
        per_tile_tiled = tiled_total / t
        working_set = per_tile_tiled + full
        if per_tile_tiled < min_tile_bytes or working_set > vmem_bytes:
            continue
        out.append(t)
    return out or [1]


def op_roofline_us(cost: Cost) -> float:
    """The analytic time floor of one op: the slower of its MXU and HBM
    roofs (the same denominators :meth:`Cost.utilization` reads achieved
    fractions against)."""
    return max(cost.flops / V5E_PEAK_BF16_FLOPS,
               cost.hbm_bytes / V5E_PEAK_HBM_BYTES) * 1e6


def hidden_comm_bound_us(cost: Cost, chunks: int, comm_us: float) -> float:
    """Upper bound on the comm time an ``n``-way chunking of an op costing
    ``cost`` can newly hide: splitting exposes at most the op's tail —
    a transfer can start after the first chunk instead of after the whole
    op, so the newly overlappable window is ``(n-1)/n`` of the op's
    analytic time — and hiding more comm than exists is impossible
    (``comm_us``, the neighboring transfer's time)."""
    if chunks <= 1:
        return 0.0
    return min(float(comm_us), op_roofline_us(cost) * (chunks - 1) / chunks)


def prune_chunkings(cost: Cost, chunk_counts, comm_us=None,
                    combine_bytes: float = 0.0,
                    dispatch_us: float = CHUNK_DISPATCH_US,
                    min_chunk_bytes: int = MIN_TILE_BYTES):
    """Chunk counts of an audited op (core/chunking.py) that could
    possibly help, from the structurally-valid candidates
    ``chunk_counts`` — the TACCL-style sketch constraint keeping the
    enlarged decision space tractable:

    * ``n == 1`` (the unchunked op) always survives — it is the menu
      entry the op itself provides;
    * ``n > 1`` is dropped when the per-chunk share of the op's traffic
      falls under ``min_chunk_bytes`` (the dispatch-overhead floor: a
      chunk that small is all prologue, exactly the fused-tiling
      ``MIN_TILE_BYTES`` argument); and
    * when ``comm_us`` (the neighboring transfer's analytic time) is
      given, ``n`` is dropped unless the hidden-comm upper bound
      (:func:`hidden_comm_bound_us`) beats the added cost of chunking:
      ``n-1`` extra dispatches plus ``n-1`` extra passes over the
      combine traffic (``combine_bytes`` — the output bytes every
      partial's read-modify-write re-presents, at HBM bandwidth).
      ``comm_us=None`` skips this rule (the caller models no transfer —
      only the traffic floor applies).

    ``cost`` is the CHUNKED OP's own roofline cost (one op, not the whole
    workload).  The menus the models build from this are what the
    solvers search — measurements are never spent on chunkings the
    analytic model already rules out.
    """
    out = []
    for n in sorted({int(n) for n in chunk_counts}):
        if n < 1:
            continue
        if n == 1:
            out.append(1)
            continue
        if cost.hbm_bytes / n < min_chunk_bytes:
            continue
        if comm_us is not None:
            added = (n - 1) * (float(dispatch_us) +
                               float(combine_bytes) /
                               V5E_PEAK_HBM_BYTES * 1e6)
            if hidden_comm_bound_us(cost, n, comm_us) <= added:
                continue
        out.append(n)
    return out or [1]


def chunk_menu(counts, cost: Cost, comm_us=None, combine_bytes: float = 0.0,
               relax: bool = False, cap: int = MENU_CHUNK_CAP):
    """THE shared ``*_chunk_menu`` scaffold every audited model uses:
    cap the op's structurally-valid chunk ``counts`` at ``cap`` partials,
    ``relax=True`` (tests / CPU smoke / toy shapes) keeps them all
    unpruned so the machinery stays searchable, otherwise
    :func:`prune_chunkings` applies the sketch constraint against the
    op's ``cost``/``comm_us``/``combine_bytes`` and each surviving
    ``n > 1`` is priced by :func:`hidden_comm_bound_us`.  Returns the
    ``(pruned counts, {count: est hidden µs})`` pair the models' choice
    builders consume."""
    counts = [int(c) for c in counts if int(c) <= cap]
    if relax:
        return list(counts), {}
    pruned = prune_chunkings(cost, counts, comm_us=comm_us,
                             combine_bytes=combine_bytes)
    est = {n: hidden_comm_bound_us(cost, n, comm_us or 0.0)
           for n in pruned if n > 1}
    return pruned, est


def prune_sketches(cands: Dict[str, Dict], fixed_floor_us: float,
                   overlap_us: float = 0.0,
                   dispatch_us: float = CHUNK_DISPATCH_US):
    """Sketch instantiations of a synthesized collective
    (collectives/synth.py) that could possibly beat the FIXED collective,
    from the priced candidates ``cands`` — the synth twin of
    :func:`prune_chunkings`, closing the same TACCL-style tractability
    loop: the solvers only ever search instantiations the analytic model
    cannot already rule out.

    ``cands`` maps a label (``"ring.c2"``) to its alpha-beta census:
    ``est_us`` (the serial wire cost over the topology links), ``steps``
    (separately posted transfers) and ``chunks``.  ``fixed_floor_us`` is
    the fixed engine's one-post alpha-beta floor for the same payload;
    ``overlap_us`` the neighboring compute a pipelined decomposition
    could hide transfers under (the GC3 credit — 0 when the caller models
    no neighbor).

    The rule, mirroring ``prune_chunkings``' added-cost-vs-hidden-comm
    test: each extra post beyond the fixed engine's single one pays a
    dispatch (``steps - 1`` extra), and chunk routing earns back at most
    ``min(overlap_us, est_us * (k-1)/k)`` — a ``k``-chunk pipeline can
    hide all but its head chunk's wire time, and hiding more compute
    than exists is impossible.  An instantiation survives iff its
    effective cost still beats ``fixed_floor_us``.

    Returns ``(kept labels, {label: non-empty prune reason})``.
    """
    kept, pruned = [], {}
    for label, c in cands.items():
        est = float(c.get("est_us", 0.0))
        steps = max(1, int(c.get("steps", 1)))
        k = max(1, int(c.get("chunks", 1)))
        credit = min(float(overlap_us), est * (k - 1) / k)
        eff = est + (steps - 1) * float(dispatch_us) - credit
        if eff < float(fixed_floor_us):
            kept.append(label)
        else:
            pruned[label] = (
                f"effective {eff:.1f}us (wire {est:.1f} + "
                f"{steps - 1} extra dispatch @ {dispatch_us:.0f} - "
                f"overlap credit {credit:.1f}) cannot beat the fixed "
                f"one-post floor {float(fixed_floor_us):.1f}us")
    return kept, pruned


def spmv_cost(m: int, nnz: int, bytes_per_el: int = 4) -> Cost:
    """CSR y = A x: 2 FLOPs per stored element; HBM reads vals + cols +
    gathered x per stored element, plus per row one y write and one 4-byte
    row-offset read (ADVICE r3: the per-row term is y + offsets only)."""
    flops = 2.0 * nnz
    hbm = float(nnz) * (2 * bytes_per_el + 4) + float(m) * (bytes_per_el + 4)
    return Cost(flops=flops, hbm_bytes=hbm)
