"""Async compile pipeline: overlap XLA compilation with device measurement.

The search wall of every bench run is dominated by *serialized* compiles
(~3.4 s per distinct schedule, 64 compiles inside a 147 s MCTS wall in the
r5 driver tail): ``TraceExecutor`` traces+compiles lazily on the first call
of the jitted program — i.e. inside the measurement path, while the device
sits idle.  But compilation is CPU-bound and GIL-releasing, measurement is
device-bound, and the solvers already know (or can cheaply guess) their next
candidates — the classic compile/execute pipelining MPK and TACCL lean on to
make schedule search affordable (PAPERS.md).

:class:`PrefetchingBenchmarker` wraps the *measurement* benchmarker (the
device stand-in at the bottom of the fault stack) and accepts **candidate
hints**: ``prefetch(orders)`` kicks off AOT compiles
(``TraceExecutor.precompile`` — ``jax.jit(...).lower(...).compile()`` into
the executor's schedule-JSON-keyed program cache) on a bounded background
thread pool while the foreground measurement runs.  An in-flight dedup map
guarantees each schedule compiles at most once; a foreground ``benchmark()``
for a schedule whose compile is still in flight joins it (paying only the
remainder) instead of compiling a duplicate.

Fault discipline — background threads NEVER touch the control plane:

* a background compile failure is recorded (classified via
  ``fault/errors.classify_error`` for telemetry) and **surfaced on the
  foreground ``benchmark()`` call** for that schedule: the stored exception
  is raised once on the caller's thread, where the
  :class:`~tenzing_tpu.fault.resilient.ResilientBenchmarker` above runs its
  normal classification, rank-coherent ``agree_fault`` agreement, and
  quarantine — exactly as if the compile had failed inline.  A transient
  verdict's retry passes through to a fresh foreground attempt (the stored
  failure is consumed by the raise).
* hints are *advisory*: they consume no solver RNG, touch no platform state
  (``provision_events`` is foreground-only bookkeeping), and a full queue
  drops excess hints rather than blocking — with prefetch disabled (or every
  hint dropped) behavior is bit-identical to today's.

Observability (docs/performance.md): ``pipeline.prefetch.issued`` /
``hits`` / ``wasted`` / ``failed`` / ``surfaced`` / ``dropped`` counters, a
``pipeline.queue_depth`` gauge, and a ``pipeline.precompile`` span per
background compile (the executor's ``executor.compile`` spans — ``aot: true``
for background ones — give the compile wall; overlap fraction falls out of
comparing them against ``bench.benchmark`` spans on the main thread).

Shutdown: ``close()`` cancels pending compiles and joins the workers (no
leaked threads); a SIGINT/SIGABRT trap handler (utils/trap.py) only flips
the closed flag — it must not touch pool locks the interrupted thread may
hold — after which the signal's SIG_DFL re-raise tears the process down
(running compiles are abandoned like the resilient watchdog's workers;
Python cannot interrupt a thread blocked in C).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor, wait
from typing import List, Optional

from tenzing_tpu.bench.benchmarker import BenchOpts, BenchResult, schedule_id
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.obs.metrics import get_metrics
from tenzing_tpu.obs.tracer import get_tracer
from tenzing_tpu.utils import trap


class PrefetchingBenchmarker:
    """Candidate-hint compile prefetcher (see module docstring).

    ``executor`` is anything with ``precompile(order) -> bool`` (and
    optionally ``is_compiled(order) -> bool``) — ``runtime.TraceExecutor``
    in production, a fake in tests.  ``workers`` bounds the pool;
    ``depth`` (default ``4 * workers``) bounds the in-flight queue — excess
    hints are dropped (re-hintable later), never queued unboundedly.
    ``rank`` (optional, e.g. the PR-2 ``SurrogateBenchmarker``) orders each
    hint batch most-promising-first by predicted time, so the compile budget
    lands on candidates most likely to be measured."""

    def __init__(self, inner, executor, workers: int = 2,
                 depth: Optional[int] = None, rank=None):
        self.inner = inner
        self.executor = executor
        self.workers = max(1, int(workers))
        self.depth = int(depth) if depth is not None else 4 * self.workers
        self.rank = rank
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="tz-prefetch")
        self._lock = threading.Lock()
        self._inflight: dict = {}   # schedule id -> Future
        self._failed: dict = {}     # schedule id -> background compile exc
        self._ready: set = set()    # precompiled, not yet consumed
        self._seen: set = set()     # ids ever submitted (dedup)
        self._closed = False
        # tallies mirrored into the metrics registry; read by the driver's
        # ``perf`` meta block (bench.py) and the pipeline tests
        self.issued = 0
        self.hits = 0
        self.failed = 0
        self.surfaced = 0
        self.dropped = 0
        # wrapper idiom of the fault stack: forward the batch protocol and
        # provenance probes only when the wrapped benchmarker offers them
        if hasattr(inner, "benchmark_batch_times"):
            self.benchmark_batch_times = self._batch_times
        self.rank_coherent = getattr(inner, "rank_coherent", False)
        self._wasted_counted = False
        self._trap_registered = True
        trap.register_handler(self._trap_cancel)

    # -- lifecycle ----------------------------------------------------------
    def __enter__(self) -> "PrefetchingBenchmarker":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _trap_cancel(self) -> None:
        """SIGINT/SIGABRT path: ONLY flip the closed flag — no pool calls.
        ``ThreadPoolExecutor.shutdown`` takes the same non-reentrant
        ``_shutdown_lock`` every ``submit()`` holds, and the trap runs on
        the interrupted thread (possibly mid-``prefetch``), so touching the
        pool here could deadlock the very dump path trap.py exists to
        protect.  The flag stops new work; the real signal path then
        re-raises via SIG_DFL (process dies, threads with it), and the
        test/cleanup path reaches :meth:`close`, which cancels + joins."""
        self._closed = True

    def close(self) -> None:
        """Cancel pending compiles and join the workers.  Idempotent (also
        after the trap handler already shut the pool down); after close
        every hint is a no-op and ``wasted()`` is final."""
        self._closed = True
        if self._trap_registered:
            self._trap_registered = False
            trap.unregister_handler(self._trap_cancel)
        # cancel_futures drops queued work; shutdown(wait=True) joins the
        # workers once their current compile returns (compiles finish — XLA
        # has no cancellation — so the join is bounded by one compile)
        self._pool.shutdown(wait=True, cancel_futures=True)
        if not self._wasted_counted:
            self._wasted_counted = True
            get_metrics().counter("pipeline.prefetch.wasted").inc(
                self.wasted())

    def wasted(self) -> int:
        """Background-compiled programs no foreground benchmark consumed
        (yet) — the cost of speculation, reported in the ``perf`` block."""
        with self._lock:
            return len(self._ready)

    def stats(self) -> dict:
        """The ``perf`` meta block's prefetch section."""
        return {
            "workers": self.workers,
            "issued": self.issued,
            "hits": self.hits,
            "wasted": self.wasted(),
            "failed": self.failed,
            "surfaced": self.surfaced,
            "dropped": self.dropped,
        }

    # -- hinting ------------------------------------------------------------
    def prefetch(self, orders) -> int:
        """Accept candidate hints; returns how many background compiles were
        actually issued.  Non-Sequence orders (CallableRunner names), dupes,
        already-compiled schedules, and hints beyond the queue bound are
        skipped — dropped hints may be re-hinted later (the DFS frontier
        window re-offers its slice every iteration)."""
        if self._closed:
            return 0
        cands: List[Sequence] = [o for o in orders
                                 if isinstance(o, Sequence)]
        # dedup BEFORE any ranking work: re-offered windows (the DFS
        # frontier slice arrives every iteration) must cost one memoized
        # schedule_id + set lookup per candidate, not a surrogate
        # featurization of schedules already submitted.  The live set is
        # read without the lock — _seen is mutated only by prefetch()
        # itself (one logical caller at a time), membership is GIL-atomic,
        # and the per-order re-check under the lock below is authoritative
        cands = [o for o in cands if schedule_id(o) not in self._seen]
        if not cands:
            return 0
        if self.rank is not None and len(cands) > 1:
            try:
                cands = sorted(cands,
                               key=lambda o: self.rank.predict(o)[0])
            except Exception:
                pass  # ranking is best-effort; hint order is advisory
        reg = get_metrics()
        is_compiled = getattr(self.executor, "is_compiled", None)
        issued = 0
        for order in cands:
            key = schedule_id(order)
            with self._lock:
                if self._closed or key in self._seen:
                    continue
                if len(self._inflight) >= self.depth:
                    self.dropped += 1
                    reg.counter("pipeline.prefetch.dropped").inc()
                    continue
                if is_compiled is not None and is_compiled(order):
                    self._seen.add(key)  # nothing to do, ever
                    continue
                self._seen.add(key)
                try:
                    fut = self._pool.submit(self._compile_one, key, order)
                except RuntimeError:  # pool shut down by the trap handler
                    self._seen.discard(key)
                    break
                self._inflight[key] = fut
                depth = len(self._inflight)
            issued += 1
            self.issued += 1
            reg.counter("pipeline.prefetch.issued").inc()
            reg.gauge("pipeline.queue_depth").set(depth)
        return issued

    def _compile_one(self, key: str, order: Sequence) -> None:
        """Worker body: AOT-compile one schedule, record success/failure.
        Runs off the control plane — errors are stored for the foreground,
        never raised into the pool."""
        reg = get_metrics()
        tr = get_tracer()
        try:
            with tr.span("pipeline.precompile", schedule=key):
                self.executor.precompile(order)
            with self._lock:
                self._ready.add(key)
        except BaseException as e:  # noqa: BLE001 — classified + surfaced
            from tenzing_tpu.fault.errors import classify_error

            reg.counter("pipeline.prefetch.failed").inc()
            if tr.enabled:
                tr.event("pipeline.precompile_failed", schedule=key,
                         error=type(e).__name__,
                         error_class=classify_error(e),
                         message=str(e)[:200])
            with self._lock:
                # under the lock: workers race each other on this tally
                # (every other tally is foreground-only)
                self.failed += 1
                self._failed[key] = e
        finally:
            with self._lock:
                self._inflight.pop(key, None)
                depth = len(self._inflight)
            reg.gauge("pipeline.queue_depth").set(depth)

    # -- foreground join ----------------------------------------------------
    def _join(self, order, cancel_queued: bool = True) -> None:
        """Settle any in-flight background compile for ``order``.

        A compile already RUNNING is waited on (the foreground pays only
        the remainder).  With ``cancel_queued``, a compile still queued
        BEHIND a backlog (more in flight than workers) is cancelled
        instead: compiling inline is faster than draining the queue, and a
        watchdog sized for one compile (``--measure-timeout``) must not
        fire on queue depth.  Without a backlog the future is about to run
        (or running) — waiting costs the inline compile at most, and a
        just-hinted schedule reliably lands as a prefetch hit."""
        with self._lock:
            fut = self._inflight.get(schedule_id(order))
            backlog = len(self._inflight) > self.workers
        if fut is None:
            return
        if cancel_queued and backlog and fut.cancel():
            # never started: _compile_one will not run, so drop the
            # in-flight entry here and let the foreground compile inline
            with self._lock:
                self._inflight.pop(schedule_id(order), None)
                depth = len(self._inflight)
            get_metrics().gauge("pipeline.queue_depth").set(depth)
            return
        wait([fut])

    def _consume(self, order) -> None:
        """Account a prefetch hit and surface a stored background compile
        failure ON THE CALLER'S THREAD — the resilient layer above
        classifies, agrees rank-coherently, and quarantines exactly as for
        an inline compile failure.  The failure is consumed: a retry after
        a transient verdict reaches the real (foreground) attempt."""
        key = schedule_id(order)
        with self._lock:
            exc = self._failed.pop(key, None)
            hit = key in self._ready
            self._ready.discard(key)
        reg = get_metrics()
        if hit:
            self.hits += 1
            reg.counter("pipeline.prefetch.hits").inc()
        if exc is not None:
            self.surfaced += 1
            reg.counter("pipeline.prefetch.surfaced").inc()
            raise exc

    def benchmark(self, order, opts: Optional[BenchOpts] = None) -> BenchResult:
        if isinstance(order, Sequence):
            self._join(order)
            self._consume(order)
        return self.inner.benchmark(order, opts)

    def _batch_times(self, orders, opts: Optional[BenchOpts] = None,
                     seed: int = 0, times_out=None, group_seeds=None):
        """Batch members parallel-compile across the pool before the inner
        batch warms them (today: a serial compile per member); a stored
        background failure for any member surfaces here, like the inline
        warmup failure it replaces.  Members queued behind an unrelated
        backlog take the same cancel-and-compile-inline escape as the
        single path — the resilient batch watchdog scales with the batch
        size, not with speculative work hinted earlier."""
        self.prefetch(orders)
        for o in orders:
            if isinstance(o, Sequence):
                self._join(o)
                self._consume(o)
        # forward group_seeds only when grouping is requested, so inner
        # benchmarkers that predate fused rounds keep their old signature
        kw = {} if group_seeds is None else {"group_seeds": group_seeds}
        return self.inner.benchmark_batch_times(
            orders, opts, seed=seed, times_out=times_out, **kw)

    def was_degraded(self, order) -> bool:
        fn = getattr(self.inner, "was_degraded", None)
        return bool(fn(order)) if fn is not None else False
