"""Persistent XLA compilation cache shared by the bench drivers.

The search wall is dominated by compiles (~3.4 s per distinct schedule — the
counter report in the driver tail), and repeat/confirm driver invocations
re-trace identical schedules; cache hits turn those into milliseconds, so the
same wall budget buys more search.  Measured times are unaffected (the cache
only skips the XLA compile step)."""

import os


def enable_compile_cache(min_compile_secs: float = 1.0) -> str:
    """Point JAX at the persistent compilation cache directory
    (``TZ_COMPILE_CACHE``, default /tmp/tz_jax_cache) and return the path."""
    import jax

    path = os.environ.get("TZ_COMPILE_CACHE", "/tmp/tz_jax_cache")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs
    )
    return path
