"""Cross-run search memory: rank recorded search-database rows for warm
starts.

The driver dumps every run's measured schedules as a CSV database (one row
per distinct schedule, naive as row 0 at final fidelity — bench.py
--dump-csv; the reference's mcts_csv checkpoint/replay workflow,
tenzing-mcts/examples).  ``rank_recorded`` turns a set of such databases
into the best distinct schedules to carry into the NEXT run as first-class
candidates and climb seeds.

Ranking is by each row's paired ratio against ITS OWN FILE's naive anchor:
absolute pct50s are not comparable across files because chip regimes swing
>1.3x between runs, and a cross-regime sort would drop exactly the
discoveries worth carrying (observed: the r4k 2.48x winner recorded in a
40 ms-naive regime vs stale 1.73x rows from a 16 ms regime)."""

from typing import List, Optional, Tuple

from tenzing_tpu.bench.benchmarker import CSV_DELIM, CsvBenchmarker, split_fidelity
from tenzing_tpu.core.schedule import remove_redundant_syncs
from tenzing_tpu.core.sequence import Sequence, canonical_key


def naive_anchor_of(path: str) -> Optional[float]:
    """The file's row-0 pct50, read numerically — the naive ops themselves
    may not resolve against a later graph (recorded pre-menu), but the
    anchor only needs the number.  None if the file has no row-0 anchor, or
    if row 0 carries a non-"full" fidelity tag: a screen-floor naive was
    measured ~100x off the regime every other anchor represents, and an
    off-regime anchor would corrupt every in-file ratio computed against it
    (the dump side asserts the same invariant — bench.py --dump-csv)."""
    with open(path) as f:
        first = f.readline().split(CSV_DELIM)
    try:
        if not first or first[0] != "0":
            return None
        fid, _ = split_fidelity([c.strip() for c in first])
        if fid != "full":
            return None
        return float(first[3])
    except (ValueError, IndexError):
        return None


def scored_rows(
    paths: List[str], graph, log=None
) -> Tuple[List[Tuple[float, float, Sequence, str]], dict]:
    """``(scored, stats)``: every admissible recorded row across
    ``paths`` as ``(in-file ratio, pct50, sequence, source path)``,
    best-ratio-first, plus ``{"files", "rows", "skipped"}`` counts.

    THE admission rule — FULL-fidelity rows with a positive pct50 that
    beat their own file's naive anchor — shared by the warm-start
    loader (:func:`rank_recorded`) and the serving store's warm path
    (serve/service.py), so the search's cross-run memory and the
    serving corpus can never drift on which rows count.  A
    multi-fidelity screen row's pct50 came from a far cheaper
    measurement floor than the file's naive anchor, so its in-file
    ratio is not a regime-honest score; rows that don't resolve against
    ``graph`` are skipped (strict=False); files without a naive anchor
    contribute nothing (regime unknown)."""
    scored: List[Tuple[float, float, Sequence, str]] = []
    n_files = n_rows = n_skip = 0
    for path in paths:
        try:
            anchor = naive_anchor_of(path)
            db = CsvBenchmarker.from_file(path, graph, strict=False,
                                          normalize=True)
        except Exception as e:  # unreadable file: report, keep going
            if log:
                log(f"recorded db: {path} unreadable ({e})")
            continue
        n_files += 1
        n_rows += len(db.entries)
        n_skip += len(db.skipped)
        # parallel by construction (CsvBenchmarker appends both in one
        # block); fail loudly rather than mislabel rows "full"
        assert len(db.fidelities) == len(db.entries)
        if anchor is None:
            continue
        for (seq, res), fid in zip(db.entries, db.fidelities):
            if fid == "full" and res.pct50 > 0 and anchor / res.pct50 > 1.0:
                scored.append((anchor / res.pct50, res.pct50, seq, path))
    scored.sort(key=lambda e: -e[0])
    return scored, {"files": n_files, "rows": n_rows, "skipped": n_skip}


def rank_recorded(
    paths: List[str], graph, topk: int, log=None
) -> List[Tuple[Sequence, float]]:
    """Top ``topk`` distinct recorded schedules across ``paths``, best-first
    by in-file paired ratio (admission: :func:`scored_rows`)."""
    scored, stats = scored_rows(paths, graph, log=log)
    n_rows, n_skip = stats["rows"], stats["skipped"]
    seen: set = set()
    out: List[Tuple[Sequence, float]] = []
    for ratio, _pct50, seq, _path in scored:
        if len(out) >= topk:
            break
        # dedup modulo redundant syncs — the same equivalence CsvBenchmarker
        # matches on (normalize=True), so a DFS-dumped and an MCTS-cleaned
        # copy of one program don't burn two warm-start slots
        key = canonical_key(remove_redundant_syncs(seq))
        if key in seen:
            continue
        seen.add(key)
        out.append((seq, ratio))
    if log and paths:
        log(
            f"recorded db: {len(paths)} files, {n_rows} rows "
            f"({n_skip} skipped), carrying top {len(out)} by in-file ratio: "
            + ", ".join(f"{r:.3f}" for _, r in out)
        )
    return out
