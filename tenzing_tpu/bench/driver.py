"""The library driver: workload builders + the search→gate→JSON loop.

``bench.py`` used to be a 1,678-line monolith: four workload builders, the
anytime search (greedy incumbents → recorded warm starts → MCTS →
hill-climbs), the paired screen/final verdict, the result-integrity gate,
attribution profiling, and the driver-JSON assembly — all inside one
``main()`` reachable only through argparse.  The schedule-serving
subsystem (``tenzing_tpu/serve/``, docs/serving.md) needs exactly that
loop as a *callable*: a cold request enqueues a work item a driver drains,
and the warm path needs the workload graphs without a CLI in the way.

This module is that API:

* :class:`DriverRequest` — the typed request, field-for-field the CLI's
  argparse namespace (defaults asserted equal by tests/test_driver.py, so
  the two can never drift);
* :func:`run` — the whole search→gate→JSON loop; returns a
  :class:`DriverResult` whose ``verdict`` dict, serialized, is
  byte-identical to the JSON line ``bench.py`` prints;
* :func:`build_workload` / :func:`graph_for` / :func:`workload_shape` —
  the workload builders, with a device-free graph/shape path for serving
  (fingerprints and corpus deserialization must not touch a backend);
* :exc:`DriverConfigError` — an invalid request (the shim maps it to
  ``argparse.error``, keeping CLI behavior identical).

``bench.py`` is now a thin argparse shim over this module.

Workloads (``DriverRequest.workload`` / the CLI's ``--workload``):
* ``halo`` (default, the north-star metric — BASELINE.md): the 3D
  halo-exchange pipeline (nQ=3, 512^3 cells, radius 3, the reference config
  halo_run_strategy.hpp:42-49) as six pack -> post -> await -> unpack chains
  whose transfers are async host round-trip DMAs; MCTS searches order x lane x
  kernel (XLA slice vs Pallas plane-DMA) against the fully-synchronous naive
  serialization.
* ``spmv``: distributed-SpMV iteration (reference config: m=150000 rows,
  nnz=10*m, band matrix, 2 lanes — spmv_run_strategy.cuh:44-47).
* ``attn``: single-chip blockwise (flash) attention over a long context —
  the kernel menu (XLA vs Pallas MXU) plus order x lane space.
* ``moe``: single-chip MoE dispatch/combine pipeline — routed tokens staged
  through async host round-trip DMAs to the resident experts (the
  expert-parallel network-hop analog), searched over order x lane x
  expert-kernel (XLA vs Pallas) across independent microbatch chunk chains.

The search is anytime: greedy domain incumbents (for halo, an engine x
lane-count grid), the best recorded schedules from previous runs' databases
(``--seed-csv``, bench/recorded.py — cross-run search memory ranked by
in-file paired ratio), and a FastMin MCTS that explores at CHEAP measurement
cost — search-time numbers only steer the tree — followed by drift-immune
hill-climbs seeded from the best recorded schedule's menu choices and from
the strongest hand disciplines.  Candidate selection and the
verdict are both *paired decorrelated batches* (reference batch benchmark,
benchmarker.cpp:21-76): a moderate-cost screen ranks the distinct candidates
by paired per-iteration speedup vs naive and drops anything below 1.0, then
the final batch (3x iterations, 20x adaptive measurement floor,
benchmarker.cpp:83-119) re-measures naive + the top 3 survivors together,
visited in a fresh random order per iteration.  ``vs_baseline`` is the best
finalist's **paired speedup** (median of naive[k]/cand[k] with a bootstrap
CI, utils.numeric.paired_speedup) — drift common to both schedules cancels
instead of masquerading as, or drowning, a schedule difference; a win
additionally requires the CI to exclude 1.0.

Prints ONE JSON line:
  {"metric": ..., "value": <best pct50, us>, "unit": "us",
   "vs_baseline": <naive_pct50 / best_pct50>}

On backend-init failure (e.g. the TPU tunnel is down — the way round 1's
BENCH died, VERDICT r1 item 1) the device is probed first with one retry, and
failure still prints a parseable JSON line with an ``error`` field.

``--smoke`` runs a tiny CPU-friendly configuration (used by tests/CI).
"""

from __future__ import annotations

import dataclasses
import json
import os as _os_mod
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# the CLI's relative default globs (--seed-csv) resolve against the repo
# root, where bench.py lives — anchored here so the extracted driver keeps
# resolving the same files the monolith did
REPO_ROOT = _os_mod.path.dirname(_os_mod.path.dirname(
    _os_mod.path.dirname(_os_mod.path.abspath(__file__))))


class DriverConfigError(ValueError):
    """An invalid :class:`DriverRequest` — the library analog of
    ``argparse.ArgumentParser.error`` (the CLI shim catches it and calls
    exactly that, so bad flag combinations fail identically to the
    monolith)."""


@dataclass
class DriverRequest:
    """The driver's typed request — field-for-field the ``bench.py``
    argparse namespace, with identical defaults (tests/test_driver.py
    asserts the parser and this dataclass agree, so CLI and API can never
    drift).  Construct with keyword overrides and hand to :func:`run`;
    the shim builds one via ``DriverRequest(**vars(args))``."""

    smoke: bool = False
    workload: str = "halo"
    moe_tokens: int = 8192
    m: Optional[int] = None
    spmv_bw: Optional[int] = None
    halo_n: int = 512
    lanes: Optional[int] = None
    mcts_iters: int = 56
    iters: int = 20
    search_iters: int = 6
    climb_budget: int = 44
    prefetch_compiles: int = 2
    dump_csv: Optional[str] = None
    trace_out: Optional[str] = None
    metrics_json: Optional[str] = None
    seed_csv: Optional[str] = None
    seed_topk: int = 3
    learn_train: Optional[List[str]] = None
    learn_trace: Optional[List[str]] = None
    learn_model: Optional[str] = None
    learn_screen: bool = False
    checkpoint: Optional[str] = None
    resume: bool = False
    measure_timeout: Optional[float] = None
    inject_faults: Optional[str] = None
    inject_hang_secs: float = 60.0
    profile_winner: bool = False
    profile_repeats: int = 7
    fuse_winner: bool = False
    fuse_search_tiles: bool = False
    chunk: bool = False
    synth_collectives: bool = False
    no_verify: bool = False
    verify_tol: float = 0.02
    search_workers: int = 0
    measure_batch: int = 0

    def to_json(self) -> Dict[str, Any]:
        """A JSON-ready dict (the serve work-queue payload —
        ``DriverRequest(**item)`` round-trips)."""
        return dataclasses.asdict(self)


@dataclass
class DriverResult:
    """What :func:`run` returns: the verdict dict whose ``json.dumps`` is
    the driver JSON line (key order preserved — the shim's print is
    byte-identical to the monolith's)."""

    verdict: Dict[str, Any] = field(default_factory=dict)

    def to_json_line(self) -> str:
        return json.dumps(self.verdict)


def probe_backend(retries: int = 1, wait_secs: float = 15.0):
    """Initialize the JAX backend, retrying on transient tunnel failure via
    the shared backoff helper (fault/backoff.py — each retry lands as a
    ``fault.retry`` obs event with attempt count and error class).  Returns
    the device list; raises after the final retry."""
    import jax

    from tenzing_tpu.fault.backoff import BackoffPolicy, retry_call

    def on_retry(e, attempt, delay):
        sys.stderr.write(f"backend init failed (attempt {attempt + 1}): {e}\n")
        # a failed init is cached; clear and retry fresh
        import jax.extend as jex

        jex.backend.clear_backends()

    return retry_call(
        jax.devices,
        policy=BackoffPolicy(retries=retries, base_secs=wait_secs,
                             factor=2.0, jitter=0.25),
        # the legacy probe retried any RuntimeError from backend init —
        # broader than the transient-only default, and right here: an init
        # failure is a tunnel/plugin problem, never a broken schedule
        retry_on=lambda e: isinstance(e, RuntimeError),
        where="backend.init",
        on_retry=on_retry,
    )


# the measured per-face aliased-unpack recipe (the r5 discovery, see
# experiments/MENU_INCUMBENT2.json / MENU_INCUMBENT3.json): the ghost-shell
# write must lower IN PLACE (a non-aliased write copies the 2.07 GB grid,
# ~5 ms) and these are the aliased Pallas kernels per face axis.  ONE
# definition — the greedy incumbents and the climb seeds must refine the
# same recipe.
ALIAS_UNPACK = {"x": ".pallas", "y": ".pallasf", "z": ".pallasb"}


def alias_unpack_choice(op_name, choices):
    """The aliased kernel for an ``unpack_*`` op from the menu, or None when
    it is off-menu — the one lookup both the greedy seeding and the climb
    disciplines share."""
    want = ALIAS_UNPACK[op_name[-1]]
    return next((c for c in choices if c.endswith(want)), None)


def generic_xla_prefer(op_name, choices):
    """Workload-agnostic default policy: the plain XLA lowering when the
    menu has one — the fleet's smoke-job prefer (safe on any workload)."""
    return next((c for c in choices if c.endswith(".xla")), None)


def halo_alias_prefer(op_name, choices):
    """The halo climb policy: all-rdma + the aliased-unpack kernel map (the
    measured r5 recipe — in-place ghost-shell writes per face,
    MENU_INCUMBENT2/3).  Module-level so a fleet worker process can rebuild
    it by name from the job spec (search/fleet.py resolve_prefer)."""
    if op_name.startswith("xfer_"):
        return next((c for c in choices if c.endswith(".rdma")), None)
    if op_name.startswith("unpack_"):
        hit = alias_unpack_choice(op_name, choices)
        if hit is not None:
            return hit
    return next((c for c in choices if c.endswith(".xla")), None)


def moe_bf16_prefer(op_name, choices):
    """The moe climb policy: whole-chain staging choice — device-resident
    bf16 transfers (the measured 10.97x winner); kernel choices default to
    XLA."""
    return next(
        (c for c in choices if c.endswith(".bf16-rdma")),
        next((c for c in choices if c.endswith(".xla")), None),
    )


def recorded_prefer(chosen: Dict[str, str]):
    """The climb policy replicating a recorded winner's menu choices
    (``chosen``: base op name -> ``".suffix"``) — the factory form of the
    legacy closure, so a fleet worker can rebuild it from the job spec's
    serialized ``chosen`` map."""

    def prefer(op_name, choices):
        want = chosen.get(op_name)
        if want is not None:
            c = next((c for c in choices if c.endswith(want)), None)
            if c is not None:
                return c
        if op_name.startswith("xfer_"):
            # a recorded host-staged transfer leaves no "xfer_*" vertex
            # (the HostRoundTrip compound expands into spill/fetch)
            return next((c for c in choices if c.endswith(".host")), None)
        return next((c for c in choices if c.endswith(".xla")), None)

    return prefer


def metric_for(workload: str, args) -> str:
    """The metric name for a workload config — the single source both the
    success path (build_* return) and the backend-init-failure path use, so
    the two always land in the same metric series."""
    if workload == "halo":
        return f"halo_iter_pct50_searched_n{4 if args.smoke else args.halo_n}"
    if workload == "spmv":
        m = args.m if args.m is not None else (512 if args.smoke else 150_000)
        sfx = f"_bw{args.spmv_bw}" if args.spmv_bw is not None else ""
        return f"spmv_iter_pct50_searched_m{m}{sfx}"
    if workload == "moe":
        t = 32 if args.smoke else args.moe_tokens
        return f"moe_pipe_pct50_searched_t{t}"
    n_ctx = 4 * 16 if args.smoke else 8 * 1024
    return f"attn_blockwise_pct50_searched_n{n_ctx}"


def workload_cost(workload: str, built):
    """The workload's roofline :class:`~tenzing_tpu.bench.roofline.Cost`
    for the attribution profiler's fraction-of-peak join (``built`` is the
    matching ``build_*`` return).  One iteration's arithmetic + traffic —
    the same accounting experiments/halo_roofline.py reports against."""
    from tenzing_tpu.bench import roofline

    if workload == "halo":
        h = built[3]
        return roofline.halo_cost(h.nq, h.lx, h.ly, h.lz, h.radius)
    if workload == "spmv":
        m = built[3]
        return roofline.spmv_cost(m, nnz=10 * m)
    if workload == "moe":
        margs = built[3][0]
        return roofline.moe_cost(margs.tokens, margs.d_model, margs.d_ff,
                                 staged=True, n_experts=margs.n_experts)
    a = built[3]  # attn
    return roofline.attention_cost(a.batch, a.n_devices * a.seq_local,
                                   a.head_dim)


def build_halo(args):
    from tenzing_tpu.models.halo import HaloArgs
    from tenzing_tpu.models.halo_pipeline import (
        build_graph,
        host_buffer_names,
        make_pipeline_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    if args.smoke:
        hargs = HaloArgs(nq=2, lx=4, ly=4, lz=4, radius=1)
    else:
        n = args.halo_n
        hargs = HaloArgs(nq=3, lx=n, ly=n, lz=n, radius=3)
    bufs, _ = make_pipeline_buffers(hargs, seed=0, with_expected=False)
    jbufs = TraceExecutor.place_host_buffers(bufs, host_buffer_names())
    # kernel + transfer-engine menus only where a real TPU compiles them;
    # interpret-mode Pallas would dominate a CPU smoke timing
    impl_choice = not args.smoke
    g = build_graph(hargs, impl_choice=impl_choice, xfer_choice=impl_choice)
    return g, jbufs, metric_for("halo", args), hargs


def build_spmv(args):
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.spmv import (
        SpMVCompound,
        make_spmv_buffers,
        spmv_host_buffer_names,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    m = args.m if args.m is not None else (512 if args.smoke else 150_000)
    # --spmv-bw widens the band, growing the remote-column exchange relative
    # to the local compute: the transfer-bound sweep of VERDICT r2 item 7
    synth = bool(args.synth_collectives)
    bufs, _ = make_spmv_buffers(m=m, nnz_per_row=10, bw=args.spmv_bw, seed=0,
                                synth=synth)
    n_rem = int(bufs["x_remote"].shape[0])
    jbufs = TraceExecutor.place_host_buffers(
        bufs, spmv_host_buffer_names(n_rem, synth=synth))
    # impl_choice: the kernel menu (XLA gather vs Pallas vreg-gather) is part
    # of the searched space alongside order and lane assignment; known x sizes
    # prune Pallas choices that would only alias the XLA path (ADVICE r1).
    # exchange="host": the x exchange is a posted async host round-trip DMA
    # (the reference's MPI hop), so the post/wait split gives the search a
    # real transfer to hide behind the local SpMV
    x_sizes = {"x_local": int(jbufs["x_local"].shape[0]),
               "x_remote": int(jbufs["x_remote"].shape[0])}
    mk = lambda: SpMVCompound(impl_choice=True, x_sizes=x_sizes,
                              exchange="host", synth=synth,
                              synth_relax=args.smoke)
    g = Graph()
    g.start_then(mk())
    g.then_finish(mk())
    return g, jbufs, metric_for("spmv", args), m


def build_moe(args):
    from tenzing_tpu.models.moe_pipeline import (
        MoEPipeArgs,
        build_graph,
        host_buffer_names,
        make_pipe_buffers,
    )
    from tenzing_tpu.runtime.executor import TraceExecutor

    if args.smoke:
        margs = MoEPipeArgs(n_experts=4, tokens=32, d_model=8, d_ff=16,
                            n_chunks=2)
    else:
        margs = MoEPipeArgs(tokens=args.moe_tokens)
    # the searched space includes the staging-precision menu (f32 vs
    # half-width bf16 transfers) on the real chip
    staging = "f32" if args.smoke else "choice"
    bufs, _, cap = make_pipe_buffers(margs, seed=0, with_expected=False,
                                     staging=staging)
    jbufs = TraceExecutor.place_host_buffers(
        bufs, host_buffer_names(margs, staging=staging))
    impl_choice = not args.smoke  # same rationale as build_halo
    g = build_graph(margs, cap, impl_choice=impl_choice, staging=staging,
                    chunk=args.chunk, chunk_relax=args.smoke)
    return g, jbufs, metric_for("moe", args), (margs, cap)


def build_attn(args):
    import jax.numpy as jnp

    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.models.ring_attention import (
        BlockedAttention,
        RingAttnArgs,
        make_blocked_buffers,
    )

    if args.smoke:
        aargs = RingAttnArgs(n_devices=4, batch=1, seq_local=16, head_dim=8)
    else:
        # 8k context in 8 blocks of 1024, head dim 128
        aargs = RingAttnArgs(n_devices=8, batch=4, seq_local=1024, head_dim=128)
    bufs, _ = make_blocked_buffers(aargs, seed=0)
    bufs = {k: jnp.asarray(v) for k, v in bufs.items()}
    g = Graph()
    op = BlockedAttention(aargs, impl_choice=True, fused_choice=True,
                          chunk=args.chunk, chunk_relax=args.smoke)
    g.start_then(op)
    g.then_finish(op)
    return g, bufs, metric_for("attn", args), aargs


# workload name -> device builder (graph + device-placed buffers + metric +
# workload args) — the search path's entry; serving uses graph_for below
BUILDERS = {"halo": build_halo, "spmv": build_spmv, "attn": build_attn,
            "moe": build_moe}


def build_workload(req: DriverRequest):
    """``(graph, buffers, metric, workload-args)`` for ``req`` — the
    device-placing builder dispatch :func:`run` uses (buffers land in
    pinned host / device memory; needs an initialized backend)."""
    return BUILDERS[req.workload](req)


def workload_shape(req: DriverRequest) -> Dict[str, int]:
    """The request's exact shape parameters, as the builders resolve them
    — THE single source the serving fingerprint keys on (serve/
    fingerprint.py), kept next to the builders so a new shape knob cannot
    silently stay out of the fingerprint.  Pure request arithmetic: no
    jax, no buffers, no backend."""
    w = req.workload
    if w == "halo":
        if req.smoke:
            return {"nq": 2, "n": 4, "radius": 1}
        return {"nq": 3, "n": req.halo_n, "radius": 3}
    if w == "spmv":
        m = req.m if req.m is not None else (512 if req.smoke else 150_000)
        # bw resolves exactly as models/spmv.py make_spmv_buffers does
        # (None -> max(1, m // 8)): a default request and an explicit
        # --spmv-bw of the same value build the SAME matrix and must
        # fingerprint identically, or independently-warmed stores
        # fragment and exact hits are missed
        bw = req.spmv_bw if req.spmv_bw is not None else max(1, m // 8)
        return {"m": m, "nnz_per_row": 10, "bw": bw}
    if w == "moe":
        if req.smoke:
            return {"n_experts": 4, "tokens": 32, "d_model": 8, "d_ff": 16,
                    "n_chunks": 2}
        return {"tokens": req.moe_tokens}
    if w == "attn":
        if req.smoke:
            return {"n_devices": 4, "batch": 1, "seq_local": 16,
                    "head_dim": 8}
        return {"n_devices": 8, "batch": 4, "seq_local": 1024,
                "head_dim": 128}
    raise DriverConfigError(f"unknown workload {w!r}")


def search_lanes(req: DriverRequest) -> int:
    """The search platform's lane count for ``req`` — the same default
    rule :func:`run` applies (8 for full-size halo, else 2, unless
    overridden), exposed so the serving fingerprint's mesh signature and
    the search agree by construction."""
    if req.lanes:
        return req.lanes
    return 8 if req.workload == "halo" and not req.smoke else 2


def graph_for(req: DriverRequest):
    """``(graph, nbytes)`` for ``req`` **without touching a backend**: the
    choice graph recorded schedules deserialize/verify against, plus a
    buffer-size map for the surrogate featurizer.  The serving path's
    builder (docs/serving.md): resolution and corpus warm-up must work on
    a host with no accelerator at all.

    ``nbytes`` is ``{}`` for the full-size halo config — materializing its
    2 GB grid just to read ``.nbytes`` is not a serving-path cost; the
    featurizer degrades to zero comm-bytes features, consistently at train
    and predict time because both sides use this same map.

    The other workloads DO build their (tens-of-MB) host buffers once per
    fingerprint, deliberately: spmv's choice graph depends on the
    constructed buffers (``x_sizes`` comes from the random band matrix's
    actual remote-column split), so deriving sizes analytically here
    would risk a serving-side graph that silently diverges from the one
    the driver searches — a correctness risk worth more than a transient
    allocation that the resolver's per-fingerprint cache amortizes."""
    w = req.workload
    impl_choice = not req.smoke
    if w == "halo":
        from tenzing_tpu.models.halo import HaloArgs
        from tenzing_tpu.models.halo_pipeline import build_graph

        s = workload_shape(req)
        hargs = HaloArgs(nq=s["nq"], lx=s["n"], ly=s["n"], lz=s["n"],
                         radius=s["radius"])
        g = build_graph(hargs, impl_choice=impl_choice,
                        xfer_choice=impl_choice)
        nbytes: Dict[str, int] = {}
        if req.smoke:
            from tenzing_tpu.models.halo_pipeline import make_pipeline_buffers

            bufs, _ = make_pipeline_buffers(hargs, seed=0,
                                            with_expected=False)
            nbytes = {k: int(getattr(v, "nbytes", 0))
                      for k, v in bufs.items()}
        return g, nbytes
    if w == "spmv":
        from tenzing_tpu.core.graph import Graph
        from tenzing_tpu.models.spmv import SpMVCompound, make_spmv_buffers

        s = workload_shape(req)
        synth = bool(req.synth_collectives)
        bufs, _ = make_spmv_buffers(m=s["m"], nnz_per_row=s["nnz_per_row"],
                                    bw=req.spmv_bw, seed=0, synth=synth)
        x_sizes = {"x_local": int(bufs["x_local"].shape[0]),
                   "x_remote": int(bufs["x_remote"].shape[0])}
        mk = lambda: SpMVCompound(impl_choice=True, x_sizes=x_sizes,
                                  exchange="host", synth=synth,
                                  synth_relax=req.smoke)
        g = Graph()
        g.start_then(mk())
        g.then_finish(mk())
        return g, {k: int(getattr(v, "nbytes", 0)) for k, v in bufs.items()}
    if w == "moe":
        from tenzing_tpu.models.moe_pipeline import (
            MoEPipeArgs,
            build_graph,
            make_pipe_buffers,
        )

        margs = MoEPipeArgs(**workload_shape(req))
        staging = "f32" if req.smoke else "choice"
        bufs, _, cap = make_pipe_buffers(margs, seed=0, with_expected=False,
                                         staging=staging)
        g = build_graph(margs, cap, impl_choice=impl_choice, staging=staging,
                        chunk=req.chunk, chunk_relax=req.smoke)
        return g, {k: int(getattr(v, "nbytes", 0)) for k, v in bufs.items()}
    if w == "attn":
        from tenzing_tpu.core.graph import Graph
        from tenzing_tpu.models.ring_attention import (
            BlockedAttention,
            RingAttnArgs,
            make_blocked_buffers,
        )

        aargs = RingAttnArgs(**workload_shape(req))
        bufs, _ = make_blocked_buffers(aargs, seed=0)
        g = Graph()
        op = BlockedAttention(aargs, impl_choice=True, fused_choice=True,
                              chunk=req.chunk, chunk_relax=req.smoke)
        g.start_then(op)
        g.then_finish(op)
        return g, {k: int(getattr(v, "nbytes", 0)) for k, v in bufs.items()}
    raise DriverConfigError(f"unknown workload {w!r}")


def _mismatched_outputs(out_a, out_b, tol: float) -> List[str]:
    """THE numeric-agreement policy of the result-integrity gate: names
    (shared by both output dicts) whose arrays differ in shape or fail
    ``allclose(rtol=tol, atol=tol*1e-3, equal_nan=True)`` in float64.
    Used by the winner-vs-naive gate and the fused-vs-stepped gate — one
    copy, so a tolerance or NaN-policy change cannot split their
    semantics."""
    import jax as _jax
    import numpy as _np

    mismatched = []
    for name in sorted(set(out_a) & set(out_b)):
        a = _np.asarray(_jax.device_get(out_a[name]), dtype=_np.float64)
        b = _np.asarray(_jax.device_get(out_b[name]), dtype=_np.float64)
        if a.shape != b.shape or not _np.allclose(
                a, b, rtol=tol, atol=tol * 1e-3, equal_nan=True):
            mismatched.append(name)
    return mismatched


class _RunScope:
    """Per-call registration bookkeeping for :func:`run`.

    The monolith registered its crash-path handlers (telemetry flush,
    prefetcher shutdown, checkpoint cursor stamps) with ``atexit`` and
    the signal trap and simply leaked them — correct for a one-shot CLI
    process, wrong for the library API a work-queue drainer calls in a
    loop: item N's SIGINT must not fire item N-1's handlers (stamping
    ``interrupted`` into checkpoints of runs that completed cleanly),
    and each run's closures must not pin its executor and buffers until
    process exit.  The scope registers exactly like the monolith while
    the run is live, then on close runs each exit finalizer once (they
    are all idempotent — the same calls the success path already makes
    explicitly) and unregisters everything."""

    def __init__(self):
        self._finalizers: list = []
        self._traps: list = []

    def on_exit(self, fn) -> None:
        """Run ``fn`` at scope close AND (as a crash backstop while the
        scope is live) at interpreter exit."""
        import atexit

        atexit.register(fn)
        self._finalizers.append(fn)

    def on_trap(self, fn) -> None:
        """Run ``fn`` on SIGINT/SIGABRT while the scope is live."""
        from tenzing_tpu.utils import trap

        trap.register_handler(fn)
        self._traps.append(fn)

    def close(self) -> None:
        import atexit

        from tenzing_tpu.utils import trap

        # LIFO, like the atexit machinery these used to ride on: the
        # prefetcher's close() (registered after write_telemetry) must
        # finalize the pipeline counters BEFORE the telemetry flush
        # writes them out on a crash path
        for fn in reversed(self._finalizers):
            try:
                fn()
            except Exception as e:  # a failed finalizer must not mask
                sys.stderr.write(   # the run's own result/exception
                    f"driver: finalizer {getattr(fn, '__name__', fn)!r} "
                    f"failed ({type(e).__name__}: {str(e)[:120]})\n")
        for fn in self._finalizers:
            atexit.unregister(fn)
        for fn in self._traps:
            trap.unregister_handler(fn)
        self._finalizers.clear()
        self._traps.clear()


def run(req: DriverRequest) -> DriverResult:
    """Execute the whole search→gate→verdict loop for ``req``.

    Safe to call repeatedly in one process (the work-queue drain loop,
    docs/serving.md): every atexit/signal registration is scoped to the
    call and disposed on return, so runs cannot stamp each other's
    checkpoints or accumulate handlers.  One process-wide caveat: a
    ``smoke`` request pins ``jax_platforms`` to CPU for the remainder of
    the process (JAX backend selection is process-global and sticks
    after first initialization) — drain smoke and full-size items in
    separate processes."""
    # a shallow copy: run() resolves defaults in place (seed_csv globs,
    # smoke iteration caps) exactly like the monolith mutated its argparse
    # namespace, without surprising a caller who reuses the request
    args = dataclasses.replace(req)
    if args.workload not in BUILDERS:
        # validate BEFORE the backend probe: argparse choices protect
        # the CLI, but a library caller (a drainer on a hand-edited work
        # item) must get the API's config error, not a KeyError after a
        # wasted init/retry cycle — or worse, a backend-failure verdict
        # mislabeled into metric_for's fall-through metric series
        raise DriverConfigError(f"unknown workload {args.workload!r}")
    if args.resume and not args.checkpoint:
        # silently ignoring resume would re-measure a multi-hour search
        # from scratch while the output JSON claims a resume happened
        raise DriverConfigError("--resume requires --checkpoint DIR")
    # adopt a parent process's trace context (obs/context.py): a drain
    # child spawned by the daemon — or a bare bench.py run under
    # TENZING_TRACE_CONTEXT — stamps every span/event with the
    # originating query's trace_id, so its bundle stitches into the
    # fleet trace.  Installed as the process default (worker threads —
    # the prefetch pool — inherit it) and restored on return: run() is
    # called in a loop by in-process drainers.
    from tenzing_tpu.obs import context as _obs_context

    env_ctx = None
    prev_ctx = None
    if _obs_context.current() is None:
        env_ctx = _obs_context.from_env()
        if env_ctx is not None:
            prev_ctx = _obs_context.set_process_default(env_ctx)
    scope = _RunScope()
    try:
        return _run(args, scope)
    finally:
        scope.close()
        if env_ctx is not None:
            _obs_context.set_process_default(prev_ctx)


def _run(args: DriverRequest, scope: _RunScope) -> DriverResult:

    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from tenzing_tpu.bench.compile_cache import enable_compile_cache

    compile_cache_dir = enable_compile_cache()

    from tenzing_tpu import obs

    if args.trace_out:
        obs.configure(enabled=True)

    _telemetry_done = {"v": False}
    # per-lane Gantt tracks from --profile-winner (chrome trace-event
    # dicts, obs/attrib/explain.py): filled late in the run, exported by
    # write_telemetry into the same Perfetto bundle as the PR-1 spans
    attrib_extra: list = []

    def write_telemetry():
        """Archive the telemetry bundle once.  Registered with atexit (for
        crashes: the interpreter still exits normally after an unhandled
        exception) AND with utils.trap (for SIGINT/SIGABRT: the trap handler
        re-raises via SIG_DFL, which kills the process without running
        atexit) so an interrupted search — the run where the trace matters
        most — still archives everything recorded so far.  The explicit call
        on the success path just makes the files land before the final JSON
        line.  Filenames are rank-qualified past rank 0 so multi-host runs
        writing to a shared directory do not clobber each other's bundles."""
        import os

        if _telemetry_done["v"]:
            return
        _telemetry_done["v"] = True
        rank = obs.get_tracer().rank
        sfx = "" if rank == 0 else f".rank{rank}"
        if args.trace_out:
            os.makedirs(args.trace_out, exist_ok=True)
            obs.write_jsonl(obs.get_tracer(),
                            os.path.join(args.trace_out, f"trace{sfx}.jsonl"))
            obs.write_chrome_trace(
                obs.get_tracer(),
                os.path.join(args.trace_out, f"trace{sfx}.json"),
                extra_events=attrib_extra or None)
            sys.stderr.write(f"trace bundle: {args.trace_out}\n")
        if args.metrics_json:
            # block=False: this runs from the signal trap, where the
            # interrupted thread may hold an instrument lock — the
            # non-blocking read falls back to GIL-atomic copies instead of
            # deadlocking the Ctrl-C path (the exporters above are
            # non-blocking by construction, obs/export.py)
            with open(args.metrics_json + sfx, "w") as f:
                json.dump(obs.get_metrics().to_json(block=False), f,
                          indent=2, sort_keys=True)
            sys.stderr.write(f"metrics: {args.metrics_json}{sfx}\n")

    if args.trace_out or args.metrics_json:
        scope.on_exit(write_telemetry)
        scope.on_trap(write_telemetry)

    metric_name = metric_for(args.workload, args)
    try:
        devs = probe_backend()
        sys.stderr.write(f"backend: {devs}\n")
    except Exception as e:  # still emit a parseable line (VERDICT r1 item 1)
        write_telemetry()
        return DriverResult(verdict={
            "metric": metric_name,
            "value": -1.0,
            "unit": "us",
            "vs_baseline": 0.0,
            "error": f"backend init failed: {e}",
        })

    from tenzing_tpu.bench.benchmarker import (
        BenchOpts,
        CachingBenchmarker,
        EmpiricalBenchmarker,
        result_row,
    )
    from tenzing_tpu.core.platform import Platform
    from tenzing_tpu.core.state import State
    from tenzing_tpu.runtime.executor import TraceExecutor
    from tenzing_tpu.solve.mcts import MctsOpts, explore
    from tenzing_tpu.solve.mcts.strategies import FastMin

    built = BUILDERS[args.workload](args)
    g, bufs, metric = built[0], built[1], built[2]
    # buffer byte sizes feed the surrogate's comm-bytes + analytic-makespan
    # features (learn/features.py) — the same map for train and screen, so
    # the feature contract holds across the two phases
    learn_nbytes = {k: int(getattr(v, "nbytes", 0)) for k, v in bufs.items()}

    if args.learn_train:
        # corpus -> features -> ridge ensemble -> model JSON, then exit:
        # training is offline (no device measurement), it only needs the
        # workload graph to deserialize the recorded schedules against
        import glob as _glob

        from tenzing_tpu import obs as _obs
        from tenzing_tpu.learn import train_from_corpus

        log = lambda m: sys.stderr.write(m + "\n")
        paths = sorted(p for pat in args.learn_train
                       for p in _glob.glob(pat))
        with _obs.get_tracer().span("learn.train", n_files=len(paths)):
            tpaths = (sorted(p for pat in args.learn_trace
                             for p in _glob.glob(pat))
                      if args.learn_trace else None)
            # THE shared training recipe (learn/train.py) — the serving
            # warm path trains through the same call
            model, info = train_from_corpus(
                paths, g, nbytes=learn_nbytes, trace_paths=tpaths, log=log)
            out = {"metric": f"learn_train_{args.workload}", **info}
            if model is not None and args.learn_model:
                model.save(args.learn_model)
                out["model"] = args.learn_model
                log(f"learn model: {args.learn_model} "
                    f"({info['rows']} rows, train spearman "
                    f"{out['train_spearman']})")
        write_telemetry()
        return DriverResult(verdict=out)

    surrogate = None
    if args.learn_screen and args.learn_model:
        from tenzing_tpu.learn import (
            FEATURE_NAMES,
            RidgeEnsemble,
            SurrogateBenchmarker,
        )

        model = RidgeEnsemble.load(args.learn_model,
                                   expect_features=list(FEATURE_NAMES))
        surrogate = SurrogateBenchmarker(model, nbytes=learn_nbytes)
        sys.stderr.write(
            f"learn screen: {args.learn_model} "
            f"({model.n_train} training rows)\n")
    elif args.learn_screen:
        sys.stderr.write("learn screen: no --learn-model given — "
                         "screening disabled\n")
    # 8 lanes for halo: the probed greedy lane-count curve peaks at 6-8 lanes
    # (paired 1.38-1.42 vs 1.18-1.23 at 2) and the repeat driver winner is the
    # mixed-engine 8-lane incumbent — searching on 8 lanes puts the hill-climb
    # and MCTS in the same neighborhood instead of a 6-lane one.  Smoke stays
    # at 2 lanes and a small tree (the CPU path exists to be cheap).
    # THE default rule lives in search_lanes() — the serving fingerprint's
    # mesh signature keys on the same call, so the two cannot drift
    n_lanes = search_lanes(args)
    plat = Platform.make_n_lanes(n_lanes)
    if args.smoke:
        args.mcts_iters = min(args.mcts_iters, 12)
    ex = TraceExecutor(plat, bufs)
    # --fuse-search-tiles (ISSUE 10 satellite of the PR-8 backend): plant
    # the megakernel tile menu as a decision node in the choice graph BEFORE
    # the verifier/search are built, so MCTS/DFS/hill-climb search tile
    # counts in-driver (the way tests/test_fused.py drives the library
    # workloads) instead of only sweeping the menu post-verdict.  Every
    # measurement then lowers through the schedule's ``fuse_tile.tN``
    # directive (FusedExecutor reads it back; tiles=None).
    measure_ex = ex
    tile_menu = None
    tile_planted = False
    if args.fuse_search_tiles:
        from tenzing_tpu.runtime.fused import FusedExecutor, with_tile_menu

        # the menu needs a complete schedule to partition: the cheap
        # first-decision serialization on one lane (host-side only)
        probe_state = State(g)
        probe_plat = Platform.make_n_lanes(1)
        while not probe_state.is_terminal():
            probe_state = probe_state.apply(
                probe_state.get_decisions(probe_plat)[0])
        # smoke relaxes the traffic floor like tests/test_fused.py
        # (min_tile_bytes=0): toy buffers would prune every count and CI
        # could never exercise the searched tile nodes
        fuse_kw = {"min_tile_bytes": 0} if args.smoke else {}
        tile_menu = FusedExecutor(ex, **fuse_kw).plan(
            probe_state.sequence).tile_menu
        if len(tile_menu) > 1:
            g = with_tile_menu(g, tile_menu)
            measure_ex = FusedExecutor(ex, **fuse_kw)
            tile_planted = True
            sys.stderr.write(
                f"fuse-search-tiles: menu {tile_menu} planted in the "
                "choice graph; measurements lower through the searched "
                "directive\n")
        else:
            sys.stderr.write(
                "fuse-search-tiles: tile menu is [1] (no fusible "
                "decomposition survived pruning) — nothing to search\n")

    def with_tile1(seq):
        """An out-of-graph sequence (naive_order/greedy helpers, recorded
        rows predating the tile node) completed with the ``fuse_tile.t1``
        directive the planted choice requires — without it the verifier
        would reject the schedule as an unresolved choice.  The directive
        goes AFTER the leading start sentinel: the planted choice is a
        successor of Start, so a directive at position 0 would violate
        the projected start->directive edge and fail verification."""
        if not tile_planted:
            return seq
        from tenzing_tpu.core.sequence import Sequence as _Seq
        from tenzing_tpu.runtime.fused import FuseTile, TILE_PREFIX

        ops_ = list(seq.vector())
        if any(op.name().startswith(TILE_PREFIX) for op in ops_):
            return seq
        at = 1 if ops_ and ops_[0].name() == "start" else 0
        return _Seq(ops_[:at] + [FuseTile(1)] + ops_[at:])

    emp = EmpiricalBenchmarker(measure_ex)
    # fault-tolerance stack (docs/robustness.md), inside-out:
    #   EmpiricalBenchmarker            device measurement
    #   [FaultInjectingBenchmarker]     --inject-faults seeded chaos
    #                                   (measurement-fault kinds)
    #   [PrefetchingBenchmarker]        --prefetch-compiles async compile
    #                                   pipeline: solver hints AOT-compile
    #                                   in the background, failures surface
    #                                   on the foreground call so the
    #                                   resilient layer above classifies /
    #                                   agrees / quarantines as usual
    #   ResilientBenchmarker            soundness gate / watchdog /
    #                                   classified retry / quarantine /
    #                                   degradation
    #   [FaultInjectingBenchmarker]     --inject-faults corrupt: schedule
    #                                   corruption — ABOVE the resilient
    #                                   layer so its verifier gate sees
    #                                   (and quarantines) the mutation
    #   [JournalingBenchmarker]         --checkpoint measurement journal
    #   CachingBenchmarker              equivalence-keyed cache (also the
    #                                   --resume restore target)
    from tenzing_tpu.fault import (
        JournalingBenchmarker,
        Quarantine,
        ResilientBenchmarker,
        SearchCheckpoint,
    )
    from tenzing_tpu.verify import ScheduleVerifier

    verifier = None if args.no_verify else ScheduleVerifier(g)
    inner_specs, corrupt_specs = [], []
    if args.inject_faults:
        from tenzing_tpu.fault import parse_inject_specs

        specs = parse_inject_specs(args.inject_faults)
        inner_specs = [s for s in specs if s.kind != "corrupt"]
        corrupt_specs = [s for s in specs if s.kind == "corrupt"]
        if corrupt_specs and verifier is None:
            # corruption without the verifier would MEASURE broken
            # schedules — a chaos run that poisons its own archive
            raise DriverConfigError(
                "--inject-faults corrupt: requires the soundness "
                "verifier (drop --no-verify)")
        sys.stderr.write(f"chaos: injecting {args.inject_faults}\n")
    measured_stack = emp
    injector = None
    if inner_specs:
        from tenzing_tpu.fault import FaultInjectingBenchmarker

        injector = FaultInjectingBenchmarker(
            emp, inner_specs, hang_secs=args.inject_hang_secs)
        measured_stack = injector
    prefetcher = None
    if args.prefetch_compiles > 0 and args.resume:
        # a resumed run answers journaled measurements without touching the
        # executor (the PR 3 "0 compiles" provenance); background hints
        # would compile programs the journal already answers — keep the
        # resume contract and skip the pipeline
        sys.stderr.write("prefetch: disabled under --resume (journaled "
                         "answers never compile)\n")
    elif args.prefetch_compiles > 0:
        from tenzing_tpu.bench.pipeline import PrefetchingBenchmarker

        # ABOVE injection (background compiles are not chaos targets — the
        # injector's per-attempt draws stay keyed to benchmark() calls
        # only) and BELOW the resilient layer (surfaced compile failures
        # ride the normal classify/agree/quarantine path)
        measured_stack = prefetcher = PrefetchingBenchmarker(
            measured_stack, executor=measure_ex,
            workers=args.prefetch_compiles, rank=surrogate)
        # exception paths too (not only the happy-path close below): a
        # fatal mid-search error must not leave queued background compiles
        # draining at interpreter exit — the pool's own shutdown hook joins
        # only AFTER the queue empties (~3.4 s per pending compile), while
        # close() cancels pending first.  Idempotent; SIGINT has the trap.
        scope.on_exit(prefetcher.close)
    ckpt = SearchCheckpoint(args.checkpoint) if args.checkpoint else None
    quar = Quarantine(ckpt.quarantine_path if ckpt else None,
                      log=lambda m: sys.stderr.write(m + "\n"))
    if len(quar):
        sys.stderr.write(
            f"quarantine: {len(quar)} schedule(s) carried from previous "
            "runs will not be re-measured\n")
    resilient = ResilientBenchmarker(
        measured_stack, timeout_secs=args.measure_timeout, quarantine=quar,
        fallback=surrogate, verifier=verifier)
    guarded = resilient
    corrupt_injector = None
    if corrupt_specs:
        from tenzing_tpu.fault import FaultInjectingBenchmarker

        corrupt_injector = FaultInjectingBenchmarker(
            resilient, corrupt_specs,
            unsound_check=lambda o: not verifier(o).ok)
        guarded = corrupt_injector
    bench = CachingBenchmarker(
        JournalingBenchmarker(guarded, ckpt) if ckpt else guarded)
    if ckpt is not None:
        config = {"workload": args.workload, "metric": metric,
                  "smoke": bool(args.smoke), "seed_topk": args.seed_topk}
        prior = None
        try:
            prior = ckpt.load_state()
        except Exception as e:  # corrupt snapshot: resume from journal only
            sys.stderr.write(f"checkpoint: state unreadable ({e}); "
                             "journal + quarantine still apply\n")
        if prior is not None and prior.get("config") not in (None, config):
            sys.stderr.write(
                "checkpoint: recorded config differs from this run "
                f"({prior.get('config')} vs {config}); journal rows that "
                "do not resolve against this workload are skipped\n")
        want_inject = args.inject_faults or None
        if args.resume and prior is not None and \
                prior.get("inject") != want_inject:
            # a resumed chaos run whose injection spec disagrees with the
            # one the checkpoint was written under would replay journaled
            # answers from a DIFFERENT fault universe and silently diverge
            # from both the original run and a clean rerun — refuse loudly
            raise DriverConfigError(
                "--resume: this run's --inject-faults "
                f"({want_inject!r}) disagrees with the checkpoint's "
                f"recorded injection spec ({prior.get('inject')!r}); "
                "use the same spec (including seeds) or start a fresh "
                "checkpoint directory")
        if args.resume:
            restored = ckpt.restore_into(
                bench, g, log=lambda m: sys.stderr.write(m + "\n"))
            sys.stderr.write(
                f"resume: {restored} recorded measurement(s) restored — "
                "already-measured schedules will not touch the device\n")
        ckpt.save_state(config=config, inject=want_inject)

        # final snapshots: the journal and quarantine are already on disk
        # (appended/rewritten as each measurement landed), so these only
        # stamp the cursor document.  The trap path marks the interrupt
        # (SIG_DFL then kills without running the exit finalizers); a
        # normal return (or crash) marks completion at scope close.
        scope.on_exit(lambda: ckpt.save_state(done=True))
        scope.on_trap(lambda: ckpt.save_state(interrupted=True))
    # max_retries=2 (library default 10): the runs-test retry loop re-measures
    # the whole series on rejection, and in the tunnel's slow regime that blew
    # a single naive benchmark to 558 s of wall; the verdict comes from the
    # paired batches (which have no retry loop), so the search-phase numbers
    # only need to be cheap, not certified-stationary
    opts = BenchOpts(n_iters=max(5, args.iters), max_retries=2,
                     target_secs=0.002 if args.smoke else 0.02)
    # the search phase buys BREADTH with cheap measurements (VERDICT r2 weak
    # #2: 24 iters at full measurement cost explored a 109-node tree of a far
    # larger space); ranking candidates is the paired screening batch's job,
    # so search-time numbers only need to steer the tree
    search_opts = BenchOpts(
        n_iters=max(3, args.search_iters),
        max_retries=2,
        target_secs=0.002 if args.smoke else 0.01,
    )

    # naive incumbent: the fully-synchronous serialization on one lane (the
    # reference's "sequential ordering on one stream" baseline, BASELINE.json)
    naive_plat = Platform.make_n_lanes(1)
    if args.workload == "halo":
        from tenzing_tpu.models.halo_pipeline import naive_order

        naive_seq = naive_order(built[3], naive_plat)
    elif args.workload == "moe":
        from tenzing_tpu.models.moe_pipeline import naive_order

        naive_seq = naive_order(built[3][0], built[3][1], naive_plat)
    else:
        naive_state = State(g)
        while not naive_state.is_terminal():
            naive_state = naive_state.apply(naive_state.get_decisions(naive_plat)[0])
        naive_seq = naive_state.sequence
    # a planted tile menu makes the directive part of every complete
    # schedule; the out-of-graph naive builders predate it
    naive_seq = with_tile1(naive_seq)
    # the baseline is not a search candidate: exempt it from the
    # identity-keyed candidate-fault kinds (deterministic/corrupt), which
    # would otherwise deterministically kill the run under ~rate of the
    # seeds before the search starts.  Tunnel-fault kinds still apply.
    for inj in (injector, corrupt_injector):
        if inj is not None:
            from tenzing_tpu.bench.benchmarker import schedule_id as _sid

            inj.exempt_ids.add(_sid(naive_seq))
    if prefetcher is not None:
        # hint the baseline itself: its compile starts on a worker while
        # argument/driver setup finishes, the foreground join consumes it,
        # and every run deterministically exercises the AOT-program /
        # prepare_n cache-key agreement on the real executor (the CI smoke
        # asserts prefetch hits > 0 on exactly this)
        prefetcher.prefetch([naive_seq])
    t0 = time.time()
    naive = bench.benchmark(naive_seq, opts)
    sys.stderr.write(f"naive: pct50={naive.pct50*1e6:.1f}us (wall {time.time()-t0:.0f}s)\n")

    # anytime search: heuristic incumbents first, then the directed search.
    # For halo the domain heuristic is the post-all-before-await-any overlap
    # discipline — the one the reference's graph hard-codes via its
    # every-post-before-any-wait edges (ops_halo_exchange.cu:249-256)
    incumbents = []
    incumbent_labels: dict = {}
    # MCTS warm-start seeds: incumbent disciplines as DECISION PATHS on the
    # search platform over the choice graph (filled alongside the incumbents;
    # VERDICT r3 item 1)
    seed_paths = []
    # informed MCTS playouts: rollouts complete with the workload's best
    # hand discipline (epsilon-noised) instead of uniform random — a
    # ~100-decision halo schedule essentially never assembles a coherent
    # discipline by chance, which is why random-playout MCTS lagged the
    # climbs for four rounds (VERDICT r4 item 2)
    mcts_rollout_policy = None
    if args.workload == "attn" and not args.smoke:
        # kernel incumbents: (a) the per-block chain with every block on the
        # bf16 Pallas kernel (the r2-r4 winner), (b) the fused single-kernel
        # flash with VMEM-resident state (the r5 HBM-state-traffic fix) —
        # the directed search starts from both, the final batch must include
        # whichever survives the screen
        from tenzing_tpu.core.state import ChooseOp
        from tenzing_tpu.solve.mcts.mcts import SimResult

        def attn_incumbent(label, engine_suffix, kernel_suffix):
            st = State(g)
            while not st.is_terminal():
                ds = st.get_decisions(naive_plat)
                pick = next(
                    (d for d in ds if isinstance(d, ChooseOp)
                     and d.choice.name().endswith(engine_suffix)),
                    None,
                ) or next(
                    (d for d in ds if isinstance(d, ChooseOp)
                     and d.choice.name().endswith(kernel_suffix)),
                    ds[0],
                )
                st = st.apply(pick)
            t0 = time.time()
            try:
                res_i = bench.benchmark(st.sequence, search_opts)
            except Exception as e:
                sys.stderr.write(
                    f"{label} incumbent rejected ({type(e).__name__}: "
                    f"{str(e)[:160]})\n")
                return
            sys.stderr.write(
                f"{label} incumbent: pct50={res_i.pct50*1e6:.1f}us "
                f"(wall {time.time()-t0:.0f}s)\n"
            )
            sim = SimResult(order=st.sequence, result=res_i)
            incumbent_labels[id(sim)] = label
            incumbents.append(sim)

        attn_incumbent("bf16-kernel", ".chain", ".pallas_bf16")
        attn_incumbent("fused-bf16", ".fused_bf16", ".pallas_bf16")
    if args.workload in ("halo", "moe"):
        from tenzing_tpu.solve.mcts.mcts import SimResult

        if args.workload == "halo":
            from tenzing_tpu.models.halo_pipeline import (
                greedy_overlap_order,
                paired_overlap_order,
            )

            greedy_seqs = []
            if args.smoke:
                greedy_seqs.append(
                    ("greedy-overlap", greedy_overlap_order(built[3], plat)))
            else:
                from tenzing_tpu.models.halo import (
                    DIRECTIONS as _DIRS,
                    dir_name as _dn,
                )
                from tenzing_tpu.models.halo_pipeline import (
                    HALO_PHASES as _PH,
                    paired_priority,
                )
                from tenzing_tpu.solve.local import drive, phase_policy

                _dirs = [_dn(d) for d in _DIRS]

                def mk_prefer(engine):
                    def prefer(op_name, choices):
                        if op_name.startswith("xfer_"):
                            i = _dirs.index(op_name.split("_", 1)[1])
                            want = {"host": ".host", "rdma": ".rdma",
                                    "alias": ".rdma"}.get(
                                engine, ".rdma" if i % 2 == 0 else ".host")
                            return next(
                                (c for c in choices if c.endswith(want)), None)
                        if engine == "alias" and op_name.startswith("unpack_"):
                            hit = alias_unpack_choice(op_name, choices)
                            if hit is not None:
                                return hit
                        return next(
                            (c for c in choices if c.endswith(".xla")), None)

                    return prefer

                # rollouts complete with the measured r5 alias discipline
                # (phase_policy is stateful via its lane round-robin, which
                # adds completion diversity on top of rollout_eps)
                mcts_rollout_policy = phase_policy(
                    plat, _PH, mk_prefer("alias"))

                # search-platform (8-lane) incumbents are driven on the
                # CHOICE graph itself, and their decision paths double as the
                # MCTS warm-start seeds (re-measured at the cheap screen
                # floor — a few ms of device time — since the multi-fidelity
                # split keys the cache per-floor)
                for label, engine, pri in (
                    ("greedy-host-8l", "host", None),
                    ("greedy-rdma-8l", "rdma", None),
                    ("greedy-mixed-8l", "mixed", None),
                    ("greedy-paired-8l", "mixed", paired_priority("mixed")),
                    ("greedy-alias-8l", "alias", None),
                ):
                    seq, decs = drive(g, plat, phase_policy(
                        plat, _PH, mk_prefer(engine), priority=pri))
                    greedy_seqs.append((label, seq))
                    seed_paths.append(decs)
                # other lane counts: engine-fixed graphs (probed on v5e:
                # rdma peaks at 2-3 lanes, mixed also strong at 6)
                for label, engine, nl in (
                    ("greedy-rdma-2l", "rdma", 2),
                    ("greedy-rdma-3l", "rdma", 3),
                    ("greedy-mixed-6l", "mixed", 6),
                ):
                    greedy_seqs.append((label, greedy_overlap_order(
                        built[3], Platform.make_n_lanes(nl), engine=engine)))
                greedy_seqs.append(("greedy-paired-6l", paired_overlap_order(
                    built[3], Platform.make_n_lanes(6), engine="mixed")))
                # the aliased-unpack recipe at the probed lane counts
                # (experiments/MENU_INCUMBENT3.json: 3.2-3.4x paired at
                # 2/3/6 lanes, best at 6) — driven on the choice graph so
                # their decision paths also seed the tree
                for label, nl in (("greedy-alias-3l", 3),
                                  ("greedy-alias-6l", 6)):
                    plat_a = Platform.make_n_lanes(nl)
                    seq, decs = drive(g, plat_a, phase_policy(
                        plat_a, _PH, mk_prefer("alias")))
                    greedy_seqs.append((label, seq))
                    seed_paths.append(decs)
        else:
            from tenzing_tpu.models.moe_pipeline import greedy_overlap_order

            margs_, cap_ = built[3]
            greedy_seqs = [
                ("greedy-overlap", greedy_overlap_order(margs_, cap_, plat))
            ]
            if not args.smoke:
                # the half-width-transfer incumbent (bf16 staging) and the
                # device-resident-transfer incumbents (rdma engine): the
                # likely winners the search should start from
                greedy_seqs.append((
                    "greedy-overlap-bf16",
                    greedy_overlap_order(margs_, cap_, plat, staging="bf16"),
                ))
                greedy_seqs.append((
                    "greedy-bf16-rdma",
                    greedy_overlap_order(margs_, cap_, plat, staging="bf16",
                                         engine="rdma"),
                ))
                greedy_seqs.append((
                    "greedy-f32-rdma",
                    greedy_overlap_order(margs_, cap_, plat, engine="rdma"),
                ))
        greedy_seqs = [(label, with_tile1(s)) for label, s in greedy_seqs]
        if prefetcher is not None:
            # the incumbent grid is known up front: incumbent k+1 compiles
            # in the background while incumbent k measures
            prefetcher.prefetch([s for _, s in greedy_seqs])
        for label, greedy_seq in greedy_seqs:
            t0 = time.time()
            # search-phase cost: incumbents are re-ranked by the paired
            # screen anyway, this number only seeds the tree
            greedy = bench.benchmark(greedy_seq, search_opts)
            sys.stderr.write(
                f"{label} incumbent: pct50={greedy.pct50*1e6:.1f}us "
                f"(wall {time.time()-t0:.0f}s)\n"
            )
            sim = SimResult(order=greedy_seq, result=greedy)
            incumbent_labels[id(sim)] = label
            incumbents.append(sim)

    # recorded-best warm start: the best distinct schedules from previous
    # runs' search databases are first-class candidates (the search
    # remembers its own discoveries across runs — CSV checkpoint/resume, the
    # reference's mcts_csv workflow) and, below, a hill-climb seed
    # discipline.  r4l motivated this: r4k's climb discovered the
    # batched-z-unpack combination at paired 2.48, and the next run's climbs
    # wandered to 1.42 local optima instead of starting from it.
    recorded = []  # best-first sequences, filled below
    if args.seed_csv is None:
        args.seed_csv = {
            "halo": "experiments/halo_search_tpu_r[45]*.csv",
            "moe": "experiments/moe_search_tpu_r[45]*.csv",
            "attn": "experiments/attn_search_tpu_r[45]*.csv",
        }.get(args.workload, "")
    if args.seed_csv and args.seed_topk > 0 and not args.smoke:
        import glob as _glob
        import os.path as _osp

        from tenzing_tpu.bench.recorded import rank_recorded
        from tenzing_tpu.solve.mcts.mcts import SimResult

        pat = args.seed_csv
        if not _osp.isabs(pat):
            pat = _osp.join(REPO_ROOT, pat)
        paths = sorted(_glob.glob(pat))
        if not paths:
            sys.stderr.write(f"recorded db: no files match {pat!r}\n")
        picked = rank_recorded(
            paths, g, args.seed_topk,
            log=lambda m: sys.stderr.write(m + "\n"),
        )
        # recorded rows predating a planted tile menu carry no directive
        picked = [(with_tile1(s), r) for s, r in picked]
        recorded_ok = []
        if prefetcher is not None:
            prefetcher.prefetch([s for s, _ in picked])
        from tenzing_tpu.fault.backoff import BackoffPolicy as _BP, retry_call

        for ri, (seq_r, ratio) in enumerate(picked):
            t0 = time.time()
            # transient-classified retry via the shared backoff helper (the
            # tunnel has flaky spells); a deterministic failure — a recorded
            # schedule this chip genuinely cannot run — drops immediately
            try:
                meas = retry_call(
                    lambda seq_r=seq_r: bench.benchmark(seq_r, search_opts),
                    policy=_BP(retries=1, base_secs=2.0),
                    where="recorded.warmstart",
                )
            except Exception as err:
                sys.stderr.write(
                    f"recorded[{ri}] dropped "
                    f"({type(err).__name__}: {str(err)[:200]})\n"
                )
                continue
            sys.stderr.write(
                f"recorded[{ri}] candidate: pct50={meas.pct50*1e6:.1f}us "
                f"(recorded ratio {ratio:.3f}, wall {time.time()-t0:.0f}s)\n"
            )
            sim = SimResult(order=seq_r, result=meas)
            incumbent_labels[id(sim)] = f"recorded[{ri}]"
            incumbents.append(sim)
            recorded_ok.append((seq_r, meas.pct50))
        # best by RE-MEASURED time first for the climb seed (this run's
        # regime, same fidelity across the three)
        recorded = [s for s, _ in sorted(recorded_ok, key=lambda e: e[1])]

    # moe warm-start seed (halo's were recorded with its incumbents above)
    if not args.smoke and args.workload == "moe":
        from tenzing_tpu.models.moe_pipeline import PHASES as _MOE_PH
        from tenzing_tpu.solve.local import drive, phase_policy

        def moe_seed_prefer(op_name, choices):
            return next(
                (c for c in choices if c.endswith(".bf16-rdma")),
                next((c for c in choices if c.endswith(".xla")), None),
            )

        _, decs = drive(g, plat, phase_policy(plat, _MOE_PH, moe_seed_prefer))
        seed_paths.append(decs)
        mcts_rollout_policy = phase_policy(plat, _MOE_PH, moe_seed_prefer)

    # directed search over the order x lane x kernel x engine space, at the
    # cheap search-phase measurement cost.  Multi-fidelity (VERDICT r4 item
    # 2): rollouts are measured at a ~1 ms screen floor — search-time numbers
    # only steer the tree — and the top-k distinct schedules are re-measured
    # at the climb floor before the dump, so MCTS's official candidates carry
    # comparable-fidelity numbers into the paired screen
    t0 = time.time()
    mcts_screen = BenchOpts(
        n_iters=2, max_retries=2,
        target_secs=0.0005 if args.smoke else 0.001,
    )
    mcts_confirm = BenchOpts(
        n_iters=max(5, args.iters), max_retries=2,
        target_secs=search_opts.target_secs * 10,
    )
    search_bench = bench
    if surrogate is not None:
        # the learned screen slots into the existing screen/confirm split:
        # rollout queries (mcts_screen opts) may be answered by the model,
        # while the confirm pass and everything at any other fidelity
        # always reaches the device (screen_only_opts)
        from tenzing_tpu.learn import ScreeningBenchmarker

        search_bench = ScreeningBenchmarker(
            surrogate, bench, escalate_topk=max(4, args.seed_topk + 1),
            screen_only_opts=mcts_screen,
        )
    res = explore(
        g,
        plat,
        search_bench,
        MctsOpts(n_iters=args.mcts_iters, bench_opts=mcts_confirm,
                 screen_opts=mcts_screen, confirm_topk=4, seed=0,
                 rollout_policy=mcts_rollout_policy,
                 checkpoint=ckpt, verify=verifier, prefetch=prefetcher),
        strategy=FastMin,
        seeds=seed_paths,
    )
    if surrogate is not None:
        sys.stderr.write(
            f"learn screen: {search_bench.hits} surrogate answers / "
            f"{search_bench.escalations} escalations\n")
    confirmed = [s for s in res.sims if s.fidelity == "full"]
    best_seen = min(
        (s.result.pct50 for s in (confirmed or res.sims)),
        default=float("inf"),
    )
    sys.stderr.write(
        f"mcts wall {time.time()-t0:.0f}s, tree={res.tree_size}, "
        f"{len(res.sims)} rollouts ({len(seed_paths)} seeded, "
        f"{len(confirmed)} confirmed at {mcts_confirm.target_secs}s floor), "
        f"best-seen pct50={best_seen*1e6:.1f}us\n"
    )
    # where the search wall goes (VERDICT r3 weak #5): per-phase counters +
    # benchmark-cache economics in the driver tail
    if res.counters is not None:
        sys.stderr.write(res.counters.report() + "\n")
    sys.stderr.write(
        f"bench cache: {bench.hits} hits / {bench.misses} misses; "
        f"compiled programs: {ex.compile_count} "
        f"({ex.compile_secs:.1f}s compile wall)\n"
    )
    if prefetcher is not None:
        pst = prefetcher.stats()
        sys.stderr.write(
            "prefetch: %(issued)d issued / %(hits)d hits / %(wasted)d "
            "wasted / %(failed)d failed / %(dropped)d dropped\n" % pst)
    res.sims = incumbents + res.sims

    # neighborhood search from the best-known heuristic: hill-climb in
    # decision space (solve/local.py) refines it with measured
    # single-substitution moves — the local complement to MCTS's global
    # exploration, at the same cheap search cost
    climb_cfg = []

    def recorded_prefer_and_lanes():
        """(prefer, n_lanes, chosen) replicating the best recorded
        schedule's menu choices — the climb starts in the recorded winner's
        kernel/engine configuration and searches order/lane/flip moves from
        there.  ``chosen`` rides along so a fleet job spec can serialize
        the policy for a worker process (recorded_prefer rebuilds it)."""
        from tenzing_tpu.core.serdes import sequence_to_json

        js = sequence_to_json(recorded[0])
        chosen: dict = {}
        for j in js:
            n = j.get("name", "")
            if "." in n:
                base, suffix = n.rsplit(".", 1)
                chosen.setdefault(base, "." + suffix)

        lanes_used = [j.get("lane") for j in js if j.get("lane") is not None]
        return (recorded_prefer(chosen),
                (max(lanes_used) + 1 if lanes_used else 2), chosen)

    # each climb config carries its prefer SPEC (name + serialized chosen
    # map) beside the callable, so the fleet can ship the policy to a
    # worker process (search/fleet.py resolve_prefer rebuilds the same
    # module-level functions — inline and worker execution agree
    # decision-for-decision)
    if args.workload == "halo" and not args.smoke:
        from tenzing_tpu.models.halo_pipeline import HALO_PHASES

        # climbs: one seeded from the best RECORDED schedule's menu choices
        # (when a database is present — the cross-run memory), then the two
        # strongest measured disciplines, split 4:3: the aliased-unpack
        # all-rdma recipe at its two best probed lane counts
        # (MENU_INCUMBENT3.json: 3.2-3.4x paired at 3 and 6 lanes) — the
        # climb refines order/lane/kernel-flip moves from there
        b_rec = (args.climb_budget // 3) if recorded else 0
        rest = args.climb_budget - b_rec
        b1 = (rest * 4) // 7
        plat3 = Platform.make_n_lanes(3)
        climb_cfg = [
            (plat3, HALO_PHASES, halo_alias_prefer, None, b1,
             "halo_alias", None),
            (Platform.make_n_lanes(6), HALO_PHASES, halo_alias_prefer, None,
             rest - b1, "halo_alias", None),
        ]
        if b_rec:
            rec_prefer, n_rec, rec_chosen = recorded_prefer_and_lanes()
            climb_cfg.insert(
                0,
                (Platform.make_n_lanes(n_rec), HALO_PHASES, rec_prefer, None,
                 b_rec, "recorded", rec_chosen),
            )
    elif args.workload == "moe" and not args.smoke:
        from tenzing_tpu.models.moe_pipeline import PHASES as MOE_PHASES

        b_rec = (args.climb_budget // 2) if recorded else 0
        climb_cfg = [(plat, MOE_PHASES, moe_bf16_prefer, None,
                      args.climb_budget - b_rec, "moe_bf16", None)]
        if b_rec:
            rec_prefer, n_rec, rec_chosen = recorded_prefer_and_lanes()
            climb_cfg.insert(
                0,
                (Platform.make_n_lanes(n_rec), MOE_PHASES, rec_prefer, None,
                 b_rec, "recorded", rec_chosen),
            )
    # distributed search fleet (docs/performance.md, "Distributed search"):
    # --search-workers N / --measure-batch K route the SAME climb jobs
    # through search/fleet.py — (1,1) is the serialized inline baseline
    # (bit-identical to the legacy loop below), N>=2 spawns worker
    # processes measuring through fused K-candidate rounds.  0/0 keeps the
    # legacy loop byte-for-byte.
    fleet_n = max(0, int(args.search_workers or 0))
    fleet_k = max(0, int(args.measure_batch or 0))
    fleet_engaged = fleet_n > 0 or fleet_k > 0
    distributed_stats = None
    if fleet_engaged and not climb_cfg and args.climb_budget > 0:
        # --smoke builds no climb configs; synthesize a deterministic 2-job
        # split of the climb budget — the job list depends only on the
        # request (never on N or K), so the (1,1) serialized baseline and
        # the fused fleet spend the same candidate budget
        if args.workload == "halo":
            from tenzing_tpu.models.halo_pipeline import HALO_PHASES as _FPH
        elif args.workload == "moe":
            from tenzing_tpu.models.moe_pipeline import PHASES as _FPH
        else:
            _FPH = ("",)
        _half = max(1, args.climb_budget // 2)
        climb_cfg = [
            (plat, _FPH, generic_xla_prefer, None, _half,
             "generic_xla", None),
            (plat, _FPH, generic_xla_prefer, None, _half,
             "generic_xla", None),
        ]
    if climb_cfg and args.climb_budget > 0:
        from dataclasses import replace as _replace

        from tenzing_tpu.solve.local import LocalOpts, hill_climb

        # paired=True: accept moves only on a back-to-back paired comparison
        # with the incumbent — the r4a run showed unpaired first-improvement
        # climbing chases chip drift (climb "best" 96 ms that the paired
        # screen ranked below its own seed).  Accepts run at SCREEN fidelity
        # (r4c: accepts at the cheap 0.01s floor did not replicate under the
        # screen's 0.1s floor — measurement-regime-dependent overlap), which
        # costs ~1.6s of measurement per neighbor on top of the ~3s compile.
        climb_opts = _replace(search_opts, n_iters=8,
                              target_secs=10 * search_opts.target_secs)
        if fleet_engaged:
            from tenzing_tpu.search.fleet import (
                FleetJob,
                run_fleet,
                run_serialized,
            )

            jobs = [
                FleetJob(index=ci, budget=cbudget, seed=2 + ci,
                         lanes=len(cplat.lanes), phases=tuple(cphases),
                         prefer=pname, chosen=chosen)
                for ci, (cplat, cphases, _cpf, _cpri, cbudget, pname,
                         chosen) in enumerate(climb_cfg)
            ]
            n_w, k_fuse = max(1, fleet_n), max(1, fleet_k)
            t0 = time.time()
            if n_w == 1 and k_fuse == 1:
                fres = run_serialized(
                    g, jobs, bench, climb_opts, surrogate=surrogate,
                    ckpt=ckpt, verifier=verifier, prefetcher=prefetcher)
            else:
                fres = run_fleet(
                    g, args.to_json(), jobs, bench, climb_opts, n_w, k_fuse,
                    prefetcher=prefetcher, verify=not args.no_verify)
            distributed_stats = fres.stats
            for jr in fres.jobs:
                if jr.failed:
                    sys.stderr.write(
                        f"fleet job {jr.index}: FAILED ({jr.failed})\n")
                    continue
                for s in jr.sims:
                    incumbent_labels[id(s)] = "climb"
                res.sims = res.sims + jr.sims
                if jr.final is not None:
                    # the accepted chain tip always advances to the paired
                    # screen, exactly like the legacy climb loop's
                    incumbent_labels[id(jr.final)] = "climb-tip"
                    incumbents.append(jr.final)
                    res.sims = res.sims + [jr.final]
            st = distributed_stats
            sys.stderr.write(
                f"fleet: {st['workers']}w K={st['measure_batch']}: "
                f"{st['candidates']} candidates / {st['jobs']} jobs in "
                f"{st['wall_s']}s ({st['rounds']} fused rounds, occupancy "
                f"{st['batch_occupancy']}, {st['singles']} singles, "
                f"{st['reclaimed_subtrees']} reclaimed, scaling "
                f"{st['scaling_factor']}x, wall {time.time()-t0:.0f}s)\n")
        else:
            for ci, (cplat, cphases, cprefer, cpriority, cbudget, _pname,
                     _chosen) in enumerate(climb_cfg):
                t0 = time.time()
                lres = hill_climb(
                    g, cplat, bench, cphases, prefer=cprefer,
                    priority=cpriority,
                    opts=LocalOpts(budget=cbudget, bench_opts=climb_opts,
                                   seed=2 + ci, paired=True,
                                   prescreen=surrogate, checkpoint=ckpt,
                                   verify=verifier, prefetch=prefetcher),
                )
                lbest = lres.best()
                sys.stderr.write(
                    f"hill-climb[{ci}] ({len(cplat.lanes)} lanes): "
                    f"{len(lres.sims)} candidates, best "
                    f"pct50={lbest.result.pct50*1e6:.1f}us "
                    f"(wall {time.time()-t0:.0f}s)\n"
                )
                for s in lres.sims:
                    incumbent_labels[id(s)] = "climb"
                res.sims = res.sims + lres.sims
                if lres.final is not None:
                    # the accepted chain tip is the climb's official output:
                    # it always advances to the paired screen, like the
                    # incumbents
                    incumbent_labels[id(lres.final)] = "climb-tip"
                    incumbents.append(lres.final)
                    res.sims = res.sims + [lres.final]

    # Candidate selection is DRIFT-IMMUNE (VERDICT r2 weak #1: raw search-
    # phase pct50s picked final candidates while naive drifted 254ms -> 129ms
    # within one run, and 2 of 4 finalists lost to naive).  Two paired
    # decorrelated batches (reference batch benchmark, benchmarker.cpp:21-76):
    #
    #   screen: naive + the distinct candidates (incumbent grid + top
    #           searched), moderate cost; paired
    #           per-iteration speedups rank them, dropping everything whose
    #           paired median is < 1.0 — search-time drift cancels because
    #           iteration k visits every schedule back-to-back;
    #   final:  naive + the top 3 screened, 3x iterations and a 20x adaptive
    #           measurement floor (the reference's >=10ms floor scaled up,
    #           benchmarker.cpp:83-119) so single-execution jitter cannot
    #           widen the bootstrap CI across 1.0 when the margin is real.
    #
    # All programs are already compiled (executor cache) — pure measurement.
    from dataclasses import replace

    from tenzing_tpu.bench.benchmarker import BenchResult
    from tenzing_tpu.core.sequence import canonical_key
    from tenzing_tpu.utils.numeric import paired_speedup

    def batch_paired(seqs, bopts, seed):
        """(results, paired-vs-naive) for [naive] + candidates run as one
        decorrelated batch — through the resilient wrapper, so a tunnel
        flake mid-verdict retries the batch instead of killing the run."""
        times = resilient.benchmark_batch_times(
            [naive_seq] + list(seqs), bopts, seed=seed)
        results = [BenchResult.from_times(ts) for ts in times]
        paired = [paired_speedup(times[0], ts, seed=seed + 1) for ts in times[1:]]
        return results, paired

    def engine_of(seq) -> str:
        names = [op.desc() for op in seq.vector()]
        return "rdma" if any(".rdma" in n for n in names) else "host"

    def label_of(s) -> str:
        """'greedy-host-8l' for a labeled incumbent, 'climb/<engine>' for a
        hill-climb candidate, 'mcts/<engine>' for an MCTS rollout — the
        screen/final printouts must distinguish the entries they compare."""
        base = incumbent_labels.get(id(s), "mcts")
        if base in ("mcts", "climb", "climb-tip"):
            return f"{base}/{engine_of(s.order)}"
        return base

    # distinct candidates by canonical key; heuristic incumbents always
    # advance to screening (search-time noise must not knock them out).
    # The mcts pool is the confirm-pass sims (re-measured at the same 10x
    # floor the climbs use), but each pool is still sorted within itself and
    # the screen slots interleave the pools: measurements taken minutes
    # apart on a drifting chip are safer ranked per-pool than jointly.
    from itertools import chain, zip_longest

    seen = set()
    cands = []
    inc_ids = {id(s) for s in incumbents}
    # screen-fidelity MCTS rollouts never advance directly: their ~1 ms-floor
    # pct50s are not comparable with any other pool, and the confirm pass
    # already re-measured the best of them at the climb floor
    others = [s for s in res.sims
              if id(s) not in inc_ids
              and getattr(s, "fidelity", "full") == "full"]
    pools = {
        label: sorted(
            (s for s in others if incumbent_labels.get(id(s), "mcts") == label),
            key=lambda s: s.result.pct50,
        )
        for label in ("climb", "mcts")
    }
    interleaved = [
        s
        for pair in zip_longest(pools["climb"], pools["mcts"])
        for s in pair
        if s is not None
    ]
    for s in chain(incumbents, interleaved):
        key = canonical_key(s.order)
        if key not in seen:
            seen.add(key)
            cands.append(s)
    # the screen needs room for searched candidates BEYOND the incumbent
    # grid (7 labeled incumbents for halo) without shrinking the pool for
    # workloads with few incumbents
    cands = cands[: max(8, len(incumbents) + 4) if not args.smoke else 4]

    vs = 1.0
    value_us = naive.pct50 * 1e6
    finals = []
    top = []
    if resilient.degraded:
        # graceful degradation (docs/robustness.md): the device was lost
        # mid-search and the run finished against cache + surrogate.  The
        # paired screen/final need live hardware, and a verdict from
        # predicted numbers must never pass as a measurement — report the
        # pre-loss naive measurement with vs_baseline 1.0 and degraded
        # provenance instead of a fabricated win.
        sys.stderr.write(
            "degraded: device lost mid-search — skipping the paired "
            "screen/final; reporting no-win with degraded provenance\n")
        cands = []
    # constructed unconditionally: the regime metadata in the final JSON
    # reads the ACTUAL floors these carry, so tuning a multiplier at one
    # site cannot silently desynchronize the reported metadata
    screen_opts = replace(opts, target_secs=5 * opts.target_secs)
    fin_opts = replace(
        opts, n_iters=3 * opts.n_iters, target_secs=20 * opts.target_secs
    )
    if cands:
        for attempt in range(2):
            t0 = time.time()
            _, screen = batch_paired(
                [s.order for s in cands], screen_opts, seed=1 + 10 * attempt
            )
            sys.stderr.write(
                "screen (paired vs naive, wall %.0fs): %s\n"
                % (
                    time.time() - t0,
                    ", ".join(
                        "%s=%.4f" % (label_of(s), p[0])
                        for s, p in zip(cands, screen)
                    ),
                )
            )
            # DEGENERATE-SCREEN guard: the tunnel has a slow regime in which
            # every measurement is latency-dominated and all paired ratios
            # collapse toward 1.0 (observed: a MoE screen ranking everything
            # 0.95-1.05 minutes before the final batch measured the same
            # candidates at 10.9-12.2x).  A screen is suspect only when it
            # separates nothing (max ratio < 1.1) while the search-time
            # medians PREDICTED real separation (naive vs best candidate
            # >= 1.5x) — honest no-win workloads (SpMV ~1.0 everywhere)
            # never trip it.  One re-run, then the measurement stands.
            predicted = naive.pct50 / min(s.result.pct50 for s in cands)
            best_screen = max(p[0] for p in screen)
            # second clause added after r4w: a degraded chip regime flattened
            # the whole screen to 1.02-1.18 while the search predicted 3.4x
            # (the high-floor final then measured the survivors at 2.39x —
            # but the RANKING had already been made under the flattened
            # regime, advancing a 1.30 incumbent over stronger climbs)
            degenerate = (best_screen < 1.1 and predicted > 1.5) or (
                best_screen < 1.25 and predicted > 1.8
            )
            if not degenerate or attempt == 1:
                break
            sys.stderr.write(
                f"screen degenerate (best ratio {best_screen:.2f}, search "
                f"predicted {predicted:.2f}x) — re-running once\n"
            )
        ranked = sorted(
            zip(cands, screen), key=lambda sp: sp[1][0], reverse=True
        )
        # only candidates that beat naive under the paired screen advance —
        # the final batch reports no sub-1.0 losers
        top = [s for s, p in ranked if p[0] > 1.0][:3]
    if top:
        t0 = time.time()
        finals, paired = batch_paired([s.order for s in top], fin_opts, seed=3)
        fin_naive, fin_cands = finals[0], finals[1:]
        sys.stderr.write(
            "final batch (wall %.0fs): naive=%.1fus candidates=[%s]us\n"
            % (
                time.time() - t0,
                fin_naive.pct50 * 1e6,
                ", ".join("%.1f" % (r.pct50 * 1e6) for r in fin_cands),
            )
        )
        best_i = max(range(len(paired)), key=lambda i: paired[i][0])
        m, lo, hi = paired[best_i]
        sys.stderr.write(
            "paired speedup vs naive: best=%.4f [%.4f, %.4f] 95%% CI "
            "(all: %s)\n"
            % (
                m, lo, hi,
                ", ".join(
                    "%s=%.4f [%.4f, %.4f]" % (label_of(s), p[0], p[1], p[2])
                    for s, p in zip(top, paired)
                ),
            )
        )
        # a win requires the bootstrap CI to exclude 1.0, not just the bare
        # median — otherwise sampling noise reports a spurious speedup on
        # roughly half of no-difference runs
        if m > 1.0 and lo > 1.0:
            value_us = fin_cands[best_i].pct50 * 1e6
            vs = m
        else:
            value_us = fin_naive.pct50 * 1e6
            vs = 1.0

    # result-integrity gate (docs/robustness.md, "Schedule soundness"): the
    # schedule whose number the JSON is about to report re-executes on the
    # device next to naive, and their outputs must numerically agree — plus
    # the independent verifier must pass it.  A fast-but-WRONG schedule
    # (an under-synchronized winner whose race made it fast) can therefore
    # never be the answer: a failed gate demotes the run to no-win and
    # stamps ``verified: false`` with the verdict into the fault meta.
    integrity = None
    # gate outputs stashed for reuse: the fused phase compares against the
    # stepped program's outputs, which the gate just computed — re-running
    # a multi-GB workload's program for the same answer is pure waste
    gate_outs: Dict[int, Dict[str, Any]] = {}
    if verifier is not None and not resilient.degraded:
        winner_seq = (top[best_i].order if top and finals and vs > 1.0
                      else naive_seq)
        verdict = verifier(winner_seq)
        num_ok = False
        gate_err = None
        try:
            from tenzing_tpu.fault.backoff import (
                BackoffPolicy as _GP,
                retry_call as _gate_retry,
            )

            t0 = time.time()
            # transient-classified retry (default retry_on), like every
            # other device interaction: one tunnel flake must not demote a
            # multi-hour search's legitimate winner to verified: false
            out_w = _gate_retry(lambda: ex.run(winner_seq),
                                policy=_GP(retries=2, base_secs=2.0),
                                where="verify.gate")
            out_n = (out_w if winner_seq is naive_seq
                     else _gate_retry(lambda: ex.run(naive_seq),
                                      policy=_GP(retries=2, base_secs=2.0),
                                      where="verify.gate"))
            gate_outs[id(winner_seq)] = out_w
            gate_outs[id(naive_seq)] = out_n
            mismatched = _mismatched_outputs(out_n, out_w, args.verify_tol)
            num_ok = not mismatched
            if mismatched:
                gate_err = f"outputs diverge on {mismatched[:4]}"
            sys.stderr.write(
                "integrity gate: winner-vs-naive outputs "
                f"{'agree' if num_ok else 'DIVERGE'}, verifier "
                f"{'ok' if verdict.ok else 'UNSOUND'} "
                f"(wall {time.time()-t0:.0f}s)\n")
        except Exception as e:
            gate_err = f"{type(e).__name__}: {str(e)[:200]}"
            sys.stderr.write(
                f"integrity gate: winner re-execution failed ({gate_err})\n")
        integrity = {"verified": bool(verdict.ok and num_ok)}
        if not verdict.ok:
            integrity["verdict"] = verdict.witness()
        if gate_err is not None:
            integrity["error"] = gate_err
        if not integrity["verified"] and vs > 1.0:
            sys.stderr.write(
                "integrity gate FAILED — demoting the winner to no-win\n")
            value_us = (finals[0].pct50 if finals else naive.pct50) * 1e6
            vs = 1.0
    elif verifier is not None:
        # degraded: no device to re-execute on — the answer is explicitly
        # NOT verified (and already demoted to the pre-loss naive number)
        integrity = {"verified": False, "error": "degraded: no device"}

    # the schedule whose number the JSON reports, AFTER any gate demotion —
    # the one object the profiling and fusion phases both operate on
    reported_seq = (top[best_i].order if top and finals and vs > 1.0
                    else naive_seq)

    # attribution profiling (docs/observability.md, "Attribution"): per-op
    # stepped timing of the schedule whose number the JSON reports, plus
    # naive for the decision diff — the attrib block is the measurement
    # substrate the mega-kernel and chunking work will be judged with
    # (dispatch overhead removed, which ops fail to overlap).
    attrib_block = None
    profiled_attrib = None
    if args.profile_winner and resilient.degraded:
        sys.stderr.write("profile-winner: skipped (device lost — no "
                         "hardware to step ops on)\n")
    elif args.profile_winner:
        import os as _os

        t0 = time.time()
        try:
            from tenzing_tpu.obs import attrib as _attrib

            winner_seq_p = reported_seq
            cost = workload_cost(args.workload, built)
            naive_meas_us = (finals[0].pct50 if finals else naive.pct50) * 1e6
            w_tl = _attrib.stepped_timeline(ex, winner_seq_p,
                                            repeats=args.profile_repeats)
            w_at = _attrib.analyze(winner_seq_p.vector(), w_tl,
                                   measured_us=value_us, cost=cost)
            # stash for the fusion phase: its "before" timeline is this
            # exact (sequence, repeats, measured_us) analysis — with both
            # --profile-winner and --fuse-winner set, re-stepping a
            # multi-GB workload per op twice is minutes of pure waste
            profiled_attrib = w_at
            attrib_block = w_at.to_json()
            expl = None
            if winner_seq_p is not naive_seq:
                n_tl = _attrib.stepped_timeline(ex, naive_seq,
                                                repeats=args.profile_repeats)
                n_at = _attrib.analyze(naive_seq.vector(), n_tl,
                                       measured_us=naive_meas_us, cost=cost)
                expl = _attrib.explain(naive_seq.vector(),
                                       winner_seq_p.vector(),
                                       naive_attrib=n_at,
                                       winner_attrib=w_at)
                attrib_block["explain"] = expl.get("timing", {})
            # the winner's raw measurement series rides along for the
            # report CLI's noise-aware regression check (obs/report.py)
            fin_res = (finals[1 + best_i] if top and finals and vs > 1.0
                       else (finals[0] if finals else naive))
            if fin_res.times:
                attrib_block["measured_times"] = [
                    round(t, 9) for t in fin_res.times]
            if args.trace_out:
                _os.makedirs(args.trace_out, exist_ok=True)
                doc = dict(expl) if expl is not None else {}
                doc["attrib"] = attrib_block
                _attrib.write_explain(
                    _os.path.join(args.trace_out, "explain.json"), doc)
                rank = obs.get_tracer().rank
                # anchor the Gantt at the current unix-us instant so the
                # per-lane tracks render next to the span timeline (span
                # timestamps are unix-anchored, obs/tracer.py)
                t0_us = time.time() * 1e6
                attrib_extra.extend(_attrib.timeline_trace_events(
                    w_at, pid=rank, t0_us=t0_us, label="attrib/winner"))
                if expl is not None:
                    attrib_extra.extend(_attrib.timeline_trace_events(
                        n_at, pid=rank, t0_us=t0_us, label="attrib/naive",
                        tid_base=2000))
                sys.stderr.write(
                    f"explain: {_os.path.join(args.trace_out, 'explain.json')}\n")
            eff = attrib_block.get("overlap_efficiency")
            sys.stderr.write(
                "profile-winner: %d ops stepped, sum-of-parts %.1fus, "
                "critical path %.1fus, dispatch overhead %.1fus, overlap "
                "efficiency %s (wall %.0fs)\n"
                % (attrib_block["n_timed"],
                   attrib_block["sum_of_parts_us"],
                   attrib_block["critical_path_us"],
                   attrib_block["dispatch_overhead_us"],
                   f"{eff:.3f}" if eff is not None else "n/a",
                   time.time() - t0))
        except Exception as e:
            # profiling is observability, never a verdict gate: a stepped
            # program that cannot compile (or a mesh platform) degrades to
            # an error-carrying block instead of killing a finished search
            sys.stderr.write(
                f"profile-winner failed ({type(e).__name__}: "
                f"{str(e)[:200]})\n")
            attrib_block = {"error": f"{type(e).__name__}: {str(e)[:200]}"}

    # megakernel fusion (docs/performance.md, "Megakernel fusion"): lower
    # the reported schedule into fused Pallas regions (runtime/fused.py),
    # sweep the roofline-pruned tile menu, gate the best fused program
    # through the result-integrity machinery (allclose vs the stepped
    # program + re-verified), and stamp the ``perf.fused`` provenance
    # block with the dispatch overhead before/after (obs/attrib) — the
    # measured answer to "what did fusing the dispatches buy".
    fused_block = None
    if args.fuse_winner and resilient.degraded:
        sys.stderr.write("fuse-winner: skipped (device lost — no hardware "
                         "to run fused programs on)\n")
        fused_block = {"error": "degraded: no device"}
    elif args.fuse_winner:
        t0 = time.time()
        try:
            from tenzing_tpu.obs import attrib as _attrib
            from tenzing_tpu.runtime.fused import FusedExecutor, fused_summary

            winner_seq_f = reported_seq
            cost = workload_cost(args.workload, built)
            # "before": the unfused program's dispatch overhead — per-op
            # stepped sum-of-parts minus the reported whole-program pct50.
            # --profile-winner already produced this exact analysis of the
            # same sequence/repeats/measured_us: reuse it instead of
            # re-stepping every op
            if profiled_attrib is not None:
                at_b = profiled_attrib
            else:
                tl_b = _attrib.stepped_timeline(ex, winner_seq_f,
                                                repeats=args.profile_repeats)
                at_b = _attrib.analyze(winner_seq_f.vector(), tl_b,
                                       measured_us=value_us, cost=cost)
            # compile tallies snapshot AFTER the stepped timeline: the
            # per-op sub-program compiles above are attribution cost, not
            # fusion cost — the stamped delta covers plan + tile variants
            # + the gate's executions only
            compile0, csecs0 = ex.compile_count, ex.compile_secs
            plan0 = FusedExecutor(ex).plan(winner_seq_f)
            menu = plan0.tile_menu
            by_tiles: Dict[str, float] = {}
            best_t, best_us, best_fex = 1, None, None
            for t in menu:
                # fresh benchmarker per variant: the shared CachingBenchmarker
                # keys by canonical schedule, which would collide the fused
                # variants with the stepped measurement of the same order
                fex_t = FusedExecutor(ex, tiles=t)
                res_t = EmpiricalBenchmarker(fex_t).benchmark(
                    winner_seq_f, opts)
                us = res_t.pct50 * 1e6
                by_tiles[str(t)] = round(us, 2)
                if best_us is None or us < best_us:
                    best_t, best_us, best_fex = t, us, fex_t
            plan = best_fex.plan(winner_seq_f)
            # result-integrity gate on the fused outputs: allclose vs the
            # stepped program, and the schedule re-verified (PR 4 gate)
            out_f = best_fex.run(winner_seq_f)
            # the PR-4 gate already executed this exact sequence — reuse
            # its outputs instead of re-running a potentially multi-GB
            # program (gate skipped/failed -> fresh execution)
            out_s = gate_outs.get(id(winner_seq_f))
            if out_s is None:
                out_s = ex.run(winner_seq_f)
            mismatched = _mismatched_outputs(out_s, out_f, args.verify_tol)
            num_ok = not mismatched
            re_verdict = verifier(winner_seq_f) if verifier is not None \
                else None
            fused_verified = bool(
                num_ok and (re_verdict.ok if re_verdict is not None
                            else True))
            # "after": the FUSED program's remaining dispatch overhead —
            # one stepped unit per region instead of per op
            fseq = best_fex.fused_order(winner_seq_f)
            tl_a = _attrib.stepped_timeline(ex, fseq,
                                            repeats=args.profile_repeats)
            at_a = _attrib.analyze(fseq.vector(), tl_a,
                                   measured_us=best_us, cost=cost)
            fused_block = {
                "regions": len(plan.regions),
                "region_sizes": [r.n_ops for r in plan.regions],
                "fused_ops": plan.n_ops_fused,
                "n_ops_total": plan.n_ops_total,
                "tiles": {"chosen": best_t, "menu": menu,
                          "per_region": [r.tiles for r in plan.regions],
                          "by_tiles_us": by_tiles},
                "measured_us": {"stepped": round(value_us, 2),
                                "fused": round(best_us, 2)},
                "compile_secs": round(ex.compile_secs - csecs0, 3),
                "compiled_programs": ex.compile_count - compile0,
                "verified": fused_verified,
                "dispatch_overhead_us": {
                    "before": round(at_b.dispatch_overhead_us, 3),
                    "after": round(at_a.dispatch_overhead_us, 3)},
                "sum_of_parts_us": {
                    "before": round(at_b.sum_of_parts_us, 3),
                    "after": round(at_a.sum_of_parts_us, 3)},
            }
            if mismatched:
                fused_block["error"] = \
                    f"fused outputs diverge on {mismatched[:4]}"
            if re_verdict is not None and not re_verdict.ok:
                fused_block["verdict"] = re_verdict.witness()
            sys.stderr.write(
                "fuse-winner: %s; tiles %s -> best t=%d %.1fus (stepped "
                "%.1fus); dispatch overhead %.1f -> %.1fus; %s (wall "
                "%.0fs)\n" % (
                    fused_summary(plan), by_tiles, best_t, best_us,
                    value_us,
                    fused_block["dispatch_overhead_us"]["before"],
                    fused_block["dispatch_overhead_us"]["after"],
                    "verified" if fused_verified else "GATE FAILED",
                    time.time() - t0))
        except Exception as e:
            # like profiling, fusion provenance must never kill a finished
            # search — an error-carrying block instead
            sys.stderr.write(
                f"fuse-winner failed ({type(e).__name__}: "
                f"{str(e)[:200]})\n")
            fused_block = {"error": f"{type(e).__name__}: {str(e)[:200]}"}

    # op-chunking provenance (ISSUE 10, docs/performance.md "Chunked
    # overlap"): the roofline-pruned chunk menus the models offered, what
    # the search visited and chose, and the hidden comm the chunking bought
    # — estimated (the roofline upper bound carried on the menu) vs
    # measured (transfer-unit overlap with the chunk partials on the
    # obs/attrib stepped timeline).  Like profiling/fusion, provenance
    # only: a failure degrades to an error-carrying block.
    chunked_block = None
    if args.chunk:
        try:
            from tenzing_tpu.core.chunking import chunk_menus, chunks_of

            menus = chunk_menus(g)
            chosen = chunks_of(reported_seq)
            searched_counts: set = set()
            n_cand_chunked = 0
            for s in res.sims:
                cm = chunks_of(s.order)
                if cm:
                    n_cand_chunked += 1
                    searched_counts.update(cm.values())
            est_total = 0.0
            for base, n in chosen.items():
                m = menus.get(base)
                if m:
                    est_total += float(
                        m.get("est_hidden_us", {}).get(n, 0.0))
            chunked_block = {
                "menus": {
                    b: {"counts": list(m["counts"]),
                        "est_hidden_us": {
                            str(k): round(float(v), 2)
                            for k, v in m.get("est_hidden_us", {}).items()}}
                    for b, m in sorted(menus.items())},
                "searched_counts": sorted(int(c) for c in searched_counts),
                "n_candidates_chunked": n_cand_chunked,
                "chosen": {b: int(n) for b, n in sorted(chosen.items())},
                "hidden_comm_us": {"estimated": round(est_total, 2),
                                   "measured": None},
            }
            if menus and all(
                    not [c for c in m["counts"] if c > 1]
                    for m in menus.values()):
                chunked_block["note"] = (
                    "roofline pruned every chunking: no transfer whose "
                    "hidden-comm bound beats the dispatch+combine cost on "
                    "this workload/hardware (bench/roofline.py::"
                    "prune_chunkings)")
            elif not menus:
                chunked_block["note"] = (
                    "workload offers no chunkable-op menus (--chunk is a "
                    "no-op for it)")
            if chosen and not resilient.degraded:
                from tenzing_tpu.core.chunking import hidden_comm_measured_us
                from tenzing_tpu.obs import attrib as _attrib

                t0 = time.time()
                if profiled_attrib is not None:
                    at_c = profiled_attrib
                else:
                    tl_c = _attrib.stepped_timeline(
                        ex, reported_seq, repeats=args.profile_repeats)
                    at_c = _attrib.analyze(reported_seq.vector(), tl_c,
                                           measured_us=value_us)
                measured = hidden_comm_measured_us(reported_seq.vector(),
                                                   at_c)
                chunked_block["hidden_comm_us"]["measured"] = round(
                    measured, 2)
                sys.stderr.write(
                    "chunked: winner uses %s; hidden comm est %.1fus / "
                    "measured %.1fus (wall %.0fs)\n"
                    % (chunked_block["chosen"], est_total, measured,
                       time.time() - t0))
            else:
                sys.stderr.write(
                    "chunked: %d menu(s), %d chunked candidate(s) "
                    "searched, winner unchunked\n"
                    % (len(menus), n_cand_chunked))
        except Exception as e:
            sys.stderr.write(
                f"chunked provenance failed ({type(e).__name__}: "
                f"{str(e)[:200]})\n")
            chunked_block = {
                "error": f"{type(e).__name__}: {str(e)[:200]}"}

    # synthesized-collective provenance (ISSUE 17, docs/performance.md
    # "Synthesized collectives"): the priced-and-pruned sketch menus each
    # exchange site offered, what the search visited and chose, analytic
    # est vs measured hidden comm of the chosen decomposition, and the
    # result-integrity verdict on the reported projection.  Provenance
    # only: a failure degrades to an error-carrying block.
    synth_block = None
    if args.synth_collectives:
        try:
            from tenzing_tpu.collectives.synth import (
                synth_hidden_comm_measured_us,
                synth_menus,
                synths_of,
            )

            smenus = synth_menus(g)
            schosen = synths_of(reported_seq)
            searched_sketches: set = set()
            n_cand_synth = 0
            for s in res.sims:
                sm = synths_of(s.order)
                if sm:
                    n_cand_synth += 1
                    searched_sketches.update(
                        f"{v['sketch']}.c{v['chunks']}" for v in sm.values())
            sest_total = 0.0
            for base, v in schosen.items():
                m = smenus.get(base)
                if m:
                    sest_total += float(m.get("est_us", {}).get(
                        f"{v['sketch']}.c{v['chunks']}", 0.0))
            synth_block = {
                "menus": {
                    b: {"menu": list(m["menu"]),
                        "est_us": {k: round(float(v2), 3)
                                   for k, v2 in m.get("est_us", {}).items()},
                        "pruned": dict(m.get("pruned", {})),
                        "note": m.get("note", "")}
                    for b, m in sorted(smenus.items())},
                "searched_sketches": sorted(searched_sketches),
                "n_candidates_synth": n_cand_synth,
                "chosen": {b: f"{v['sketch']}.c{v['chunks']}"
                           for b, v in sorted(schosen.items())},
                "est_comm_us": round(sest_total, 3),
                "measured_hidden_us": None,
                "verified": bool(integrity and integrity.get("verified")),
            }
            if not smenus:
                synth_block["note"] = (
                    "workload offers no synthesized-collective menus "
                    "(--synth-collectives is a no-op for it)")
            elif all(len(m.get("menu", [])) <= 1 for m in smenus.values()):
                synth_block["note"] = (
                    "roofline pruned every sketch instantiation: no "
                    "decomposition whose alpha-beta estimate beats the "
                    "fixed engine's one-post floor on this "
                    "workload/hardware (bench/roofline.py::prune_sketches)")
            else:
                synth_block["note"] = "; ".join(
                    f"{b}: {m.get('note', '')}"
                    for b, m in sorted(smenus.items()))
            if schosen and not resilient.degraded:
                from tenzing_tpu.obs import attrib as _attrib

                t0 = time.time()
                if profiled_attrib is not None:
                    at_s = profiled_attrib
                else:
                    tl_s = _attrib.stepped_timeline(
                        ex, reported_seq, repeats=args.profile_repeats)
                    at_s = _attrib.analyze(reported_seq.vector(), tl_s,
                                           measured_us=value_us)
                smeasured = synth_hidden_comm_measured_us(
                    reported_seq.vector(), at_s)
                synth_block["measured_hidden_us"] = round(smeasured, 2)
                sys.stderr.write(
                    "synth: winner uses %s; est comm %.1fus / hidden "
                    "measured %.1fus (wall %.0fs)\n"
                    % (synth_block["chosen"], sest_total, smeasured,
                       time.time() - t0))
            else:
                sys.stderr.write(
                    "synth: %d menu(s), %d synthesized candidate(s) "
                    "searched, winner fixed-engine\n"
                    % (len(smenus), n_cand_synth))
        except Exception as e:
            sys.stderr.write(
                f"synth provenance failed ({type(e).__name__}: "
                f"{str(e)[:200]})\n")
            synth_block = {
                "error": f"{type(e).__name__}: {str(e)[:200]}"}

    if args.dump_csv:
        # One row per distinct schedule.  The decorrelated final-batch results
        # *supersede* the search-time measurements for naive and the finalists
        # (CsvBenchmarker returns the first equivalence match, so appending
        # duplicate rows would leave the finals unreachable) — the headline
        # verdict is replayable from the recorded database.
        results = [naive] + [s.result for s in res.sims]
        if finals:
            results[0] = finals[0]
            for r, s in zip(finals[1:], top):
                # identity, not ==: sync ops compare kind-only, so two distinct
                # schedules can be ==-equal and .index() would mis-attribute
                idx = next(i for i, s2 in enumerate(res.sims) if s2 is s)
                results[1 + idx] = r
        orders = [naive_seq] + [s.order for s in res.sims]
        # fidelity tags keep the DB honest: MCTS screen rows were measured at
        # a ~1 ms floor and must not be ranked against full-floor rows by the
        # warm-start loader (bench/recorded.py skips non-"full" rows)
        fids = ["full"] + [getattr(s, "fidelity", "full") for s in res.sims]
        if finals:
            for s in top:
                idx = next(i for i, s2 in enumerate(res.sims) if s2 is s)
                fids[1 + idx] = "full"  # superseded by the final batch
        # rows the learned screen answered from the MODEL carry no device
        # measurement at all — tag them fid=model (inert to every reader,
        # like screen rows) so the archive never passes predictions off as
        # measurements
        if surrogate is not None:
            for i, s in enumerate(res.sims):
                if fids[1 + i] == "screen" and search_bench.was_predicted(
                        s.order):
                    fids[1 + i] = "model"
        # rows answered after device loss carry degraded provenance — like
        # fid=model they are inert to every reader (CsvBenchmarker admits
        # only "full" rows, recorded.py skips non-"full"), so a degraded
        # run's archive can never pass predictions off as measurements
        if resilient.degraded:
            for i, s in enumerate(res.sims):
                if resilient.was_degraded(s.order):
                    fids[1 + i] = "degraded"
        # screen rows cannot shadow full-fidelity twins on replay:
        # CsvBenchmarker only admits "full" rows into its equivalence cache
        rows = [
            result_row(i, r, o, fidelity=None if f == "full" else f)
            for i, (r, o, f) in enumerate(zip(results, orders, fids))
        ]
        # THE dump invariant every downstream reader trusts (recorded.py
        # naive_anchor_of, learn/dataset.py): row 0 is the naive schedule at
        # FINAL fidelity — checked at dump time (a real exception, not an
        # assert: it must hold under python -O too) so a future reshuffle of
        # the results list cannot silently poison every in-file ratio
        # computed against this file's anchor
        if orders[0] is not naive_seq or fids[0] != "full":
            raise RuntimeError(
                "dump-csv invariant violated: row 0 must be the naive "
                "schedule at full fidelity")
        with open(args.dump_csv, "w") as f:
            f.write("\n".join(rows) + "\n")
        sys.stderr.write(f"csv: {args.dump_csv} ({len(rows)} rows)\n")
    # compile/perf provenance (ISSUE 5): "compiled programs: N" used to be
    # a stderr-only note, so a compile-wall regression was invisible to the
    # parsed BENCH_*.json series.  Close the prefetcher first (joins the
    # background workers — no leaked threads — and finalizes the wasted
    # tally), then stamp the pipeline economics into the JSON.
    if prefetcher is not None:
        prefetcher.close()
    perf = {
        "compiled_programs": ex.compile_count,
        "compile_secs": round(ex.compile_secs, 3),
        "compile_cache_dir": compile_cache_dir,
        "prefetch": (prefetcher.stats() if prefetcher is not None else
                     {"workers": 0, "issued": 0, "hits": 0, "wasted": 0,
                      "failed": 0, "surfaced": 0, "dropped": 0}),
    }
    # megakernel-fusion provenance (ISSUE 8): regions, tiles chosen, gate
    # verdict, dispatch overhead before/after — present iff --fuse-winner
    if fused_block is not None:
        perf["fused"] = fused_block
    # in-driver tile search provenance — present iff --fuse-search-tiles
    if tile_menu is not None:
        from tenzing_tpu.runtime.fused import tiles_of as _tiles_of

        perf["fuse_search_tiles"] = {
            "menu": list(tile_menu),
            "planted": tile_planted,
            "chosen": _tiles_of(reported_seq),
        }
    # op-chunking provenance (ISSUE 10) — present iff --chunk
    if chunked_block is not None:
        perf["chunked"] = chunked_block
    # synthesized-collective provenance (ISSUE 17) — present iff
    # --synth-collectives
    if synth_block is not None:
        perf["synth"] = synth_block
    # distributed-search provenance (ISSUE 20) — present iff the fleet ran
    # (--search-workers / --measure-batch): wall-clock, candidates/sec,
    # fused-round batch occupancy and the worker scaling factor, parsed by
    # the CI distributed-search gate
    if distributed_stats is not None:
        perf["distributed"] = distributed_stats
    # regime metadata (VERDICT r4 item 6): cross-round vs_baseline
    # comparisons need the chip regime (naive_us), the measurement floors
    # that produced the verdict, and the warm-start provenance — without
    # them the parsed series quietly compares different machines
    meta = {
        "perf": perf,
        "naive_us": round(
            (finals[0].pct50 if finals else naive.pct50) * 1e6, 2),
        "search_floor_s": search_opts.target_secs,
        "screen_floor_s": screen_opts.target_secs,
        "final_floor_s": fin_opts.target_secs,
        "mcts_screen_floor_s": mcts_screen.target_secs,
        "winner_label": (label_of(top[best_i])
                         if top and finals and vs > 1.0 else None),
        "recorded_seeds": len(recorded),
    }
    # attribution provenance (ISSUE 6): per-op timeline, critical path,
    # dispatch overhead and overlap efficiency of the reported schedule —
    # next to the fault/perf blocks, parsed by the report CLI
    if attrib_block is not None:
        meta["attrib"] = attrib_block
    # fault-layer provenance (ISSUE 3): a degraded verdict or a quarantine
    # -heavy run must be visible in the parsed metric series, not only in
    # stderr.  ``resumed`` distinguishes a continued run's numbers (its
    # search-phase measurements may predate the current chip regime).
    # ``verified`` (ISSUE 4) is the result-integrity gate's stamp: the
    # reported answer re-executed on device with outputs matching naive AND
    # passed the independent soundness verifier.
    injected: dict = {}
    for inj in (injector, corrupt_injector):
        if inj is not None:
            for k, v in inj.injected.items():
                if v:
                    injected[k] = injected.get(k, 0) + v
    if (resilient.degraded or len(quar) or args.resume or injected
            or integrity is not None):
        meta["fault"] = {
            "degraded": resilient.degraded,
            "quarantined": len(quar),
            "resumed": bool(args.resume),
            **({"injected": injected} if injected else {}),
            **(integrity if integrity is not None else {}),
        }
    write_telemetry()
    return DriverResult(verdict={
        "metric": metric,
        "value": round(value_us, 2),
        "unit": "us",
        "vs_baseline": round(vs, 4),
        **meta,
    })
