"""Statistical noise rejection for benchmark series.

Parity target: reference ``src/randomness.cpp:12-63``: the NIST runs-test over the
measurement series (binarized around the median); a series with too few or too
many runs (|Z| > 1.96, 95% confidence) indicates drift or interference rather than
i.i.d. noise, and the whole measurement set is rejected and retried."""

from __future__ import annotations

import math
from typing import Sequence

from tenzing_tpu.utils.numeric import med


def runs_test_z(xs: Sequence[float]) -> float:
    """Z statistic of the runs test around the median (reference randomness.cpp:12-58)."""
    m = med(xs)
    signs = [x > m for x in xs if x != m]
    n = len(signs)
    if n < 2:
        return 0.0
    n1 = sum(signs)
    n2 = n - n1
    if n1 == 0 or n2 == 0:
        return 0.0
    runs = 1 + sum(1 for a, b in zip(signs, signs[1:]) if a != b)
    expected = 2.0 * n1 * n2 / n + 1.0
    variance = (2.0 * n1 * n2 * (2.0 * n1 * n2 - n)) / (n * n * (n - 1.0))
    if variance <= 0.0:
        return 0.0
    return (runs - expected) / math.sqrt(variance)


def is_random(xs: Sequence[float], z_crit: float = 1.96) -> bool:
    """True iff the series passes the runs test at the given confidence
    (reference compound_test, randomness.cpp:60-63)."""
    return abs(runs_test_z(xs)) <= z_crit
