"""Op chunking: fine-grained compute/comm overlap as searchable decisions.

The searched schedules overlap *whole ops*: a transfer can hide behind a
neighboring compute op, but never behind its own producer or consumer —
once an expensive op starts, nothing else enters its lane until it
finishes.  T3 (PAPERS.md) shows the big wins come from splitting exactly
those ops into chunks so a collective overlaps the tail chunks of the op
that feeds it.  TACCL (PAPERS.md) motivates the sketch-style constraint
that keeps the enlarged space tractable: only chunkings the analytic
roofline model says *can* help ever enter the menus
(``bench/roofline.py::prune_chunkings``).

This module is the mechanism, mirroring the megakernel-fusion protocol
(PR 8, ``runtime/fused.py``) decision-for-decision:

* **The protocol** — ``DeviceOp.chunkable()/chunk_counts()/split(n)``
  (core/operation.py): an audited op expands into ``n`` partial ops whose
  accumulating read-modify-write updates fold the combine into the chain
  (the attention sub-folds chain through the online-softmax state; the
  MoE/pipeline/TP partials chain through slice updates of the output
  buffer).  :class:`ChunkedOp` packages one such expansion as an ordinary
  CompoundOp — the scheduler inlines it via the existing ``ExpandOp``
  machinery, so the partials become first-class schedule vertices other
  ops (a pending transfer post, another chain's compute) interleave with.

* **Searchable counts** — a chunked expansion is just another alternative
  of an ordinary :class:`~tenzing_tpu.core.operation.ChoiceOp` (the
  models append :class:`ChunkedOp` variants to their existing kernel
  menus, or wrap a bare op in :class:`ChunkChoice`), resolved through the
  ordinary ``ChooseOp`` decision.  MCTS, DFS and hill-climb therefore
  search chunk counts with ZERO solver changes, the PR-4 verifier's
  projected-graph model certifies chunked schedules as-is (the compound
  expands, the choice resolves by executed names), and schedules/serdes/
  corpus carry chunked schedules like any other.

* **The executed directive** — every expansion plants a
  :class:`ChunkDirective` (``<base>.chunk.c<N>``, kind-registered like
  ``fuse_tile.tN``) as the compound's entry: a zero-cost host op whose
  only job is to ride the executed schedule so the recorded corpus, the
  surrogate featurizer (learn/features.py) and the driver's
  ``perf.chunked`` provenance can read the searched count back out.

Numerics: ``chunks=1`` IS the original op (the unchunked menu entry is
the op itself — bit-identical by construction); ``chunks>1`` re-associates
the accumulation across chunk boundaries and is held to the driver's
allclose result-integrity gate, exactly the fused path's ``tiles>1`` rule
(docs/performance.md, "Chunked overlap").
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence as Seq

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    ChoiceOp,
    CompoundOp,
    CpuOp,
    DeviceOp,
    OpBase,
    register_kind,
)

# the directive marker: a ChunkDirective is named f"{base}{CHUNK_MARK}{n}".
# learn/features.py duplicates this string (importing nothing from here so
# the featurizer stays jax-free); tests/test_chunking.py asserts they agree.
CHUNK_MARK = ".chunk.c"


@register_kind("chunk")
class ChunkDirective(CpuOp):
    """The executed chunk directive: a no-op host op named
    ``<base>.chunk.c<N>`` whose only effect is to ride the schedule so the
    chosen chunk count is readable from the executed op list — the exact
    shape of the fusion backend's ``fuse_tile.tN``.  A CpuOp so it costs
    nothing in the traced program."""

    def __init__(self, base: str, chunks: int):
        super().__init__(f"{base}{CHUNK_MARK}{int(chunks)}")
        self._base = base
        self._chunks = int(chunks)

    def base(self) -> str:
        return self._base

    def chunks(self) -> int:
        return self._chunks

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(),
                "base": self._base, "chunks": self._chunks}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "ChunkDirective":
        return cls(j["base"], int(j["chunks"]))


class ChunkedOp(CompoundOp):
    """One chunked expansion of an audited op: the ``chunk.cN`` directive
    followed by the op's ``split(n)`` partials chained serially (every
    partial reads the buffer version its predecessor wrote — the combine
    is folded into the accumulating updates).  An ordinary CompoundOp:
    the scheduler inlines it through ``Graph.clone_but_expand``, so the
    partials are first-class vertices the search interleaves other work
    between.

    ``est_hidden_us`` carries the roofline's hidden-comm upper bound for
    this count (``bench/roofline.py::hidden_comm_bound_us``) into the
    driver's ``perf.chunked`` provenance; ``None`` when the menu was
    built un-priced (tests, relaxed smoke menus)."""

    def __init__(self, op: DeviceOp, chunks: int,
                 est_hidden_us: Optional[float] = None):
        super().__init__(f"{op.name()}.chunked.c{int(chunks)}")
        if int(chunks) < 2:
            raise ValueError("ChunkedOp needs chunks >= 2 (1 = the op itself)")
        if not op.chunkable():
            raise ValueError(f"op {op.name()!r} does not declare chunkable()")
        self._op = op
        self._chunks = int(chunks)
        self.est_hidden_us = est_hidden_us

    def base_op(self) -> DeviceOp:
        return self._op

    def chunks(self) -> int:
        return self._chunks

    def graph(self) -> Graph:
        g = Graph()
        prev: OpBase = ChunkDirective(self._op.name(), self._chunks)
        g.start_then(prev)
        for part in self._op.split(self._chunks):
            g.then(prev, part)
            prev = part
        g.then_finish(prev)
        return g

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self.name(),
                "base": self._op.name(), "chunks": self._chunks}


class ChunkChoice(ChoiceOp):
    """The chunk-count menu for an op that has no pre-existing kernel
    ChoiceOp to extend: the op unchanged (chunks=1) vs its chunked
    expansions, named ``<op>.chunks`` so the choice vertex never collides
    with an executed op name.  Models with an existing menu (the attn
    kernel choice, the MoE FFN choice) append :class:`ChunkedOp` variants
    to it directly instead."""

    def __init__(self, op: DeviceOp, variants: Seq[ChunkedOp]):
        super().__init__(op.name() + ".chunks")
        self._op = op
        self._variants = list(variants)
        self.chunk_menu = menu_info(
            op.name(), [1] + [v.chunks() for v in self._variants],
            {v.chunks(): v.est_hidden_us for v in self._variants})

    def choices(self) -> List[OpBase]:
        return [self._op] + list(self._variants)


def menu_info(base: str, counts: Seq[int],
              est: Optional[Dict[int, Optional[float]]] = None
              ) -> Dict[str, Any]:
    """The ``chunk_menu`` attribute choice nodes carry for provenance:
    ``base`` is the name the chunked variants wrap (matching the keys
    :func:`chunks_of` extracts from an executed schedule), ``counts`` the
    pruned menu, ``est_hidden_us`` the per-count roofline bound."""
    return {"base": base,
            "counts": sorted({int(c) for c in counts} | {1}),
            "est_hidden_us": {int(n): e for n, e in (est or {}).items()
                              if e is not None}}


def pow2_counts(extent: Optional[int], cap: int = 8) -> List[int]:
    """The structurally valid chunk counts of a split axis: 1 plus every
    power of two ``<= cap`` dividing ``extent`` — THE ``chunk_counts()``
    recipe every audited model shares.  ``extent=None`` (the op was built
    without its split-axis size) returns ``[1]``: an unknown extent is
    not chunkable, never guessed."""
    out = [1]
    if not extent:
        return out
    n = 2
    while n <= cap and extent % n == 0:
        out.append(n)
        n *= 2
    return out


def chunk_variants(op: DeviceOp, counts: Seq[int],
                   est: Optional[Dict[int, float]] = None
                   ) -> List[ChunkedOp]:
    """``ChunkedOp`` alternatives of ``op`` for the pruned ``counts``
    (entries ``<= 1`` are skipped — 1 is the op itself)."""
    est = est or {}
    return [ChunkedOp(op, n, est_hidden_us=est.get(n))
            for n in sorted({int(c) for c in counts}) if n > 1]


def chunks_of(order) -> Dict[str, int]:
    """The chunk counts an executed schedule carries, by directive base
    name (``{}`` for an unchunked schedule) — parsed from the
    ``<base>.chunk.c<N>`` directives, the read-back twin of
    ``runtime/fused.py::tiles_of``."""
    out: Dict[str, int] = {}
    for op in order:
        name = op.name() if hasattr(op, "name") else ""
        i = name.rfind(CHUNK_MARK)
        if i < 0:
            continue
        try:
            out[name[:i]] = max(1, int(name[i + len(CHUNK_MARK):]))
        except ValueError:
            continue
    return out


def hidden_comm_measured_us(ops, attrib) -> float:
    """Measured hidden comm of a chunked schedule: the total
    Gantt-interval overlap between transfer units and the chunk-partial
    units, from the attribution profiler's stepped timeline
    (obs/attrib — durations measured per unit, starts reconstructed from
    the happens-before relation).  This is the driver's
    ``perf.chunked.hidden_comm_us.measured``: comm time that ran UNDER a
    chunked op's partials, i.e. exactly the overlap whole-op scheduling
    could not express.  ``ops`` is the executed op list
    (``order.vector()``), ``attrib`` the filled
    :class:`~tenzing_tpu.obs.attrib.analysis.Attribution` of the same
    schedule; 0.0 for an unchunked schedule or a comm-free workload."""
    from tenzing_tpu.bench.model import ICI_KINDS, PCIE_KINDS

    chosen = chunks_of(ops)
    if not chosen:
        return 0.0
    ops = list(ops)
    part_prefixes = tuple(f"{base}.c{n}p" for base, n in chosen.items())
    comm_kinds = set(ICI_KINDS) | set(PCIE_KINDS) | {
        "await_transfer", "multi_await"}

    def op_kind(pos: int) -> str:
        if pos >= len(ops):
            return ""
        op = ops[pos]
        base = op.unbound() if hasattr(op, "unbound") else op
        return getattr(base, "KIND", "") or ""

    parts: List = []
    comms: List = []
    for rec in attrib.timeline.records:
        if rec.dur_us <= 0:
            continue
        if rec.name.startswith(part_prefixes):
            parts.append((rec.start_us, rec.end_us))
        elif any(op_kind(p) in comm_kinds for p in rec.positions):
            comms.append((rec.start_us, rec.end_us))
    total = 0.0
    for cs, ce in comms:
        for ps, pe in parts:
            total += max(0.0, min(ce, pe) - max(cs, ps))
    return total


def chunk_menus(graph: Graph) -> Dict[str, Dict[str, Any]]:
    """Every chunk menu a choice graph offers, keyed by the wrapped base
    op name: walks vertices recursively (compound sub-graphs, choice
    alternatives — the serdes descent) collecting the ``chunk_menu``
    attribute the chunk-aware choice nodes carry.  The driver's
    ``perf.chunked`` block reports these next to what the search chose."""
    menus: Dict[str, Dict[str, Any]] = {}
    seen: set = set()

    def visit(op: OpBase) -> None:
        key = id(op)
        if key in seen:
            return
        seen.add(key)
        menu = getattr(op, "chunk_menu", None)
        if isinstance(menu, dict) and "base" in menu:
            menus[menu["base"]] = menu
        if isinstance(op, CompoundOp):
            for v in op.graph().vertices():
                visit(v)
        if isinstance(op, ChoiceOp):
            for c in op.choices():
                visit(c)

    for v in graph.vertices():
        visit(v)
    return menus


__all__ = [
    "CHUNK_MARK", "ChunkDirective", "ChunkedOp", "ChunkChoice",
    "chunk_variants", "chunks_of", "chunk_menus", "menu_info",
    "hidden_comm_measured_us", "pow2_counts",
]
