"""The TPU platform: lanes, device mesh, buffer shardings, event provisioning.

Parity target: reference ``include/tenzing/platform.hpp`` / ``src/platform.cpp``:
``Platform`` owns the real streams + MPI communicator + a ``ResourceMap`` from
virtual events to ``cudaEvent_t`` (platform.hpp:131-144), with
``Platform::make_n_streams`` (platform.hpp:211-215) and a ``CudaEventPool``
amortizing event creation across search iterations (platform.hpp:221-242).

TPU-native redesign (fixing the reference's own "Platform mixes static and
per-order resources" design issue, README.md:59-71): the immutable platform
description (lanes, mesh, buffer partition specs) is separate from per-schedule
provisioning.  Lanes and events are *structural* — they become
optimization-barrier token chains and cross-lane token edges when the schedule is
traced (runtime/executor.py) — so "provisioning an event" allocates a token slot,
not a device object.  ``EventPool``/``ResourceMap`` keep the reference's
provisioning API shape so the solvers' per-candidate reset loop
(mcts.hpp:247-270, dfs.hpp:145-167) carries over.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from tenzing_tpu.core.resources import Event, Lane


class ResourceMap:
    """Virtual Event -> provisioned token slot (reference ResourceMap,
    platform.hpp:131-144; slots are symbolic on TPU)."""

    def __init__(self) -> None:
        self._slots: Dict[Event, int] = {}

    def insert(self, event: Event, slot: int) -> None:
        self._slots[event] = slot

    def __contains__(self, event: Event) -> bool:
        return event in self._slots

    def __getitem__(self, event: Event) -> int:
        return self._slots[event]

    def __len__(self) -> int:
        return len(self._slots)

    def clear(self) -> None:
        self._slots.clear()


class EventPool:
    """Amortized event provisioning (reference CudaEventPool,
    platform.hpp:221-242): ``reset()`` between candidate schedules, ``get()``
    hands out slots."""

    def __init__(self) -> None:
        self._next = 0

    def reset(self) -> None:
        self._next = 0

    def get(self) -> int:
        slot = self._next
        self._next += 1
        return slot


class Platform:
    """Immutable execution context: virtual lanes, the device mesh, and the
    partition specs of named buffers (reference Platform, platform.hpp:131-215).

    ``mesh``/``axis_names`` describe the SPMD decomposition: when set, schedules
    are traced under ``shard_map`` over the mesh and comm ops may use collectives
    over the named axes.  ``specs`` maps buffer name -> ``PartitionSpec`` (default
    fully replicated)."""

    def __init__(
        self,
        lanes: List[Lane],
        mesh=None,
        specs: Optional[Dict[str, object]] = None,
    ):
        self.lanes = lanes
        self.mesh = mesh
        self.specs = dict(specs) if specs else {}
        self.event_pool = EventPool()
        self.resource_map = ResourceMap()

    @staticmethod
    def make_n_lanes(n: int, mesh=None, specs: Optional[Dict[str, object]] = None) -> "Platform":
        """reference Platform::make_n_streams (platform.hpp:211-215)."""
        return Platform([Lane(i) for i in range(n)], mesh=mesh, specs=specs)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names) if self.mesh is not None else ()

    def spec(self, name: str):
        """Partition spec for buffer ``name`` (replicated when unspecified)."""
        if name in self.specs:
            return self.specs[name]
        from jax.sharding import PartitionSpec

        return PartitionSpec()

    def provision_events(self, events: Iterable[Event]) -> ResourceMap:
        """Per-candidate event provisioning (reference mcts.hpp:247-270 /
        dfs.hpp:145-167 reset loop)."""
        self.event_pool.reset()
        self.resource_map.clear()
        for e in events:
            if e not in self.resource_map:
                self.resource_map.insert(e, self.event_pool.get())
        return self.resource_map
