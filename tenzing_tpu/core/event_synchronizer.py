"""The correctness oracle: which sync ops must precede an op for a schedule to be
legal.

Parity target: reference ``include/tenzing/event_synchronizer.hpp`` /
``src/event_synchronizer.cpp``.  The case analysis over predecessor x op kind
(event_synchronizer.hpp:183-242) carries over with HOST standing in for the CPU
thread and lanes for CUDA streams:

===========  ===========  ================================================
pred         op           required ordering
===========  ===========  ================================================
host         host         free (host chain = program order)
host         device lane  free (dispatch order: executor joins host token)
device lane  same lane    free (lane token chain)
device lane  other lane   EventRecord(pred.lane, e) ... WaitEvent(op.lane, e)
device lane  host         EventRecord(pred.lane, e) ... EventSync(e)
===========  ===========  ================================================

``is_synced`` checks the executed sequence for the required record/wait pairs
(reference is_synced_gpu_then_gpu, event_synchronizer.hpp:29-65; gpu_then_cpu,
event_synchronizer.cpp:3-27).  ``make_syncs`` emits the *next* missing sync op for
each unsynced predecessor — first an EventRecord on a fresh event, then the
matching WaitEvent/EventSync — deduplicating identical syncs
(event_synchronizer.hpp:246-329).

Because a schedule only becomes executable through these checks, searched
schedules are race-free by construction (the compiled program's token edges are a
superset of the graph's data edges; see SURVEY.md §5).
"""

from __future__ import annotations

from typing import List, Optional, Sequence as Seq

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import BoundDeviceOp, BoundOp, OpBase
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import EventRecord, EventSync, LaneSync, SyncOp, WaitEvent


def _index_of(seq: Sequence, op: OpBase) -> Optional[int]:
    for i, o in enumerate(seq):
        if o == op:
            return i
    return None


def _sync_attr_eq(a: SyncOp, b: SyncOp) -> bool:
    """Attribute-level equality (sync-op ``eq`` is kind-only by design)."""
    return type(a) is type(b) and a.to_json() == b.to_json()


class EventSynchronizer:
    """All-static oracle (reference EventSynchronizer)."""

    # -- is_synced ---------------------------------------------------------
    @staticmethod
    def _find_record_after(seq: Sequence, pos: int, lane: Lane) -> Optional[EventRecord]:
        for i in range(pos + 1, len(seq)):
            op = seq[i]
            if isinstance(op, EventRecord) and op.lane() == lane:
                return op
        return None

    @staticmethod
    def _device_then_device_synced(seq: Sequence, pred: BoundDeviceOp, op: BoundDeviceOp) -> bool:
        """reference is_synced_gpu_then_gpu, event_synchronizer.hpp:29-65."""
        if pred.lane() == op.lane():
            return True
        pi = _index_of(seq, pred)
        assert pi is not None, f"pred {pred!r} not executed"
        for i in range(pi + 1, len(seq)):
            rec = seq[i]
            if isinstance(rec, EventRecord) and rec.lane() == pred.lane():
                for j in range(i + 1, len(seq)):
                    w = seq[j]
                    if (
                        isinstance(w, WaitEvent)
                        and w.lane() == op.lane()
                        and w.event() == rec.event()
                    ):
                        return True
        return False

    @staticmethod
    def _device_then_host_synced(seq: Sequence, pred: BoundDeviceOp) -> bool:
        """reference is_synced GPU-then-CPU case, event_synchronizer.cpp:3-27."""
        pi = _index_of(seq, pred)
        assert pi is not None, f"pred {pred!r} not executed"
        for i in range(pi + 1, len(seq)):
            rec = seq[i]
            if isinstance(rec, LaneSync) and rec.lane() == pred.lane():
                return True
            if isinstance(rec, EventRecord) and rec.lane() == pred.lane():
                for j in range(i + 1, len(seq)):
                    s = seq[j]
                    if isinstance(s, EventSync) and s.event() == rec.event():
                        return True
        return False

    @staticmethod
    def is_synced(graph: Graph, seq: Sequence, op: BoundOp) -> bool:
        """True iff every graph predecessor of ``op`` is provably ordered before
        it (reference event_synchronizer.hpp:183-242)."""
        if isinstance(op, SyncOp) or op not in graph:
            return True  # scheduler-inserted syncs are freely placeable
        for pred in graph.preds(op):
            if not isinstance(pred, BoundDeviceOp):
                continue  # host -> anything is free
            if isinstance(op, BoundDeviceOp):
                if not EventSynchronizer._device_then_device_synced(seq, pred, op):
                    return False
            else:
                if not EventSynchronizer._device_then_host_synced(seq, pred):
                    return False
        return True

    # -- make_syncs --------------------------------------------------------
    @staticmethod
    def _fresh_event(seq: Sequence, pending: Seq[SyncOp]) -> Event:
        """Smallest event id free in the sequence *and* the syncs pending in this
        call (delegates to Sequence.new_unique_event, sequence.hpp:77-93)."""
        return Sequence(list(seq) + list(pending)).new_unique_event()

    @staticmethod
    def make_syncs(graph: Graph, seq: Sequence, op: BoundOp) -> List[SyncOp]:
        """The next missing sync op(s) before ``op`` is executable; empty iff
        already synced (reference event_synchronizer.hpp:246-329)."""
        syncs: List[SyncOp] = []

        def emit(s: SyncOp) -> None:
            if not any(_sync_attr_eq(s, t) for t in syncs):
                syncs.append(s)

        if isinstance(op, SyncOp) or op not in graph:
            return syncs
        for pred in graph.preds(op):
            if not isinstance(pred, BoundDeviceOp):
                continue
            if isinstance(op, BoundDeviceOp):
                if EventSynchronizer._device_then_device_synced(seq, pred, op):
                    continue
            else:
                if EventSynchronizer._device_then_host_synced(seq, pred):
                    continue
            pi = _index_of(seq, pred)
            assert pi is not None, f"pred {pred!r} not executed"
            rec = EventSynchronizer._find_record_after(seq, pi, pred.lane())
            if rec is None:
                # also covered if an identical record is already pending this call
                pending = next(
                    (
                        s
                        for s in syncs
                        if isinstance(s, EventRecord) and s.lane() == pred.lane()
                    ),
                    None,
                )
                if pending is None:
                    emit(EventRecord(pred.lane(), EventSynchronizer._fresh_event(seq, syncs)))
            else:
                if isinstance(op, BoundDeviceOp):
                    emit(WaitEvent(op.lane(), rec.event()))
                else:
                    emit(EventSync(rec.event()))
        return syncs
