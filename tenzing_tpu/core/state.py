"""Sequential decision process over schedules.

Parity target: reference ``include/tenzing/state.hpp`` / ``src/state.cpp`` and
``include/tenzing/decision.hpp``.  A :class:`State` is (graph, sequence-so-far).
``get_decisions`` walks the graph frontier and emits per-op-kind decisions
(state.cpp:25-69); ``apply`` produces the successor state (state.cpp:71-106);
``frontier`` is apply-all **with equivalence dedup** — implemented here, fixing the
reference's unimplemented-dedup defect (state.cpp:121 ``#warning``; SURVEY.md §7.3).

State equivalence = sequence equivalence and graph equivalence under mutually
consistent lane/event bijections (reference state.cpp:126-143).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from tenzing_tpu.core import graph as graph_mod
from tenzing_tpu.core import sequence as sequence_mod
from tenzing_tpu.core.event_synchronizer import EventSynchronizer
from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import (
    BoundOp,
    ChoiceOp,
    CompoundOp,
    DeviceOp,
    OpBase,
)
from tenzing_tpu.core.resources import Equivalence, Lane
from tenzing_tpu.core.sequence import Sequence


def _freeze(obj) -> Any:
    """JSON-able value -> hashable key with the same equality."""
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    return obj


class Decision:
    """Base decision (reference decision.hpp:13-20).

    Equality/hash is by JSON content (resource-sensitive: two syncs on
    different lanes are different decisions), via a key frozen once per
    instance — decisions are compared and deduped hot in the solvers."""

    _key: Optional[tuple] = None

    def desc(self) -> str:
        raise NotImplementedError

    def to_json(self) -> Dict[str, Any]:
        raise NotImplementedError

    def key(self) -> tuple:
        if self._key is None:
            self._key = (type(self).__name__, _freeze(self.to_json()))
        return self._key

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Decision) and self.key() == other.key()

    def __hash__(self) -> int:
        return hash(self.key())

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.desc()


class ExecuteOp(Decision):
    """Append an executable op to the sequence (reference decision.hpp:22-30)."""

    def __init__(self, op: BoundOp):
        self.op = op

    def desc(self) -> str:
        return f"Execute({self.op.desc()})"

    def to_json(self) -> Dict[str, Any]:
        return {"decision": "execute", "op": self.op.to_json()}


class AssignLane(Decision):
    """Bind a device op to a lane (reference AssignOpStream, decision.hpp:54-63)."""

    def __init__(self, op: DeviceOp, lane: Lane):
        self.op = op
        self.lane = lane

    def desc(self) -> str:
        return f"AssignLane({self.op.desc()},{self.lane!r})"

    def to_json(self) -> Dict[str, Any]:
        return {"decision": "assign_lane", "op": self.op.to_json(), "lane": self.lane.id}


class ExpandOp(Decision):
    """Inline a CompoundOp's sub-graph (reference decision.hpp:32-40)."""

    def __init__(self, op: CompoundOp):
        self.op = op

    def desc(self) -> str:
        return f"Expand({self.op.desc()})"

    def to_json(self) -> Dict[str, Any]:
        return {"decision": "expand", "op": self.op.to_json()}


class ChooseOp(Decision):
    """Replace a ChoiceOp with one of its choices (reference decision.hpp:42-52)."""

    def __init__(self, op: ChoiceOp, choice: OpBase):
        self.op = op
        self.choice = choice

    def desc(self) -> str:
        return f"Choose({self.op.desc()}->{self.choice.desc()})"

    def to_json(self) -> Dict[str, Any]:
        return {
            "decision": "choose",
            "op": self.op.to_json(),
            "choice": self.choice.to_json(),
        }


class State:
    """(graph, sequence) — a partial schedule (reference SDP::State, state.hpp:15-49)."""

    def __init__(self, graph: Graph, sequence: Optional[Sequence] = None):
        self.graph = graph
        self.sequence: Sequence = (
            sequence if sequence is not None else Sequence([graph.start()])
        )

    def is_terminal(self) -> bool:
        """Complete schedule: Finish executed."""
        return self.sequence.contains(self.graph.finish())

    def get_decisions(self, platform) -> List[Decision]:
        """Frontier -> decisions (reference state.cpp:25-69).  ``platform`` must
        expose ``lanes`` (list of Lane)."""
        decisions: List[Decision] = []
        for op in self.graph.frontier(self.sequence.vector()):
            if isinstance(op, BoundOp):
                syncs = EventSynchronizer.make_syncs(self.graph, self.sequence, op)
                if not syncs:
                    decisions.append(ExecuteOp(op))
                else:
                    decisions.extend(ExecuteOp(s) for s in syncs)
            elif isinstance(op, CompoundOp):
                decisions.append(ExpandOp(op))
            elif isinstance(op, ChoiceOp):
                decisions.extend(ChooseOp(op, c) for c in op.choices())
            elif isinstance(op, DeviceOp):
                decisions.extend(AssignLane(op, lane) for lane in platform.lanes)
            else:  # pragma: no cover - defensive
                raise TypeError(f"frontier op of unknown kind: {op!r}")
        # dedup identical decisions (e.g. the same sync demanded by two frontier ops)
        seen = set()
        out: List[Decision] = []
        for d in decisions:
            k = d.key()
            if k not in seen:
                seen.add(k)
                out.append(d)
        return out

    def apply(self, d: Decision) -> "State":
        """Successor state (reference state.cpp:71-106)."""
        if isinstance(d, ExecuteOp):
            seq = Sequence(self.sequence.vector())
            seq.push_back(d.op)
            return State(self.graph, seq)
        if isinstance(d, AssignLane):
            g = self.graph.clone_but_replace(d.op.bind(d.lane), d.op)
            return State(g, Sequence(self.sequence.vector()))
        if isinstance(d, ExpandOp):
            g = self.graph.clone_but_expand(d.op)
            return State(g, Sequence(self.sequence.vector()))
        if isinstance(d, ChooseOp):
            g = self.graph.clone_but_replace(d.choice, d.op)
            return State(g, Sequence(self.sequence.vector()))
        raise TypeError(f"unknown decision {d!r}")

    def frontier(self, platform, dedup: bool = True) -> List["State"]:
        """All successor states, deduplicated under resource-renaming equivalence
        (implements the dedup the reference left unimplemented, state.cpp:121).

        Candidates are bucketed by the sequence's O(1) ``canonical_key`` —
        states in different buckets cannot be equivalent (state equivalence
        requires sequence equivalence, which canonical keys decide exactly) —
        and only within a bucket does the full pairwise state check (sequence
        AND graph under one consistent bijection) run."""
        succs = [self.apply(d) for d in self.get_decisions(platform)]
        if not dedup:
            return succs
        out: List[State] = []
        buckets: Dict[tuple, List[State]] = {}
        for s in succs:
            key = sequence_mod.canonical_key(s.sequence)
            bucket = buckets.setdefault(key, [])
            if not any(get_equivalence(s, t) for t in bucket):
                bucket.append(s)
                out.append(s)
        return out

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"State(seq={self.sequence.desc()})"


def get_equivalence(a: State, b: State) -> Equivalence:
    """State equivalence: one consistent lane/event renaming must witness both the
    sequences and the graphs (reference state.cpp:126-143)."""
    e = sequence_mod.get_equivalence(a.sequence, b.sequence)
    if not e:
        return Equivalence.falsy()
    return graph_mod.get_equivalence(a.graph, b.graph, base=e)
