"""Program DAG over operations.

Parity target: reference ``include/tenzing/graph.hpp`` / ``src/graph.cpp``:
adjacency maps keyed by op identity (graph.hpp:19-30), edge insertion
``then/start_then/then_finish`` (graph.hpp:46-73), ``clone`` (graph.hpp:223-245),
``clone_but_replace`` for lane-binding surgery (graph.hpp:130-158),
``clone_but_expand`` for CompoundOp inlining (graph.hpp:162-219),
``frontier`` (graph.hpp:482-540), graphviz dump (graph.cpp:13-40), whole-graph
lane-assignment enumeration (graph.cpp:42-234), and graph equivalence under
resource bijection (graph.cpp:236-420).

TPU-native notes: vertices are keyed by resource-insensitive op identity
(operation.py ``eq_key``), so binding a DeviceOp to a Lane replaces the stored
vertex object but not its key — bound/unbound matching (reference
``succs_find_or_find_unbound``, graph.hpp:383-391) falls out of the identity model
instead of needing a parallel lookup path.
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, Iterable, List, Optional, Sequence as Seq, Set, Tuple

from tenzing_tpu.core.operation import (
    BoundDeviceOp,
    CompoundOp,
    DeviceOp,
    Finish,
    OpBase,
    Start,
)
from tenzing_tpu.core.resources import Equivalence, Lane


class Graph:
    """A DAG of ops with Start/Finish sentinels (reference Graph<OpBase>)."""

    def __init__(self, start: Optional[OpBase] = None, finish: Optional[OpBase] = None):
        self.start_: OpBase = start if start is not None else Start()
        self.finish_: OpBase = finish if finish is not None else Finish()
        # insertion-ordered adjacency; the stored key object IS the graph vertex
        self.succs_: Dict[OpBase, List[OpBase]] = {}
        self.preds_: Dict[OpBase, List[OpBase]] = {}
        self._canon: Dict[Tuple, OpBase] = {}  # eq_key -> stored vertex object
        self._add_vertex(self.start_)
        self._add_vertex(self.finish_)

    # -- construction -----------------------------------------------------
    def _add_vertex(self, op: OpBase) -> OpBase:
        if op not in self.succs_:
            self.succs_[op] = []
            self.preds_[op] = []
            self._canon[op.eq_key()] = op
        return self._canon[op.eq_key()]

    def vertex(self, op: OpBase) -> OpBase:
        """Return the stored vertex object equal to ``op`` (O(1)) — the stored
        object carries the current resource binding (e.g. after
        clone_but_replace lane surgery)."""
        try:
            return self._canon[op.eq_key()]
        except KeyError:
            raise KeyError(f"op {op!r} not in graph") from None

    # backward-compatible private alias
    _vertex = vertex

    def then(self, a: OpBase, b: OpBase) -> OpBase:
        """Add edge a->b, inserting vertices as needed; returns b for chaining
        (reference graph.hpp:46-60)."""
        a = self._add_vertex(a)
        b = self._add_vertex(b)
        if b not in self.succs_[a]:
            self.succs_[a].append(b)
        if a not in self.preds_[b]:
            self.preds_[b].append(a)
        return b

    def start_then(self, b: OpBase) -> OpBase:
        return self.then(self.start_, b)

    def then_finish(self, a: OpBase) -> OpBase:
        return self.then(a, self.finish_)

    # -- queries ----------------------------------------------------------
    def vertices(self) -> List[OpBase]:
        return list(self.succs_.keys())

    def vertex_size(self) -> int:
        return len(self.succs_)

    def __contains__(self, op: OpBase) -> bool:
        return op in self.succs_

    def succs(self, op: OpBase) -> List[OpBase]:
        return self.succs_[op]

    def preds(self, op: OpBase) -> List[OpBase]:
        return self.preds_[op]

    def start(self) -> OpBase:
        return self.start_

    def finish(self) -> OpBase:
        return self.finish_

    def frontier(self, executed: Seq[OpBase]) -> List[OpBase]:
        """Ops whose predecessors have all executed and which have not themselves
        executed (reference graph.hpp:482-540).  ``executed`` may contain
        scheduler-inserted sync ops (not graph vertices) and bound versions of
        graph vertices — both handled by resource-insensitive identity."""
        done: Set[Tuple] = {op.eq_key() for op in executed}
        out: List[OpBase] = []
        for v in self.succs_:
            if v.eq_key() in done:
                continue
            if all(p.eq_key() in done for p in self.preds_[v]):
                out.append(v)
        return out

    # -- clone surgery ----------------------------------------------------
    def _clone_mapped(self, fn: Callable[[OpBase], OpBase]) -> "Graph":
        """Clone with every vertex passed through ``fn``."""
        g = Graph.__new__(Graph)
        mapped: Dict[OpBase, OpBase] = {v: fn(v) for v in self.succs_}
        keys = [m.eq_key() for m in mapped.values()]
        if len(set(keys)) != len(keys):
            raise ValueError("vertex substitution collides with an existing vertex")
        g.start_ = mapped[self.start_]
        g.finish_ = mapped[self.finish_]
        g.succs_ = {mapped[v]: [mapped[s] for s in ss] for v, ss in self.succs_.items()}
        g.preds_ = {mapped[v]: [mapped[p] for p in ps] for v, ps in self.preds_.items()}
        g._canon = {m.eq_key(): m for m in mapped.values()}
        return g

    def clone(self) -> "Graph":
        """Clone sharing op objects (ops are immutable values; reference
        graph.hpp:223-245 clones shared_ptrs for the same effect)."""
        return self._clone_mapped(lambda v: v)

    def clone_but_replace(self, new: OpBase, old: OpBase) -> "Graph":
        """Clone with vertex ``old`` replaced by ``new`` — lane binding keeps the
        identity key; ChooseOp substitution may change it (reference
        graph.hpp:130-158)."""
        old = self._vertex(old)
        return self._clone_mapped(lambda v: new if v == old else v)

    def clone_but_expand(self, compound: CompoundOp) -> "Graph":
        """Clone with ``compound`` inlined: its sub-graph's interior vertices are
        spliced in; preds(compound) -> succs(inner start); preds(inner finish) ->
        succs(compound) (reference graph.hpp:162-219)."""
        inner = compound.graph()
        comp = self._vertex(compound)
        g = self.clone()
        outer_preds = list(g.preds_[comp])
        outer_succs = list(g.succs_[comp])
        # remove compound vertex
        del g.succs_[comp]
        del g.preds_[comp]
        del g._canon[comp.eq_key()]
        for v in g.succs_:
            g.succs_[v] = [s for s in g.succs_[v] if s != comp]
            g.preds_[v] = [p for p in g.preds_[v] if p != comp]
        # splice interior vertices and edges
        interior = [v for v in inner.succs_ if v not in (inner.start_, inner.finish_)]
        for v in interior:
            if v in g:
                raise ValueError(
                    f"compound interior op {v!r} collides with an existing vertex"
                )
            g._add_vertex(v)
        for v in interior:
            for s in inner.succs_[v]:
                if s == inner.finish_:
                    continue
                g.then(v, s)
        entries = [s for s in inner.succs_[inner.start_] if s != inner.finish_]
        exits = [p for p in inner.preds_[inner.finish_] if p != inner.start_]
        for p in outer_preds:
            for e in entries:
                g.then(p, e)
            if not entries:
                for s in outer_succs:
                    g.then(p, s)
        for e in exits:
            for s in outer_succs:
                g.then(e, s)
        return g

    # -- whole-graph lane assignment (reference graph.cpp:42-234) ----------
    def device_vertices(self) -> List[OpBase]:
        return [
            v
            for v in self.succs_
            if isinstance(v, (DeviceOp, BoundDeviceOp))
        ]

    def apply_lane_assignment(self, assignment: Dict[OpBase, Lane]) -> "Graph":
        """Bind every DeviceOp per ``assignment`` (reference apply_assignment,
        graph.cpp:200-234)."""

        def fn(v: OpBase) -> OpBase:
            if v in assignment:
                lane = assignment[v]
                if isinstance(v, BoundDeviceOp):
                    return v.with_lane(lane)
                if isinstance(v, DeviceOp):
                    return v.bind(lane)
            return v

        return self._clone_mapped(fn)

    def use_lanes(self, lanes: Seq[Lane]) -> List["Graph"]:
        """Enumerate every total lane assignment of the graph's device ops
        (reference use_streams/use_streams2, graph.cpp:42-199)."""
        dops = self.device_vertices()
        out: List[Graph] = []
        for combo in itertools.product(lanes, repeat=len(dops)):
            out.append(self.apply_lane_assignment(dict(zip(dops, combo))))
        return out

    # -- visualization ----------------------------------------------------
    def dump_graphviz(self, path: Optional[str] = None) -> str:
        """Graphviz dot text (reference graph.cpp:13-40)."""
        ids = {v: i for i, v in enumerate(self.succs_)}
        lines = ["digraph G {"]
        for v, i in ids.items():
            lines.append(f'  n{i} [label="{v.desc()}"];')
        for v, ss in self.succs_.items():
            for s in ss:
                lines.append(f"  n{ids[v]} -> n{ids[s]};")
        lines.append("}")
        text = "\n".join(lines) + "\n"
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text


# -- graph equivalence under resource bijection (reference graph.cpp:236-420) ----


def get_equivalence(a: Graph, b: Graph, base: Optional[Equivalence] = None) -> Equivalence:
    """An Equivalence witnessing that ``a`` and ``b`` are the same DAG up to a
    consistent renaming of lanes (events never appear as graph vertices), or a
    falsy Equivalence (reference get_equivalence, graph.cpp:348-420).  When
    ``base`` is given the renaming must consistently extend it (used by state
    equivalence, reference state.cpp:126-143)."""
    e = base.copy() if base is not None else Equivalence()
    if not e:
        return Equivalence.falsy()
    averts = {v.eq_key(): v for v in a.succs_}
    bverts = {v.eq_key(): v for v in b.succs_}
    if set(averts) != set(bverts):
        return Equivalence.falsy()
    for k, av in averts.items():
        bv = bverts[k]
        ab = isinstance(av, BoundDeviceOp)
        bb = isinstance(bv, BoundDeviceOp)
        if ab != bb:
            return Equivalence.falsy()
        if ab and not e.check_or_insert_lane(av.lane(), bv.lane()):
            return Equivalence.falsy()
    for v, ss in a.succs_.items():
        bss = b.succs_[bverts[v.eq_key()]]
        if {s.eq_key() for s in ss} != {s.eq_key() for s in bss}:
            return Equivalence.falsy()
    return e


def is_equivalent_lane_mapping(a: Graph, b: Graph) -> bool:
    """reference is_equivalent_stream_mapping, graph.cpp:236-346."""
    return bool(get_equivalence(a, b))
