"""Ordered (partial or total) schedules.

Parity target: reference ``include/tenzing/sequence.hpp`` / ``src/sequence.cpp``:
a vector of ops with bound/unbound matching (sequence.hpp:48-75), smallest-free
virtual event allocation (``new_unique_event``, sequence.hpp:77-93), sequence
equivalence under lane/event bijection (sequence.cpp:21-86), and schedule
broadcast across hosts (``mpi_bcast``, sequence.cpp:88-125 — here realized by the
control plane in tenzing_tpu.parallel.control_plane, serializing to JSON and
re-materializing ops against the local graph).
"""

from __future__ import annotations

from typing import Generic, Iterable, Iterator, List, Optional, TypeVar

from tenzing_tpu.core.operation import BoundDeviceOp, OpBase, unbound
from tenzing_tpu.core.resources import Equivalence, Event

OpT = TypeVar("OpT", bound=OpBase)


class Sequence(Generic[OpT]):
    """An ordered list of ops (reference Sequence<OpType>)."""

    def __init__(self, ops: Optional[Iterable[OpT]] = None):
        self._ops: List[OpT] = list(ops) if ops is not None else []
        # derived-value memo (canonical key, serialized JSON, schedule id):
        # every benchmark/cache/verify/journal/injection lookup re-derives
        # one of these from the same op list, and a search queries the same
        # schedule through many layers.  Entries are (version, value) and a
        # mutation bumps the version, so a mutated sequence can never serve
        # a stale value; ops themselves are immutable (bind() returns a new
        # BoundDeviceOp), so the op list is the only invalidation source.
        self._version = 0
        self._memo: dict = {}

    def cached(self, key: str, compute):
        """Memoize ``compute()`` under ``key`` until this sequence mutates.

        Safe under concurrent readers (worst case: both recompute — dict
        get/set are GIL-atomic), which the background compile-prefetch
        threads (bench/pipeline.py) rely on."""
        ent = self._memo.get(key)
        if ent is not None and ent[0] == self._version:
            return ent[1]
        # capture the version BEFORE computing: a mutation racing compute()
        # then leaves a stale-versioned entry (recomputed on the next read)
        # instead of a fresh-versioned stale value (served forever)
        version = self._version
        val = compute()
        self._memo[key] = (version, val)
        return val

    # -- list protocol ----------------------------------------------------
    def __len__(self) -> int:
        return len(self._ops)

    def __iter__(self) -> Iterator[OpT]:
        return iter(self._ops)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return Sequence(self._ops[i])
        return self._ops[i]

    def push_back(self, op: OpT) -> None:
        self._ops.append(op)
        self._version += 1  # invalidate cached() derivations

    def vector(self) -> List[OpT]:
        return list(self._ops)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Sequence) and self._ops == other._ops

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Sequence([{', '.join(op.desc() for op in self._ops)}])"

    # -- bound/unbound matching (reference sequence.hpp:48-75) -------------
    def contains(self, op: OpBase) -> bool:
        return any(o == op for o in self._ops)

    def contains_unbound(self, op: OpBase) -> bool:
        """True if the sequence contains ``op`` or a lane-bound version of it
        (reference contains_unbound; with resource-insensitive identity this is
        plain equality)."""
        target = unbound(op)
        return any(unbound(o) == target for o in self._ops)

    def find_unbound(self, op: OpBase) -> Optional[OpBase]:
        """The sequence entry matching ``op`` modulo lane binding, or None
        (reference find_unbound, sequence.cpp:140-167)."""
        target = unbound(op)
        for o in self._ops:
            if unbound(o) == target:
                return o
        return None

    # -- event allocation (reference sequence.hpp:77-93) -------------------
    def new_unique_event(self) -> Event:
        """Smallest virtual Event id not used by any op in the sequence."""
        used = set()
        for op in self._ops:
            events = getattr(op, "events", None)
            if events is not None:
                used.update(e.id for e in events())
        i = 0
        while i in used:
            i += 1
        return Event(i)

    def desc(self, delim: str = ", ") -> str:
        return delim.join(op.desc() for op in self._ops)


def get_equivalence(a: Sequence, b: Sequence, base: Optional[Equivalence] = None) -> Equivalence:
    """Equivalence of two sequences up to a consistent renaming of lanes and
    events (reference sequence.cpp:21-86): ops must match pairwise in order by
    resource-insensitive identity, and their lane/event uses must admit mutually
    consistent bijections (extending ``base`` when given)."""
    if len(a) != len(b):
        return Equivalence.falsy()
    e = base.copy() if base is not None else Equivalence()
    if not e:
        return Equivalence.falsy()
    for x, y in zip(a, b):
        if x.eq_key() != y.eq_key():
            return Equivalence.falsy()
        xl = x.lanes() if hasattr(x, "lanes") else []
        yl = y.lanes() if hasattr(y, "lanes") else []
        if len(xl) != len(yl):
            return Equivalence.falsy()
        for la, lb in zip(xl, yl):
            if not e.check_or_insert_lane(la, lb):
                return Equivalence.falsy()
        xe = x.events() if hasattr(x, "events") else []
        ye = y.events() if hasattr(y, "events") else []
        if len(xe) != len(ye):
            return Equivalence.falsy()
        for ea, eb in zip(xe, ye):
            if not e.check_or_insert_event(ea, eb):
                return Equivalence.falsy()
    return e


def is_equivalent(a: Sequence, b: Sequence) -> bool:
    return bool(get_equivalence(a, b))


def canonical_key(seq: Sequence) -> tuple:
    """A hashable canonical form of ``seq`` under lane/event renaming:
    per op, (eq_key, lanes relabeled in first-use order, events likewise).

    Two sequences are bijection-equivalent (``get_equivalence`` with no base)
    iff their canonical keys are equal: a consistent bijection must map the
    i-th distinct lane of one to the i-th distinct lane of the other (at each
    first use, injectivity in both directions forces fresh->fresh), so a
    bijection exists exactly when the first-use-relabeled streams coincide.
    This is the O(1)-lookup replacement for pairwise bijection scans (the
    same canonicalization the native core's canonical_key uses,
    native/src/core.cpp) — ``get_equivalence`` remains the semantic ground
    truth and the cross-check test asserts agreement.

    Memoized on the sequence (``Sequence.cached``): the solvers' dedup
    loops, the benchmark cache, the verifier cache, and the journal all key
    on the canonical form of the same object, and the relabeling walk is
    O(n) per query.  A mutation (``push_back``) invalidates.
    """
    if isinstance(seq, Sequence):
        return seq.cached("canonical_key", lambda: _canonical_key_of(seq))
    return _canonical_key_of(seq)


def _canonical_key_of(seq: Sequence) -> tuple:
    lanes: dict = {}
    events: dict = {}
    items = []
    for op in seq:
        ls = tuple(
            lanes.setdefault(l.id, len(lanes))
            for l in (op.lanes() if hasattr(op, "lanes") else [])
        )
        es = tuple(
            events.setdefault(e.id, len(events))
            for e in (op.events() if hasattr(op, "events") else [])
        )
        items.append((op.eq_key(), ls, es))
    return tuple(items)
