"""JSON serialization of schedules, anchored to a graph on deserialization.

Parity target: reference ``include/tenzing/operation_serdes.hpp`` /
``src/operation_serdes.cpp``: ops serialize themselves (``OpBase.to_json``);
deserialization searches the graph (descending into CompoundOp sub-graphs and
ChoiceOp choices) for an op whose name matches, rebinding device ops with the
serialized lane; scheduler-inserted sync ops absent from the graph are
reconstructed from their ``kind`` field (operation_serdes.cpp:14-76).

This is the foundation of cross-host schedule broadcast (reference
sequence.cpp:88-125 ``mpi_bcast``; here parallel/control_plane.py) and of the
recorded-timings benchmarker (bench/benchmarker.py CsvBenchmarker).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core import sync_ops as _sync_ops  # noqa: F401 — registers the
# scheduler-inserted sync-op kinds; without this, resolving a serialized
# event_record/event_sync would depend on whether the caller happened to import
# sync_ops first
from tenzing_tpu.core.operation import (
    ChoiceOp,
    CompoundOp,
    DeviceOp,
    OpBase,
    kind_registry,
)
from tenzing_tpu.core.resources import Lane
from tenzing_tpu.core.sequence import Sequence


def sequence_to_json(seq: Sequence) -> List[Dict[str, Any]]:
    return [op.to_json() for op in seq]


def sequence_to_json_str(seq: Sequence) -> str:
    """Serialized schedule, memoized on the sequence: the executor's program
    cache, schedule ids, and the journal all key on this string for the same
    object many times per search (``Sequence.cached`` invalidates on
    mutation).  The per-op dict list from :func:`sequence_to_json` is NOT
    memoized — callers may mutate it."""
    if isinstance(seq, Sequence):
        return seq.cached(
            "json_str", lambda: json.dumps(sequence_to_json(seq)))
    return json.dumps(sequence_to_json(seq))


def _search_op(op: OpBase, name: str) -> Optional[OpBase]:
    """Uniform recursive match on one op: its own name, then — whatever the
    nesting — compound sub-graphs and choice alternatives (reference
    operation_serdes.cpp:14-56 recurses uniformly; a ChoiceOp nested inside a
    choice alternative's compound must resolve the same as a top-level one)."""
    if op.name() == name:
        return op
    if isinstance(op, CompoundOp):
        hit = _find_by_name(op.graph(), name)
        if hit is not None:
            return hit
    if isinstance(op, ChoiceOp):
        for c in op.choices():
            hit = _search_op(c, name)
            if hit is not None:
                return hit
    return None


def _find_by_name(graph: Graph, name: str) -> Optional[OpBase]:
    """Recursive graph-anchored lookup (reference operation_serdes.cpp:14-56):
    search vertices, descending into compound sub-graphs and choice alternatives."""
    for v in graph.vertices():
        hit = _search_op(v, name)
        if hit is not None:
            return hit
    return None


def op_from_json(j: Dict[str, Any], graph: Graph) -> OpBase:
    """Re-materialize one op against the local graph (reference
    operation_serdes.cpp:58-76)."""
    kind = j.get("kind")
    registry = kind_registry()
    cls = registry.get(kind)
    if cls is not None and hasattr(cls, "from_json"):
        # scheduler-inserted sync ops carry everything they need
        return cls.from_json(j)
    name = j["name"]
    op = _find_by_name(graph, name)
    if op is None:
        raise KeyError(f"op {name!r} not found in graph during deserialization")
    from tenzing_tpu.core.operation import BoundDeviceOp, unbound

    base = unbound(op)
    if "lane" in j:
        if not isinstance(base, DeviceOp):
            raise TypeError(f"serialized lane on non-device op {name!r}")
        return base.bind(Lane(j["lane"]))
    return base


def sequence_from_json(j: List[Dict[str, Any]], graph: Graph) -> Sequence:
    return Sequence([op_from_json(oj, graph) for oj in j])


def sequence_from_json_str(s: str, graph: Graph) -> Sequence:
    return sequence_from_json(json.loads(s), graph)
