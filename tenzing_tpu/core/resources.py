"""Virtual execution resources: lanes, events, and resource bijections.

TPU-native reinterpretation of the reference's virtual ``Stream``/``Event`` handles
(reference: include/tenzing/platform.hpp:22-86) and the ``Bijection`` used to prove
two schedules identical up to resource renaming (include/tenzing/bijection.hpp:3-47,
platform.hpp:248-270).

A **Lane** is a virtual execution lane: an ordering chain inside the compiled XLA
program (ops bound to the same lane execute in sequence order; ops on different
lanes are unordered unless an event edge connects them).  This is the searchable
analog of a CUDA stream.  An **Event** is a virtual cross-lane ordering token, the
analog of a cudaEvent.  Both are small integer ids bound late: the search
manipulates ids only; the executor materializes them as dependency edges.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Generic, Iterator, Optional, Tuple, TypeVar


@dataclass(frozen=True, order=True)
class Lane:
    """Virtual execution lane id (reference Stream, platform.hpp:22-52)."""

    id: int = 0

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"lane{self.id}"


@dataclass(frozen=True, order=True)
class Event:
    """Virtual cross-lane ordering event id (reference Event, platform.hpp:54-86)."""

    id: int = 0

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"event{self.id}"


T = TypeVar("T")


class Bijection(Generic[T]):
    """A growable one-to-one mapping used for resource-renaming equivalence.

    ``check_or_insert(a, b)`` succeeds iff adding the pair (a, b) keeps the mapping
    a bijection.  Mirrors the reference's ``Bijection<T>`` (bijection.hpp:3-47).
    """

    def __init__(self) -> None:
        self._fwd: Dict[T, T] = {}
        self._rev: Dict[T, T] = {}

    def check_or_insert(self, a: T, b: T) -> bool:
        if a in self._fwd:
            return self._fwd[a] == b
        if b in self._rev:
            return self._rev[b] == a
        self._fwd[a] = b
        self._rev[b] = a
        return True

    def __contains__(self, a: T) -> bool:
        return a in self._fwd

    def __getitem__(self, a: T) -> T:
        return self._fwd[a]

    def __len__(self) -> int:
        return len(self._fwd)

    def items(self) -> Iterator[Tuple[T, T]]:
        return iter(self._fwd.items())

    def copy(self) -> "Bijection[T]":
        out: Bijection[T] = Bijection()
        out._fwd = dict(self._fwd)
        out._rev = dict(self._rev)
        return out

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Bijection({self._fwd})"


class Equivalence:
    """A pair of bijections (lanes, events) witnessing that two schedules are
    identical up to resource renaming (reference Equivalence, platform.hpp:248-270).

    Truthy iff it represents a discovered equivalence; ``Equivalence.falsy()``
    is the "not equivalent" witness.
    """

    def __init__(self, ok: bool = True) -> None:
        self.lanes: Bijection[Lane] = Bijection()
        self.events: Bijection[Event] = Bijection()
        self._ok = ok

    @staticmethod
    def falsy() -> "Equivalence":
        return Equivalence(ok=False)

    def __bool__(self) -> bool:
        return self._ok

    def check_or_insert_lane(self, a: Lane, b: Lane) -> bool:
        return self.lanes.check_or_insert(a, b)

    def check_or_insert_event(self, a: Event, b: Event) -> bool:
        return self.events.check_or_insert(a, b)

    def copy(self) -> "Equivalence":
        out = Equivalence(ok=self._ok)
        out.lanes = self.lanes.copy()
        out.events = self.events.copy()
        return out

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Equivalence(ok={self._ok}, lanes={self.lanes}, events={self.events})"
