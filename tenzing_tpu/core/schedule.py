"""Complete schedules and the redundant-sync peephole cleanup.

Parity target: reference ``include/tenzing/schedule.hpp`` / ``src/schedule.cpp``.
``remove_redundant_syncs`` is a fixed-point pass deleting (schedule.cpp:19-321):

1. EventRecords whose event is never consumed (schedule.cpp:68-94)
2. WaitEvents with no subsequent device op in the waiting lane (96-117)
3. duplicate same-lane LaneSyncs with no device op between (119-164)
4. duplicate EventRecords at the same lane point — consumers rewritten to the
   surviving event (171-235)
5. sync pairs made redundant by a later-recorded-but-earlier-waited event on the
   same lane (247-306)

Also the legacy whole-space enumerators ``make_schedules`` (BFS over all
topological orders, schedule.cpp:327-390) and ``make_schedules_random``
(schedule.cpp:395-529) — the latter with an explicit seeded PRNG, fixing the
reference's unseeded rank-divergent ``rand()`` defect (schedule.cpp:400,459
``#warning``; SURVEY.md §7.3).
"""

from __future__ import annotations

import random as _random
from typing import Dict, List, Optional, Sequence as Seq, Tuple

from tenzing_tpu.core.graph import Graph
from tenzing_tpu.core.operation import BoundDeviceOp, BoundOp, OpBase
from tenzing_tpu.core.resources import Event, Lane
from tenzing_tpu.core.sequence import Sequence
from tenzing_tpu.core.sync_ops import (
    EventRecord,
    EventSync,
    LaneSync,
    LaneWait,
    WaitEvent,
)


class Schedule:
    """A complete schedule: the total order of bound ops (reference
    schedule.hpp:15-45; ``run`` lives on the executor in this design)."""

    def __init__(self, order: Sequence):
        self.order = order

    def __len__(self) -> int:
        return len(self.order)


def _event_consumers(order: List[OpBase], event: Event) -> List[int]:
    out = []
    for i, op in enumerate(order):
        if isinstance(op, WaitEvent) and op.event() == event:
            out.append(i)
        elif isinstance(op, EventSync) and op.event() == event:
            out.append(i)
    return out


def _lane_advances_between(order: List[OpBase], lane: Lane, lo: int, hi: int) -> bool:
    """True if the lane's token moves strictly between positions lo and hi: a
    device op runs on it, or a WaitEvent/LaneWait joins foreign work into it.
    Two EventRecords with no advance between capture the same progress."""
    for i in range(lo + 1, hi):
        op = order[i]
        if isinstance(op, BoundDeviceOp) and op.lane() == lane:
            return True
        if isinstance(op, WaitEvent) and op.lane() == lane:
            return True
        if isinstance(op, LaneWait) and op.waiter() == lane:
            return True
    return False


def _lane_token_consumed_after(order: List[OpBase], lane: Lane, pos: int) -> bool:
    """True if anything after ``pos`` observes the lane's token: a device op runs
    on the lane, an EventRecord snapshots it (transitive sync chains), or a
    LaneSync/LaneWait reads it."""
    for i in range(pos + 1, len(order)):
        op = order[i]
        if isinstance(op, BoundDeviceOp) and op.lane() == lane:
            return True
        if isinstance(op, EventRecord) and op.lane() == lane:
            return True
        if isinstance(op, LaneSync) and op.lane() == lane:
            return True
        if isinstance(op, LaneWait) and op.waitee() == lane:
            return True
    return False


def _rule_unconsumed_records(order: List[OpBase]) -> Optional[List[OpBase]]:
    """Rule 1 (schedule.cpp:68-94)."""
    for i, op in enumerate(order):
        if isinstance(op, EventRecord) and not _event_consumers(order, op.event()):
            return order[:i] + order[i + 1 :]
    return None


def _rule_wait_without_later_device(order: List[OpBase]) -> Optional[List[OpBase]]:
    """Rule 2 (schedule.cpp:96-117): a WaitEvent only matters if the waiting
    lane's token is observed afterwards (device op, record, or host sync on it)."""
    for i, op in enumerate(order):
        if isinstance(op, WaitEvent):
            if not _lane_token_consumed_after(order, op.lane(), i):
                return order[:i] + order[i + 1 :]
    return None


def _rule_duplicate_lane_syncs(order: List[OpBase]) -> Optional[List[OpBase]]:
    """Rule 3 (schedule.cpp:119-164): two LaneSyncs on one lane with no device op
    between — the later one is free."""
    for i, a in enumerate(order):
        if not isinstance(a, LaneSync):
            continue
        for j in range(i + 1, len(order)):
            b = order[j]
            if isinstance(b, LaneSync) and b.lane() == a.lane():
                if not _lane_advances_between(order, a.lane(), i, j):
                    return order[:j] + order[j + 1 :]
    return None


def _rule_duplicate_records(order: List[OpBase]) -> Optional[List[OpBase]]:
    """Rule 4 (schedule.cpp:171-235): two EventRecords at the same lane point
    record the same progress; rewrite consumers of the later event and drop it."""
    for i, a in enumerate(order):
        if not isinstance(a, EventRecord):
            continue
        for j in range(i + 1, len(order)):
            b = order[j]
            if isinstance(b, EventRecord) and b.lane() == a.lane():
                if _lane_advances_between(order, a.lane(), i, j):
                    break  # different lane point; later records are distinct
                out = order[:j] + order[j + 1 :]
                rewritten: List[OpBase] = []
                for op in out:
                    if isinstance(op, WaitEvent) and op.event() == b.event():
                        rewritten.append(WaitEvent(op.lane(), a.event()))
                    elif isinstance(op, EventSync) and op.event() == b.event():
                        rewritten.append(EventSync(a.event()))
                    else:
                        rewritten.append(op)
                return rewritten
    return None


def _rule_covered_pairs(order: List[OpBase]) -> Optional[List[OpBase]]:
    """Rule 5 (schedule.cpp:247-306): if event e2 is recorded at a later-or-equal
    point of the same lane than e1 but waited earlier by the same consumer chain,
    e1's wait adds nothing — drop e1's record+wait pair."""
    recs: Dict[Event, Tuple[int, Lane]] = {}
    for i, op in enumerate(order):
        if isinstance(op, EventRecord):
            recs[op.event()] = (i, op.lane())
    for e1, (p1, l1) in recs.items():
        cons1 = _event_consumers(order, e1)
        if not cons1:
            continue
        for e2, (p2, l2) in recs.items():
            # e2 recorded at a later-or-equal point of the same lane covers at
            # least all of e1's work
            if e1 == e2 or l1 != l2 or p2 < p1:
                continue
            cons2 = _event_consumers(order, e2)
            for c1 in cons1:
                o1 = order[c1]
                for c2 in cons2:
                    # e2's wait must itself be effective: after e2's record and
                    # at-or-before e1's wait
                    if c2 > c1 or c2 < p2:
                        continue
                    o2 = order[c2]
                    same_scope = (
                        isinstance(o1, WaitEvent)
                        and isinstance(o2, WaitEvent)
                        and o1.lane() == o2.lane()
                    ) or (isinstance(o1, EventSync) and isinstance(o2, EventSync))
                    if same_scope:
                        out = [
                            op
                            for k, op in enumerate(order)
                            if k != c1 and not (k == p1 and len(cons1) == 1)
                        ]
                        return out
    return None


def _rule_duplicate_consumers(order: List[OpBase]) -> Optional[List[OpBase]]:
    """Waiting twice on the same event in the same scope adds nothing — drop the
    later duplicate (arises when rule 4 rewrites consumers onto one event)."""
    seen: List[Tuple] = []
    for i, op in enumerate(order):
        if isinstance(op, WaitEvent):
            key = ("wait", op.lane(), op.event())
        elif isinstance(op, EventSync):
            key = ("sync", op.event())
        else:
            continue
        if key in seen:
            return order[:i] + order[i + 1 :]
        seen.append(key)
    return None


_RULES = (
    _rule_unconsumed_records,
    _rule_wait_without_later_device,
    _rule_duplicate_lane_syncs,
    _rule_duplicate_records,
    _rule_covered_pairs,
    _rule_duplicate_consumers,
)


def remove_redundant_syncs(order: Sequence) -> Sequence:
    """Fixed-point application of the five peephole rules (reference
    Schedule::remove_redundant_syncs, schedule.cpp:19-321)."""
    ops = order.vector()
    changed = True
    while changed:
        changed = False
        for rule in _RULES:
            out = rule(ops)
            if out is not None:
                ops = out
                changed = True
                break
    return Sequence(ops)


# -- legacy whole-space enumerators (reference schedule.cpp:327-529) -------------


def make_schedules(g: Graph, max_schedules: Optional[int] = None) -> List[Sequence]:
    """BFS over all topological orders of ``g`` (reference make_schedules,
    schedule.cpp:327-390).  No lane assignment or sync insertion — the raw
    order space."""
    out: List[Sequence] = []
    partials: List[List[OpBase]] = [[g.start()]]
    while partials:
        cur = partials.pop()
        frontier = g.frontier(cur)
        if not frontier:
            out.append(Sequence(cur))
            if max_schedules is not None and len(out) >= max_schedules:
                return out
            continue
        for op in frontier:
            partials.append(cur + [op])
    return out


def make_schedules_random(
    g: Graph, n: int, seed: int = 0
) -> List[Sequence]:
    """Weighted random topological samples with an explicit seeded PRNG
    (reference make_schedules_random, schedule.cpp:395-529; unseeded-rand defect
    fixed per SURVEY.md §7.3)."""
    rng = _random.Random(seed)
    out: List[Sequence] = []
    for _ in range(n):
        cur: List[OpBase] = [g.start()]
        while True:
            frontier = g.frontier(cur)
            if not frontier:
                break
            cur.append(frontier[rng.randrange(len(frontier))])
        out.append(Sequence(cur))
    return out
