"""Synchronization ops: the vocabulary the scheduler inserts to make cross-lane
orders legal.

Parity target: reference ``include/tenzing/cuda/ops_cuda.hpp`` /
``src/cuda/ops_cuda.cpp``: CudaEventRecord -> :class:`EventRecord`,
CudaStreamWaitEvent -> :class:`WaitEvent`, CudaEventSync -> :class:`EventSync`,
StreamSync -> :class:`LaneSync`, StreamWait -> :class:`LaneWait`; the
HasEvent/HasLane introspection interfaces (ops_cuda.hpp:24-31) become ``events()``
/ ``lanes()`` methods.

TPU-native semantics (see runtime/executor.py): instead of cudaEvent calls these
manipulate ordering tokens while the schedule's program is traced —

* ``EventRecord(lane, e)``   : event token e := lane token (marker in the chain)
* ``WaitEvent(lane, e)``     : lane token := join(lane token, event token e)
* ``EventSync(e)``           : host chain := join(host chain, event token e)
* ``LaneSync(lane)``         : host chain := join(host chain, lane token)
* ``LaneWait(waiter, waitee)``: waiter token := join(waiter, waitee tokens)

Sync ops compare equal per *kind* regardless of lane/event ids (reference
ops_cuda.hpp:15-20): the search must not distinguish schedules that differ only in
which fresh event id a sync uses — resource renaming is handled by the bijection
equivalence (core/sequence.py, core/resources.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from tenzing_tpu.core.operation import BoundOp, register_kind
from tenzing_tpu.core.resources import Event, Lane


class SyncOp(BoundOp):
    """Base for scheduler-inserted synchronization ops."""

    def is_sync(self) -> bool:
        return True

    def eq_key(self) -> Tuple:
        return ("sync", self.KIND)


@register_kind("event_record")
class EventRecord(SyncOp):
    """Record lane progress into an event (reference CudaEventRecord)."""

    def __init__(self, lane: Lane, event: Event):
        super().__init__(f"er-{lane.id}-{event.id}")
        self._lane = lane
        self._event = event

    def lane(self) -> Lane:
        return self._lane

    def event(self) -> Event:
        return self._event

    def lanes(self) -> List[Lane]:
        return [self._lane]

    def events(self) -> List[Event]:
        return [self._event]

    def desc(self) -> str:
        return f"EventRecord({self._lane!r},{self._event!r})"

    def trace(self, tc) -> None:
        tc.record_event(self._lane, self._event)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "lane": self._lane.id, "event": self._event.id}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "EventRecord":
        return cls(Lane(j["lane"]), Event(j["event"]))


@register_kind("wait_event")
class WaitEvent(SyncOp):
    """Make a lane wait for an event (reference CudaStreamWaitEvent)."""

    def __init__(self, lane: Lane, event: Event):
        super().__init__(f"we-{lane.id}-{event.id}")
        self._lane = lane
        self._event = event

    def lane(self) -> Lane:
        return self._lane

    def event(self) -> Event:
        return self._event

    def lanes(self) -> List[Lane]:
        return [self._lane]

    def events(self) -> List[Event]:
        return [self._event]

    def desc(self) -> str:
        return f"WaitEvent({self._lane!r},{self._event!r})"

    def trace(self, tc) -> None:
        tc.wait_event(self._lane, self._event)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "lane": self._lane.id, "event": self._event.id}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "WaitEvent":
        return cls(Lane(j["lane"]), Event(j["event"]))


@register_kind("event_sync")
class EventSync(SyncOp):
    """Make the host chain wait for an event (reference CudaEventSync)."""

    def __init__(self, event: Event):
        super().__init__(f"es-{event.id}")
        self._event = event

    def event(self) -> Event:
        return self._event

    def events(self) -> List[Event]:
        return [self._event]

    def desc(self) -> str:
        return f"EventSync({self._event!r})"

    def trace(self, tc) -> None:
        tc.sync_event_host(self._event)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "event": self._event.id}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "EventSync":
        return cls(Event(j["event"]))


@register_kind("lane_sync")
class LaneSync(SyncOp):
    """Make the host chain wait for a whole lane (reference StreamSync)."""

    def __init__(self, lane: Lane):
        super().__init__(f"ls-{lane.id}")
        self._lane = lane

    def lane(self) -> Lane:
        return self._lane

    def lanes(self) -> List[Lane]:
        return [self._lane]

    def desc(self) -> str:
        return f"LaneSync({self._lane!r})"

    def trace(self, tc) -> None:
        tc.sync_lane_host(self._lane)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "lane": self._lane.id}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "LaneSync":
        return cls(Lane(j["lane"]))


@register_kind("lane_wait")
class LaneWait(SyncOp):
    """Make one lane wait for another (reference StreamWait)."""

    def __init__(self, waiter: Lane, waitee: Lane):
        super().__init__(f"lw-{waiter.id}-{waitee.id}")
        self._waiter = waiter
        self._waitee = waitee

    def waiter(self) -> Lane:
        return self._waiter

    def waitee(self) -> Lane:
        return self._waitee

    def lanes(self) -> List[Lane]:
        return [self._waiter, self._waitee]

    def desc(self) -> str:
        return f"LaneWait({self._waiter!r}<-{self._waitee!r})"

    def trace(self, tc) -> None:
        tc.wait_lane(self._waiter, self._waitee)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "waiter": self._waiter.id, "waitee": self._waitee.id}

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "LaneWait":
        return cls(Lane(j["waiter"]), Lane(j["waitee"]))
