"""Operation model: the vertices of the program DAG.

Parity target: reference ``include/tenzing/operation.hpp`` (OpBase/ChoiceOp/BoundOp/
CpuOp/Start/Finish/NoOp, operation.hpp:20-157) and ``cuda/ops_cuda.hpp:194-238``
(GpuOp/BoundGpuOp) — redesigned for TPU:

* A **DeviceOp** is a pure function over named device buffers (``reads()`` /
  ``writes()`` / ``apply()``); it must be bound to a virtual :class:`Lane` before it
  is executable.  Binding produces a :class:`BoundDeviceOp`.  Where the reference's
  GpuOp launches a CUDA kernel on a ``cudaStream_t``, a DeviceOp contributes a traced
  XLA/Pallas computation to the schedule's compiled program, ordered by its lane's
  token chain (see runtime/executor.py).
* A **CpuOp** runs host-side logic; in the compiled program it occupies the implicit
  HOST lane (host program order), matching the reference's free CPU->CPU ordering
  (event_synchronizer.hpp:183-242 case table).
* Equality is *resource-insensitive*: a BoundDeviceOp compares equal to its unbound
  DeviceOp and to a binding on any other lane (reference operation.hpp:20-32
  stream-insensitive ``eq``).  Scheduler-inserted sync ops compare equal per *kind*
  regardless of lane/event ids (reference ops_cuda.hpp:15-20 dedup invariant).

Identity and ordering come from ``eq_key()``: ``__eq__``/``__hash__``/``__lt__`` all
derive from it, so ops can key dicts (the Graph adjacency maps) and sort stably
(reference ``OpBase::compare_lt``).
"""

from __future__ import annotations

import copy
from typing import TYPE_CHECKING, Any, Dict, Iterable, List, Optional, Sequence as Seq, Tuple

from tenzing_tpu.core.resources import Event, Lane

if TYPE_CHECKING:  # pragma: no cover
    from tenzing_tpu.core.graph import Graph
    from tenzing_tpu.runtime.executor import TraceContext


# Registry of op kinds for serdes (kind tag -> class).  Scheduler-inserted sync ops
# are reconstructed from their kind; workload ops are looked up in the graph by name
# (reference operation_serdes.cpp:14-76).
_KIND_REGISTRY: Dict[str, type] = {}


def register_kind(kind: str):
    def deco(cls):
        cls.KIND = kind
        _KIND_REGISTRY[kind] = cls
        return cls

    return deco


def kind_registry() -> Dict[str, type]:
    return dict(_KIND_REGISTRY)


class OpBase:
    """Abstract DAG vertex (reference OpBase, operation.hpp:20-32)."""

    KIND = "op"

    def __init__(self, name: str):
        self._name = name

    # -- identity ---------------------------------------------------------
    def name(self) -> str:
        return self._name

    def desc(self) -> str:
        """Human-readable description including resource bindings."""
        return self._name

    def eq_key(self) -> Tuple:
        """Resource-insensitive identity key; drives __eq__/__hash__/__lt__."""
        return ("named", self._name)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, OpBase) and self.eq_key() == other.eq_key()

    def __ne__(self, other: object) -> bool:
        return not self.__eq__(other)

    def __hash__(self) -> int:
        return hash(self.eq_key())

    def __lt__(self, other: "OpBase") -> bool:
        return self.eq_key() < other.eq_key()

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"{type(self).__name__}({self.desc()})"

    # -- structure --------------------------------------------------------
    def clone(self) -> "OpBase":
        return copy.copy(self)

    def uses_pallas(self) -> bool:
        """True when tracing this op emits a Pallas kernel (the executor relaxes
        shard_map's varying-axes check only for such schedules)."""
        return False

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "name": self._name}


class BoundOp(OpBase):
    """An executable op: all resource choices made (reference operation.hpp:96-99).

    Executable means it can contribute to a schedule's compiled program via
    ``trace`` and/or run host-side via ``run``.
    """

    def reads(self) -> List[str]:
        """Names of device buffers this op reads."""
        return []

    def writes(self) -> List[str]:
        """Names of device buffers this op (re)defines."""
        return []

    def trace(self, tc: "TraceContext") -> None:
        """Contribute this op to the schedule's traced program.

        Default: nothing (pure control op).  The TraceContext handles lane-token
        tie/join; ops with data just implement reads/writes/apply.
        """
        tc.trace_default(self)

    def apply(self, bufs: Dict[str, Any], ctx: "TraceContext") -> Dict[str, Any]:
        """Pure computation: map read buffers to written buffers (jax-traceable)."""
        return {}

    def run(self, platform) -> None:
        """Host-side execution for the dispatch executor (CPU ops, debugging)."""
        return None


class CpuOp(BoundOp):
    """A host-side op; occupies the implicit HOST lane (reference operation.hpp:102-111)."""

    KIND = "cpu"

    def is_host(self) -> bool:
        return True


@register_kind("start")
class Start(CpuOp):
    """Graph entry sentinel (reference operation.hpp:114-124)."""

    def __init__(self):
        super().__init__("start")

    def eq_key(self) -> Tuple:
        return ("start",)


@register_kind("finish")
class Finish(CpuOp):
    """Graph exit sentinel (reference operation.hpp:127-136)."""

    def __init__(self):
        super().__init__("finish")

    def eq_key(self) -> Tuple:
        return ("finish",)


@register_kind("noop")
class NoOp(CpuOp):
    """A do-nothing named CPU op, the unit test workhorse (reference operation.hpp:141-157)."""


class ChoiceOp(OpBase):
    """A non-executable op standing for a set of implementation choices
    (reference operation.hpp:90-93).  The scheduler replaces it in the graph with
    one of ``choices()`` via a ChooseOp decision (state.cpp:61-65)."""

    KIND = "choice"

    def choices(self) -> List[OpBase]:
        raise NotImplementedError


class CompoundOp(OpBase):
    """An op that packages a whole sub-graph (reference operation_compound.hpp:1-13).

    The scheduler inlines it via Graph.clone_but_expand (ExpandOp decision).
    """

    KIND = "compound"

    def graph(self) -> "Graph":
        raise NotImplementedError


class DeviceOp(OpBase):
    """A device computation that must be bound to a Lane before execution
    (reference GpuOp, ops_cuda.hpp:194-197).

    Subclasses implement reads()/writes()/apply(): a pure jax function over the
    named buffers.  ``apply`` may use collectives (lax.ppermute etc.) — the
    schedule's program is traced under shard_map over the platform mesh.
    """

    KIND = "device"

    def reads(self) -> List[str]:
        return []

    def writes(self) -> List[str]:
        return []

    def apply(self, bufs: Dict[str, Any], ctx: "TraceContext") -> Dict[str, Any]:
        raise NotImplementedError

    def bind(self, lane: Lane) -> "BoundDeviceOp":
        return BoundDeviceOp(self, lane)

    # -- megakernel-fusion protocol (runtime/fused.py) ---------------------
    def fusible(self) -> bool:
        """True when ``apply`` may be traced INSIDE a Pallas kernel body:
        pure buffer->buffer jax computation — no collectives (no mesh axis
        context inside a kernel), no nested ``pallas_call``
        (``uses_pallas`` ops are excluded by the partitioner regardless),
        no host/transfer semantics.  Opt-in per op class: the fusion
        backend only ever fuses ops that declare it, so an un-audited op
        can never silently land inside a megakernel."""
        return False

    def fuse_tiling(self) -> Optional[Dict[str, Optional[int]]]:
        """Row-decomposition declaration for fused-region tiling: a map
        over this op's reads+writes of the axis along which the op is
        independent (``None`` value = the op needs the FULL buffer, e.g.
        a gathered x or the K/V block of an attention fold).  ``None``
        return = not tileable; the op still fuses, but its region only
        offers the trivial single-tile kernel."""
        return None

    # -- op-chunking protocol (core/chunking.py) ---------------------------
    def chunkable(self) -> bool:
        """True when this op can expand into ``n`` partial ops plus a
        combine via :meth:`split` — the T3-style fine-grained-overlap
        protocol (core/chunking.py), the chunking sibling of the
        megakernel ``fusible()/fuse_tiling()`` audit above.  Opt-in per op
        class: chunked variants only ever enter a choice menu for ops
        that declare it, so an un-audited op can never be silently
        re-associated."""
        return False

    def chunk_counts(self) -> List[int]:
        """Structurally valid chunk counts (always contains 1): the
        counts :meth:`split` accepts — typically powers of two dividing
        the op's split-axis extent.  Validity only; profitability is the
        roofline's question (``bench/roofline.py::prune_chunkings``)."""
        return [1]

    def split(self, n: int) -> List["DeviceOp"]:
        """This op as ``n`` partial ops (plus a combine where the partials
        do not already fold into an accumulating update), executed in list
        order: :class:`~tenzing_tpu.core.chunking.ChunkedOp` chains them
        serially, because every partial reads the buffer version its
        predecessor wrote (read-modify-write under the executor's SSA
        buffer semantics) — the schedule freedom chunking buys is OTHER
        ops interleaving between the partials, e.g. a transfer posting
        after the head chunks of its producer."""
        raise NotImplementedError(
            f"{type(self).__name__} declares no split() — chunkable() ops "
            "must implement the chunking protocol")


class BoundDeviceOp(BoundOp):
    """DeviceOp + Lane = executable (reference BoundGpuOp, ops_cuda.hpp:202-238).

    Identity delegates to the wrapped op (lane-insensitive equality), so a graph
    vertex keeps its key across lane-assignment surgery
    (Graph.clone_but_replace, reference graph.hpp:130-158).
    """

    KIND = "bound_device"

    def __init__(self, op: DeviceOp, lane: Lane):
        super().__init__(op.name())
        self._op = op
        self._lane = lane

    def unbound(self) -> DeviceOp:
        return self._op

    def lane(self) -> Lane:
        return self._lane

    def lanes(self) -> List[Lane]:
        """Resource introspection (reference HasStream, ops_cuda.hpp:24-31)."""
        return [self._lane]

    def with_lane(self, lane: Lane) -> "BoundDeviceOp":
        return BoundDeviceOp(self._op, lane)

    def desc(self) -> str:
        return f"{self._op.desc()}@{self._lane!r}"

    def eq_key(self) -> Tuple:
        return self._op.eq_key()

    def reads(self) -> List[str]:
        return self._op.reads()

    def writes(self) -> List[str]:
        return self._op.writes()

    def apply(self, bufs: Dict[str, Any], ctx: "TraceContext") -> Dict[str, Any]:
        return self._op.apply(bufs, ctx)

    def uses_pallas(self) -> bool:
        return self._op.uses_pallas()

    def fusible(self) -> bool:
        return self._op.fusible()

    def fuse_tiling(self) -> Optional[Dict[str, Optional[int]]]:
        return self._op.fuse_tiling()

    def chunkable(self) -> bool:
        return self._op.chunkable()

    def chunk_counts(self) -> List[int]:
        return self._op.chunk_counts()

    def split(self, n: int) -> List[DeviceOp]:
        return self._op.split(n)

    def to_json(self) -> Dict[str, Any]:
        j = self._op.to_json()
        j["lane"] = self._lane.id
        return j


# -- helpers (reference operation.cpp:36-100) ------------------------------------


def make_lane_variations(op: OpBase, lanes: Seq[Lane]) -> List[OpBase]:
    """All lane bindings of ``op`` (reference make_platform_variations,
    operation.cpp:36-49).  Non-device ops pass through unchanged."""
    if isinstance(op, BoundDeviceOp):
        return [op.with_lane(lane) for lane in lanes]
    if isinstance(op, DeviceOp):
        return [op.bind(lane) for lane in lanes]
    return [op]


def unbound(op: OpBase) -> OpBase:
    """Strip a lane binding if present (reference BoundGpuOp::unbound)."""
    if isinstance(op, BoundDeviceOp):
        return op.unbound()
    return op


def keep_uniques(ops: Iterable[OpBase]) -> List[OpBase]:
    """Order-preserving dedup by op equality (reference keep_uniques, operation.cpp:51-62)."""
    seen = set()
    out: List[OpBase] = []
    for op in ops:
        k = op.eq_key()
        if k not in seen:
            seen.add(k)
            out.append(op)
    return out
