"""tenzing_tpu — a TPU-native framework for searching over execution schedules.

A TPU+ICI program (halo exchange, distributed SpMV, ...) is modeled as a DAG of
operations.  Remaining implementation freedom — the total order of operations, the
assignment of device ops to execution *lanes*, the insertion of synchronization ops
that make a given order legal, and choices among implementation variants — is a
sequential decision problem searched by exhaustive DFS (`tenzing_tpu.solve.dfs`) and
Monte-Carlo tree search (`tenzing_tpu.solve.mcts`).  Every candidate schedule is
lowered to a single XLA program whose dependency structure *is* the schedule
(token-threaded lanes, see `tenzing_tpu.runtime.executor`) and empirically
benchmarked on the device.

Capability parity target: sandialabs/tenzing (see SURVEY.md).  This is a new
TPU-first design, not a port: CUDA streams -> virtual lanes realized as
value-preserving scalar data-tie chains inside one compiled XLA program (the
TPU backend strips `optimization_barrier`, so ties are real data dependencies);
cudaEvent -> cross-lane token edges; MPI Isend/Irecv -> async post/wait ICI
transfers (`tenzing_tpu.ops.comm_ops`) under `shard_map`; MPI control plane ->
host-side process coordination (`tenzing_tpu.parallel.control_plane`).

See docs/GUIDE.md for the user guide and the reference->TPU migration map.
"""

__version__ = "0.1.0"

from tenzing_tpu.core.operation import (  # noqa: F401
    OpBase,
    BoundOp,
    ChoiceOp,
    CompoundOp,
    CpuOp,
    DeviceOp,
    BoundDeviceOp,
    Start,
    Finish,
    NoOp,
)
from tenzing_tpu.core.graph import Graph  # noqa: F401
from tenzing_tpu.core.sequence import Sequence  # noqa: F401
from tenzing_tpu.core.resources import Lane, Event, Bijection, Equivalence  # noqa: F401
