"""Pallas flash-attention block kernel: one online-softmax update step.

The MXU workhorse of the ring-attention workload (models/ring_attention.py):
given local queries Q and one K/V block of the ring, fold the block into the
running (acc, m, l) online-softmax state:

    s     = Q K^T * scale          (MXU)
    m'    = max(m, rowmax(s))
    alpha = exp(m - m')
    p     = exp(s - m')
    l'    = l * alpha + rowsum(p)
    acc'  = acc * alpha + p V      (MXU)

State tensors m and l are carried broadcast to (b, n, d) — same shape/layout as
acc — so every in-kernel operand is a clean 2D (n, d) or (n, nkv) tile (no
lane<->sublane transposes, no last-dim-1 blocks; see ops/spmv_pallas.py for the
Mosaic layout constraints that motivate this).

The kernel grid runs over the batch dimension; one program folds one batch
element's whole block — Q/K/V blocks of ring attention are already VMEM-sized
by construction (n_local x d per step).

``interpret=True`` (automatic off-TPU) runs the same kernel in the Pallas
interpreter for CPU tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tenzing_tpu.ops.common import out_struct


def _attn_block_kernel(scale, q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                       acc_out, m_out, l_out):
    q = q_ref[0]  # (n, d)
    k = k_ref[0]  # (nkv, d)
    v = v_ref[0]
    m_old = m_ref[0]  # (n, d) broadcast copies of the running row max
    l_old = l_ref[0]
    acc_old = acc_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (n, nkv)
    m_blk = jnp.max(s, axis=1, keepdims=True)  # (n, 1)
    m_new = jnp.maximum(m_old, jnp.broadcast_to(m_blk, m_old.shape))
    alpha = jnp.exp(m_old - m_new)
    p = jnp.exp(s - m_new[:, :1])  # (n, nkv)
    l_new = l_old * alpha + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), l_old.shape
    )
    acc_new = acc_old * alpha + jnp.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    acc_out[0] = acc_new.astype(acc_out.dtype)
    m_out[0] = m_new
    l_out[0] = l_new


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def attn_block_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    acc: jax.Array,
    m: jax.Array,
    l: jax.Array,
    scale: float,
    *,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fold one K/V block into the online-softmax state; returns (acc', m', l').

    Shapes: q (b, n, d); k/v (b, nkv, d); acc/m/l (b, n, d) with m/l broadcast
    along the last axis.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, d = q.shape
    nkv = k.shape[1]
    # tile the (row-independent) update over query blocks so VMEM holds one
    # q/state tile + the whole K/V block, never all n queries at once; ragged n
    # is padded up to the tile (rows are independent, pad rows stay finite:
    # zero q/m give s=0, alpha=1 — no NaN/inf to leak) and sliced back off
    bq = min(n, 512)
    pad = (-n) % bq
    np_ = n + pad
    if pad:
        padw = ((0, 0), (0, pad), (0, 0))
        q, acc, m, l = (jnp.pad(t, padw) for t in (q, acc, m, l))
    qblk = pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0))
    kvblk = pl.BlockSpec((1, nkv, d), lambda i, j: (i, 0, 0))
    specs_in = [qblk, kvblk, kvblk, qblk, qblk, qblk]
    operands = (q, k, v, acc, m, l)
    out_shape = [
        out_struct((b, np_, d), acc.dtype, *operands),
        out_struct((b, np_, d), m.dtype, *operands),
        out_struct((b, np_, d), l.dtype, *operands),
    ]
    specs_out = [qblk, qblk, qblk]
    kernel = functools.partial(_attn_block_kernel, float(scale))
    outs = pl.pallas_call(
        kernel,
        grid=(b, np_ // bq),
        in_specs=specs_in,
        out_specs=specs_out,
        out_shape=out_shape,
        interpret=interpret,
    )(q, k, v, acc, m, l)
    if pad:
        outs = [o[:, :n] for o in outs]
    return tuple(outs)
