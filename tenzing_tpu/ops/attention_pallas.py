"""Pallas flash-attention block kernel: one online-softmax update step.

The MXU workhorse of the ring-attention workload (models/ring_attention.py):
given local queries Q and one K/V block of the ring, fold the block into the
running (acc, m, l) online-softmax state:

    s     = Q K^T * scale          (MXU)
    m'    = max(m, rowmax(s))
    alpha = exp(m - m')
    p     = exp(s - m')
    l'    = l * alpha + rowsum(p)
    acc'  = acc * alpha + p V      (MXU)

State tensors m and l are carried broadcast to (b, n, d) — same shape/layout as
acc — so every in-kernel operand is a clean 2D (n, d) or (n, nkv) tile (no
lane<->sublane transposes, no last-dim-1 blocks; see ops/spmv_pallas.py for the
Mosaic layout constraints that motivate this).

The kernel grid runs over the batch dimension; one program folds one batch
element's whole block — Q/K/V blocks of ring attention are already VMEM-sized
by construction (n_local x d per step).

``interpret=True`` (automatic off-TPU) runs the same kernel in the Pallas
interpreter for CPU tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tenzing_tpu.ops.common import out_struct


def _attn_block_kernel(scale, q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                       acc_out, m_out, l_out):
    q = q_ref[0]  # (n, d)
    k = k_ref[0]  # (nkv, d)
    v = v_ref[0]
    m_old = m_ref[0]  # (n, d) broadcast copies of the running row max
    l_old = l_ref[0]
    acc_old = acc_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (n, nkv)
    m_blk = jnp.max(s, axis=1, keepdims=True)  # (n, 1)
    m_new = jnp.maximum(m_old, jnp.broadcast_to(m_blk, m_old.shape))
    alpha = jnp.exp(m_old - m_new)
    p = jnp.exp(s - m_new[:, :1])  # (n, nkv)
    l_new = l_old * alpha + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), l_old.shape
    )
    acc_new = acc_old * alpha + jnp.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    acc_out[0] = acc_new.astype(acc_out.dtype)
    m_out[0] = m_new
    l_out[0] = l_new


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def attn_block_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    acc: jax.Array,
    m: jax.Array,
    l: jax.Array,
    scale: float,
    *,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fold one K/V block into the online-softmax state; returns (acc', m', l').

    Shapes: q (b, n, d); k/v (b, nkv, d); acc/m/l (b, n, d) with m/l broadcast
    along the last axis.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, d = q.shape
    nkv = k.shape[1]
    # tile the (row-independent) update over query blocks so VMEM holds one
    # q/state tile + the whole K/V block, never all n queries at once; ragged n
    # is padded up to the tile (rows are independent, pad rows stay finite:
    # zero q/m give s=0, alpha=1 — no NaN/inf to leak) and sliced back off
    bq = min(n, 512)
    pad = (-n) % bq
    np_ = n + pad
    if pad:
        padw = ((0, 0), (0, pad), (0, 0))
        q, acc, m, l = (jnp.pad(t, padw) for t in (q, acc, m, l))
    qblk = pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0))
    kvblk = pl.BlockSpec((1, nkv, d), lambda i, j: (i, 0, 0))
    specs_in = [qblk, kvblk, kvblk, qblk, qblk, qblk]
    operands = (q, k, v, acc, m, l)
    out_shape = [
        out_struct((b, np_, d), acc.dtype, *operands),
        out_struct((b, np_, d), m.dtype, *operands),
        out_struct((b, np_, d), l.dtype, *operands),
    ]
    specs_out = [qblk, qblk, qblk]
    kernel = functools.partial(_attn_block_kernel, float(scale))
    outs = pl.pallas_call(
        kernel,
        grid=(b, np_ // bq),
        in_specs=specs_in,
        out_specs=specs_out,
        out_shape=out_shape,
        interpret=interpret,
    )(q, k, v, acc, m, l)
    if pad:
        outs = [o[:, :n] for o in outs]
    return tuple(outs)


def _attn_fused_kernel(scale, nkv_steps, q_ref, k_ref, v_ref, acc_in, m_in,
                       l_in, acc_out, m_out, l_out, acc_s, m_s, l_s):
    """One (batch, q-tile, kv-block) grid step of the fused flash kernel:
    state lives in VMEM scratch across the kv dimension (innermost, strictly
    sequential), so acc/m/l touch HBM exactly twice per q-tile (initial read,
    final write) instead of twice per kv block."""
    kv = pl.program_id(2)

    @pl.when(kv == 0)
    def _():
        acc_s[...] = acc_in[0]
        m_s[...] = m_in[0]
        l_s[...] = l_in[0]

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    m_old = m_s[...]
    l_old = l_s[...]
    acc_old = acc_s[...]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    m_blk = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_old, jnp.broadcast_to(m_blk, m_old.shape))
    alpha = jnp.exp(m_old - m_new)
    p = jnp.exp(s - m_new[:, :1])
    l_new = l_old * alpha + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), l_old.shape
    )
    acc_new = acc_old * alpha + jnp.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    acc_s[...] = acc_new
    m_s[...] = m_new
    l_s[...] = l_new

    @pl.when(kv == nkv_steps - 1)
    def _():
        acc_out[0] = acc_s[...].astype(acc_out.dtype)
        m_out[0] = m_s[...]
        l_out[0] = l_s[...]


@functools.partial(jax.jit, static_argnames=("scale", "bkv", "interpret"))
def attn_fused_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    acc: jax.Array,
    m: jax.Array,
    l: jax.Array,
    scale: float,
    bkv: int = 1024,
    *,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fold the ENTIRE resident K/V into the online-softmax state in ONE
    kernel — the fused alternative to chaining :func:`attn_block_pallas`
    per block.

    Why it exists (measured, r5): at b=4, n=8k, d=128 the chained version
    moves the (b, n, d) f32 state acc/m/l through HBM twice per block —
    8 blocks x 6 x 16.8 MB ~= 0.8 GB per iteration, ~1.2 ms at v5e peak —
    so the chain is HBM-state-bound at 66.5% MFU while the roofline says
    compute-bound.  Keeping the state in VMEM scratch across the kv grid
    dimension (strictly sequential, pinned "arbitrary") cuts state traffic
    to one read + one write per q-tile.

    Shapes: q (b, n, d); k/v (b, nkv, d) with nkv % bkv == 0; acc/m/l
    (b, n, d) broadcast state as in :func:`attn_block_pallas`.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, d = q.shape
    nkv = k.shape[1]
    bkv = min(bkv, nkv)
    assert nkv % bkv == 0, (nkv, bkv)
    nkv_steps = nkv // bkv
    bq = min(n, 512)
    pad = (-n) % bq
    np_ = n + pad
    if pad:
        padw = ((0, 0), (0, pad), (0, 0))
        q, acc, m, l = (jnp.pad(t, padw) for t in (q, acc, m, l))
    qblk = pl.BlockSpec((1, bq, d), lambda i, j, kv: (i, j, 0))
    kvblk = pl.BlockSpec((1, bkv, d), lambda i, j, kv: (i, kv, 0))
    operands = (q, k, v, acc, m, l)
    kernel = functools.partial(_attn_fused_kernel, float(scale), nkv_steps)
    from jax.experimental.pallas import tpu as pltpu

    from tenzing_tpu.ops.pallas_compat import compiler_params

    outs = pl.pallas_call(
        kernel,
        # kv innermost and strictly sequential: the VMEM scratch state
        # carries across kv steps of one (batch, q-tile)
        grid=(b, np_ // bq, nkv_steps),
        in_specs=[qblk, kvblk, kvblk, qblk, qblk, qblk],
        out_specs=[qblk, qblk, qblk],
        out_shape=[
            out_struct((b, np_, d), acc.dtype, *operands),
            out_struct((b, np_, d), m.dtype, *operands),
            out_struct((b, np_, d), l.dtype, *operands),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=compiler_params(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, acc, m, l)
    if pad:
        outs = [o[:, :n] for o in outs]
    return tuple(outs)
